"""Reconfiguration plane (raft_sim_tpu/reconfig): joint-consensus membership
change, TimeoutNow leadership transfer, and ReadIndex reads.

Kernel-vs-oracle bit-exactness for these extensions rides tests/
test_oracle_parity.py (the n5-reconfig-plane rows); this file covers the
protocol semantics the parity rows cannot state directly: configuration-
masked quorums at bitplane word boundaries, joint-phase entry/exit and
removed-leader stepdown, the transfer lease, read serving, the three
TEST-ONLY mutants' violations (and the real kernel's cleanliness under the
same programs), the checker's two new property dimensions, and the v22
checkpoint round trip.

Program budget: the word-boundary and lifecycle tests drive single `step`
calls (tiny jit programs); the run-level tests share two small scan programs
and the mutant/checker tests two small windowed trace programs.
"""

from __future__ import annotations

import dataclasses
import functools
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_sim_tpu import RaftConfig, init_state
from raft_sim_tpu.models import raft
from raft_sim_tpu.ops import bitplane
from raft_sim_tpu.scenario.mutation import mutant_config
from raft_sim_tpu.sim import scan, telemetry
from raft_sim_tpu.trace import checker as tchecker
from raft_sim_tpu.trace import events as tev
from raft_sim_tpu.trace import history as thistory
from raft_sim_tpu.trace.ring import TraceSpec
from raft_sim_tpu.types import CANDIDATE, FOLLOWER, LEADER, NIL, StepInputs
from raft_sim_tpu.utils import checkpoint
from raft_sim_tpu.utils.config import PRESETS


def _quiet_inputs(cfg: RaftConfig, **over) -> StepInputs:
    """No faults, no messages dropped, timers far in the future."""
    n = cfg.n_nodes
    far = 10_000
    base = dict(
        deliver_mask=bitplane.pack(jnp.ones((n, n), bool), axis=1),
        skew=jnp.ones((n,), jnp.int32),
        timeout_draw=jnp.full((n,), far, jnp.int32),
        client_cmd=jnp.int32(NIL),
        client_target=jnp.int32(0),
        client_bounce=jnp.zeros((cfg.client_pipeline,), jnp.int32),
        alive=jnp.ones((n,), bool),
        restarted=jnp.zeros((n,), bool),
    )
    base.update(over)
    return StepInputs(**base)


def _mask(n: int, members) -> jnp.ndarray:
    return bitplane.pack(
        jnp.asarray([i in members for i in range(n)], bool)
    )


# ----------------------------------- packed dual quorum at word boundaries


@pytest.mark.parametrize(
    "n",
    [
        5, 31, 32, 33,
        # Slow tier (870s budget): the config5 width re-runs the same packed
        # dual-popcount at W=2 words; the 31/32/33 triplet already pins the
        # word-boundary arithmetic in tier 1, and test_bitplane pins the
        # N=51 popcount itself.
        pytest.param(51, marks=pytest.mark.slow),
    ],
)
def test_joint_dual_quorum_at_word_boundaries(n):
    """During a joint phase a candidate needs majorities of BOTH packed
    configurations. Exercised at the bitplane word boundaries (31/32/33 and
    the config5 width 51): one vote short of either majority loses, and a
    vote set that satisfies C_old via the to-be-removed node does NOT
    satisfy C_new."""
    cfg = RaftConfig(n_nodes=n, log_capacity=8, reconfig_interval=1000)
    removed = n - 1
    maj_old = n // 2 + 1
    maj_new = (n - 1) // 2 + 1

    def outcome(voters) -> bool:
        s = init_state(cfg, jax.random.key(0))
        s = s._replace(
            role=s.role.at[0].set(CANDIDATE),
            term=jnp.full((n,), 5, jnp.int32),
            voted_for=s.voted_for.at[0].set(0),
            votes=s.votes.at[0].set(_mask(n, set(voters))),
            member_new=_mask(n, set(range(n)) - {removed}),
            cfg_pend=jnp.int32(1000),  # joint: exit far away
        )
        s2, _ = jax.jit(lambda st, i: raft.step(cfg, st, i))(
            s, _quiet_inputs(cfg)
        )
        return int(s2.role[0]) == LEADER

    need = max(maj_old, maj_new)
    assert outcome(range(need))  # both majorities met
    assert not outcome(range(need - 1))  # one short of the larger majority
    # C_old-majority via the removed node, but one short in C_new: the dual
    # test must refuse (a single-config kernel would elect -- the mutant).
    if maj_old == maj_new:
        tricky = list(range(maj_old - 1)) + [removed]
        assert not outcome(tricky)


def test_single_config_quorum_when_not_joint():
    """Outside a joint phase the masked quorum degenerates to the plain
    majority of the (single) current configuration."""
    n = 7
    cfg = RaftConfig(n_nodes=n, log_capacity=8, reconfig_interval=1000)
    s = init_state(cfg, jax.random.key(0))
    s = s._replace(
        role=s.role.at[2].set(CANDIDATE),
        term=jnp.full((n,), 3, jnp.int32),
        voted_for=s.voted_for.at[2].set(2),
        votes=s.votes.at[2].set(_mask(n, {1, 2, 3, 4})),
    )
    s2, _ = jax.jit(lambda st, i: raft.step(cfg, st, i))(s, _quiet_inputs(cfg))
    assert int(s2.role[2]) == LEADER


# ----------------------------------------- joint lifecycle + stepdown


def test_joint_entry_exit_epochs_and_removed_leader_stepdown():
    """A remove toggle enters the joint phase (epoch +1), the exit fires once
    a member leader's commit covers the change point (epoch +1 again), and
    the removed leader steps down AT the switch -- the non-voting catch-up
    role (it never campaigns again: phase-7 membership gate)."""
    n = 5
    cfg = RaftConfig(n_nodes=n, log_capacity=8, reconfig_interval=1000)
    s = init_state(cfg, jax.random.key(0))
    # Node 0 an established leader of term 2.
    s = s._replace(
        role=s.role.at[0].set(LEADER),
        term=jnp.full((n,), 2, jnp.int32),
        leader_id=jnp.zeros((n,), jnp.int32),
    )
    step = jax.jit(lambda st, i: raft.step(cfg, st, i))
    # Tick 1: the admin offers "toggle node 0" -> joint phase.
    s, _ = step(s, _quiet_inputs(cfg, reconfig_cmd=jnp.int32(0)))
    assert int(s.cfg_epoch) == 1 and int(s.cfg_pend) > 0
    assert bool(np.asarray(bitplane.unpack(s.member_new, n))[0]) is False
    assert bool(np.asarray(bitplane.unpack(s.member_old, n))[0]) is True
    assert int(s.role[0]) == LEADER  # leads THROUGH the joint phase
    # Tick 2: commit (0) already covers the change point -> exit + stepdown.
    s, _ = step(s, _quiet_inputs(cfg))
    assert int(s.cfg_epoch) == 2 and int(s.cfg_pend) == 0
    assert bool(np.asarray(bitplane.unpack(s.member_old, n))[0]) is False
    assert int(s.role[0]) == FOLLOWER  # removed leader stepped down
    # A second command is accepted only now (refused while joint): re-add 0.
    s, _ = step(s, _quiet_inputs(cfg, reconfig_cmd=jnp.int32(0)))
    assert int(s.cfg_epoch) == 2  # no leader in the new config yet: refused


def test_reconfig_command_refused_while_joint_and_below_two_voters():
    n = 3
    cfg = RaftConfig(n_nodes=n, log_capacity=8, reconfig_interval=1000)
    s = init_state(cfg, jax.random.key(0))
    s = s._replace(
        role=s.role.at[0].set(LEADER),
        term=jnp.full((n,), 2, jnp.int32),
        member_new=_mask(n, {0, 1}),
        cfg_pend=jnp.int32(1000),  # joint pending, exit far away
    )
    step = jax.jit(lambda st, i: raft.step(cfg, st, i))
    s2, _ = step(s, _quiet_inputs(cfg, reconfig_cmd=jnp.int32(1)))
    assert int(s2.cfg_epoch) == 0  # refused: joint phase pending
    # Not joint, but the toggle would strand a single voter: refused.
    s3 = s._replace(cfg_pend=jnp.int32(0), member_old=_mask(n, {0, 1}))
    s4, _ = step(s3, _quiet_inputs(cfg, reconfig_cmd=jnp.int32(1)))
    assert int(s4.cfg_epoch) == 0
    assert np.array_equal(np.asarray(s4.member_new), np.asarray(s3.member_new))


# --------------------------------------------------- transfer lease + flow


def test_transfer_lease_blocks_writes_and_fires_timeout_now():
    """An accepted transfer parks on xfer_to, refuses client commands (the
    lease handoff), and fires REQ_TIMEOUT_NOW at the caught-up target on the
    leader's heartbeat tick."""
    from raft_sim_tpu.types import REQ_TIMEOUT_NOW

    n = 5
    cfg = RaftConfig(n_nodes=n, log_capacity=8, transfer_interval=1000,
                     client_interval=4)
    s = init_state(cfg, jax.random.key(0))
    s = s._replace(
        role=s.role.at[0].set(LEADER),
        term=jnp.full((n,), 2, jnp.int32),
        leader_id=jnp.zeros((n,), jnp.int32),
        ack_age=jnp.zeros((n, n), s.ack_age.dtype),  # everyone responsive
        deadline=s.deadline.at[0].set(1),  # heartbeat fires next tick
    )
    step = jax.jit(lambda st, i: raft.step(cfg, st, i))
    s, _ = step(s, _quiet_inputs(
        cfg, transfer_cmd=jnp.int32(3), client_cmd=jnp.int32(77)
    ))
    assert int(s.xfer_to[0]) == 3
    assert int(s.log_len[0]) == 0  # lease: the offered command was refused
    # Heartbeat tick: target matches (log empty), so the broadcast slot is
    # the TimeoutNow, not the heartbeat.
    assert int(s.mailbox.req_type[0]) == REQ_TIMEOUT_NOW
    assert int(s.mailbox.xfer_tgt[0]) == 3


def test_transfer_fires_and_elects_during_joint_phase():
    """PR 10's named follow-up, deterministic: a TimeoutNow transfer
    accepted, fired, received, and COMPLETED while a membership change is
    parked in its joint phase. The target's bypass election runs under the
    DUAL quorum, the joint phase stays open throughout (the exit bound is
    far), and the deposed old leader's pending transfer aborts on term
    adoption -- the temporal interaction the randomized
    n5-transfer-during-joint parity row sweeps, pinned step by step."""
    from raft_sim_tpu.types import REQ_TIMEOUT_NOW, REQ_VOTE

    n = 5
    cfg = RaftConfig(
        n_nodes=n, log_capacity=8, reconfig_interval=1000,
        transfer_interval=1000, client_interval=4,
    )
    s = init_state(cfg, jax.random.key(0))
    s = s._replace(
        role=s.role.at[0].set(LEADER),
        term=jnp.full((n,), 2, jnp.int32),
        leader_id=jnp.zeros((n,), jnp.int32),
        ack_age=jnp.zeros((n, n), s.ack_age.dtype),  # everyone responsive
        deadline=s.deadline.at[0].set(1),  # heartbeat fires on tick 1
        # Joint phase mid-flight: removing node 4, exit bound far away.
        member_new=_mask(n, {0, 1, 2, 3}),
        cfg_pend=jnp.int32(10),
        cfg_epoch=jnp.int32(1),
    )
    step = jax.jit(lambda st, i: raft.step(cfg, st, i))
    # Tick 1: transfer to node 1 accepted WHILE joint; the heartbeat slot
    # carries the TimeoutNow (target trivially caught up: empty logs).
    s, _ = step(s, _quiet_inputs(cfg, transfer_cmd=jnp.int32(1)))
    assert int(s.xfer_to[0]) == 1 and int(s.cfg_pend) == 10
    assert int(s.mailbox.req_type[0]) == REQ_TIMEOUT_NOW
    assert int(s.mailbox.xfer_tgt[0]) == 1
    # Tick 2: the target receives it at the current term and starts a REAL
    # election immediately -- term bump, self-vote, RequestVote broadcast.
    s, _ = step(s, _quiet_inputs(cfg))
    assert int(s.role[1]) == CANDIDATE and int(s.term[1]) == 3
    assert int(s.mailbox.req_type[1]) == REQ_VOTE
    # Tick 3: voters adopt term 3 and grant; the deposed old leader's
    # pending transfer aborts on adoption (volatile leader state).
    s, _ = step(s, _quiet_inputs(cfg))
    assert int(s.role[0]) == FOLLOWER and int(s.term[0]) == 3
    assert int(s.xfer_to[0]) == NIL
    # Tick 4: the target banks a DUAL quorum (majorities of C_old AND C_new
    # -- all five granted here, covering both) and wins, with the joint
    # phase still open: leadership moved INSIDE the membership change.
    s, _ = step(s, _quiet_inputs(cfg))
    assert int(s.role[1]) == LEADER
    assert int(s.cfg_pend) == 10 and int(s.cfg_epoch) == 1
    # One more quiet tick: no spurious joint exit (commit still below the
    # bound) and exactly one leader.
    s, info = step(s, _quiet_inputs(cfg))
    assert int(s.cfg_pend) == 10
    assert int(info.n_leaders) == 1 and not bool(info.viol_election_safety)


def test_transfer_run_moves_leadership_without_violations():
    """A standing transfer cadence under light drop: leadership actually
    moves between nodes (TimeoutNow elections complete) and no safety
    invariant ever fires. Also covers pre_vote: the target bypasses the
    probe, so transfers complete despite the lease-quiet voters."""
    cfg = RaftConfig(n_nodes=5, log_capacity=16, client_interval=3,
                     transfer_interval=12, drop_prob=0.05, pre_vote=True)
    key = jax.random.key(1)
    k_init, k_run = jax.random.split(key)
    state = init_state(cfg, k_init)
    final, metrics, infos = jax.jit(
        lambda s, k: scan.run(cfg, s, k, 400, trace=True)
    )(state, k_run)
    assert int(np.asarray(metrics.violations)) == 0
    leaders = {int(x) for x in np.asarray(infos.leader) if int(x) != NIL}
    assert len(leaders) > 1, "leadership never transferred"


# --------------------------------------------------------- ReadIndex reads


def test_reads_serve_with_metrics():
    cfg = RaftConfig(n_nodes=5, log_capacity=32, client_interval=2,
                     read_interval=2)
    _, m = scan.simulate(cfg, 7, 8, 300)
    served = int(np.sum(np.asarray(m.reads_served)))
    assert served > 0
    assert int(np.sum(np.asarray(m.read_hist))) == served
    # Every served read waited at least the one-tick confirmation round.
    assert int(np.sum(np.asarray(m.read_lat_sum))) >= served


def test_read_confirmation_uses_tick_start_config_at_joint_exit():
    """Kernel-vs-oracle pin for the one-tick coincidence of a joint-phase
    EXIT and a pending read's serve decision: both judge the confirmation
    under the TICK-START (joint) configuration, so a read whose acks satisfy
    only the incoming configuration stays pending through the switch (a
    late-bound oracle closure once served it -- review regression)."""
    from tests import oracle

    n = 5
    cfg = RaftConfig(n_nodes=n, log_capacity=8, reconfig_interval=1000,
                     read_interval=1000)
    s = init_state(cfg, jax.random.key(0))
    # Joint {0,1,2,3} -> {0..4} about to exit (commit 0 covers pend - 1 = 0);
    # leader 0 holds a pending read acked by {1, 4}: with self that is 3 --
    # a majority of the NEW config (maj 3) but only 2 of the OLD members
    # {0,1,2,3} (maj 3). Tick-start rule: NOT confirmed this tick.
    s = s._replace(
        role=s.role.at[0].set(LEADER),
        term=jnp.full((n,), 2, jnp.int32),
        leader_id=jnp.zeros((n,), jnp.int32),
        member_old=_mask(n, {0, 1, 2, 3}),
        member_new=_mask(n, {0, 1, 2, 3, 4}),
        cfg_pend=jnp.int32(1),
        read_idx=s.read_idx.at[0].set(1),
        read_tick=s.read_tick.at[0].set(1),
        read_acks=s.read_acks.at[0].set(_mask(n, {1, 4})),
    )
    inp = _quiet_inputs(cfg)
    s2, _ = jax.jit(lambda st, i: raft.step(cfg, st, i))(s, inp)
    assert int(s2.cfg_pend) == 0  # the joint phase DID exit this tick
    assert int(s2.read_idx[0]) == 1  # ...but the read stayed pending
    inp_np = {f: np.asarray(v) for f, v in zip(inp._fields, inp)}
    got = oracle.oracle_step(cfg, oracle.state_to_dict(s), inp_np)
    assert int(got["read_idx"][0]) == 1  # oracle agrees (tick-start masks)
    assert np.array_equal(np.asarray(got["read_idx"]), np.asarray(s2.read_idx))


@pytest.mark.slow  # budget re-tier (PR 12): the read_cmd override is
# exercised every tier-1 run through its production consumers -- Session.
# offer_read (test_lease) and the tenancy serve fixture's read planes
# (test_tenancy) -- so this direct-unit form, which pays its own windowed
# compile, rides the slow tier.
def test_tick_batch_minor_read_cmd_override():
    """External read ingest on the serve tick body (docs/SERVE.md): the
    per-tick read_cmd override drives captures exactly like the scheduled
    cadence -- a fleet fed reads via the override serves them; NIL feeds
    none. Uses a huge scheduled cadence so every served read is
    override-attributable."""
    from raft_sim_tpu.models import raft_batched
    from raft_sim_tpu.types import init_batch

    cfg = RaftConfig(n_nodes=5, log_capacity=32, client_interval=4,
                     read_interval=100_000)
    root = jax.random.key(4)
    k_init, k_run = jax.random.split(root)
    B = 4
    keys = jax.random.split(k_run, B)

    def drive(ticks, read_every):
        s = raft_batched.to_batch_minor(init_batch(cfg, k_init, B))
        m = raft_batched.to_batch_minor(scan.init_metrics_batch(B))
        for t in range(ticks):
            rc = 1 if (read_every and t % read_every == 0) else NIL
            s, m, _ = scan.tick_batch_minor(cfg, s, keys, m, read_cmd=rc)
        return int(np.sum(np.asarray(m.reads_served)))

    assert drive(60, read_every=3) > 0
    assert drive(30, read_every=0) == 0


# ------------------------------------------------- mutants vs real kernel


def test_blind_transfer_mutant_violates_real_kernel_clean():
    """The transfer-as-a-coup mutant truncates committed entries off
    followers (device commit-checksum violations); the REAL kernel under the
    identical program stays clean -- the CE hunt's target signal."""
    base = RaftConfig(n_nodes=5, log_capacity=16, client_interval=2,
                      drop_prob=0.25, transfer_interval=9)
    _, m_real = scan.simulate(base, 0, 16, 400)
    _, m_mut = scan.simulate(mutant_config("blind-transfer", base), 0, 16, 400)
    assert int(np.sum(np.asarray(m_real.violations))) == 0
    assert int(np.sum(np.asarray(m_mut.violations))) > 0


@pytest.mark.slow
def test_joint_bypass_mutant_violates_real_kernel_clean():
    """The one-step membership-change mutant: consecutive toggles under
    partitions + drop produce non-intersecting quorums -> device violations.
    Needs a longer horizon and a wider fleet than the coup mutant (the race
    window is narrow), so it rides the slow tier; the trace-checker test
    below pins the property-level rejection in tier 1."""
    base = RaftConfig(n_nodes=5, log_capacity=16, client_interval=2,
                      drop_prob=0.3, partition_period=16, partition_prob=0.6,
                      reconfig_interval=7)
    _, m_real = scan.simulate(base, 0, 64, 800)
    _, m_mut = scan.simulate(mutant_config("joint-bypass", base), 0, 64, 800)
    assert int(np.sum(np.asarray(m_real.violations))) == 0
    assert int(np.sum(np.asarray(m_mut.violations))) > 0


# ------------------------------------------- trace checker, new properties


CFG_TRACE = RaftConfig(
    n_nodes=5, client_interval=4, reconfig_interval=17, transfer_interval=23,
    read_interval=5, drop_prob=0.25, partition_period=16, partition_prob=0.5,
    crash_prob=0.2, crash_period=32, crash_down_ticks=8, track_trace=True,
)
SPEC = TraceSpec(depth=512)


@functools.lru_cache(maxsize=1)
def _real_report():
    out = telemetry.simulate_windowed(CFG_TRACE, 5, 12, 448, 64, 0, None, 1, SPEC)
    return tchecker.check_history(thistory.from_device(out[4]))


@pytest.mark.slow  # budget re-tier (PR 12): real-kernel-passes-the-checker
# is now pinned three times per tier-1 run by the corpus checker tests
# (test_corpus.py real-kernel replays, incl. a transfer-carrying config),
# and CI's reconfig smoke runs this exact add/remove-under-fire leg through
# the driver -- the in-suite variant joins the slow tier.
def test_real_kernel_passes_all_properties_under_add_remove_under_fire():
    """The acceptance run: membership toggles + transfers + reads under
    drop/partition/crash churn; the whole-history checker passes every
    property -- including the two new ones -- on a COMPLETE history."""
    rep = _real_report()
    assert rep.complete, rep.problems
    assert rep.ok, {k: r.note for k, r in rep.results.items() if not r.ok}
    assert set(rep.results) == set(tchecker.PROPERTIES)
    assert "read_linearizability" in rep.results


def test_stale_read_mutant_rejected_with_witness():
    """The stale-read mutant serves unconfirmed reads; a deposed leader in a
    minority partition then serves below the committed frontier, and the
    checker names read_linearizability with the (issue, serve) witness."""
    cfg = dataclasses.replace(
        CFG_TRACE, reconfig_interval=0, transfer_interval=0,
        read_interval=2, crash_prob=0.0,
    )
    out = telemetry.simulate_windowed(
        mutant_config("stale-read", cfg), 3, 8, 256, 32, 0, None, 1, SPEC
    )
    rep = tchecker.check_history(thistory.from_device(out[4]))
    assert "read_linearizability" in rep.violated
    w = rep.results["read_linearizability"].witness
    assert [e["kind"] for e in w] == ["read_issue", "read_serve"]
    assert "below the committed frontier" in rep.results["read_linearizability"].note


def _hist(events_by_cluster):
    ev = {c: [thistory.Event(*e) for e in evs]
          for c, evs in events_by_cluster.items()}
    return thistory.History(
        events=ev,
        emitted={c: len(v) for c, v in ev.items()},
        dropped={c: 0 for c in ev},
        n_windows=1,
        problems=[],
    )


def test_checker_epoch_scoped_election_safety():
    L, E = tev.EV_LEADER, tev.EV_EPOCH
    D = tchecker.EPOCH_EXEMPT_DISTANCE
    # Two leaders for one term WITHIN an epoch: violation.
    rep = tchecker.check_history(_hist({0: [(5, 0, L, 3), (9, 2, L, 3)]}))
    assert rep.violated == ["election_safety"]
    assert "epoch" in rep.results["election_safety"].note
    # One full toggle apart (2 epoch bumps): single-config majorities one
    # toggle apart ALWAYS intersect, so same-term double leadership is still
    # a double-voted node -- violation, not exempt (review regression: the
    # naive per-epoch keying passed this).
    rep = tchecker.check_history(_hist({0: [
        (5, 0, L, 3), (10, NIL, E, 1), (11, NIL, E, 2), (20, 2, L, 3),
    ]}))
    assert rep.violated == ["election_safety"]
    # Two full joint cycles apart (>= EPOCH_EXEMPT_DISTANCE bumps): the
    # electorates can be disjoint under the admin model -- exempt.
    far = [(5, 0, L, 3)] + [
        (10 + i, NIL, E, i + 1) for i in range(D)
    ] + [(30, 2, L, 3)]
    rep = tchecker.check_history(_hist({0: far}))
    assert rep.ok
    # ...and within the new era the scope applies afresh.
    rep = tchecker.check_history(_hist({0: [
        (5, 0, L, 3), (10, NIL, E, 1), (20, 2, L, 4), (25, 3, L, 4),
    ]}))
    assert rep.violated == ["election_safety"]


def test_checker_read_linearizability_negatives():
    C, RI, RS = tev.EV_COMMIT, tev.EV_READ_ISSUE, tev.EV_READ_SERVE
    # A read issued at index 3 while the frontier sits at 5: serving it is
    # the violation (it misses committed writes).
    rep = tchecker.check_history(_hist({0: [
        (4, 0, C, 5), (8, 1, RI, 3), (10, 1, RS, 3),
    ]}))
    assert rep.violated == ["read_linearizability"]
    # A read at the frontier is linearizable.
    rep = tchecker.check_history(_hist({0: [
        (4, 0, C, 5), (8, 0, RI, 5), (10, 0, RS, 5),
    ]}))
    assert rep.ok
    # An issued-but-never-served stale read is NOT a violation (the real
    # kernel's confirmation round kills exactly these).
    rep = tchecker.check_history(_hist({0: [
        (4, 0, C, 5), (8, 1, RI, 3),
    ]}))
    assert rep.ok


# ------------------------------------------------------- checkpoint v22


def test_checkpoint_v22_round_trips_reconfig_state(tmp_path):
    """The new planes ride the checkpoint: a mid-run config8-family fleet
    saves and loads bit-identically (membership masks, epochs, transfer and
    read slots included)."""
    from raft_sim_tpu.types import init_batch

    cfg, _ = PRESETS["config8"]
    root = jax.random.key(9)
    k_init, k_run = jax.random.split(root)
    state = init_batch(cfg, k_init, 2)
    keys = jax.random.split(k_run, 2)
    state, metrics = scan.run_batch_minor(cfg, state, keys, 120)
    assert int(np.max(np.asarray(state.cfg_epoch))) > 0  # churn happened
    path = checkpoint.save(str(tmp_path / "ck"), cfg, state, keys, metrics, seed=9)
    cfg2, state2, keys2, metrics2, seed2, scenario = checkpoint.load(path)
    assert cfg2 == cfg and seed2 == 9 and scenario is None
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(state2)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(metrics), jax.tree.leaves(metrics2)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
