"""The device-side trace ring + transition-coverage bitmap (scan-carry legs).

Generalizes `sim/telemetry.py`'s violation-frozen flight recorder into an
always-recordable, trigger-armable event stream: where the flight recorder
keeps the last K ticks of StepInfo and freezes at the first violation, the
trace ring keeps up to `depth` discrete EVENTS (trace/events.py) per cluster
per telemetry window, exports them every window (so the full history streams
out at bounded device cost), and can optionally stop recording after the
first occurrence of a chosen event kind (`freeze_kind` -- the economy knob
for "capture through the first X, then stop").

Overflow clamps rather than wraps: a window emits its FIRST `depth` events in
order and counts the rest as dropped (`TraceWin.n` is the emitted total, so
dropped = n - min(n, depth)). Clamping keeps every exported window a strict
history PREFIX -- the checker can flag the gap precisely instead of reasoning
about a wrapped tail -- and the sizing is priced by the cost model like every
other carry leg (docs/OBSERVABILITY.md "Protocol traces").

The coverage plane is a packed bitmap (ops/bitplane words) over two blocks:

  role x kind    bit r * N_KINDS + k: an event of kind k was emitted by a
                 node in role r (ROLE_CLUSTER for cluster-scope events).
  kind -> kind   bit BASE + p * N_KINDS + k: an event of kind k directly
                 followed one of kind p in this cluster's stream (within-tick
                 order = slot order; the previous window's last kind seeds
                 the first adjacency of a window, so coverage is exact across
                 window cuts).

It is OR-folded in the telemetry window carry and exported cumulatively per
window -- the novelty signal `scenario/search.py --fitness=coverage` hunts
with (ROADMAP item 5's seed).

Everything here is batch-minor ([..., B] trailing) and integer-only: the
extraction feeding it reads state deltas, so recording can never perturb the
trajectory it observes (pinned in tests/test_trace.py).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_sim_tpu.ops import bitplane
from raft_sim_tpu.trace import events as tev
from raft_sim_tpu.utils.config import RaftConfig

# Coverage bit layout (module docstring): role-x-kind block, then adjacency.
ROLE_KIND_BITS = tev.ROLE_DIM * tev.N_KINDS
ADJ_BASE = ROLE_KIND_BITS
COV_BITS = ROLE_KIND_BITS + tev.N_KINDS * tev.N_KINDS
COV_WORDS = bitplane.n_words(COV_BITS)


@dataclasses.dataclass(frozen=True)
class TraceSpec:
    """Static trace-plane parameters (hashable -> a static jit argument).

    depth        events retained per cluster per telemetry window; overflow
                 is counted, never silently lost. Size so that a window's
                 expected event volume fits (docs/OBSERVABILITY.md prices it:
                 4 int32 planes of `depth` words per cluster in the carry).
    coverage     fold the transition-coverage bitmap (COV_WORDS uint32 per
                 cluster in the carry).
    freeze_kind  EV_NONE (0) records forever; an EV_* kind stops a cluster's
                 recording after the tick that first emits that kind
                 (inclusive) -- the trace-side analogue of the flight
                 recorder's trigger (sim/telemetry.py `trigger_kind`).
    """

    depth: int = 128
    coverage: bool = True
    freeze_kind: int = 0

    def __post_init__(self):
        assert self.depth >= 1
        assert 0 <= self.freeze_kind < tev.N_KINDS


class TraceWin(NamedTuple):
    """One window's event buffer for every cluster (batch-minor carry leg;
    reset each window and emitted as the window's trace export). Slot i of
    ev_* holds the window's i-th event; EV_NONE kind = empty slot."""

    ev_tick: jax.Array  # [R, B] int32 absolute tick
    ev_node: jax.Array  # [R, B] int32 node id (NIL = cluster-scope)
    ev_kind: jax.Array  # [R, B] int32 (EV_*; EV_NONE = empty)
    ev_detail: jax.Array  # [R, B] int32
    n: jax.Array  # [B] int32 events EMITTED this window (may exceed R)


class TracePersist(NamedTuple):
    """Trace state carried ACROSS windows (batch-minor)."""

    frozen: jax.Array  # [B] bool: freeze_kind latched (recording stopped)
    last_kind: jax.Array  # [B] int32: the stream's previous event kind
    cov: jax.Array  # [COV_WORDS, B] uint32 cumulative coverage bitmap
    total: jax.Array  # [B] int32 events emitted over the whole run


class TraceWindowOut(NamedTuple):
    """Per-window trace export: the window's event buffer plus the cumulative
    coverage snapshot at window end (monotone across windows)."""

    win: TraceWin
    cov: jax.Array  # [COV_WORDS, B] uint32


def init_window(spec: TraceSpec, batch: int) -> TraceWin:
    r = spec.depth
    z = lambda *s: jnp.zeros((*s, batch), jnp.int32)
    return TraceWin(
        ev_tick=z(r), ev_node=z(r), ev_kind=z(r), ev_detail=z(r), n=z()
    )


def init_persist(spec: TraceSpec, batch: int) -> TracePersist:
    return TracePersist(
        frozen=jnp.zeros((batch,), bool),
        last_kind=jnp.zeros((batch,), jnp.int32),
        cov=jnp.zeros((COV_WORDS, batch), jnp.uint32),
        total=jnp.zeros((batch,), jnp.int32),
    )


def _coverage(spec, tp, write, ev, kv, prev_kind):
    """OR this tick's (role x kind) and (prev-kind -> kind) bits into the
    packed coverage words. `write` [M, B] gates; kv is the static [M] slot
    kind table; prev_kind [M, B] the adjacency predecessor per slot."""
    b = write.shape[1]
    # role x kind block: one-hot the role axis, any-reduce each static kind
    # block -> [ROLE_DIM, N_KINDS, B] occurrence matrix.
    r_oh = (
        jnp.arange(tev.ROLE_DIM, dtype=jnp.int32)[:, None, None] == ev.role[None]
    ) & write[None]
    rk = []
    pk_oh = (
        jnp.arange(tev.N_KINDS, dtype=jnp.int32)[:, None, None] == prev_kind[None]
    ) & write[None]
    adj = []
    for k in range(tev.N_KINDS):
        idx = np.flatnonzero(kv == k)
        if idx.size == 0:
            rk.append(jnp.zeros((tev.ROLE_DIM, b), bool))
            adj.append(jnp.zeros((tev.N_KINDS, b), bool))
        else:
            rk.append(jnp.any(r_oh[:, idx], axis=1))
            adj.append(jnp.any(pk_oh[:, idx], axis=1))
    # [N_KINDS, ROLE_DIM, B] -> bit r * N_KINDS + k wants role-major flatten.
    rk_m = jnp.stack(rk)  # [K, ROLE_DIM, B]
    rk_flat = jnp.moveaxis(rk_m, 0, 1).reshape(ROLE_KIND_BITS, b)
    adj_m = jnp.stack(adj)  # [K(next), K(prev), B] -> prev-major flatten
    adj_flat = jnp.moveaxis(adj_m, 0, 1).reshape(tev.N_KINDS * tev.N_KINDS, b)
    # pack pads the last word's tail bits to zero itself (canonical words).
    bits = jnp.concatenate([rk_flat, adj_flat], axis=0)
    return tp.cov | bitplane.pack(bits, axis=0)


def record(
    cfg: RaftConfig,
    spec: TraceSpec,
    tw: TraceWin,
    tp: TracePersist,
    ev: tev.TickEvents,
    now: jax.Array,
) -> tuple[TraceWin, TracePersist]:
    """Fold one tick's extracted events into the window buffer + persist
    legs. `now` is the [B] pre-tick absolute tick (lockstep). Compaction of
    the sparse candidate slots into dense buffer positions is an exclusive
    cumsum + one scatter per plane; events past `depth` are counted (n) but
    not stored (module docstring: clamp, not wrap)."""
    m = ev.flags.shape[0]
    batch = ev.flags.shape[1]
    kv = tev.slot_kinds(cfg.n_nodes)  # static [M]
    nv = tev.slot_nodes(cfg.n_nodes)
    write = ev.flags & ~tp.frozen[None, :]  # [M, B]
    wi = write.astype(jnp.int32)
    cum = jnp.cumsum(wi, axis=0)
    emitted = cum[-1]  # [B]
    pos = tw.n[None, :] + cum - wi  # exclusive cumsum offset
    ok = write & (pos < spec.depth)
    slot = jnp.where(ok, pos, spec.depth)  # out-of-range rows drop
    biota = jnp.broadcast_to(jnp.arange(batch, dtype=jnp.int32)[None], (m, batch))
    kv_b = jnp.broadcast_to(jnp.asarray(kv)[:, None], (m, batch))
    nv_b = jnp.broadcast_to(jnp.asarray(nv)[:, None], (m, batch))
    now_b = jnp.broadcast_to(now[None], (m, batch))
    put = lambda plane, val: plane.at[slot, biota].set(val, mode="drop")
    tw2 = TraceWin(
        ev_tick=put(tw.ev_tick, now_b),
        ev_node=put(tw.ev_node, nv_b),
        ev_kind=put(tw.ev_kind, kv_b),
        ev_detail=put(tw.ev_detail, ev.detail),
        n=tw.n + emitted,
    )
    # Adjacency predecessor per slot: the kind of the latest valid slot
    # strictly before it this tick, else the carried stream tail.
    midx = jnp.where(write, jnp.arange(m, dtype=jnp.int32)[:, None], -1)
    incl = lax.cummax(midx, axis=0)  # [M, B]
    prev_idx = jnp.concatenate(
        [jnp.full((1, batch), -1, jnp.int32), incl[:-1]], axis=0
    )
    kv_arr = jnp.asarray(kv)
    prev_kind = jnp.where(
        prev_idx >= 0,
        kv_arr[jnp.clip(prev_idx, 0, m - 1)],
        tp.last_kind[None, :],
    )
    cov = _coverage(spec, tp, write, ev, kv, prev_kind) if spec.coverage else tp.cov
    last_idx = incl[-1]  # [B]
    last_kind = jnp.where(
        last_idx >= 0, kv_arr[jnp.clip(last_idx, 0, m - 1)], tp.last_kind
    )
    frozen = tp.frozen
    if spec.freeze_kind:
        hit_idx = np.flatnonzero(kv == spec.freeze_kind)
        frozen = frozen | jnp.any(write[hit_idx], axis=0)
    tp2 = TracePersist(
        frozen=frozen, last_kind=last_kind, cov=cov, total=tp.total + emitted
    )
    return tw2, tp2


def cov_popcount(cov) -> jax.Array:
    """Set bits per cluster of a [COV_WORDS, B] coverage plane -> [B] int32
    (or any leading layout: reduces the word axis 0)."""
    return jnp.sum(lax.population_count(jnp.asarray(cov)).astype(jnp.int32), axis=0)
