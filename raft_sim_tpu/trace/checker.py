"""Whole-history checker: the five Raft safety properties over a complete run.

The per-tick `viol_*` flags (models/raft.py phase 9) check each property's
INSTANTANEOUS form -- two leaders this tick, a mutated prefix this tick. The
Raft paper states them as HISTORY claims (fig. 3), and some violations only
exist as history: two leaders elected for one term three windows apart never
coexist on any tick. This module replays a reconstructed History
(trace/history.py) through a per-cluster state machine and verifies:

  election_safety        at most one leader ELECTED per term across the whole
                         run (pure history: the EV_LEADER events; witness =
                         the two conflicting leader events).
  leader_append_only     a node never truncates its log while it holds
                         leadership (pure history: EV_TRUNCATE between a
                         node's EV_LEADER and its role loss).
  leader_completeness    the cluster's committed frontier (max commit index
                         ever witnessed) is never re-committed-below by a
                         LEADER: a correct leader's commit advance only lands
                         on current-term entries, which sit strictly above
                         everything committed before its election -- a
                         leader commit below the frontier means its log was
                         missing committed entries. Followers legally trail
                         the frontier; only leader-attributed commits count.
  state_machine_safety   per-node commit indices are monotone except across a
                         restart (commit legally resumes from the durable
                         snapshot base), plus the device-side committed-
                         prefix-immutability flag (EV_VIOLATION commit bit --
                         index monotonicity alone cannot see a same-index
                         CONTENT change; the kernel's carried checksum can).
  log_matching           device-backed: the kernel's O(N^2 CAP) cross-node
                         prefix comparison runs on device (EV_VIOLATION
                         log-matching bit); the history carries its verdicts.
                         Content never leaves the device, so this property is
                         honest about being flag-backed, not re-derived.

  The within-tick event order events.py defines is load-bearing here: role
  transitions precede commit/append/truncate kinds, so "stepped down then
  truncated in one tick" replays in kernel phase order.

A history with holes (ring overflow, truncated or reordered trace.jsonl)
can still FAIL -- a witnessed violation is a violation -- but can never PASS:
undecided properties report ok=None with an incomplete-history note
(tests/test_trace.py pins both directions).

CLI: `python -m raft_sim_tpu.trace.checker <telemetry dir> [--json]`
exit 0 = all five hold, 1 = a named property is violated (witness printed),
2 = incomplete history and no violation found.
"""

from __future__ import annotations

import dataclasses
import json

from raft_sim_tpu.trace import events as tev
from raft_sim_tpu.trace.history import Event, History

PROPERTIES = (
    "election_safety",
    "leader_append_only",
    "log_matching",
    "leader_completeness",
    "state_machine_safety",
    "read_linearizability",
)


@dataclasses.dataclass
class PropertyResult:
    name: str
    ok: bool | None  # None = undecidable (incomplete history, no witness)
    witness: list[dict]  # minimal witnessing events (empty when ok)
    note: str = ""

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class CheckReport:
    results: dict[str, PropertyResult]
    complete: bool
    problems: list[str]
    clusters: int

    @property
    def ok(self) -> bool:
        return all(r.ok for r in self.results.values())

    @property
    def violated(self) -> list[str]:
        return [n for n, r in self.results.items() if r.ok is False]

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "complete": self.complete,
            "violated": self.violated,
            "problems": self.problems,
            "clusters": self.clusters,
            "properties": {n: r.to_dict() for n, r in self.results.items()},
        }


def _check_cluster(c: int, evs: list[Event], fail) -> None:
    """Replay one cluster's timeline; report violations via fail(prop,
    witness_events, note)."""
    # Election safety is UNCONDITIONAL per term under log-carried
    # configuration (models/cfglog.py; thesis 4.3): every vote is cast under
    # the voter's own log-derived configuration, every configuration is a
    # chain of log entries from the boot config, and joint consensus makes
    # adjacent configurations' majorities intersect -- so two same-term
    # leaders ALWAYS imply a double-voted node or a broken config chain
    # (exactly what the act-on-commit / single-server-change mutants break).
    # The admin-era EPOCH_EXEMPT_DISTANCE carve-out is GONE: it existed
    # because lockstep admin switches were not log entries, so distant
    # electorates could legally be disjoint; per-node log-carried configs
    # cannot. A second, per-voter check keys on (voter, term): granting two
    # DIFFERENT candidates in one term is named directly -- under log-carried
    # configs no config state can excuse it, so the config is deliberately
    # NOT part of the key -- while an idempotent re-grant (same candidate,
    # e.g. after a restart) stays legal. Each node's cfg_epoch is replayed
    # from the EV_CFG_APPLY/EV_CFG_ROLLBACK stream and recorded with every
    # vote for ATTRIBUTION only: the failure note names the config era each
    # grant was cast under (what makes act-on-commit witnesses readable).
    leaders_by_term: dict[int, list[Event]] = {}  # term -> [ev]
    leader_set: dict[int, Event] = {}  # node -> its EV_LEADER event
    node_term: dict[int, int] = {}  # node -> current term (role/term events)
    node_cfg_epoch: dict[int, int] = {}  # node -> derived config epoch
    votes_cast: dict[tuple[int, int], tuple[int, int, Event]] = {}
    # (voter, term) -> (candidate, cfg_epoch at vote time, ev)
    frontier = 0
    frontier_ev: Event | None = None
    last_commit: dict[int, tuple[int, Event]] = {}
    restarted_since: dict[int, bool] = {}
    # ReadIndex linearizability: a read captured at issue time must cover the
    # committed frontier AS OF ISSUE (every write committed anywhere before
    # the read began) -- checked when the read is SERVED, because a stale
    # leader legally captures a stale index it can never confirm (the real
    # kernel's quorum round kills it; only a served stale read violates).
    pending_reads: dict[int, tuple[int, int, Event]] = {}  # node -> (idx, frontier, ev)
    # Vote-durability model (raft_sim_tpu/storage). Under the durable
    # storage plane a cast vote is EXPOSED only once a flush covers it
    # (section-3.8 gate 2), and crash recovery rewinds votedFor to the
    # durable snapshot -- so a vote cast after the node's last flush is
    # legally un-promised by a restart, and counting it against a
    # post-recovery re-vote would fail the REAL kernel. Votes therefore sit
    # in `pending_votes` until the node's next EV_FSYNC makes them durable
    # (clears the pending set; the votes stay cast), and an EV_RESTART
    # un-casts whatever is still pending. The model activates only when the
    # history shows the plane (any storage event): perfect-disk histories
    # keep the strict rule. Known limit: a durability history whose every
    # flush stalled shows no storage event, so a never-flushed vote stays
    # cast -- but such a run exposes no votes and elects no leaders either.
    durable = any(e.kind in (tev.EV_FSYNC, tev.EV_RECOVER_TRUNC) for e in evs)
    pending_votes: dict[int, list[tuple[int, int]]] = {}  # node -> [(term, cand)]
    for e in evs:
        k = e.kind
        if k in (tev.EV_FOLLOWER, tev.EV_PRECANDIDATE, tev.EV_CANDIDATE):
            leader_set.pop(e.node, None)
            node_term[e.node] = e.detail  # role kinds carry the new term
        elif k == tev.EV_TERM:
            node_term[e.node] = e.detail
        elif k in (tev.EV_CFG_APPLY, tev.EV_CFG_ROLLBACK):
            node_cfg_epoch[e.node] = e.detail  # detail = the new cfg_epoch
        elif k == tev.EV_VOTE:
            # Double-vote detection, keyed on the voter's (term, config) at
            # vote time: granting two DIFFERENT candidates in one term is a
            # genuine election-safety break no configuration can excuse;
            # re-granting the SAME candidate (restart re-grant) is legal.
            t = node_term.get(e.node, 0)
            ce = node_cfg_epoch.get(e.node, 0)
            prev_v = votes_cast.get((e.node, t))
            if prev_v is not None and prev_v[0] != e.detail:
                fail(
                    "election_safety", [prev_v[2], e],
                    f"cluster {c}: node {e.node} voted for both node "
                    f"{prev_v[0]} (config epoch {prev_v[1]}) and node "
                    f"{e.detail} (config epoch {ce}) in term {t}",
                )
            votes_cast[(e.node, t)] = (e.detail, ce, e)
            if durable:
                pending_votes.setdefault(e.node, []).append((t, e.detail))
        elif k == tev.EV_FSYNC:
            # The flush covers the node's live (term, votedFor): every
            # pending vote is durable now -- it survives restarts and stays
            # in votes_cast permanently.
            pending_votes.pop(e.node, None)
        elif k == tev.EV_READ_ISSUE:
            pending_reads[e.node] = (e.detail, frontier, e)
        elif k == tev.EV_READ_SERVE:
            pend = pending_reads.pop(e.node, None)
            if pend is not None and e.detail < pend[1]:
                fail(
                    "read_linearizability", [pend[2], e],
                    f"cluster {c}: node {e.node} served a ReadIndex read at "
                    f"index {e.detail} (issued tick {pend[2].tick}) below the "
                    f"committed frontier {pend[1]} at issue time: the read "
                    "misses committed writes",
                )
        elif k == tev.EV_LEADER:
            term = e.detail
            node_term[e.node] = term
            prior = next(iter(leaders_by_term.get(term, [])), None)
            if prior is not None:
                fail(
                    "election_safety", [prior, e],
                    f"cluster {c}: two leaders elected for term {term} "
                    f"(node {prior.node} at tick {prior.tick}, node "
                    f"{e.node} at tick {e.tick}) -- under log-carried "
                    "configuration every electorate chains from the boot "
                    "config through joint phases, so same-term majorities "
                    "always intersect: a double-voted node or a broken "
                    "config chain (act-on-commit / single-server-change)",
                )
            leaders_by_term.setdefault(term, []).append(e)
            leader_set[e.node] = e
        elif k == tev.EV_TRUNCATE:
            led = leader_set.get(e.node)
            if led is not None:
                fail(
                    "leader_append_only", [led, e],
                    f"cluster {c}: node {e.node} truncated its log to "
                    f"{e.detail} at tick {e.tick} while leader (elected tick "
                    f"{led.tick}, term {led.detail})",
                )
        elif k == tev.EV_COMMIT:
            if e.node in leader_set and e.detail < frontier:
                fw = [frontier_ev, e] if frontier_ev else [e]
                fail(
                    "leader_completeness", fw,
                    f"cluster {c}: leader node {e.node} committed index "
                    f"{e.detail} at tick {e.tick} below the committed "
                    f"frontier {frontier}: its log was missing committed "
                    "entries at election",
                )
            prev = last_commit.get(e.node)
            if (
                prev is not None
                and e.detail < prev[0]
                and not restarted_since.get(e.node, False)
            ):
                fail(
                    "state_machine_safety", [prev[1], e],
                    f"cluster {c}: node {e.node} commit index regressed "
                    f"{prev[0]} -> {e.detail} without an intervening restart",
                )
            last_commit[e.node] = (e.detail, e)
            restarted_since[e.node] = False
            if e.detail > frontier:
                frontier, frontier_ev = e.detail, e
        elif k == tev.EV_RESTART:
            restarted_since[e.node] = True
            leader_set.pop(e.node, None)  # restart wipes role (defensive:
            # the same-tick EV_FOLLOWER, ordered first, already removed it)
            if e.detail > 0:
                # detail = the post-tick term: recovery can REWIND the term
                # (a decrease the EV_TERM increase-delta never reports), so
                # re-anchor the model here. Pre-storage-plane histories
                # carry detail 0 -- skip, the old model had no rewinds.
                node_term[e.node] = e.detail
            for t, cand in pending_votes.pop(e.node, []):
                # Un-cast never-flushed votes: recovery rewound votedFor to
                # the durable snapshot, and gate 2 means the grant was never
                # exposed -- the protocol never saw it, so a post-recovery
                # re-vote in the same term is NOT a double vote.
                cur = votes_cast.get((e.node, t))
                if cur is not None and cur[0] == cand:
                    votes_cast.pop((e.node, t))
        elif k == tev.EV_VIOLATION:
            if e.detail & tev.VIOL_LOG_MATCHING:
                fail(
                    "log_matching", [e],
                    f"cluster {c}: device log-matching check failed at tick "
                    f"{e.tick} (cross-node committed prefixes disagree)",
                )
            if e.detail & tev.VIOL_COMMIT:
                fail(
                    "state_machine_safety", [e],
                    f"cluster {c}: device commit invariant failed at tick "
                    f"{e.tick} (committed prefix mutated or commit left "
                    "bounds -- the carried checksum check)",
                )
            if e.detail & tev.VIOL_ELECTION:
                # Per-tick concurrent same-term leaders: normally the two
                # EV_LEADER events already witnessed this; keep the flag as
                # the fallback witness (e.g. when one election predates a
                # partial history's first window).
                fail(
                    "election_safety", [e],
                    f"cluster {c}: device election-safety flag at tick "
                    f"{e.tick} (two same-term leaders coexist)",
                )


def check_history(hist: History) -> CheckReport:
    """Run all five property checks over every cluster's timeline."""
    results = {p: PropertyResult(p, True, []) for p in PROPERTIES}

    def fail(prop: str, witness: list[Event], note: str, cluster: int = -1):
        r = results[prop]
        if r.ok is False:
            return  # first witness per property is the minimal report
        r.ok = False
        r.witness = [w.to_dict(cluster if cluster >= 0 else None) for w in witness]
        r.note = note

    for c in sorted(hist.events):
        _check_cluster(
            c, hist.events[c],
            lambda prop, w, note, _c=c: fail(prop, w, note, _c),
        )
    if not hist.complete:
        gaps = hist.incomplete_clusters()
        parts = []
        if gaps:
            parts.append(f"events dropped in clusters {gaps[:8]}")
        if hist.freeze_armed:
            parts.append(
                "recording freeze-truncated by design (freeze_kind armed: "
                "a capture-economy prefix, not a whole-run history)"
            )
        parts.extend(hist.problems[:4])
        note = "incomplete history: " + "; ".join(parts)
        for r in results.values():
            if r.ok is True:  # a found violation stands; a pass demotes
                r.ok = None
                r.note = note
    return CheckReport(
        results=results,
        complete=hist.complete,
        problems=list(hist.problems),
        clusters=len(hist.events),
    )


def check_directory(directory: str) -> CheckReport:
    from raft_sim_tpu.trace import history as hmod

    return check_history(hmod.load(directory))


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="raft_sim_tpu.trace.checker", description=__doc__.splitlines()[0]
    )
    ap.add_argument("directory", help="telemetry sink dir with trace.jsonl")
    ap.add_argument("--json", action="store_true", help="machine-readable report")
    args = ap.parse_args(argv)
    rep = check_directory(args.directory)
    if args.json:
        print(json.dumps(rep.to_dict(), indent=1))
    else:
        for name in PROPERTIES:
            r = rep.results[name]
            verdict = {True: "ok", False: "VIOLATED", None: "undecided"}[r.ok]
            line = f"{name:<22} {verdict}"
            if r.note:
                line += f"  ({r.note})"
            print(line)
            for w in r.witness:
                print(f"    witness: {w}")
        if not rep.complete:
            print(f"history INCOMPLETE: {'; '.join(rep.problems[:6]) or 'events dropped'}")
    if rep.violated:
        return 1
    if not rep.complete or not rep.ok:
        return 2
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
