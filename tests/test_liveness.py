"""Replication-liveness regression tests for the shared-entry-window outbox.

The AppendEntries entry payload is one shared E-entry window per sender
(types.Mailbox). If the window start were the minimum prev over ALL peers, a
permanently dead peer (next_index pinned at its initial value, never acking)
would pin the window start forever, and no live follower could ever receive
entries past window_start + E: commit would stall despite a live quorum -- a
liveness loss the reference cannot have, since it ships unbounded per-peer log
suffixes (core.clj:59-67). The responsiveness filter (config.ack_timeout_ticks,
ClusterState.ack_age) drops never-acking peers out of the window-start min;
these tests pin that behavior end to end.
"""

import jax
import jax.numpy as jnp
import pytest

from raft_sim_tpu import NIL, RaftConfig, StepInputs, init_state
from raft_sim_tpu.models import raft
from raft_sim_tpu.ops import bitplane

CFG = RaftConfig(n_nodes=5, log_capacity=64, max_entries_per_rpc=4, client_interval=1)


def run_ticks(cfg, s, n_ticks, alive, cmd_base=100):
    """Drive raft.step with full delivery, steady clocks, one offered command per
    tick, and a fixed alive mask. Returns the final state."""
    n = cfg.n_nodes
    step = jax.jit(raft.step, static_argnums=0)
    for t in range(n_ticks):
        inp = StepInputs(
            deliver_mask=bitplane.pack(jnp.ones((n, n), bool), axis=1),
            skew=jnp.ones((n,), jnp.int32),
            timeout_draw=jnp.full((n,), 8 + (t % 5), jnp.int32),
            client_cmd=jnp.int32(cmd_base + t),
            client_target=jnp.int32(0),
            client_bounce=jnp.zeros((cfg.client_pipeline,), jnp.int32),
            alive=jnp.asarray(alive, bool),
            restarted=jnp.zeros((n,), bool),
        )
        s, _ = step(cfg, s, inp)
    return s


@pytest.mark.parametrize("dead", [4, 0])
def test_dead_peer_does_not_stall_replication(dead):
    """One node down from tick 0, a command offered every tick: commit must advance
    far past E (= max_entries_per_rpc) on every live node."""
    e = CFG.max_entries_per_rpc
    alive = [i != dead for i in range(CFG.n_nodes)]
    s = run_ticks(CFG, init_state(CFG, jax.random.key(1)), 120, alive)
    live = jnp.asarray(alive)
    live_commit = jnp.where(live, s.commit_index, 10**6)
    # Every live node's commit far exceeds the E-entry window bound that a pinned
    # window start would impose.
    assert int(jnp.min(live_commit)) > 4 * e, (
        f"commit stalled at {s.commit_index} (window pinned by dead peer {dead}?)"
    )
    # The live quorum converged on identical logs.
    lead = int(jnp.argmax(s.commit_index))
    for i in range(CFG.n_nodes):
        if alive[i] and i != lead:
            m = min(int(s.commit_index[i]), int(s.commit_index[lead]))
            assert jnp.array_equal(s.log_val[i, :m], s.log_val[lead, :m])


def test_healed_laggard_catches_up():
    """A node down for the first 60 ticks (while the cluster commits >> E entries)
    must converge to the leader's log after it comes back."""
    n = CFG.n_nodes
    down = [i != 4 for i in range(n)]
    s = run_ticks(CFG, init_state(CFG, jax.random.key(2)), 60, down)
    gap = int(jnp.max(s.commit_index))
    assert gap > 2 * CFG.max_entries_per_rpc  # the laggard is far behind on return
    # Node 4 restarts (volatile wipe; its empty log is its durable state).
    restart = StepInputs(
        deliver_mask=bitplane.pack(jnp.ones((n, n), bool), axis=1),
        skew=jnp.ones((n,), jnp.int32),
        timeout_draw=jnp.full((n,), 9, jnp.int32),
        client_cmd=jnp.int32(NIL),
        client_target=jnp.int32(0),
        client_bounce=jnp.zeros((CFG.client_pipeline,), jnp.int32),
        alive=jnp.ones((n,), bool),
        restarted=jnp.asarray([i == 4 for i in range(n)], bool),
    )
    s, _ = jax.jit(raft.step, static_argnums=0)(CFG, s, restart)
    s = run_ticks(CFG, s, 120, [True] * n, cmd_base=500)
    # The healed node caught all the way up to the cluster commit frontier.
    assert int(s.commit_index[4]) >= gap, (
        f"laggard stuck at {int(s.commit_index[4])} of {gap}"
    )
    lead = int(jnp.argmax(s.commit_index))
    m = min(int(s.commit_index[4]), int(s.commit_index[lead]))
    assert jnp.array_equal(s.log_val[4, :m], s.log_val[lead, :m])
