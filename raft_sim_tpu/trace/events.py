"""Device-side protocol event extraction: state deltas -> a compact event stream.

The reference's whole observability story is a println of node + message per
loop iteration (core.clj:182-186); `sim/trace.py` already diffs HOST-side
state stacks into events, but stacking full states is exactly what a 100k-
cluster fleet cannot afford. This module computes the same discrete events ON
DEVICE, from the (old state, new state, inputs, StepInfo) the tick body
already holds -- reads only, zero trajectory perturbation -- so histories
stream out of the windowed telemetry scan at ring-buffer cost instead of
full-trajectory cost.

Vocabulary (KINDS): one small-int code per event kind, with (tick, node,
kind, detail) fields per emitted event. The WITHIN-TICK ordering is
(kind, node) lexicographic over the static slot table below, and the kind
NUMBERING is load-bearing for the checker: role-transition kinds come before
commit/append/truncate kinds so that a node which loses leadership and
accepts entries in the same tick is processed as "stepped down, then
truncated" -- matching the kernel's phase order (models/raft.py phase 1
adoption precedes phase 3 append) -- and fault kinds come last. The checker
(trace/checker.py) replays events in exactly this order.

Extraction is delta-based on purpose: both kernels (models/raft.py and
models/raft_batched.py) produce the same ClusterState leaves, so ONE
extractor serves both (and any step_fn override, e.g. the weak-quorum test
mutant) without either kernel changing. The leaves read here -- role, term,
voted_for, commit_index, log_len, and (durable storage plane) the
dur_len/dur_term/dur_vote watermarks -- are the delta contract the kernels
document; everything is elementwise over the node axis, so the same code
runs on single-cluster [N] leaves and batch-minor [N, B] leaves.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_sim_tpu.ops import bitplane
from raft_sim_tpu.types import (
    CANDIDATE,
    FOLLOWER,
    LEADER,
    NIL,
    PRECANDIDATE,
    ClusterState,
    StepInfo,
    StepInputs,
)
from raft_sim_tpu.utils.config import RaftConfig

# Event kinds. 0 is reserved for "empty ring slot"; the numbering encodes the
# within-tick processing order (module docstring). detail semantics per kind:
#   role kinds      new term
#   term            new term
#   vote            candidate voted for
#   commit          new commit index        append/truncate  new log length
#   crash/restart   0                       drop             dropped in-edges
#   violation       bitmask: 1 election-safety, 2 commit, 4 log-matching
#   partition       cut-edge count after the change (0 = healed)
EV_NONE = 0
EV_FOLLOWER = 1
EV_PRECANDIDATE = 2
EV_CANDIDATE = 3
EV_LEADER = 4
EV_TERM = 5
EV_VOTE = 6
EV_COMMIT = 7
EV_APPEND = 8
EV_TRUNCATE = 9
EV_CRASH = 10
EV_RESTART = 11
EV_DROP = 12
# Reconfiguration-plane kinds (raft_sim_tpu/reconfig). Read kinds sit ABOVE
# the commit kind on purpose: a read served this tick is checked against
# commits that landed this tick (the kernel serves against the
# post-advancement commit), so the checker must replay commit before serve.
# detail semantics: xfer = target node; read issue/serve = the captured read
# index.
EV_XFER = 13
EV_READ_ISSUE = 14
EV_READ_SERVE = 15
# Log-carried configuration kinds (models/cfglog.py), PER NODE -- the
# admin-era cluster-scope EV_EPOCH is gone with the admin model: configuration
# is per-node derived state now, so its events attribute to the node whose
# log changed. All three replay after the role/commit/truncate kinds,
# matching the kernel's end-of-tick derivation. detail semantics:
#   cfg_append    config-entry slots written to this node's log this tick
#                 (origination or replication)
#   cfg_apply     the node's NEW cfg_epoch after entries entered its derived
#                 config (apply-on-append: same tick as the append on the
#                 real kernel; commit-lagged on the act-on-commit mutant)
#   cfg_rollback  the node's NEW cfg_epoch after a truncation REMOVED config
#                 entries from its prefix (the dissertation's rollback)
EV_CFG_APPEND = 16
EV_CFG_APPLY = 17
EV_CFG_ROLLBACK = 18
# Durable storage plane kinds (raft_sim_tpu/storage), PER NODE. They slot in
# numerically BEFORE the cluster kinds (which shifted 19/20 -> 21/22 at trace
# schema N_KINDS=23): the slot table is kind-major ascending with the cluster
# kinds last, so every per-node kind must number below them. Both replay after
# EV_RESTART -- the checker's vote-durability model needs the restart's
# un-cast to land before the same tick's covering flush clears the pending
# set. detail semantics:
#   fsync          the node's new durable length (dur_len) after the flush;
#                  the flag fires on ANY durable-snapshot advance (dur_len
#                  up, or dur_term/dur_vote changed -- phase 7.5 is the only
#                  writer that moves them that way, so the event IS a
#                  completed flush; the truncation clamp only lowers dur_len
#                  and recovery never touches the snapshot)
#   recover_trunc  the node's recovered log length: a log_len DROP on a
#                  `restarted` node is always the recovery truncation
#                  (restarted nodes receive nothing, so the AE conflict
#                  truncation cannot co-occur); the same delta also fires
#                  the plain EV_TRUNCATE -- this kind marks it as recovery
EV_RECOVER_TRUNC = 19
EV_FSYNC = 20
EV_VIOLATION = 21
EV_PARTITION = 22
N_KINDS = 23

KINDS = {
    "follower": EV_FOLLOWER,
    "precandidate": EV_PRECANDIDATE,
    "candidate": EV_CANDIDATE,
    "leader": EV_LEADER,
    "term": EV_TERM,
    "vote": EV_VOTE,
    "commit": EV_COMMIT,
    "append": EV_APPEND,
    "truncate": EV_TRUNCATE,
    "crash": EV_CRASH,
    "restart": EV_RESTART,
    "drop": EV_DROP,
    "violation": EV_VIOLATION,
    "partition": EV_PARTITION,
    "xfer": EV_XFER,
    "read_issue": EV_READ_ISSUE,
    "read_serve": EV_READ_SERVE,
    "cfg_append": EV_CFG_APPEND,
    "cfg_apply": EV_CFG_APPLY,
    "cfg_rollback": EV_CFG_ROLLBACK,
    "fsync": EV_FSYNC,
    "recover_trunc": EV_RECOVER_TRUNC,
}
KIND_NAMES = {v: k for k, v in KINDS.items()}

# Per-NODE kinds in slot order; the cluster-scope kinds follow them with
# node = NIL. Slot m's (node, kind) pair is a compile-time constant -- only
# the flag and detail are data.
PER_NODE_KINDS = (
    EV_FOLLOWER, EV_PRECANDIDATE, EV_CANDIDATE, EV_LEADER, EV_TERM, EV_VOTE,
    EV_COMMIT, EV_APPEND, EV_TRUNCATE, EV_CRASH, EV_RESTART, EV_DROP,
    EV_XFER, EV_READ_ISSUE, EV_READ_SERVE,
    EV_CFG_APPEND, EV_CFG_APPLY, EV_CFG_ROLLBACK,
    # Storage kinds replay LAST among per-node kinds: recovery precedes the
    # flush in the kernel (phase -1 vs 7.5), and the checker's vote-
    # durability model needs the restart's un-cast (EV_RESTART, above) to
    # land before the same tick's covering flush clears the pending set.
    EV_RECOVER_TRUNC, EV_FSYNC,
)
assert PER_NODE_KINDS == tuple(sorted(PER_NODE_KINDS))  # slot order == kind order
CLUSTER_KINDS = (EV_VIOLATION, EV_PARTITION)

# Violation bitmask bits (EV_VIOLATION detail).
VIOL_ELECTION = 1
VIOL_COMMIT = 2
VIOL_LOG_MATCHING = 4

# Coverage role axis: the four node roles plus a fifth row for cluster-scope
# events (trace/ring.py's role x kind bitmap).
ROLE_DIM = 5
ROLE_CLUSTER = 4
assert {FOLLOWER, CANDIDATE, LEADER, PRECANDIDATE} == {0, 1, 2, 3}


def n_slots(n: int) -> int:
    """Candidate event slots per cluster per tick (static given N)."""
    return n * len(PER_NODE_KINDS) + len(CLUSTER_KINDS)


def slot_nodes(n: int) -> np.ndarray:
    """[M] int32 node id per slot (NIL for cluster-scope slots); static."""
    per_node = np.tile(np.arange(n, dtype=np.int32), len(PER_NODE_KINDS))
    return np.concatenate([per_node, np.full(len(CLUSTER_KINDS), NIL, np.int32)])


def slot_kinds(n: int) -> np.ndarray:
    """[M] int32 event kind per slot; static. Kind-major layout: slot order
    IS the within-tick event order (module docstring)."""
    per_node = np.repeat(np.asarray(PER_NODE_KINDS, np.int32), n)
    return np.concatenate([per_node, np.asarray(CLUSTER_KINDS, np.int32)])


class TickEvents(NamedTuple):
    """One tick's candidate events over the static slot table: `flags[m]` is
    whether slot m's (node, kind) event occurred, `detail[m]` its payload and
    `role[m]` the emitting node's role AFTER the tick (ROLE_CLUSTER for
    cluster-scope slots) -- the coverage bitmap's role axis. Leaves are [M]
    single-cluster or [M, B] batch-minor."""

    flags: jax.Array  # [M(, B)] bool
    detail: jax.Array  # [M(, B)] int32
    role: jax.Array  # [M(, B)] int32 in [0, ROLE_DIM)


def _bc(x, like):
    """Broadcast a per-cluster scalar ([],[B]) to one slot row ([1],[1, B])."""
    return jnp.broadcast_to(jnp.asarray(x), like.shape[1:])[None]


def extract(
    cfg: RaftConfig,
    old: ClusterState,
    new: ClusterState,
    inp: StepInputs,
    info: StepInfo,
    crashed: jax.Array,
    cut_now: jax.Array,
    cut_prev: jax.Array,
) -> TickEvents:
    """Derive this tick's events from the state delta (old -> new), the tick
    inputs, and the kernel's StepInfo. `crashed`/`cut_now`/`cut_prev` are the
    fault-lattice facts StepInputs does not carry (faults.trace_fault_inputs:
    the crash edge and the partition cut-edge counts at now and now - 1,
    recomputed from the same key streams as make_inputs). All-integer and
    elementwise over the node axis: works on [N] and [N, B] leaves alike."""
    n = cfg.n_nodes
    z32 = jnp.zeros_like(new.term)

    def became(role_code):
        return (new.role == role_code) & (old.role != role_code)

    # Incoming-drop count per receiver: popcount of the packed delivery row
    # (diagonal self-bit included in the mask, so delivered <= n). Under the
    # compacted layout the word plane ships flat ([N*W(, B)], ops/tile.py):
    # restore the [N, W(, B)] row view first.
    dm = inp.deliver_mask
    if cfg.compact_planes:
        dm = dm.reshape((n, -1) + dm.shape[1:])
    delivered = bitplane.count(dm, axis=1)  # [N(, B)]
    dropped = jnp.int32(n) - delivered
    burst = dropped >= max(1, (n + 1) // 2)

    # Per-node (flag, detail) blocks, in PER_NODE_KINDS order.
    vote_flag = (new.voted_for != old.voted_for) & (new.voted_for != NIL)
    if cfg.durable_storage:
        # Recovery REWINDS votedFor to the durable snapshot on restart ticks
        # (storage/plane.recover): that state change is not a grant, and a
        # restarted node receives nothing this tick so no genuine grant can
        # co-occur -- suppress, or the checker would read the rewind as a
        # second vote. (Gated: without the plane restart preserves votedFor
        # and the suppression would be dead structure in the program.)
        vote_flag = vote_flag & ~inp.restarted
    blocks = (
        (became(FOLLOWER), new.term),
        (became(PRECANDIDATE), new.term),
        (became(CANDIDATE), new.term),
        (became(LEADER), new.term),
        (new.term > old.term, new.term),
        (vote_flag, new.voted_for),
        (new.commit_index > old.commit_index, new.commit_index),
        (new.log_len > old.log_len, new.log_len),
        (new.log_len < old.log_len, new.log_len),
        (crashed, z32),
        # Restart detail = the node's POST-tick term: recovery can rewind
        # the term (a decrease the EV_TERM increase-delta cannot see), so
        # the checker re-anchors its per-node term model here. Pre-storage-
        # plane histories carry detail 0 (the checker skips those).
        (inp.restarted, new.term),
        (burst, dropped),
    )
    # Reconfiguration-plane kinds, delta-derived like everything else (the
    # serve-vs-cancel disambiguation rides the kernels' documented clear
    # rules: a slot dropped while its holder stays a same-term leader was
    # SERVED; every cancel path -- role loss, term adoption, restart --
    # changes role/term or sets `restarted`). Structurally gated configs
    # leave these planes untouched, so the flags are constant-false there.
    xfer_flag = (new.xfer_to != old.xfer_to) & (new.xfer_to != NIL)
    read_issue = (new.read_idx > 0) & (new.read_idx != old.read_idx)
    read_serve = (
        (old.read_idx > 0)
        & (new.read_idx == 0)
        & (new.role == LEADER)
        & (new.term == old.term)
        & ~inp.restarted
    )
    # Log-carried configuration kinds, per node: append = the log_cfg plane
    # gained entries (delta over the slot planes, statically gated --
    # disabled configs carry the plane untouched and the compare would be
    # [N, CAP]-sized dead work); apply/rollback = the derived cfg_epoch
    # moved (the end-of-tick derivation counts config entries in the
    # prefix, so epoch-up = entries entered the effective config and
    # epoch-down = a truncation removed them). Known append-event limit: a
    # slot-value compare cannot see a config entry re-replicated into a slot
    # still holding the IDENTICAL code from a truncated-away predecessor
    # (truncation shortens log_len without scrubbing slots) -- the coverage
    # bitmap undercounts that one append, but the epoch channel still fires
    # cfg_apply for it, so the checker's config replay is unaffected.
    if cfg.reconfig:
        chg = (new.log_cfg != old.log_cfg) & (new.log_cfg != 0)
        cfg_append = jnp.any(chg, axis=1)
        cfg_append_d = jnp.sum(chg, axis=1).astype(jnp.int32)
        cfg_apply = new.cfg_epoch > old.cfg_epoch
        cfg_rollback = new.cfg_epoch < old.cfg_epoch
    else:
        cfg_append = jnp.zeros(new.term.shape, bool)
        cfg_append_d = z32
        cfg_apply = jnp.zeros(new.term.shape, bool)
        cfg_rollback = jnp.zeros(new.term.shape, bool)
    # Durable storage plane kinds (kind-numbering comment above): flush =
    # any durable-snapshot advance; recovery truncation = log drop on a
    # restarted node. Structurally gated like the config kinds -- without
    # the plane the dur legs are carry passthroughs and the compares would
    # be constant-false dead work.
    if cfg.durable_storage:
        fsync_flag = (
            (new.dur_len > old.dur_len)
            | (new.dur_term != old.dur_term)
            | (new.dur_vote != old.dur_vote)
        )
        rec_trunc = inp.restarted & (new.log_len < old.log_len)
    else:
        fsync_flag = jnp.zeros(new.term.shape, bool)
        rec_trunc = jnp.zeros(new.term.shape, bool)
    blocks = blocks + (
        (xfer_flag, new.xfer_to),
        (read_issue, new.read_idx - 1),
        (read_serve, old.read_idx - 1),
        (cfg_append, cfg_append_d),
        (cfg_apply, new.cfg_epoch),
        (cfg_rollback, new.cfg_epoch),
        (rec_trunc, new.log_len),
        (fsync_flag, new.dur_len),
    )
    viol_mask = (
        info.viol_election_safety * VIOL_ELECTION
        + info.viol_commit * VIOL_COMMIT
        + info.viol_log_matching * VIOL_LOG_MATCHING
    ).astype(jnp.int32)
    like = new.term[:1]  # [1(, B)] template for cluster rows
    cluster = (
        (_bc(viol_mask != 0, like), _bc(viol_mask, like)),
        (_bc(cut_now != cut_prev, like), _bc(cut_now, like)),
    )
    flags = jnp.concatenate([f for f, _ in blocks] + [f for f, _ in cluster])
    detail = jnp.concatenate(
        [jnp.broadcast_to(d, f.shape).astype(jnp.int32) for f, d in blocks]
        + [jnp.broadcast_to(d, f.shape).astype(jnp.int32) for f, d in cluster]
    )
    role_rows = jnp.concatenate(
        [new.role for _ in PER_NODE_KINDS]
        + [_bc(jnp.int32(ROLE_CLUSTER), like) for _ in CLUSTER_KINDS]
    ).astype(jnp.int32)
    return TickEvents(flags=flags, detail=detail, role=role_rows)


def any_of_kind(cfg: RaftConfig, ev: TickEvents, kind: int) -> jax.Array:
    """Per-cluster bool: any event of `kind` fired this tick -- the
    flight-recorder / trace freeze trigger predicate (slot kinds are static,
    so this is a static row-select + any-reduce)."""
    sel = slot_kinds(cfg.n_nodes) == kind  # static [M]
    idx = np.flatnonzero(sel)
    return jnp.any(ev.flags[idx], axis=0)
