"""Static-analysis gate: run the raft_sim_tpu invariant auditor.

Two passes (raft_sim_tpu/analysis): Pass A lowers the real step/scan programs
per config tier and audits the jaxprs (dtype discipline, loop-invariant carry,
recompile forks); Pass B lints the package source (traced branches, float
literals) and cross-checks the types.py dtype comments and the checkpoint
version pin against the live structures. Lowering only -- no XLA compile --
so the whole gate runs in seconds on CPU. CI runs it before the tier-1 tests.

    python tools/check.py --all                  # both passes, text report
    python tools/check.py --all --format=json    # machine-readable (CI artifact)
    python tools/check.py --ast                  # source + contract rules only
    python tools/check.py --jaxpr --configs config3,config5

Exit codes: 0 = no unwaived findings, 1 = unwaived findings (or a stale /
malformed waiver file), 2 = usage error. Intentional exceptions live in
raft_sim_tpu/analysis/waivers.json with one-line justifications
(docs/ANALYSIS.md documents the format and the rule catalogue).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--all", action="store_true", help="run both passes (default)")
    ap.add_argument("--ast", action="store_true", help="Pass B only (AST + contracts)")
    ap.add_argument("--jaxpr", action="store_true", help="Pass A only (jaxpr audit)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument(
        "--configs",
        default=None,
        help="comma-separated preset names for the jaxpr pass "
             "(default: the analysis.jaxpr_audit.AUDIT_CONFIGS tiers)",
    )
    ap.add_argument(
        "--waivers",
        default=None,
        help="waiver file (default: raft_sim_tpu/analysis/waivers.json); "
             "'none' disables waiving",
    )
    args = ap.parse_args(argv)

    from raft_sim_tpu.analysis import jaxpr_audit, run
    from raft_sim_tpu.analysis import findings as F
    from raft_sim_tpu.utils.config import PRESETS

    do_ast = args.all or args.ast or not (args.ast or args.jaxpr)
    do_jaxpr = args.all or args.jaxpr or not (args.ast or args.jaxpr)
    config_names = jaxpr_audit.AUDIT_CONFIGS
    if args.configs:
        config_names = tuple(c.strip() for c in args.configs.split(","))
        unknown = [c for c in config_names if c not in PRESETS]
        if unknown:
            print(f"unknown preset(s) {unknown}", file=sys.stderr)
            return 2
    waivers_path = run.DEFAULT_WAIVERS
    if args.waivers:
        waivers_path = None if args.waivers == "none" else args.waivers

    t0 = time.time()
    found, unused, problems = run.run_all(
        do_ast=do_ast, do_jaxpr=do_jaxpr,
        config_names=config_names, waivers_path=waivers_path,
    )
    elapsed = time.time() - t0
    unwaived = [f for f in found if not f.waived]

    if args.format == "json":
        doc = F.report(
            found,
            unused_waivers=unused,
            extras={"elapsed_s": round(elapsed, 2), "waiver_problems": problems},
        )
        print(json.dumps(doc, indent=2))
    else:
        for f in found:
            tag = f"WAIVED ({f.waiver_reason})" if f.waived else "FAIL"
            print(f"[{tag}] {f.rule} {f.location()}\n    {f.message}")
        for w in unused:
            print(f"[STALE WAIVER] {w.get('rule')} {w.get('path')}: "
                  f"matched no finding -- remove it ({w.get('reason')})")
        for p in problems:
            print(f"[WAIVER FILE ERROR] {p}")
        print(
            f"{len(found)} finding(s): {len(unwaived)} unwaived, "
            f"{len(found) - len(unwaived)} waived, {len(unused)} stale waiver(s) "
            f"({elapsed:.1f}s)"
        )
    return 1 if (unwaived or unused or problems) else 0


if __name__ == "__main__":
    sys.exit(main())
