"""Client command ingest: host sources packed into per-chunk offer planes.

The reference accepts arbitrary client commands over a long-lived HTTP server
(`POST /client-set`, server.clj:8-12, core.clj:151-160). The serve loop's
equivalent is a `CommandSource` -- any iterator of int32 payloads (a JSONL
file, stdin, a generator) -- whose values are PACKED into the next chunk's
offer plane (`pack_chunk`: one [chunk] int32 array, one offered command per
tick slot, NIL-padded) while the current chunk executes on device
(serve/loop.py's double buffer).

`pack_chunk` is the single packing helper every offer plane goes through:
the serve loop, the CI smoke harness, and tests that replay scenario-genome
client cadences as explicit planes all build their [T] arrays here, so the
NIL-padding/validation rules cannot fork.
"""

from __future__ import annotations

import json
import sys
from typing import Iterable, Iterator

import numpy as np

from raft_sim_tpu.types import NIL, NOOP

_INT32_MIN, _INT32_MAX = -(2**31), 2**31 - 1


def check_value(value: int) -> int:
    """Validate one client payload: any int32 except the NIL/NOOP sentinels
    (-1/-2) -- the SAME rule Session.offer enforces. Values that collide with
    the old tick encoding (small positive ints) are explicitly legal now:
    latency rides the offer-tick plane, never the payload."""
    value = int(value)
    if value in (NIL, NOOP):
        raise ValueError(
            f"client value {value} collides with the NIL/NOOP sentinels "
            f"({NIL}/{NOOP}); any other int32 is legal"
        )
    if not _INT32_MIN <= value <= _INT32_MAX:
        raise ValueError(f"client value must fit int32, got {value}")
    return value


def pack_chunk(values: list[int], chunk: int) -> np.ndarray:
    """THE offer-plane packing helper: up to `chunk` validated payloads into a
    [chunk] int32 plane, one command per tick slot, NIL = no offer that tick."""
    if len(values) > chunk:
        raise ValueError(f"{len(values)} values do not fit a {chunk}-tick chunk")
    plane = np.full((chunk,), NIL, np.int32)
    for i, v in enumerate(values):
        plane[i] = check_value(v)
    return plane


def pack_plane(values: list[int], chunk: int, lanes: int) -> np.ndarray:
    """The per-cluster form of pack_chunk: up to `chunk * lanes` validated
    payloads into a [chunk, lanes] int32 plane -- one command per (tick,
    cluster) slot, filled tick-major (lane 0..L-1 of tick 0 first, so a
    tenant's commands land as early as its lane width allows), NIL-padded.
    The tenancy router (serve/tenancy.py) packs each tenant's lane slice
    here, so the validation rules cannot fork from the single-lane path."""
    if lanes < 1:
        raise ValueError(f"pack_plane needs >= 1 lane, got {lanes}")
    if len(values) > chunk * lanes:
        raise ValueError(
            f"{len(values)} values do not fit a {chunk}-tick x {lanes}-lane "
            "chunk"
        )
    plane = np.full((chunk, lanes), NIL, np.int32)
    for i, v in enumerate(values):
        plane[i // lanes, i % lanes] = check_value(v)
    return plane


def parse_line(raw: str):
    """One JSONL source line -> payload int or None (blank/comment). Accepts a
    bare integer or {"value": <int>} (extra keys ignored, so richer command
    records can share the stream)."""
    line = raw.strip()
    if not line or line.startswith("#"):
        return None
    doc = json.loads(line)
    if isinstance(doc, dict):
        if "value" not in doc:
            raise ValueError(f"command record without a 'value' key: {line!r}")
        doc = doc["value"]
    if isinstance(doc, bool) or not isinstance(doc, int):
        raise ValueError(f"command value must be an integer, got {line!r}")
    return doc


def jsonl_commands(path: str) -> Iterator[int]:
    """Payload iterator over a JSONL command file ('-' = stdin): one command
    per line, bare int or {"value": v}."""
    fh = sys.stdin if path == "-" else open(path)
    try:
        for raw in fh:
            v = parse_line(raw)
            if v is not None:
                yield v
    finally:
        if fh is not sys.stdin:
            fh.close()


class CommandSource:
    """Pull-based ingest queue over any payload iterator.

    `next_chunk(chunk)` pulls up to `chunk` commands and packs them into the
    next chunk's offer plane; `exhausted` flips when the iterator ends (the
    serve loop then runs its drain chunks so trailing commits still export).
    """

    def __init__(self, commands: Iterable[int]):
        self._it = iter(commands)
        self.exhausted = False
        self.offered = 0

    def next_values(self, n: int) -> list[int]:
        """Pull up to `n` raw payloads (the tenancy router packs them into
        its lane slice via pack_plane)."""
        values: list[int] = []
        while len(values) < n and not self.exhausted:
            try:
                values.append(next(self._it))
            except StopIteration:
                self.exhausted = True
        self.offered += len(values)
        return values

    def next_chunk(self, chunk: int) -> np.ndarray:
        return pack_chunk(self.next_values(chunk), chunk)
