"""Simulator configuration.

The reference hardcodes every constant: host 127.0.0.1 (core.clj:11), port 8080+id
(core.clj:13), log filename (core.clj:17), channel buffer sizes 5 (server.clj:37,
client.clj:18), heartbeat 3000 ms and election timeout 5000+rand(5000) ms
(core.clj:171-174), and takes topology from CLI args (core.clj:197-200).

Here every knob lives in one frozen (hashable) dataclass so a config can be a static
`jit` argument: cluster size, log capacity, timer windows in *tick units* (the reference's
3000 ms heartbeat : 5000-10000 ms election ratio is preserved as 3 : 6-12 ticks), and the
fault-injection schedule parameters. The five BASELINE.json configs are named presets in
`PRESETS`.
"""

from __future__ import annotations

import dataclasses

# Saturation ceilings for ClusterState.ack_age (ticks since a peer's last
# AppendEntries ack; re-exported by types.py). Ages cap instead of growing
# without bound so the field fits a narrow dtype on arbitrarily long runs: int8
# saturating at 120 when ack_timeout_ticks fits under it (every preset does --
# the timeout is a small multiple of the heartbeat), else int16 at 30000.
# Saturation only has to exceed the timeout: every consumer tests
# `age <= ack_timeout_ticks`, so trajectories are identical at either ceiling
# (only the saturated VALUES differ). Lives here (not types.py) because the
# config validator needs it and config is the leaf module.
ACK_AGE_SAT_NARROW = 120
ACK_AGE_SAT = 30000

# --- Ceiling derivations (single source for types.py and analysis Pass E) ---
#
# The narrow-dtype ceilings used to live as hand-computed literals with ad-hoc
# module-level asserts in types.py. They are now DERIVED here from the two
# encoding bounds that motivate them, so the constants, the dtype-policy
# functions in types.py, and the value-range audit (analysis/range_audit.py)
# all read one formula and cannot drift apart.


def window_min_encoding_max(log_capacity: int) -> int:
    """Largest value the single-pass window-start min ever encodes.

    models/raft_batched.py phase 8 folds responsiveness into one min by
    biasing prev-index (0..cap) with K = cap + 1: self contributes +2K,
    unresponsive peers +K, so the ceiling is 2K + cap = 3*cap + 2.
    """
    return 3 * log_capacity + 2


def max_log_capacity_for(dtype_max: int) -> int:
    """Largest log_capacity whose window-min encoding fits a dtype ceiling."""
    return (dtype_max - 2) // 3


def max_nodes_for(dtype_max: int) -> int:
    """Largest n_nodes whose node-id vocabulary fits a dtype ceiling.

    Node planes carry ids 0..n-1, NIL = -1, and the out-of-range sentinel n
    (reconfig swaps use it as "no node"), so n itself must fit: n <= dtype_max
    with one slot to spare for the sentinel -> ceiling dtype_max - 1.
    """
    return dtype_max - 1


# Upper bound on RaftConfig.log_capacity. Log indices ride int16 state planes
# at most (ClusterState.next_index/match_index; int8 below
# types.MAX_INT8_LOG_CAPACITY = max_log_capacity_for(127)), and the
# single-pass window-start min (models/raft_batched.py phase 8) encodes its
# responsiveness fallback with K = cap + 1 offsets, so its largest encoded
# value window_min_encoding_max(cap) = 3 * cap + 2 must fit the plane dtype.
MAX_LOG_CAPACITY = 4095
assert window_min_encoding_max(MAX_LOG_CAPACITY) <= 32767  # int16 tier


@dataclasses.dataclass(frozen=True)
class RaftConfig:
    """Static simulation parameters (hashable -> usable as a static jit arg)."""

    # Topology (reference: CLI args, core.clj:197-200; dev default 3 nodes, dev/user.clj:14)
    n_nodes: int = 5

    # Replicated log (reference: unbounded vector, log.clj:33; XLA needs static shapes)
    log_capacity: int = 32
    # Max entries shipped per AppendEntries RPC (reference ships arbitrary suffixes,
    # core.clj:59-67; a bounded window keeps the mailbox record fixed-width)
    max_entries_per_rpc: int = 4

    # Timers, in ticks (reference: 3000 ms heartbeat, 5000+rand(5000) ms election,
    # core.clj:171-174 -- same 3 : 6..12 ratio here)
    heartbeat_ticks: int = 3
    election_min_ticks: int = 6
    election_range_ticks: int = 6

    # Fault injection (reference's only "fault" is a silently dropped HTTP call,
    # client.clj:38-40; here faults are first-class pure inputs)
    drop_prob: float = 0.0
    # If True, each cluster draws its own drop probability uniformly from [0, drop_prob]
    # (BASELINE config 4: p in [0, 0.3]).
    drop_prob_uniform: bool = False
    # Rolling partitions: every `partition_period` ticks, with prob `partition_prob`,
    # split the cluster into two random halves that cannot exchange messages.
    partition_period: int = 0
    partition_prob: float = 0.0
    # Clock skew: each tick, a node's local clock advances by 0 or 2 instead of 1 with
    # this probability (split evenly between stall and jump).
    clock_skew_prob: float = 0.0
    # Node crash/restart: the reference's real-world failure mode is a killed process
    # restarting with amnesia -- only committed values hit disk (log.clj:16-18), so
    # term/vote/entries are lost (bug 2.3.12). Here restart is spec-correct: the Raft
    # persistent triple (currentTerm, votedFor, log[]) survives; everything else
    # (role, leaderId, votes, next/matchIndex, commitIndex, timers) is volatile and
    # wiped. The schedule is a pure function of (cluster key, tick): time is split
    # into windows of `crash_period` ticks; in each window each node independently
    # crashes with prob `crash_prob`, staying down for a uniform 1..`crash_down_ticks`
    # span at a random offset (clipped at the window edge).
    crash_prob: float = 0.0
    crash_period: int = 64
    crash_down_ticks: int = 12

    # Shared-entry-window responsiveness horizon (ticks). A leader's AppendEntries
    # entry payload is one shared E-entry window per tick (types.Mailbox); the window
    # start is the minimum prev-index over peers that acked an AppendEntries within
    # this many ticks (falling back to all peers when none have). Without the
    # responsiveness filter a permanently dead peer pins the window start forever and
    # live followers can never receive entries past window_start + E -- a liveness
    # loss the reference cannot have (it ships unbounded per-peer suffixes,
    # core.clj:59-67). Must comfortably exceed heartbeat_ticks + the 2-tick RPC round
    # trip so a live peer is never spuriously excluded by ordinary heartbeat cadence.
    ack_timeout_ticks: int = 12

    # Log compaction / snapshotting. The reference's log is an unbounded vector
    # (log.clj:33, append at log.clj:61-67): a reference cluster accepts client
    # writes forever. 0 (default) keeps the fixed-capacity log: once full, commands
    # are rejected permanently. > 0 turns the [N, CAP] arrays into a RING over
    # absolute 1-based indices (entry i at slot (i-1) mod CAP) and each node
    # compacts its committed prefix: whenever the retained window
    # (log_len - log_base) exceeds CAP - compact_margin, log_base advances toward
    # commit_index, freeing slots so appends can wrap -- unbounded-horizon client
    # workloads never exhaust the log. Entries below log_base live on only as
    # (log_base, base_term, base_chk); leaders whose peer's next_index falls below
    # their base send an InstallSnapshot analogue instead of entries
    # (models/raft.py phase 3/8). Compaction configs carry absolute indices, so
    # the capacity-bounded next/match planes and the match/hint wire fields
    # widen to int32 (types.index_dtype).
    compact_margin: int = 0

    # Client command injection (reference: external curl POST /client-set,
    # server.clj:8-12, core.clj:151-160). Every `client_interval` ticks one command is
    # offered to each cluster; 0 disables.
    client_interval: int = 0
    # Client request routing. False: the omniscient client writes straight to the
    # current live leader (the original simulator shortcut). True: the reference's
    # real write path (core.clj:151-160, server.clj:62-63) -- each offer targets a
    # RANDOM node; a non-leader target redirects the client to its known leader
    # (the HTTP 302 analogue, costing one tick per bounce) or to a random peer
    # when leaderless (core.clj:154); the client keeps up to `client_pipeline`
    # commands in flight and drops offers only when every slot is busy.
    # Offer->commit latency is tracked either way
    # (RunMetrics.lat_sum/lat_cnt; the reference's commit watch, log.clj:83-87,
    # never fired -- bug 2.3.9).
    client_redirect: bool = False
    # In-flight client pipeline depth K (redirect mode only): the simulated
    # client holds up to K commands in flight, each independently chasing 302
    # redirects -- the array form of the reference's buffered(5) request channel
    # with one private response channel per pending client-set
    # (server.clj:18-23, 37). A fresh offer takes the first free slot (dropped
    # only when all K are busy); at most one slot is accepted per NODE per tick
    # (the reference's loop dequeues one message per wait iteration), lowest
    # slot first. 1 = the round-4 single-command client.
    client_pipeline: int = 1

    # Durable storage plane (raft_sim_tpu/storage; dissertation section 3.8's
    # persistence requirements made falsifiable). The reference persists its
    # log through a file-backed atom (log.clj:16-18) whose restart path
    # forgets term/vote (bug 2.3.12); with this gate OFF the simulator models
    # the opposite extreme -- a PERFECT disk where every write is durable the
    # instant it happens -- so the whole class of durability failures is
    # inexpressible. A nonzero `fsync_interval` turns on the explicit
    # persistence model: each node carries durable watermarks (dur_len +
    # durable term/vote snapshots) advanced only when its fsync completes
    # (cadence `fsync_interval` ticks, each due flush stalled to the next
    # cadence tick with prob `fsync_jitter_prob` -- the latency lattice),
    # AppendEntries acks and vote grants reflect ONLY durable state (the
    # section 3.8 gate: replication stalls behind a slow disk instead of
    # lying), and crash recovery truncates the un-fsynced log suffix and
    # rewinds term/vote to the durable snapshot. A restart's durable tail may
    # additionally be TORN (prob `torn_tail_prob` per restart): the WAL
    # checksum detects the partial record and recovery drops up to
    # `lost_suffix_span` extra entries. Structural-gate contract like
    # client_interval: the nonzero cadence decides which carry legs compile;
    # the cadence/probability VALUES are tunable (the scenario genome retimes
    # them as data -- disk-fault axes, scenario/genome.py). v1 restriction:
    # mutually exclusive with ring-log compaction (compact_margin > 0) -- the
    # durable watermark would need to fold across snapshot installs and
    # compaction rebases; lift when a workload needs both.
    fsync_interval: int = 0
    fsync_jitter_prob: float = 0.0
    torn_tail_prob: float = 0.0
    lost_suffix_span: int = 1

    # Standing-fleet serving (raft_sim_tpu/serve). When True, the simulator
    # expects externally ingested client commands (driver `serve`,
    # Session.offer) even with client_interval == 0, so the offer-tick plane
    # (ClusterState.log_tick) and the commit-latency metric stay live for
    # them. Purely a structural gate: it changes which carry legs the tick
    # maintains (like pre_vote/compaction), never the protocol semantics --
    # a serve config with no offers ticks identically to the plain config.
    serve_ingest: bool = False

    # Protocol trace plane (raft_sim_tpu/trace). When True, telemetry runs may
    # carry the device-side event ring + transition-coverage bitmap
    # (trace/ring.py) beside the window records: role transitions, term bumps,
    # votes, commit advances, and fault-lattice events stream out per window
    # for whole-history checking (trace/checker.py). Purely a structural gate
    # with the same zero-cost-when-off contract as track_offer_ticks: with it
    # False (the default) no trace leg exists in ANY compiled program -- every
    # standing program lowers bit-identically to pre-trace builds -- and a
    # telemetry run that requests tracing under a False gate is an error
    # (sim/telemetry.py). Event EXTRACTION never perturbs the trajectory
    # either way (tests/test_trace.py pins instrumented == plain).
    track_trace: bool = False

    # Reconfiguration plane (raft_sim_tpu/reconfig; thesis chapter 4 /
    # 3.10 / 6.4 -- all three BEYOND the reference). Each extension follows
    # the client_interval pattern: the nonzero cadence is the STRUCTURAL gate
    # (it decides which carry legs the tick maintains and which quorum form
    # compiles), while the cadence VALUE itself is tunable -- the scenario
    # genome can retime commands without forking a compile.
    #
    # Joint-consensus membership change (thesis 4.3): every
    # `reconfig_interval` ticks the admin offers a membership toggle of a
    # rotating node to the leader; the cluster transitions through a joint
    # phase in which every quorum test needs a majority of BOTH the old and
    # new configurations (ClusterState.member_old/member_new docstring).
    reconfig_interval: int = 0
    # TimeoutNow leadership transfer (thesis 3.10): every `transfer_interval`
    # ticks the admin asks the current leader to transfer leadership to a
    # rotating target. The leader stops accepting client commands while the
    # transfer is pending (the lease handoff), waits for the target to match
    # its log, then fires REQ_TIMEOUT_NOW; the target starts a REAL election
    # immediately, bypassing its timer AND pre-vote.
    transfer_interval: int = 0
    # ReadIndex linearizable reads (thesis 6.4): every `read_interval` ticks
    # one read-only request is offered. The leader captures its commit index
    # (only once it has committed a current-term entry), confirms leadership
    # with a round of AppendEntries responses from a quorum, then serves --
    # a read traffic class with its own latency histogram
    # (StepInfo.read_hist) beside the write path's commit latency.
    read_interval: int = 0
    # Lease-based reads (thesis 6.4.1): with a nonzero lease term, a leader
    # holding a fresh quorum of AppendEntries acknowledgments -- every member
    # of a configuration majority acked within the last `read_lease_ticks`
    # GLOBAL ticks (the ack_age plane) -- serves a pending read immediately,
    # with NO confirmation round. Steady-state reads then cost zero quorum
    # rounds. The safety argument (docs/PROTOCOL.md "Lease reads") leans on
    # a clock assumption: voters deny RequestVote while they heard from a
    # leader within the minimum election timeout ON THEIR LOCAL CLOCK
    # (thesis 4.2.3 -- enabled by this gate), and local clocks may run up to
    # 2x global time under clock skew, so the lease term must fit under
    # HALF the minimum election timeout with slack for the election round
    # trip: 2 * read_lease_ticks + 4 <= election_min_ticks (validated
    # below). The TEST-ONLY `lease_skew_safe` mutant hook drops exactly that
    # 2x factor -- the skewed-clock lease violation the scenario hunt must
    # produce and the trace checker's read_linearizability must reject.
    # Requires the ReadIndex plane (read_index) and the offer-tick plane
    # (track_offer_ticks: the staleness invariant reads lat_frontier).
    read_lease_ticks: int = 0
    # Standing-fleet read ingest (raft_sim_tpu/serve): keep the ReadIndex
    # plane compiled for EXTERNALLY offered reads (Session.offer_read, the
    # serve loop's per-tenant read planes) even with read_interval == 0 --
    # the read-side mirror of serve_ingest, and a structural gate like it.
    serve_reads: bool = False

    # Compacted carry layout (ops/tile.py; docs/PERF.md "node-blocked
    # tiling"). When True, the per-edge value planes
    # (next/match/ack_age/req_off/resp_kind) are carried bit-packed to their
    # config-bounded value ranges as flat uint32 word legs, and the narrow
    # word/window planes (votes, the shared entry windows, the delivery
    # mask) are carried flattened so the TPU sublane tile stops padding
    # their minor dim. PHYSICAL layout only: both kernels unpack at tick
    # entry and repack at exit, so trajectories are bit-identical with the
    # dense layout (tests/test_tile.py) -- a structural gate like pre_vote
    # (it changes which programs compile, never the protocol semantics).
    # Under compaction the unbounded int32 index planes stay dense; the
    # other legs still compact.
    compact_planes: bool = False

    # PreVote (Raft thesis 9.6; BEYOND the reference, which has neither
    # pre-vote nor leadership transfer -- SURVEY.md 2.3.12). When True, an
    # expired node becomes a PRECANDIDATE and probes a majority at its
    # prospective next term WITHOUT bumping its real term; only a pre-quorum
    # promotes it to a real candidate. Voters deny the probe while they heard
    # from a leader within the minimum election timeout, so a node partitioned
    # away cannot inflate its term and depose a stable leader when the
    # partition heals.
    pre_vote: bool = False

    # On-device safety checking (north star: invariants checked every tick)
    check_invariants: bool = True
    # Log-matching check is O(N^2 * CAP) per tick -- gate separately.
    check_log_matching: bool = False
    # Run the log-matching check only on ticks where state.now % interval == 0
    # (1 = every tick). With a large N the check dominates the tick; periodic
    # sampling keeps the strongest Raft safety property checked at bounded cost
    # (the wide-cluster preset runs it every 16 ticks). The batch runs in
    # lockstep (every cluster's `now` is equal -- init_batch starts all at 0 and
    # every path ticks them together), so the hot path skips the whole
    # computation via lax.cond on check ticks' complement.
    log_matching_interval: int = 1

    def __post_init__(self):
        # Node ids ride node_dtype wire fields (Mailbox v_to/a_ok_to): int8 up
        # to 126 nodes, int16 above (types.node_dtype). 255 is the validated
        # giant-N ceiling (config7x, the node-sharded tier); past it nothing
        # overflows int16, but no preset or test exercises the territory.
        assert 2 <= self.n_nodes <= 255
        # Narrow-dtype wire/state bounds (types.py): log indices ride int16 planes
        # (next/match and the per-responder match/hint wire fields), the AE window
        # offset rides int8, and ack ages saturate below int16 max.
        assert 1 <= self.log_capacity <= MAX_LOG_CAPACITY
        assert 1 <= self.max_entries_per_rpc <= min(self.log_capacity, 127)
        assert self.ack_timeout_ticks < ACK_AGE_SAT
        assert self.heartbeat_ticks >= 1
        assert self.election_min_ticks > self.heartbeat_ticks
        assert self.election_range_ticks >= 1
        # Needs real slack beyond heartbeat cadence + the 2-tick RPC round trip:
        # at zero slack a single dropped ack transiently excludes every live peer.
        assert self.ack_timeout_ticks >= self.heartbeat_ticks + 4
        if self.crash_prob > 0:
            assert self.crash_period >= 2
            assert 1 <= self.crash_down_ticks <= self.crash_period
        assert self.log_matching_interval >= 1
        # The pipeline is client-side redirect state; the omniscient direct
        # client never queues.
        assert self.client_pipeline == 1 or self.client_redirect
        assert 1 <= self.client_pipeline <= 16
        # Compaction slack: client injections stop max(1, margin // 2) slots short
        # of the ring so election no-ops always find room (models/raft.py phase 6);
        # margin >= 2 keeps that client ceiling above the steady-state retained
        # window (CAP - margin), and the margin must not consume the whole ring.
        assert self.compact_margin == 0 or 2 <= self.compact_margin < self.log_capacity
        # Reconfiguration-plane cadences are non-negative; membership change
        # needs at least 3 nodes so a removal can never strand a 1-voter
        # configuration mid-experiment (the kernel additionally refuses any
        # toggle that would leave < 2 voters).
        assert self.reconfig_interval >= 0
        assert self.transfer_interval >= 0
        assert self.read_interval >= 0
        # Durable storage plane (raft_sim_tpu/storage): the fsync cadence is
        # the structural gate; the disk-fault probabilities only have a
        # reader when it is on.
        assert self.fsync_interval >= 0
        assert 0.0 <= self.fsync_jitter_prob <= 1.0
        assert 0.0 <= self.torn_tail_prob <= 1.0
        if self.fsync_interval > 0:
            # v1 restriction: no ring-log compaction under the durability
            # model. The durable watermark (dur_len) tracks a plain-prefix
            # log; folding it across snapshot installs and compaction
            # rebases (the base/bterm/bchk triple becoming durable state)
            # is a designed follow-up, not a silent interaction.
            assert self.compact_margin == 0, (
                "fsync_interval > 0 is v1-incompatible with compact_margin "
                "> 0: the durable watermark does not fold across snapshot "
                "installs yet (raft_sim_tpu/storage docstring)"
            )
            # The torn-tail draw removes 1..span extra entries at recovery;
            # a span past the log capacity could never matter.
            assert 1 <= self.lost_suffix_span <= self.log_capacity
        else:
            assert self.torn_tail_prob == 0.0, (
                "torn_tail_prob needs the durable storage plane: set a "
                "nonzero fsync_interval as the base cadence it perturbs"
            )
            assert self.fsync_jitter_prob == 0.0, (
                "fsync_jitter_prob needs the durable storage plane: set a "
                "nonzero fsync_interval as the base cadence it perturbs"
            )
        assert self.reconfig_interval == 0 or self.n_nodes >= 3
        assert self.read_lease_ticks >= 0
        if self.read_lease_ticks > 0:
            # Lease reads ride the ReadIndex slot machinery and the staleness
            # invariant reads the lat_frontier leg (track_offer_ticks).
            assert self.read_index, (
                "read_lease_ticks needs the ReadIndex plane: set a nonzero "
                "read_interval or serve_reads"
            )
            assert self.track_offer_ticks, (
                "read_lease_ticks needs the offer-tick plane (client_interval "
                "> 0 or serve_ingest): the lease staleness invariant reads "
                "the committed frontier leg"
            )
            # The skew-safe bound (docs/PROTOCOL.md "Lease reads"): voters
            # deny votes for election_min_ticks of LOCAL clock after leader
            # contact, local clocks advance at most 2 per global tick, and an
            # election needs >= 2 more ticks to commit -- so the lease term
            # must fit under half the denial window with that slack.
            assert 2 * self.read_lease_ticks + 4 <= self.election_min_ticks, (
                f"read_lease_ticks {self.read_lease_ticks} breaks the "
                f"skew-safe bound 2*L+4 <= election_min_ticks "
                f"({self.election_min_ticks})"
            )
            # The lease predicate compares against the SATURATING ack_age
            # plane: any window at or past the ceiling would treat
            # arbitrarily stale (saturated) acks as fresh and hold the lease
            # forever. Bounded for the mutant's widened no-skew window
            # (election_min + 2) too, so even the TEST-ONLY weakening can
            # never alias into saturation.
            assert self.election_min_ticks + 2 < self.ack_age_sat, (
                f"lease windows (up to election_min_ticks + 2 = "
                f"{self.election_min_ticks + 2}) must stay below the ack_age "
                f"saturation ceiling ({self.ack_age_sat})"
            )
            # Lease reads and TimeoutNow transfers COEXIST since the
            # disruptive-RequestVote override (thesis 3.10 pairs TimeoutNow
            # with a flag that bypasses the 4.2.3 denial): a transfer
            # target's election carries Mailbox.req_disrupt, voters process
            # it despite their lease obligation, and the transferring leader
            # stops serving lease reads while the transfer pends (the
            # handoff covers the read path too -- docs/PROTOCOL.md "Lease
            # reads" staleness argument). The PR-11 mutual-exclusion
            # validator is gone.

    @property
    def track_offer_ticks(self) -> bool:
        """True when the offer-tick plane (ClusterState.log_tick, the
        Mailbox.ent_tick wire window, and the commit-latency metric) is
        maintained: any config that can see client commands whose latency
        should be measured -- a scheduled cadence (client_interval > 0) or a
        standing serve ingest (serve_ingest). Payload values are arbitrary
        int32 either way; latency reads ONLY this plane (never values)."""
        return self.client_interval > 0 or self.serve_ingest

    @property
    def compaction(self) -> bool:
        """True when the ring-log compaction path is active (compact_margin > 0)."""
        return self.compact_margin > 0

    @property
    def reconfig(self) -> bool:
        """True when the joint-consensus membership plane is active: the
        member bitplanes are maintained and every quorum test is
        configuration-masked (dual popcount during joint phases)."""
        return self.reconfig_interval > 0

    @property
    def leader_transfer(self) -> bool:
        """True when the TimeoutNow transfer plane is active (xfer_to state,
        the xfer_tgt wire header, and the REQ_TIMEOUT_NOW handler compile)."""
        return self.transfer_interval > 0

    @property
    def read_index(self) -> bool:
        """True when the ReadIndex read traffic class is active (read slot
        state, ack banking, and the read latency histogram compile): a
        scheduled read cadence, or standing-fleet read ingest (serve_reads --
        externally offered reads, the read-side serve_ingest)."""
        return self.read_interval > 0 or self.serve_reads

    @property
    def read_lease(self) -> bool:
        """True when lease-based reads are active (read_lease_ticks > 0):
        the vote-denial rule compiles into RequestVote handling, the lease
        predicate into read serving, and the read_fr frontier leg + the
        viol_read_stale device invariant go live."""
        return self.read_lease_ticks > 0

    @property
    def durable_storage(self) -> bool:
        """True when the durable storage plane is active (fsync_interval >
        0): the per-node durable watermarks (dur_len/dur_term/dur_vote)
        compile into the carry, the section-3.8 gates into ack/grant
        handling, and crash recovery truncates to the durable snapshot
        (raft_sim_tpu/storage)."""
        return self.fsync_interval > 0

    # -- TEST-ONLY mutation hooks (scenario/mutation.py). Each extension's
    # correctness hinges on one rule; these properties are that rule as data,
    # so a mutant config subclass can weaken exactly it and the CE hunt must
    # re-find the injected bug. Production configs always return True.
    @property
    def joint_consensus(self) -> bool:
        """False (mutants only): a membership change is ONE log entry that
        switches the configuration wholly at append -- the single-server
        change (thesis 4.1) with its known-unsafe interleaving: two leaders'
        uncommitted single-entry changes can yield majorities that do not
        intersect (the bug the joint phase exists to rule out)."""
        return True

    @property
    def act_on_append(self) -> bool:
        """False (mutants only): each node derives its configuration from
        the COMMITTED prefix of its log instead of the whole appended prefix
        -- "act on commit", the dissertation-ch.-4 anti-rule. Nodes then
        disagree about when a change takes effect (a config entry's commit
        is itself judged under some config), and the old configuration keeps
        electing leaders the new one cannot see: disjoint quorums."""
        return True

    @property
    def truncation_rollback(self) -> bool:
        """False (mutants only): a node whose truncated log LOST config
        entries keeps acting on the stale derived configuration (the
        rollback the dissertation requires is skipped). A follower that
        briefly held an uncommitted change then truncated it keeps voting
        under the phantom configuration -- quorums drawn from member sets no
        log chain ever contained."""
        return True

    @property
    def read_confirm(self) -> bool:
        """False (mutants only): ReadIndex serves at capture time with no
        leadership confirmation round and no current-term-commit capture
        gate -- the stale-read-below-the-committed-frontier bug."""
        return True

    @property
    def xfer_election(self) -> bool:
        """False (mutants only): a TimeoutNow target assumes leadership
        DIRECTLY (no vote round, no up-to-date check) and the leader fires
        without waiting for the target to catch up -- transfer as a coup."""
        return True

    @property
    def lease_skew_safe(self) -> bool:
        """False (mutants only): the lease window is judged as if local
        clocks advanced exactly one unit per global tick -- the kernel
        serves lease reads for election_min_ticks + 2 instead of the
        configured skew-safe read_lease_ticks. Correct on unskewed clocks
        (a deposing election needs a full election_min of vote-denial
        expiry plus the vote and commit round trips, one tick more than
        the widened lease);
        under clock skew a fast follower's vote-denial window halves in
        global time, a new leader commits inside the optimistic lease, and
        the deposed leader serves a stale read -- the thesis-6.4.1 clock
        assumption made falsifiable (the hunt drives the skew genome axis)."""
        return True

    @property
    def durable_acks(self) -> bool:
        """False (mutants only): AppendEntries acks and vote grants reflect
        the node's VOLATILE state -- an ack can name entries whose fsync has
        not completed, and a grant can precede the vote's persistence. The
        canonical ack-before-fsync storage bug: a leader counts a follower's
        acked-but-unfsynced entries toward commit, the follower crashes, and
        recovery truncates entries the cluster already reported committed --
        committed-entry loss (leader_completeness). Recovery still truncates
        honestly; only the acknowledgment lies."""
        return True

    @property
    def persist_vote(self) -> bool:
        """False (mutants only): crash recovery restores term/log from the
        durable snapshot but forgets votedFor -- the reference's own restart
        bug (log.clj:16-18, SURVEY.md 2.3.12) expressed inside the storage
        plane. A restarted voter re-grants in a term it already voted in, two
        candidates each reach "quorum", and two leaders share the term
        (election_safety)."""
        return True

    @property
    def ack_age_sat(self) -> int:
        """Saturation ceiling for the ack-age plane: the int8 ceiling whenever
        the responsiveness horizon fits under it (see ACK_AGE_SAT_NARROW)."""
        return (
            ACK_AGE_SAT_NARROW
            if self.ack_timeout_ticks < ACK_AGE_SAT_NARROW
            else ACK_AGE_SAT
        )

    @property
    def quorum(self) -> int:
        """Votes needed for leadership: floor(N/2)+1.

        The reference computes ceil(N/2) over peers+self (majority? core.clj:19-21),
        which equals floor(N/2)+1 for odd N but is NOT a majority for even N
        (ceil(4/2)=2 of 4). We use the spec-correct strict majority.
        """
        return self.n_nodes // 2 + 1


# The five BASELINE.json configs as named presets (see BASELINE.md). config1 is the
# 10k-tick correctness reference: its log capacity must hold every command injected
# over the run (10k ticks / interval 8 = 1250 commands).
PRESETS: dict[str, tuple[RaftConfig, int]] = {
    # name -> (config, batch size)
    "config1": (
        RaftConfig(
            n_nodes=5,
            log_capacity=2048,
            max_entries_per_rpc=8,
            client_interval=8,
            check_log_matching=True,
        ),
        1,
    ),
    "config2": (RaftConfig(n_nodes=5, client_interval=8), 1_000),
    "config3": (RaftConfig(n_nodes=5), 100_000),
    "config4": (
        RaftConfig(
            n_nodes=7,
            drop_prob=0.3,
            drop_prob_uniform=True,
            clock_skew_prob=0.1,
        ),
        100_000,
    ),
    "config5": (
        RaftConfig(
            n_nodes=51,
            log_capacity=16,
            partition_period=32,
            partition_prob=0.5,
            check_invariants=True,
            # BASELINE row 5 promises on-device safety asserts; log matching is
            # the strongest of them and O(N^2 * CAP) at N=51, so it runs on a
            # 16-tick sampling cadence (measured <= ~10% throughput cost).
            check_log_matching=True,
            log_matching_interval=16,
        ),
        10_000,
    ),
    # config5 under the compacted carry layout (ops/tile.py; ISSUE 14): the
    # SAME workload, trajectories bit-identical (tests/test_tile.py), only
    # the physical carry form moves -- the standing layout-A/B row that
    # prices the node-blocked tiling against config5's dense wall
    # (docs/PERF.md "the config5 roofline"). Priced by Pass C under its own
    # tier; bench runs it beside config5 so the first chip session measures
    # the layout delta with no extra flags.
    "config5c": (
        RaftConfig(
            n_nodes=51,
            log_capacity=16,
            partition_period=32,
            partition_prob=0.5,
            check_invariants=True,
            check_log_matching=True,
            log_matching_interval=16,
            compact_planes=True,
        ),
        10_000,
    ),
    # Not a BASELINE row: the ring-compaction acceptance preset. A deliberately
    # small ring under an unbounded client workload (one command per 4 ticks
    # forever) plus crash + drop faults: run >= 100k ticks, commands must keep
    # being accepted (commit passes many multiples of CAP) with zero violations.
    # The reference passes this trivially (unbounded log vector, log.clj:33); the
    # fixed-CAP log without compaction fails it by construction.
    "config6": (
        RaftConfig(
            n_nodes=5,
            log_capacity=32,
            compact_margin=8,
            max_entries_per_rpc=4,
            client_interval=4,
            drop_prob=0.1,
            crash_prob=0.3,
            crash_period=64,
            crash_down_ticks=12,
        ),
        1_000,
    ),
    # config6 through the reference's real write path (curl -> 302 redirect
    # chase, core.clj:151-160, server.clj:62-63): every offer targets a random
    # node, bounces cost one tick each, and the client holds up to 5 commands
    # in flight -- the reference's buffered(5) request channel (server.clj:37).
    "config6r": (
        RaftConfig(
            n_nodes=5,
            log_capacity=32,
            compact_margin=8,
            max_entries_per_rpc=4,
            client_interval=4,
            drop_prob=0.1,
            crash_prob=0.3,
            crash_period=64,
            crash_down_ticks=12,
            client_redirect=True,
            client_pipeline=5,
        ),
        1_000,
    ),
    # config3 with PreVote (thesis 9.6): the standing bench row that prices
    # pre_vote's cost against the config3 baseline -- the number used to live
    # in docs/PERF.md prose, now measured every bench run (ROADMAP item 5).
    "config3p": (RaftConfig(n_nodes=5, pre_vote=True), 100_000),
    # Reconfiguration-plane acceptance preset (raft_sim_tpu/reconfig): the
    # three thesis extensions -- joint-consensus membership change,
    # TimeoutNow leadership transfer, ReadIndex reads -- all live at once,
    # under client traffic + drop + crash churn. The add/remove-under-fire
    # tier: membership toggles land every ~97 ticks while elections, crashes,
    # and transfers are in flight; the trace checker must pass all properties
    # over its histories (tests/test_reconfig.py, CI reconfig smoke).
    "config8": (
        RaftConfig(
            n_nodes=5,
            log_capacity=64,
            max_entries_per_rpc=4,
            client_interval=4,
            drop_prob=0.1,
            crash_prob=0.25,
            crash_period=64,
            crash_down_ticks=12,
            reconfig_interval=97,
            transfer_interval=61,
            read_interval=7,
        ),
        1_000,
    ),
    # Lease-read acceptance preset (the tenancy plane's read tier): client
    # writes + a dense scheduled read stream served through leases
    # (read_lease_ticks = 4 against the widened election_min_ticks = 12 --
    # the skew-safe bound 2*4+4 <= 12 exactly), under drop + clock skew so
    # the lease's clock assumption is exercised, not idle. The trace checker
    # must pass all six properties over its histories while the lease-skew
    # mutant of the same preset is rejected naming read_linearizability
    # (tests/test_lease.py, CI serve smoke).
    "config9": (
        RaftConfig(
            n_nodes=5,
            log_capacity=64,
            compact_margin=8,
            max_entries_per_rpc=4,
            election_min_ticks=12,
            election_range_ticks=8,
            client_interval=4,
            read_interval=3,
            read_lease_ticks=4,
            drop_prob=0.05,
            clock_skew_prob=0.1,
        ),
        1_000,
    ),
    # Giant-N tier (node-axis sharding, parallel/nodeshard.py): one cluster
    # too large for comfortable single-chip batches, partitioned row-wise
    # across the mesh's "nodes" axis. N=101 keeps W=4 packed words and the
    # threshold-quorum form (log_capacity < N), with client traffic + drops so
    # replication is exercised at scale, not just elections. The feature set
    # deliberately stays inside the sharded v1 surface (no reconfig/transfer/
    # reads/redirect/log-matching); the same preset runs unsharded for the
    # bit-exactness acceptance (tests/test_nodeshard.py).
    "config7": (
        RaftConfig(
            n_nodes=101,
            log_capacity=16,
            max_entries_per_rpc=4,
            client_interval=4,
            drop_prob=0.05,
        ),
        1_000,
    ),
    # The N=255 ceiling tier (W=8 words, node ids at the int16 dtype tier):
    # config7's workload at the largest supported cluster, under rolling
    # partitions, carried in the COMPACTED layout (PR 14) on the single-chip
    # path -- the node-sharded program runs the same preset dense internally
    # (types.compact_twin; parallel/nodeshard.py), so one preset prices both
    # the packed single-chip carry and the per-device mesh bytes.
    "config7x": (
        RaftConfig(
            n_nodes=255,
            log_capacity=16,
            max_entries_per_rpc=4,
            client_interval=4,
            drop_prob=0.05,
            partition_period=32,
            partition_prob=0.25,
            compact_planes=True,
        ),
        250,
    ),
    # Durable-storage acceptance preset (raft_sim_tpu/storage; ISSUE 19): the
    # fsync/WAL model live under the full disk-fault lattice -- a 3-tick
    # fsync cadence with 20% latency jitter, torn durable tails on 30% of
    # restarts (up to 3 extra entries dropped at recovery), crash churn so
    # recovery actually runs, and client traffic + drops so the section-3.8
    # ack gate is exercised under replication pressure, not just elections.
    # Compaction stays off (the v1 restriction above). The trace checker must
    # pass all six properties over its histories while the ack-before-fsync /
    # volatile-vote mutants of the same preset are rejected naming
    # leader_completeness / election_safety (tests/test_storage.py, CI
    # durability smoke).
    "config10": (
        RaftConfig(
            n_nodes=5,
            log_capacity=64,
            max_entries_per_rpc=4,
            client_interval=4,
            drop_prob=0.1,
            crash_prob=0.3,
            crash_period=64,
            crash_down_ticks=12,
            fsync_interval=3,
            fsync_jitter_prob=0.2,
            torn_tail_prob=0.3,
            lost_suffix_span=3,
        ),
        1_000,
    ),
    # config4's fault mix carrying client traffic, so offer->commit latency is
    # measured UNDER faults in the standing bench (not only on reliable nets).
    "config4c": (
        RaftConfig(
            n_nodes=7,
            log_capacity=64,
            max_entries_per_rpc=8,
            drop_prob=0.3,
            drop_prob_uniform=True,
            clock_skew_prob=0.1,
            client_interval=8,
        ),
        100_000,
    ),
}
