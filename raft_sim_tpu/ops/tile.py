"""Node-blocked compacted plane tiling (cfg.compact_planes): the carry layout
that breaks the config5 padding wall.

docs/PERF.md's gated audit proves the dense layout's residual HBM wall is the
five int8 [N, N] planes (next/match/ack_age/req_off/resp_kind) plus the
sublane padding of the narrow word/window planes: in the batch-minor layout a
[51, 51] int8 plane pads its last node axis 51 -> 64 sublanes (policy.SUBLANE)
and a [51, 2]-word uint32 plane pads 2 -> 8, so config5 moves ~72 KB padded
per cluster-tick against ~59 KB logical -- and even the logical bytes carry
dead air, because every per-edge value is stored as a full byte while its
RANGE is a few bits (req_off is an offset in -1..E, resp_kind a RESP_* enum
0..3, next/match are capacity-bounded log indices, ack_age saturates at
cfg.ack_age_sat). This module is the event-sparse re-tiling of exactly those
legs:

  - "pack" legs: the per-edge value planes, flattened row-major over their
    leading (node, node) axes and packed k = 32 // bits values per uint32
    word, bits sized to the leg's config-bounded value range (below). A
    [51, 51] int8 plane becomes a flat [W] uint32 leg: [434] words at 5 bits
    instead of 2601 bytes -- and the flat leg pays only the 8-row sublane
    round-up of a uint32 vector (434 -> 440) instead of the 51 -> 64 per-row
    pad.
  - "flat" legs: already-word-packed or narrow-window planes (votes
    [N, W], the shared entry windows [N, E], the packed delivery mask) merely
    flattened to 1-D so the sublane tile stops padding their tiny minor dim
    (votes at N=51: [51, 2] words pad to [51, 8] = 1632 B; flat [102] pads to
    [104] = 416 B).

Value-range contract (the bit widths; canonical machine-readable form is
`pack_width_table` below -- consumed by the plans here and by the value-range
audit (analysis/range_audit.py) -- restated independently by the oracle
(tests/oracle.py pack_widths) and pinned against this module in
tests/test_constants.py):

  next_index   1 .. cap+1        -> bits_for(cap + 2)   (non-compaction only:
  match_index  0 .. cap             compaction carries absolute unbounded
                                    indices, so both stay dense int32 there)
  ack_age      0 .. ack_age_sat  -> bits_for(sat + 1)
  req_off     -1 .. E  (bias +1) -> bits_for(E + 2)
  resp_kind    0 .. 3 (RESP_*)   -> 2

The layout is PHYSICAL only: both kernels unpack to the dense planes at tick
entry and repack at exit (models/raft.py / models/raft_batched.py), so the
protocol logic -- and every trajectory -- is bit-identical to the dense
layout (tests/test_tile.py pins dense == compacted across word-boundary N).
Carry legs whose structural gate is off are passed through UNTOUCHED via
`reuse` (the carry-passthrough contract: XLA elides them from the per-tick
HBM round trip exactly as in the dense layout). Pack/unpack cost is VPU work
inside the fused tick body; what the scan carries -- and what Pass C prices
(analysis/policy.py padded_bytes) -- is the compacted form.

All ops are integer-only (float-op rule) and flatten BEFORE widening to
uint32, so no [N, N]-shaped widening convert exists for the plane-widening
rule to flag.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from raft_sim_tpu.ops import bitplane
from raft_sim_tpu.utils.config import RaftConfig

WORD = 32


def bits_for(n_values: int) -> int:
    """Bits needed to store values 0 .. n_values-1 (>= 1)."""
    return max(1, (n_values - 1).bit_length())


def index_bits(cfg: RaftConfig) -> int:
    """Bits of a packed log-index plane entry (non-compaction configs only:
    next_index <= cap + 1, match_index <= cap)."""
    return bits_for(cfg.log_capacity + 2)


def age_bits(cfg: RaftConfig) -> int:
    """Bits of a packed ack_age entry (saturates at cfg.ack_age_sat)."""
    return bits_for(cfg.ack_age_sat + 1)


def off_bits(cfg: RaftConfig) -> int:
    """Bits of a packed req_off entry: -1 (snapshot sentinel) .. E, stored
    with a +1 bias."""
    return bits_for(cfg.max_entries_per_rpc + 2)


RESP_BITS = 2  # RESP_* is 0..3 (types.py)


def pack_width_table(cfg: RaftConfig) -> dict[str, tuple[int, int, int, int]]:
    """THE pack-width table: field -> (bits, bias, lo, hi) for every bit-packed
    leg of the compacted layout, where lo..hi is the leg's dense value range
    and bias shifts it non-negative before packing (stored = value + bias,
    0 <= stored < 2**bits).

    Single source of truth: `state_plan`/`mailbox_plan` size their pack legs
    from it, the value-range audit (analysis/range_audit.py, rule
    range-pack-width) proves declared ranges fit these widths, and the
    oracle's independent restatement (tests/oracle.py `pack_widths` -- kept
    import-free of this package so it stays a real second implementation) is
    pinned against it in tests/test_constants.py. Index legs appear only for
    non-compaction configs: compaction carries absolute unbounded indices as
    dense int32, so no width exists for them.
    """
    cap, sat, e = cfg.log_capacity, cfg.ack_age_sat, cfg.max_entries_per_rpc
    table = {}
    if not cfg.compaction:
        table["next_index"] = (index_bits(cfg), 0, 1, cap + 1)
        table["match_index"] = (index_bits(cfg), 0, 0, cap)
    table["ack_age"] = (age_bits(cfg), 0, 0, sat)
    table["mb.req_off"] = (off_bits(cfg), 1, -1, e)
    table["mb.resp_kind"] = (RESP_BITS, 0, 0, 3)
    return table


def words_for(m: int, bits: int) -> int:
    """uint32 words holding m packed values at `bits` bits (k = 32 // bits
    values per word -- whole values never straddle words)."""
    k = WORD // bits
    return -(-m // k)


# --------------------------------------------------------------- word packing


def pack_words(x: jax.Array, bits: int) -> jax.Array:
    """[M, *rest] non-negative ints (< 2**bits) -> [ceil(M/k), *rest] uint32,
    k = 32 // bits values per word, value i at word i // k, lane (i % k) *
    bits. Leading-axis layout serves both the per-cluster ([M]) and
    batch-minor ([M, B]) forms. The widening convert happens on the FLAT
    shape by contract (see module docstring)."""
    k = WORD // bits
    m = x.shape[0]
    w = -(-m // k)
    xu = x.astype(jnp.uint32)
    pad = w * k - m
    if pad:
        xu = jnp.concatenate(
            [xu, jnp.zeros((pad,) + x.shape[1:], jnp.uint32)], axis=0
        )
    xu = xu.reshape((w, k) + x.shape[1:])
    out = jnp.zeros((w,) + x.shape[1:], jnp.uint32)
    for j in range(k):
        out = out | (xu[:, j] << jnp.uint32(bits * j))
    return out


def unpack_words(words: jax.Array, bits: int, m: int, dtype) -> jax.Array:
    """Inverse of `pack_words`: [W, *rest] uint32 -> [m, *rest] `dtype`."""
    k = WORD // bits
    w = words.shape[0]
    assert w == words_for(m, bits), f"{w} words cannot hold {m} x {bits}-bit"
    mask = jnp.uint32((1 << bits) - 1)
    parts = jnp.stack(
        [(words >> jnp.uint32(bits * j)) & mask for j in range(k)], axis=1
    )  # [W, k, *rest]
    flat = parts.reshape((w * k,) + words.shape[1:])[:m]
    return flat.astype(dtype)


# ----------------------------------------------------------------- leg plans


def _flatten(x: jax.Array, lead: int) -> jax.Array:
    """Merge the first `lead` axes (any trailing batch axes ride along)."""
    return x.reshape((-1,) + x.shape[lead:])


def state_plan(cfg: RaftConfig):
    """[(field, mode, lead_shape, bits, bias, dense_dtype)] for the
    ClusterState legs the compacted layout transforms. `mode` is "pack"
    (bit-packed values) or "flat" (reshape only; bits/bias unused)."""
    from raft_sim_tpu import types as rst_types

    n = cfg.n_nodes
    w = bitplane.n_words(n)
    widths = pack_width_table(cfg)
    plan = [("votes", "flat", (n, w), 0, 0, jnp.uint32)]
    if not cfg.compaction:
        # Compaction carries absolute (unbounded) int32 indices: no static
        # bit bound exists, so next/match stay dense there (types.index_dtype)
        # and pack_width_table has no entry for them.
        idt = rst_types.index_dtype(cfg)
        plan += [
            ("next_index", "pack", (n, n), widths["next_index"][0], 0, idt),
            ("match_index", "pack", (n, n), widths["match_index"][0], 0, idt),
        ]
    plan.append(
        ("ack_age", "pack", (n, n), widths["ack_age"][0], 0, rst_types.ack_dtype(cfg))
    )
    return plan


def mailbox_plan(cfg: RaftConfig):
    """The Mailbox legs the compacted layout transforms (same tuple shape as
    `state_plan`). The shared entry windows flatten regardless of their
    gates; gated-off legs are flat zeros passed through untouched
    (`pack_state` reuse)."""
    n, e = cfg.n_nodes, cfg.max_entries_per_rpc
    widths = pack_width_table(cfg)
    return [
        ("req_off", "pack", (n, n), widths["mb.req_off"][0], widths["mb.req_off"][1], jnp.int8),
        ("resp_kind", "pack", (n, n), RESP_BITS, 0, jnp.int8),
        ("ent_term", "flat", (n, e), 0, 0, jnp.int32),
        ("ent_val", "flat", (n, e), 0, 0, jnp.int32),
        ("ent_tick", "flat", (n, e), 0, 0, jnp.int32),
        ("ent_cfg", "flat", (n, e), 0, 0, jnp.int32),
    ]


# Mailbox legs whose structural gate can be OFF (the leg is then a
# loop-invariant zero plane the tick must pass through untouched -- the
# carry-passthrough contract; policy.invariant_leaves names the same gates).
def _mailbox_gates(cfg: RaftConfig) -> dict[str, bool]:
    return {
        "ent_tick": cfg.track_offer_ticks,
        "ent_cfg": cfg.reconfig,
    }


def packed_carry_dtypes(cfg: RaftConfig) -> dict[str, "jnp.dtype"]:
    """Carry-leg name -> dtype for the transformed legs (names in the
    analysis passes' convention: state bare, mailbox `mb.<f>`), so the
    carry-dtype rule can expect uint32 where the compacted layout rides."""
    out = {f: jnp.dtype(jnp.uint32) for f, *_ in state_plan(cfg)}
    for f, mode, *_rest in mailbox_plan(cfg):
        out[f"mb.{f}"] = jnp.dtype(
            jnp.uint32 if mode == "pack" else _rest[-1]
        )
    return out


def _pack_leg(x, mode, lead_shape, bits, bias):
    flat = _flatten(x, len(lead_shape))
    if mode == "flat":
        return flat
    if bias:
        flat = flat + jnp.asarray(bias, flat.dtype)
    return pack_words(flat, bits)


def _unpack_leg(x, mode, lead_shape, bits, bias, dense_dtype):
    if mode == "flat":
        return x.reshape(lead_shape + x.shape[1:]).astype(dense_dtype)
    m = 1
    for d in lead_shape:
        m *= d
    vals = unpack_words(x, bits, m, jnp.int32)
    if bias:
        vals = vals - jnp.int32(bias)
    return vals.astype(dense_dtype).reshape(lead_shape + x.shape[1:])


def pack_state(cfg: RaftConfig, dense, reuse=None):
    """Dense ClusterState -> compacted carry form. `reuse` (the tick's INPUT
    compacted state) supplies the gated-off mailbox legs verbatim, keeping
    them var-identity passthroughs the way the dense kernels do -- XLA then
    elides their HBM round trip (docs/PERF.md round-4 lesson; rule
    carry-passthrough)."""
    reps = {
        f: _pack_leg(getattr(dense, f), mode, shape, bits, bias)
        for f, mode, shape, bits, bias, _dt in state_plan(cfg)
    }
    gates = _mailbox_gates(cfg)
    mb_reps = {}
    for f, mode, shape, bits, bias, _dt in mailbox_plan(cfg):
        if reuse is not None and not gates.get(f, True):
            mb_reps[f] = getattr(reuse.mailbox, f)
        else:
            mb_reps[f] = _pack_leg(getattr(dense.mailbox, f), mode, shape, bits, bias)
    return dense._replace(mailbox=dense.mailbox._replace(**mb_reps), **reps)


def unpack_state(cfg: RaftConfig, s):
    """Compacted carry form -> dense ClusterState (the kernels' working
    view). Exact inverse of `pack_state` for in-range values."""
    reps = {
        f: _unpack_leg(getattr(s, f), mode, shape, bits, bias, dt)
        for f, mode, shape, bits, bias, dt in state_plan(cfg)
    }
    mb_reps = {
        f: _unpack_leg(getattr(s.mailbox, f), mode, shape, bits, bias, dt)
        for f, mode, shape, bits, bias, dt in mailbox_plan(cfg)
    }
    return s._replace(mailbox=s.mailbox._replace(**mb_reps), **reps)


def unpack_inputs(cfg: RaftConfig, inp):
    """Compacted StepInputs -> the kernels' dense view: the packed delivery
    mask ships flat ([N*W] uint32, sim/faults.py) and reshapes back to the
    [N, W] word plane here."""
    n = cfg.n_nodes
    w = bitplane.n_words(n)
    dm = inp.deliver_mask
    return inp._replace(deliver_mask=dm.reshape((n, w) + dm.shape[1:]))
