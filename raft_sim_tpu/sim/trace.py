"""Host-side trajectory decoding -- the TPU-native equivalent of the reference's
per-iteration println of node state + message (core.clj:182-186).

On device, tracing is just `scan.run(..., trace=True / trace_states=True)`: the scan
stacks per-tick StepInfo (cheap) or full ClusterStates (heavy, debug only) as a
trajectory. This module renders those stacks for one selected cluster as human-readable
lines, and diffs consecutive states into discrete events (elections started, votes
granted, leaders crowned, entries committed) so a failing fuzz case can be read like
the reference's console output.
"""

from __future__ import annotations

from typing import Iterator

import numpy as np

from raft_sim_tpu.types import CANDIDATE, FOLLOWER, LEADER, NIL, PRECANDIDATE

ROLE_NAMES = {
    FOLLOWER: "follower",
    CANDIDATE: "candidate",
    LEADER: "leader",
    PRECANDIDATE: "precandidate",
}


def info_lines(infos, every: int = 1) -> Iterator[str]:
    """Render stacked StepInfo (single cluster: leading axis = ticks) as one line per
    `every` ticks."""
    # Pull every field host-side once; per-tick indexing below is then pure numpy.
    f = {name: np.asarray(getattr(infos, name)) for name in infos._fields}
    viol = f["viol_election_safety"] | f["viol_commit"] | f["viol_log_matching"]
    for t in range(0, len(f["leader"]), every):
        leader = int(f["leader"][t])
        yield (
            f"tick {t:>6}  leader={'-' if leader == NIL else leader}"
            f"  n_leaders={int(f['n_leaders'][t])}"
            f"  max_term={int(f['max_term'][t])}"
            f"  commit[{int(f['min_commit'][t])},{int(f['max_commit'][t])}]"
            f"  msgs={int(f['msgs_delivered'][t])}"
            f"  cmds={int(f['cmds_injected'][t])}"
            + ("  VIOLATION" if bool(viol[t]) else "")
        )


def node_line(states, t: int, node: int) -> str:
    """One node's state at tick t (stacked states, single cluster) -- the analogue of
    the reference's `(println node)` (core.clj:183)."""
    g = lambda f: np.asarray(getattr(states, f))[t, node]
    role = ROLE_NAMES[int(g("role"))]
    vf, ld = int(g("voted_for")), int(g("leader_id"))
    base = int(g("log_base"))
    return (
        f"  node {node}: {role:<9} term={int(g('term'))}"
        f" voted_for={'-' if vf == NIL else vf}"
        f" leader={'-' if ld == NIL else ld}"
        f" commit={int(g('commit_index'))} log_len={int(g('log_len'))}"
        + (f" base={base}" if base else "")
        + f" clock={int(g('clock'))}/{int(g('deadline'))}"
    )


def events(states) -> Iterator[tuple[int, str]]:
    """Diff consecutive stacked states (single cluster) into (tick, event) pairs."""
    role = np.asarray(states.role)
    term = np.asarray(states.term)
    commit = np.asarray(states.commit_index)
    base = np.asarray(states.log_base)
    n_ticks, n = role.shape
    for t in range(1, n_ticks):
        for i in range(n):
            if role[t, i] == CANDIDATE and role[t - 1, i] != CANDIDATE:
                yield t, f"node {i} starts election for term {term[t, i]}"
            if role[t, i] == LEADER and role[t - 1, i] != LEADER:
                yield t, f"node {i} becomes leader of term {term[t, i]}"
            if role[t, i] != LEADER and role[t - 1, i] == LEADER:
                yield t, f"node {i} steps down (term {term[t - 1, i]} -> {term[t, i]})"
            if commit[t, i] > commit[t - 1, i]:
                yield t, f"node {i} commits through {commit[t, i]}"
            if base[t, i] > base[t - 1, i]:
                yield t, f"node {i} compacts through {base[t, i]}"
