"""Cross-backend trajectory parity: the accelerator must produce bit-identical
states and metrics to the CPU backend.

The test suite pins kernel/oracle/batched/sharded parity on CPU (conftest forces
the CPU platform), so hardware numerics -- int16/int8 arithmetic, uint32 wraparound
in the commit checksum, reduction orders -- are otherwise only validated indirectly
(on-device invariants holding during real-chip benches). This script runs the same
seeded simulations on the default (accelerator) backend and on CPU in a subprocess,
then compares every non-mailbox state leaf and every metric bit-for-bit.

Usage: python tools/tpu_parity_check.py      # exits nonzero on any mismatch
"""

from __future__ import annotations

import os
import subprocess
import sys

import numpy as np

# Runnable from anywhere: the package lives at the repo root (tools/..).
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

CONFIGS = {
    # name -> (RaftConfig kwargs, seed, batch, ticks)
    "reliable+client": (dict(n_nodes=5, client_interval=8), 42, 64, 300),
    "kitchen-sink": (
        dict(
            n_nodes=9,
            log_capacity=16,
            client_interval=4,
            drop_prob=0.3,
            partition_period=32,
            partition_prob=0.5,
            crash_prob=0.3,
            crash_period=40,
            crash_down_ticks=15,
            clock_skew_prob=0.1,
            check_log_matching=True,
        ),
        77,
        32,
        400,
    ),
    "wide-n51": (
        dict(n_nodes=51, log_capacity=16, partition_period=32, partition_prob=0.5),
        7,
        8,
        200,
    ),
    # Ring compaction + snapshot catch-up + the 302-redirect client path with a
    # K-deep in-flight pipeline: wide (int32) index planes, absolute-index
    # checksums, [K] routing state.
    "compaction+redirect": (
        dict(
            n_nodes=5,
            log_capacity=16,
            compact_margin=8,
            max_entries_per_rpc=4,
            client_interval=2,
            client_redirect=True,
            client_pipeline=3,
            drop_prob=0.15,
            crash_prob=0.3,
            crash_period=32,
            crash_down_ticks=10,
        ),
        11,
        32,
        500,
    ),
    # PreVote probe rounds under churn (round 5): prospective-term wire fields,
    # packed per-edge grant bits (Mailbox.pv_grant), heard_clock arithmetic.
    "prevote-churn": (
        dict(
            n_nodes=5,
            log_capacity=8,
            client_interval=3,
            pre_vote=True,
            drop_prob=0.25,
            crash_prob=0.4,
            crash_period=16,
            crash_down_ticks=8,
        ),
        13,
        32,
        400,
    ),
}

_CPU_CODE = """
import json, sys
sys.path.insert(0, sys.argv[2])
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from raft_sim_tpu import RaftConfig
from raft_sim_tpu.sim import scan
kwargs, seed, batch, ticks, path = json.loads(sys.argv[1])
f, m = scan.simulate(RaftConfig(**kwargs), seed, batch, ticks)
z = np.load(path)
bad = [k for k, v in zip(f._fields, f) if k != "mailbox"
       and not np.array_equal(np.asarray(v), z["s_" + k])]
bad += [k for k, v in zip(m._fields, m)
        if not np.array_equal(np.asarray(v), z["m_" + k])]
print(json.dumps(bad))
"""


def main() -> int:
    import json
    import tempfile

    import jax

    from raft_sim_tpu import RaftConfig
    from raft_sim_tpu.sim import scan

    plat = jax.devices()[0].platform
    if plat == "cpu":
        print("no accelerator present (platform=cpu); nothing to compare")
        return 0

    failures = 0
    for name, (kwargs, seed, batch, ticks) in CONFIGS.items():
        f, m = scan.simulate(RaftConfig(**kwargs), seed, batch, ticks)
        tmp = tempfile.NamedTemporaryFile(suffix=".npz", delete=False)
        try:
            np.savez(
                tmp.name,
                **{f"s_{k}": np.asarray(v) for k, v in zip(f._fields, f) if k != "mailbox"},
                **{f"m_{k}": np.asarray(v) for k, v in zip(m._fields, m)},
            )
            arg = json.dumps([kwargs, seed, batch, ticks, tmp.name])
            try:
                r = subprocess.run(
                    [sys.executable, "-c", _CPU_CODE, arg, _ROOT],
                    capture_output=True,
                    text=True,
                    timeout=600,
                )
            except subprocess.TimeoutExpired:
                print(f"{name}: CPU subprocess timed out (600s)")
                failures += 1
                continue
        finally:
            tmp.close()
            os.unlink(tmp.name)
        if r.returncode != 0:
            print(f"{name}: CPU subprocess failed:\n{r.stderr[-500:]}")
            failures += 1
            continue
        bad = json.loads(r.stdout.strip().splitlines()[-1])
        status = f"MISMATCH in {bad}" if bad else "OK"
        print(f"{name} ({plat} vs cpu): {status}")
        failures += bool(bad)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
