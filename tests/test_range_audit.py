"""Pass E (value-range abstract interpretation) coverage.

One seeded negative per rule -- each must be caught NAMING the rule and the
leg/site, so a regression in the interpreter cannot silently stop a gate
from firing -- plus the waiver round trip, the derivation-failure
visibility contract (a pass that cannot derive must say so, never pass
silently), and the gate-status + runtime-budget pin on HEAD.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import pytest
from jax import lax

from raft_sim_tpu.analysis import jaxpr_audit, policy, range_audit, run
from raft_sim_tpu.analysis import findings as F
from raft_sim_tpu.ops import tile
from raft_sim_tpu.utils.config import PRESETS

CFG3 = PRESETS["config3"][0]


def _program(name: str, prog: str = "simulate"):
    cfg, _batch = PRESETS[name]
    for program, closed, kind, rule_cfg in jaxpr_audit.programs(name, cfg):
        if program.endswith("/" + prog):
            return closed, kind, rule_cfg
    raise AssertionError(f"{name} has no {prog} program")


def _hits(findings, rule: str, needle: str):
    return [f for f in findings if f.rule == rule and needle in f.message]


# ---------------------------------------------------- seeded negatives (one
# per rule: the gate must name the rule AND the offending leg/site)


def test_seeded_widened_index_leg_fires_dtype_overflow():
    # Widen an index plane's declared range past its int8 plane: the scan
    # seeding must refuse the axiom and name the leg.
    closed, kind, cfg = _program("config3")
    declared = dict(policy.declared_ranges(cfg))
    assert "next_index" in declared
    declared["next_index"] = (1, 200)  # int8 plane tops out at 127
    finds, _rec = range_audit.audit_program(
        "range:seeded/simulate", closed, kind, cfg, declared=declared)
    hits = _hits(finds, "range-dtype-overflow", "`next_index`")
    assert hits, [f"{f.rule}: {f.message}" for f in finds]
    assert "does not fit" in hits[0].message


def test_seeded_pack_width_shrunk_one_bit_fires():
    cfg, _batch = PRESETS["config5c"]
    widths = dict(tile.pack_width_table(cfg))
    assert range_audit.check_pack_widths(cfg, "config5c") == []
    bits, bias, lo, hi = widths["ack_age"]
    widths["ack_age"] = (bits - 1, bias, lo, hi)  # 120 no longer fits 6 bits
    finds = range_audit.check_pack_widths(cfg, "config5c", widths=widths)
    hits = _hits(finds, "range-pack-width", "`ack_age`")
    assert hits and "does not fit" in hits[0].message


def test_seeded_unclipped_take_along_axis_fires_index_oob():
    def f(x):
        i = jnp.full((3,), 9, jnp.int32)  # provably outside operand extent 8
        return jnp.take_along_axis(x, i, axis=0, mode="promise_in_bounds")

    closed = jax.make_jaxpr(f)(jax.ShapeDtypeStruct((8,), jnp.int32))
    finds: list = []
    interp = range_audit._Interp(
        "range:seeded/oob", CFG3, declared={}, leg_names=None,
        target_nk=None, invariant=frozenset(), findings=finds)
    interp.eval_closed(
        closed, [range_audit._top(v.aval) for v in closed.jaxpr.invars])
    hits = _hits(finds, "range-index-oob", "promise")
    assert hits, [f"{f.rule}: {f.message}" for f in finds]


def test_seeded_stale_declared_range_fires_annotation_stale():
    # A declared range the initial state provably contradicts (the "comment
    # went stale" failure: code moved, annotation did not).
    closed, kind, cfg = _program("config3")
    declared = dict(policy.declared_ranges(cfg))
    assert "commit_index" in declared
    declared["commit_index"] = (5, 9)  # initial commit index is 0
    finds, _rec = range_audit.audit_program(
        "range:seeded/simulate", closed, kind, cfg, declared=declared)
    hits = _hits(finds, "range-annotation-stale", "`commit_index`")
    assert hits, [f"{f.rule}: {f.message}" for f in finds]
    assert "[5, 9]" in hits[0].message


def test_seeded_int16_term_leg_fires_horizon_below_soak():
    # A monotone protocol leg forced onto int16 wraps at 32767 -- far below
    # the 10M-tick soak budget; the horizon rule must fire naming the leg.
    def body(c, x):
        return c + jnp.int16(1), x

    def prog(xs):
        return lax.scan(body, jnp.int16(0), xs)

    closed = jax.make_jaxpr(prog)(jax.ShapeDtypeStruct((4,), jnp.int16))
    finds, rec = range_audit.audit_program(
        "range:seeded/horizon", closed, "scan", CFG3,
        declared={}, leg_names=["term"])
    hits = _hits(finds, "range-horizon", "`term`")
    assert hits, [f"{f.rule}: {f.message}" for f in finds]
    assert rec["term"]["rate"] == 1
    assert rec["term"]["horizon"] == 32767 < range_audit.SOAK_TICKS


# --------------------------------------------------- failure visibility


def test_missing_target_scan_is_visible_not_silent():
    def body(c, x):
        return c + jnp.int32(1), x

    def prog(xs):
        return lax.scan(body, jnp.int32(0), xs)

    closed = jax.make_jaxpr(prog)(jax.ShapeDtypeStruct((4,), jnp.int32))
    finds, rec = range_audit.audit_program(
        "range:seeded/miss", closed, "scan", CFG3,
        declared={}, leg_names=["a", "b"])  # no 2-leg carry exists
    assert rec is None
    assert _hits(finds, "range-golden", "NOT being checked")


def test_derivation_exception_is_visible_not_silent(monkeypatch):
    def boom(*a, **k):
        raise RuntimeError("seeded derivation failure")

    range_audit._derive_all.cache_clear()
    monkeypatch.setattr(range_audit, "audit_program", boom)
    try:
        _doc, finds = range_audit.derive_all(("config3",))
    finally:
        # Never leave the seeded-failure derivation in the shared cache.
        range_audit._derive_all.cache_clear()
    hits = _hits(finds, "range-golden", "NOT being checked")
    assert hits and "seeded derivation failure" in hits[0].message


# ------------------------------------------------------- waiver round trip


def test_range_waiver_round_trip():
    f = F.Finding(rule="range-dtype-overflow", path="range:config3/simulate",
                  message="carry leg `x`: proven interval exceeds int8")
    waivers = [{"rule": "range-dtype-overflow",
                "path": "range:config3/simulate",
                "contains": "`x`", "reason": "seeded"}]
    assert F.apply_waivers([f], waivers) == []
    assert f.waived and f.waiver_reason == "seeded"
    # Same waiver against a different leg: no match, reported stale.
    g = F.Finding(rule="range-dtype-overflow", path="range:config3/simulate",
                  message="carry leg `y`: proven interval exceeds int8")
    assert F.apply_waivers([g], waivers) == waivers
    assert not g.waived


def test_range_waivers_not_condemned_by_other_pass_runs(tmp_path):
    # Stale-waiver scoping: an AST-only run must not mark a range-rule
    # waiver stale (the range pass never got a chance to match it).
    p = tmp_path / "w.json"
    p.write_text(json.dumps({"schema_version": 1, "waivers": [{
        "rule": "range-dtype-overflow", "path": "range:config3/simulate",
        "reason": "scoping probe"}]}))
    found, unused, problems, timings = run.run_all(
        do_jaxpr=False, do_cost=False, do_race=False, do_range=False,
        waivers_path=str(p))
    assert problems == []
    assert set(timings) == {"ast"}
    assert unused == []


# ------------------------------------------------- gate status + budget


def test_range_pass_clean_on_head_within_budget():
    """HEAD derives, matches tests/golden_ranges.json, and stays inside the
    analyzer budget (lowerings are lru-shared with the jaxpr/cost passes, so
    this prices the interpreter + golden compare)."""
    t0 = time.monotonic()
    finds = range_audit.run_pass()
    elapsed = time.monotonic() - t0
    assert finds == [], "\n".join(
        f"{f.rule} {f.path}: {f.message}" for f in finds)
    assert elapsed < 60.0, f"range pass took {elapsed:.1f}s (budget 60s)"


def test_golden_pins_every_audited_tier_with_horizons():
    with open(range_audit.golden_path()) as fh:
        golden = json.load(fh)
    assert set(golden["tiers"]) == set(jaxpr_audit.AUDIT_CONFIGS)
    assert golden["soak_ticks"] == range_audit.SOAK_TICKS
    # config5c's pack widths ride the golden (the compact-plane contract).
    assert golden["tiers"]["config5c"]["pack_widths"] == {
        leg: list(w)
        for leg, w in tile.pack_width_table(PRESETS["config5c"][0]).items()}
    # Every monotone protocol leg's pinned horizon clears the soak budget.
    for name, tier in golden["tiers"].items():
        for leg, ent in tier["legs"].items():
            if ent.get("horizon") is not None and range_audit._protocol_leg(leg):
                assert ent["horizon"] >= range_audit.SOAK_TICKS, (name, leg)
