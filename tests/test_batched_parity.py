"""Batch-minor kernel parity: models/raft_batched.step_b must match vmap(raft.step)
bit-for-bit (which transitively pins it to the scalar oracle via
tests/test_oracle_parity.py)."""

import jax
import numpy as np
import pytest

from raft_sim_tpu import RaftConfig, init_batch
from raft_sim_tpu.models import raft, raft_batched
from raft_sim_tpu.sim import faults, scan

# Budget note (round 11): the per-tick lockstep rows are the suite's most
# expensive family (~20-36s each), and every config below is ALSO pinned
# against the scalar oracle every tick in tests/test_oracle_parity.py, which
# stays tier-1 in full (plus test_scenario's homogeneous-genome bit-exactness
# pinning the batched scan path). Tier-1 keeps the plain row; the fault-mix
# rows ride the slow tier (870s budget, ROADMAP.md).
CONFIGS = [
    pytest.param(RaftConfig(n_nodes=5, client_interval=8), id="n5"),
    pytest.param(
        RaftConfig(
            n_nodes=7,
            log_capacity=6,
            max_entries_per_rpc=2,
            client_interval=2,
            drop_prob=0.3,
            clock_skew_prob=0.2,
            check_log_matching=True,
        ),
        id="n7-faults",
        marks=pytest.mark.slow,
    ),
    pytest.param(
        RaftConfig(
            n_nodes=5,
            log_capacity=8,
            client_interval=4,
            drop_prob=0.1,
            crash_prob=0.5,
            crash_period=20,
            crash_down_ticks=10,
        ),
        id="n5-crashes",
        marks=pytest.mark.slow,
    ),
    pytest.param(
        RaftConfig(
            n_nodes=5,
            log_capacity=8,
            compact_margin=4,
            max_entries_per_rpc=2,
            client_interval=1,
            drop_prob=0.2,
            crash_prob=0.5,
            crash_period=20,
            crash_down_ticks=12,
            check_log_matching=True,
        ),
        id="n5-compaction-snap",  # ring wrap + rebase + InstallSnapshot sentinel,
        # wide (int32) index planes, ring-aware log-matching check
        marks=pytest.mark.slow,
    ),
    pytest.param(
        RaftConfig(
            n_nodes=5,
            log_capacity=8,
            compact_margin=4,
            client_interval=2,
            client_redirect=True,
            drop_prob=0.2,
            crash_prob=0.4,
            crash_period=16,
            crash_down_ticks=8,
        ),
        id="n5-redirect-compaction",  # 302 routing state + latency metric riding
        # the compaction ring
        marks=pytest.mark.slow,
    ),
    pytest.param(
        RaftConfig(
            n_nodes=5,
            log_capacity=8,
            compact_margin=4,
            client_interval=1,
            client_redirect=True,
            client_pipeline=4,
            drop_prob=0.2,
            crash_prob=0.4,
            crash_period=16,
            crash_down_ticks=8,
        ),
        id="n5-redirect-pipeline",  # K = 4 in-flight slots ([K, B] client state)
        marks=pytest.mark.slow,
    ),
    pytest.param(
        RaftConfig(
            n_nodes=5,
            log_capacity=8,
            client_interval=3,
            pre_vote=True,
            drop_prob=0.25,
            crash_prob=0.4,
            crash_period=16,
            crash_down_ticks=8,
        ),
        id="n5-prevote",  # thesis-9.6 probe rounds under churn
        marks=pytest.mark.slow,
    ),
    pytest.param(
        RaftConfig(
            n_nodes=5,
            log_capacity=8,
            client_interval=1,
            reconfig_interval=3,
            drop_prob=0.25,
            partition_period=8,
            partition_prob=0.8,
            crash_prob=0.5,
            crash_period=14,
            crash_down_ticks=8,
        ),
        id="n5-reconfig-truncation",  # log-carried configs under partition +
        # crash churn: per-node derived member rows diverging and rolling
        # back with truncations must match the vmap kernel bit-for-bit.
        # Slow tier (budget re-tier, ISSUE 14 -- the PR 6 convention): the
        # oracle pins the vmap form on the same config/seed family EVERY
        # tick in tier-1's test_oracle_parity.py (its n5-reconfig-truncation
        # row), the plain n5 batched row stays tier-1, and the homogeneous-
        # genome bit-exactness test pins the batched scan path.
        marks=pytest.mark.slow,
    ),
    pytest.param(
        RaftConfig(
            n_nodes=5,
            log_capacity=8,
            compact_margin=4,
            client_interval=1,
            reconfig_interval=5,
            drop_prob=0.2,
            crash_prob=0.5,
            crash_period=20,
            crash_down_ticks=12,
        ),
        id="n5-reconfig-compaction",  # config entries compacting away:
        # fold_span snapshot-context advance + req_base_mold install on
        # snapshot catch-up, vs the vmap kernel
        marks=pytest.mark.slow,
    ),
]


def tree_eq(a, b):
    for x, y in zip(jax.tree.leaves(jax.device_get(a)), jax.tree.leaves(jax.device_get(b))):
        np.testing.assert_array_equal(x, y)


@pytest.mark.parametrize("cfg", CONFIGS)
def test_step_parity_along_trajectory(cfg):
    """Step both kernels in lockstep from the same start for 120 ticks; states and
    StepInfo must agree exactly at every tick (covers elections, replication, faults,
    injection, timers as they actually occur)."""
    batch = 16
    key = jax.random.key(0)
    k_init, k_run = jax.random.split(key)
    state = init_batch(cfg, k_init, batch)
    keys = jax.random.split(k_run, batch)

    vstep = jax.jit(jax.vmap(lambda s, i: raft.step(cfg, s, i)))
    bstep = jax.jit(lambda s, i: raft_batched.step_b(cfg, s, i))

    s_lead = state
    s_min = raft_batched.to_batch_minor(state)
    for t in range(120):
        inp = jax.vmap(lambda k, now: faults.make_inputs(cfg, k, now))(keys, s_lead.now)
        s_lead, info_lead = vstep(s_lead, inp)
        s_min, info_min = bstep(s_min, raft_batched.to_batch_minor(inp))
        tree_eq(s_lead, raft_batched.from_batch_minor(s_min))
        # StepInfo rides batch-minor too (the histogram leaf is [BINS, B] there).
        tree_eq(info_lead, raft_batched.from_batch_minor(info_min))


def test_run_batch_minor_matches_run_batch():
    cfg = RaftConfig(n_nodes=5, client_interval=8, drop_prob=0.1)
    batch = 32
    key = jax.random.key(3)
    k_init, k_run = jax.random.split(key)
    state = init_batch(cfg, k_init, batch)
    keys = jax.random.split(k_run, batch)

    f_ref, m_ref, _ = jax.jit(lambda s, k: scan.run_batch(cfg, s, k, 250))(state, keys)
    f_min, m_min = jax.jit(lambda s, k: scan.run_batch_minor(cfg, s, k, 250))(state, keys)
    tree_eq(f_ref, f_min)
    tree_eq(m_ref, m_min)
