"""Node-axis sharding: one giant-N cluster partitioned row-wise across a mesh.

`parallel/mesh.py` shards the embarrassingly-parallel CLUSTER axis -- a whole
cluster's `[N, N]` planes must fit one chip, which the cost model prices out of
HBM well before N=255. This module adds the second mesh axis: the node rows of
every per-node array (the `[N, N]` bookkeeping planes, `[N, CAP]` logs, `[N]`
headers, and the writer-major mailbox) are partitioned by RECEIVER node over a
2-D `("clusters", "nodes")` mesh, the megatron move applied to the tick kernel
-- a cluster bigger than one box lives across ICI instead of across OS
processes (the reference's one-process-per-node deployment, core.clj:197-203).

Layout rules (docs/DESIGN.md "Node-axis sharding"):

- Every per-node array is partitioned on its FIRST node axis -- the axis whose
  rows the owning node WRITES (state: the node itself; mailbox: the sender for
  request legs, the responder for response legs). Second node axes (the peer
  axis of `[N, N]` planes) stay local and padded to `n_pad`.
- The node axis pads to `n_pad = n_shards * ceil(N / n_shards)`. Pad rows are
  permanently dead nodes: `alive=False` every tick, delivery masks all-zero,
  so they freeze at init values; the kernel masks the handful of reductions a
  pad row could otherwise skew (models/raft_batched.py, `pad_self` and the
  sentinel mins). The packed word count is unchanged by padding
  (`n_words(n_pad) == n_words(n)` whenever the shard count divides 32 --
  asserted below), so bitplane words need no relayout.
- The hot loop's only collectives are ONE tiled `all_gather` of the outbound
  mailbox over the `nodes` axis (the per-sender broadcast headers plus the
  narrow per-edge WIRE legs -- req_off offsets and resp_kind responses, the
  protocol's actual point-to-point traffic -- reoriented from their
  writer-major carry), the `psum`/`pmin`/`pmax` folds of the per-cluster `[B]`
  metric reductions, and -- only under `check_invariants` -- one `[n_pad, B]`
  leaders-by-term gather for the election-safety pair check. Delivery, quorum
  popcounts, and commit advancement read the gathered row locally; the wide
  `[N, N]` BOOKKEEPING planes (next_index / match_index / ack_age) never
  cross ICI. Asserted by the collective-whitelist audit
  (analysis/jaxpr_audit.node_collectives, tests/test_nodeshard.py).
- Inputs are drawn redundantly on every device from the same per-cluster key
  stream (sim/faults.make_inputs is pure in (cfg, key, now)), then padded:
  zero communication, and trajectories are bit-identical to the unsharded
  kernel at any device count (tests/test_nodeshard.py).

Unsupported surfaces (v1): the log-carried reconfiguration plane, leader
transfer, ReadIndex/lease reads, client redirect routing, and the O(N^2 * CAP)
log-matching invariant -- each needs either per-edge state the header gather
does not carry or a pad-hostile reduction. `simulate_node_sharded` raises a
ValueError naming the offending gate. `compact_planes` configs run the
sharded carry DENSE internally (the bit-packed flat layout and the row
partition compose poorly; trajectories are identical either way --
types.compact_twin).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from raft_sim_tpu.models import raft_batched
from raft_sim_tpu.models.raft_batched import NodeShardCtx
from raft_sim_tpu.ops import bitplane
from raft_sim_tpu.parallel import mesh as mesh_mod
from raft_sim_tpu.sim import faults, scan
from raft_sim_tpu.types import (
    FOLLOWER,
    NIL,
    ClusterState,
    Mailbox,
    StepInputs,
    compact_twin,
    init_batch,
)
from raft_sim_tpu.utils.config import RaftConfig

AXIS = mesh_mod.AXIS  # "clusters": the batch axis, as in parallel/mesh.py
NODE_AXIS = "nodes"

# Per-field pad spec: (node axes of the UNBATCHED leaf, pad fill value).
# Fill values mirror types.init_state -- a pad row is a node frozen at boot
# (the fills are documentation more than load-bearing: a dead node's rows are
# never read into a real node's trajectory except through the masked
# reductions the kernel guards; see module docstring). Callables take cfg.
_STATE_PAD = {
    "role": ((0,), FOLLOWER),
    "term": ((0,), 1),
    "voted_for": ((0,), NIL),
    "leader_id": ((0,), NIL),
    "votes": ((0,), 0),
    "next_index": ((0, 1), 1),
    "match_index": ((0, 1), 0),
    "ack_age": ((0, 1), lambda cfg: cfg.ack_age_sat),
    "commit_index": ((0,), 0),
    "commit_chk": ((0,), 0),
    "log_base": ((0,), 0),
    "base_term": ((0,), 0),
    "base_chk": ((0,), 0),
    "log_term": ((0,), 0),
    "log_val": ((0,), 0),
    "log_tick": ((0,), 0),
    "log_len": ((0,), 0),
    "dur_len": ((0,), 0),
    "dur_term": ((0,), 1),
    "dur_vote": ((0,), NIL),
    "clock": ((0,), 0),
    "deadline": ((0,), 0),  # expiry is gated on alive: any value is inert
    "heard_clock": ((0,), lambda cfg: -cfg.election_min_ticks),
    "member_old": ((0,), 0),
    "member_new": ((0,), 0),
    "cfg_epoch": ((0,), 0),
    "cfg_pend": ((0,), 0),
    "log_cfg": ((0,), 0),
    "base_mold": ((0,), 0),
    "base_pend": ((0,), 0),
    "base_epoch": ((0,), 0),
    "xfer_to": ((0,), NIL),
    "read_idx": ((0,), 0),
    "read_tick": ((0,), 0),
    "read_acks": ((0,), 0),
    "read_fr": ((0,), 0),
    "client_pend": ((), 0),
    "client_dst": ((), 0),
    "client_tick": ((), 0),
    "lat_frontier": ((), 0),
    "now": ((), 0),
}

_MAILBOX_PAD = {
    "req_type": ((0,), 0),
    "req_term": ((0,), 0),
    "req_commit": ((0,), 0),
    "req_last_index": ((0,), 0),
    "req_last_term": ((0,), 0),
    "ent_start": ((0,), 0),
    "ent_prev_term": ((0,), 0),
    "ent_count": ((0,), 0),
    "ent_term": ((0,), 0),
    "ent_val": ((0,), 0),
    "ent_tick": ((0,), 0),
    "req_base": ((0,), 0),
    "req_base_term": ((0,), 0),
    "req_base_chk": ((0,), 0),
    "xfer_tgt": ((0,), NIL),
    "req_disrupt": ((0,), 0),
    "ent_cfg": ((0,), 0),
    "req_base_mold": ((0,), 0),
    "req_base_pend": ((0,), 0),
    "req_base_epoch": ((0,), 0),
    "req_off": ((0, 1), 0),
    "resp_kind": ((0, 1), 0),
    "pv_grant": ((0,), 0),
    "v_to": ((0,), NIL),
    "a_ok_to": ((0,), NIL),
    "a_match": ((0,), 0),
    "a_hint": ((0,), 0),
    "resp_term": ((0,), 0),
}

_INPUT_PAD = {
    "deliver_mask": ((0,), 0),
    "skew": ((0,), 0),
    "timeout_draw": ((0,), 0),
    "client_cmd": ((), 0),
    "client_target": ((), 0),
    "client_bounce": ((), 0),
    "alive": ((0,), False),
    "restarted": ((0,), False),
    "reconfig_cmd": ((), 0),
    "transfer_cmd": ((), 0),
    "read_cmd": ((), 0),
    "fsync_fire": ((0,), False),
    "torn_drop": ((0,), 0),
}

# A new state/mailbox/input leg without a pad rule would silently corrupt the
# sharded path; fail at import instead.
assert set(_STATE_PAD) | {"mailbox"} == set(ClusterState._fields)
assert set(_MAILBOX_PAD) == set(Mailbox._fields)
assert set(_INPUT_PAD) == set(StepInputs._fields)


def _pad_leaf(x, axes, fill, pad_n: int, lead: int):
    if not axes or not pad_n:
        return x
    widths = [(0, 0)] * x.ndim
    for ax in axes:
        widths[ax + lead] = (0, pad_n)
    return jnp.pad(x, widths, constant_values=np.asarray(fill).astype(x.dtype))


def _pad_tree(cfg: RaftConfig, tree, table, pad_n: int, lead: int) -> dict:
    out = {}
    for f, (axes, fill) in table.items():
        fill_v = fill(cfg) if callable(fill) else fill
        out[f] = _pad_leaf(getattr(tree, f), axes, fill_v, pad_n, lead)
    return out


def pad_state(cfg: RaftConfig, state: ClusterState, n_pad: int, lead: int = 1):
    """Pad every node axis of a (batch-leading when lead=1) dense state from
    n_nodes to n_pad with the boot fills above. The packed-word axes need no
    padding (n_words is unchanged -- see module docstring)."""
    pad_n = n_pad - cfg.n_nodes
    kw = _pad_tree(cfg, state, _STATE_PAD, pad_n, lead)
    kw["mailbox"] = Mailbox(**_pad_tree(cfg, state.mailbox, _MAILBOX_PAD, pad_n, lead))
    return ClusterState(**kw)


def pad_inputs(cfg: RaftConfig, inp: StepInputs, n_pad: int, lead: int = 1):
    """Pad per-node input legs to n_pad: pad nodes are dead (alive=False) with
    all-zero delivery rows, which is what freezes them (module docstring)."""
    return StepInputs(**_pad_tree(cfg, inp, _INPUT_PAD, n_pad - cfg.n_nodes, lead))


def unshard_state(cfg: RaftConfig, state: ClusterState) -> ClusterState:
    """Padded writer-major sharded final state (batch-leading) -> the dense
    [B, N, ...] form `scan.simulate` returns: slice the node axes back to
    n_nodes and reorient the two transposed mailbox carry legs."""
    n = cfg.n_nodes
    n_pad = state.role.shape[1]

    def cut(x, axes, lead=1):
        for ax in axes:
            x = lax.slice_in_dim(x, 0, n, axis=ax + lead)
        return x

    kw = {f: cut(getattr(state, f), axes) for f, (axes, _) in _STATE_PAD.items()}
    mkw = {
        f: cut(getattr(state.mailbox, f), axes)
        for f, (axes, _) in _MAILBOX_PAD.items()
    }
    # The sharded carry stores responder-major response planes; the dense
    # convention is receiver-major (models/raft_batched._gather_mailbox).
    mkw["resp_kind"] = cut(jnp.swapaxes(state.mailbox.resp_kind, 1, 2), (0, 1))
    if cfg.pre_vote:
        pv = bitplane.unpack(state.mailbox.pv_grant, n_pad, axis=2)  # [B, voter, cand]
        mkw["pv_grant"] = bitplane.pack(
            cut(jnp.swapaxes(pv, 1, 2), (0, 1)), axis=2
        )
    kw["mailbox"] = Mailbox(**mkw)
    return ClusterState(**kw)


def _spec_tree(table, extra: dict | None = None) -> dict:
    specs = {
        f: P(AXIS, NODE_AXIS) if 0 in axes else P(AXIS)
        for f, (axes, _) in table.items()
    }
    if extra:
        specs.update(extra)
    return specs


def state_specs() -> ClusterState:
    """shard_map partition specs for a batch-leading padded state: batch over
    "clusters", first node axis over "nodes", everything else local."""
    return ClusterState(
        **_spec_tree(_STATE_PAD, {"mailbox": Mailbox(**_spec_tree(_MAILBOX_PAD))})
    )


def metrics_specs() -> scan.RunMetrics:
    """RunMetrics leave the shard body replicated over the node axis (every
    fold ends in a psum/pmin/pmax): sharded over "clusters" only."""
    return scan.RunMetrics(*([P(AXIS)] * len(scan.RunMetrics._fields)))


def check_shardable(cfg: RaftConfig, n_shards: int) -> int:
    """Validate cfg against the v1 node-sharded surface and return n_pad."""
    unsupported = [
        name
        for name, on in [
            ("reconfig", cfg.reconfig),
            ("leader_transfer", cfg.leader_transfer),
            ("read_index", cfg.read_index),
            ("read_lease", cfg.read_lease),
            ("durable_storage", cfg.durable_storage),
            ("client_redirect", cfg.client_redirect),
            ("check_log_matching", cfg.check_log_matching),
        ]
        if on
    ]
    if unsupported:
        raise ValueError(
            f"node sharding does not support {unsupported} (v1 surface; "
            "see parallel/nodeshard.py module docstring)"
        )
    n = cfg.n_nodes
    nl = -(-n // n_shards)
    n_pad = n_shards * nl
    if bitplane.n_words(n_pad) != bitplane.n_words(n):
        raise ValueError(
            f"padding N={n} to {n_pad} over {n_shards} shards crosses a packed "
            "word boundary (n_words changes); use a shard count dividing 32"
        )
    return n_pad


def make_node_mesh(
    n_node_shards: int | None = None, n_cluster_shards: int = 1, devices=None
) -> Mesh:
    """2-D ("clusters", "nodes") mesh: batch over the first axis, node rows
    over the second. Defaults to all devices on the node axis."""
    if devices is None:
        devices = jax.devices()
    if n_node_shards is None:
        n_node_shards = len(devices) // n_cluster_shards
    need = n_cluster_shards * n_node_shards
    if need > len(devices):
        raise ValueError(
            f"mesh {n_cluster_shards}x{n_node_shards} needs {need} devices, "
            f"only {len(devices)} available"
        )
    arr = np.asarray(devices[:need]).reshape(n_cluster_shards, n_node_shards)
    return Mesh(arr, (AXIS, NODE_AXIS))


def _shard_ctx(nl: int, n_pad: int) -> NodeShardCtx:
    return NodeShardCtx(
        axis=NODE_AXIS,
        nl=nl,
        n_pad=n_pad,
        row0=lax.axis_index(NODE_AXIS).astype(jnp.int32) * nl,
    )


def _run_shard(cfg: RaftConfig, n_ticks: int, nl: int, n_pad: int, state, keys):
    """Per-device body: scan the local node rows of every cluster shard.
    Mirrors scan.run_batch_minor's body with the sharded step kernel; inputs
    are drawn at the REAL n from the same keys on every device, then padded."""
    sh = _shard_ctx(nl, n_pad)
    batch = state.role.shape[0]
    s_t = raft_batched.to_batch_minor(state)
    m0 = raft_batched.to_batch_minor(scan.init_metrics_batch(batch))

    def body(carry, _):
        s, m = carry
        inp = jax.vmap(lambda k, now: faults.make_inputs(cfg, k, now))(keys, s.now)
        inp_t = raft_batched.to_batch_minor(pad_inputs(cfg, inp, n_pad))
        s2, info = raft_batched.step_b(cfg, s, inp_t, sh)
        m2 = scan._accumulate(m, info, s.now)
        return (s2, m2), None

    (final_t, metrics), _ = lax.scan(body, (s_t, m0), None, length=n_ticks)
    return (
        raft_batched.from_batch_minor(final_t),
        raft_batched.from_batch_minor(metrics),
    )


@functools.partial(jax.jit, static_argnums=(0, 2, 3, 4))
def simulate_node_sharded(
    cfg: RaftConfig, seed, batch: int, n_ticks: int, mesh: Mesh
):
    """`scan.simulate` with the node axis sharded over `mesh`'s "nodes" axis
    (and the batch over "clusters"). Returns (final_state, RunMetrics): the
    metrics and the `unshard_state` view of the final state are bit-identical
    to the unsharded run for the same (cfg, seed, batch, n_ticks) at any mesh
    shape (tests/test_nodeshard.py). The returned state is PADDED writer-major
    [B, n_pad, ...] -- pass it through `unshard_state` for the dense view."""
    cfg = compact_twin(cfg, False)  # sharded carries run dense (module docstring)
    n_shards = mesh.shape[NODE_AXIS]
    n_pad = check_shardable(cfg, n_shards)
    nl = n_pad // n_shards
    if batch % mesh.shape[AXIS]:
        raise ValueError(
            f"batch {batch} must divide over {mesh.shape[AXIS]} cluster shards"
        )
    root = jax.random.key(seed)
    k_init, k_run = jax.random.split(root)
    state = pad_state(cfg, init_batch(cfg, k_init, batch), n_pad)
    keys = mesh_mod._constrain_keys(jax.random.split(k_run, batch), mesh)

    sharded = mesh_mod._shard_map(
        functools.partial(_run_shard, cfg, n_ticks, nl, n_pad),
        mesh=mesh,
        in_specs=(state_specs(), P(AXIS)),
        out_specs=(state_specs(), metrics_specs()),
    )
    return sharded(state, keys)


def _run_shard_windowed(
    cfg: RaftConfig, n_ticks: int, window: int, nl: int, n_pad: int, state, keys
):
    """Windowed per-device body: telemetry.run_batch_minor_telemetry's nested
    scan (window metrics + first_viol_tick; no recorder/trace legs) over the
    sharded step -- window records come out bit-identical to the unsharded
    `simulate_windowed` (tests/test_nodeshard.py)."""
    from raft_sim_tpu.sim.chunked import merge_metrics
    from raft_sim_tpu.sim.telemetry import NEVER, WindowRecord

    sh = _shard_ctx(nl, n_pad)
    batch = state.role.shape[0]
    s_t = raft_batched.to_batch_minor(state)
    m0 = raft_batched.to_batch_minor(scan.init_metrics_batch(batch))

    def tick(carry, _):
        s, wm, fv = carry
        now = s.now
        inp = jax.vmap(lambda k, nw: faults.make_inputs(cfg, k, nw))(keys, now)
        inp_t = raft_batched.to_batch_minor(pad_inputs(cfg, inp, n_pad))
        s2, info = raft_batched.step_b(cfg, s, inp_t, sh)
        wm2 = scan._accumulate(wm, info, now)
        fv2 = jnp.minimum(fv, jnp.where(scan.step_bad(info), now, NEVER))
        return (s2, wm2, fv2), None

    def outer(carry, _):
        s, m = carry
        start = s.now
        fv0 = jnp.full((batch,), NEVER, jnp.int32)
        (s2, wm, fv), _ = lax.scan(tick, (s, m0, fv0), None, length=window)
        out = WindowRecord(start=start, first_viol_tick=fv, metrics=wm)
        return (s2, merge_metrics(m, wm)), out

    (final_t, metrics), recs = lax.scan(
        outer, (s_t, m0), None, length=n_ticks // window
    )
    return (
        raft_batched.from_batch_minor(final_t),
        raft_batched.from_batch_minor(metrics),
        raft_batched.from_batch_minor(recs),
    )


@functools.partial(jax.jit, static_argnums=(0, 2, 3, 4, 5))
def simulate_node_sharded_windowed(
    cfg: RaftConfig, seed, batch: int, n_ticks: int, window: int, mesh: Mesh
):
    """`telemetry.simulate_windowed` (no recorder / trace plane) with the node
    axis sharded: returns (final_state, metrics, records), records in the
    public [B, n_windows, ...] layout and bit-identical to the unsharded
    windowed run. n_ticks must divide by window."""
    from raft_sim_tpu.sim.telemetry import WindowRecord

    if n_ticks % window:
        raise ValueError(f"n_ticks {n_ticks} must divide by window {window}")
    cfg = compact_twin(cfg, False)
    n_shards = mesh.shape[NODE_AXIS]
    n_pad = check_shardable(cfg, n_shards)
    nl = n_pad // n_shards
    if batch % mesh.shape[AXIS]:
        raise ValueError(
            f"batch {batch} must divide over {mesh.shape[AXIS]} cluster shards"
        )
    root = jax.random.key(seed)
    k_init, k_run = jax.random.split(root)
    state = pad_state(cfg, init_batch(cfg, k_init, batch), n_pad)
    keys = mesh_mod._constrain_keys(jax.random.split(k_run, batch), mesh)

    rec_specs = WindowRecord(
        start=P(AXIS), first_viol_tick=P(AXIS), metrics=metrics_specs()
    )
    sharded = mesh_mod._shard_map(
        functools.partial(_run_shard_windowed, cfg, n_ticks, window, nl, n_pad),
        mesh=mesh,
        in_specs=(state_specs(), P(AXIS)),
        out_specs=(state_specs(), metrics_specs(), rec_specs),
    )
    return sharded(state, keys)
