from raft_sim_tpu.parallel.mesh import (
    AXIS,
    FleetSummary,
    gather_metrics,
    init_distributed,
    make_mesh,
    simulate_sharded,
    summarize,
)

__all__ = [
    "AXIS",
    "FleetSummary",
    "gather_metrics",
    "init_distributed",
    "make_mesh",
    "simulate_sharded",
    "summarize",
]
