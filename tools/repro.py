"""Violation repro/shrink tool (SURVEY.md section 4: fuzz cases must shrink).

A fuzz run reports `violations > 0` as one integer across up to 100k clusters x
millions of ticks. This tool isolates the needle: it re-runs the SAME seeded
simulation in chunks (trajectories are pure functions of (seed, cfg), so nothing
need be saved from the original run), stops at the first chunk containing a
violation, picks the first offending cluster, re-runs just that cluster with full
per-tick tracing to find the exact first violating tick, and emits

  - (cluster, tick, violation kinds),
  - the decoded event log around the violation (sim/trace.py -- the reference's
    println trail, core.clj:182-186, for exactly the window that matters),
  - per-node state lines at the violation tick, and
  - a standalone CLI command that replays the offending cluster with events.

Usage:
    python tools/repro.py --preset config4 --seed 7 --ticks 20000 [--batch N]
    python tools/repro.py --n-nodes 5 --drop-prob 0.3 --seed 3 --ticks 5000
    python tools/repro.py --scenario repro.json   # replay a shrunk artifact

Exits 0 printing {"found": false} when the run is clean. Library entry:
`shrink(cfg, seed, batch, n_ticks)` -- tests/test_repro.py demonstrates it
against an artificially broken kernel (quorum - 1).

`--scenario` replays a scenario-engine repro artifact
(raft_sim_tpu/scenario/shrink.py, `scenario shrink --out`): it rebuilds the
exact kernel (including TEST-ONLY mutants), reruns the minimized (genome,
seed) at the trimmed horizon, and exits 0 iff the violation reproduces at
the IDENTICAL tick with identical kinds -- the CI scenario smoke contract.

`--corpus DIR` batch-replays EVERY artifact in a corpus directory in one
process (one jax import; same-shape artifacts share the replay compile via
scenario/shrink.py's jitted-replay cache), printing one JSON line per
artifact and exiting nonzero NAMING THE FIRST DRIFTING ARTIFACT -- the one
command tier-1's tests/test_corpus.py and CI both converge on.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

from raft_sim_tpu import init_batch
from raft_sim_tpu.sim import chunked, scan, trace
from raft_sim_tpu.utils.config import PRESETS, RaftConfig

VIOL_FIELDS = (
    "viol_election_safety", "viol_commit", "viol_log_matching",
    "viol_read_stale",
)


def shrink(
    cfg: RaftConfig,
    seed: int,
    batch: int,
    n_ticks: int,
    chunk: int = 512,
    context: int = 30,
) -> dict | None:
    """Isolate the first violating (cluster, tick) of a seeded run.

    Returns None when no violation occurs within n_ticks; otherwise a dict with
    cluster, tick, kinds, events (list of (tick, text) around the violation),
    state_lines (per-node dump at the violation tick), and repro_cmd.
    """
    root = jax.random.key(seed)
    k_init, k_run = jax.random.split(root)
    state = init_batch(cfg, k_init, batch)
    keys = jax.random.split(k_run, batch)

    done = 0
    while done < n_ticks:
        n = min(chunk, n_ticks - done)
        nxt_state, m = chunked._chunk(cfg, state, keys, n)
        viol = np.asarray(m.violations)
        if int(viol.sum()) == 0:
            state, done = nxt_state, done + n
            continue

        # First offending cluster; replay it alone from the chunk start with
        # full per-tick info + states (bit-identical to the batched run --
        # tests/test_batched_parity.py).
        cluster = int(np.argmax(viol > 0))
        one = jax.tree.map(lambda x: x[cluster], state)
        _, _, (infos, states) = jax.jit(
            lambda s, k: scan.run(cfg, s, k, n, trace_states=True)
        )(one, keys[cluster])
        kinds_by_tick = {
            f: np.asarray(getattr(infos, f)) for f in VIOL_FIELDS
        }
        bad = np.zeros(n, bool)
        for v in kinds_by_tick.values():
            bad |= v
        assert bad.any(), "batched run flagged a violation the replay did not"
        t_rel = int(np.argmax(bad))
        tick = done + t_rel
        kinds = [f for f, v in kinds_by_tick.items() if bool(v[t_rel])]

        events = [
            (done + t, e)
            for t, e in trace.events(states)
            if abs(t - t_rel) <= context
        ]
        n_nodes = cfg.n_nodes
        state_lines = [trace.node_line(states, t_rel, i) for i in range(n_nodes)]
        return {
            "cluster": cluster,
            "tick": tick,
            "kinds": kinds,
            "chunk_start": done,
            "events": events,
            "state_lines": state_lines,
            "repro_cmd": _repro_cmd(cfg, seed, batch, tick),
        }
    return None


def _repro_cmd(cfg: RaftConfig, seed: int, batch: int, tick: int) -> str:
    """A standalone CLI line replaying the run up to just past the violation."""
    flags = []
    for f in dataclasses.fields(RaftConfig):
        v = getattr(cfg, f.name)
        if v != f.default:
            flag = "--" + f.name.replace("_", "-")
            flags.append(f"{flag} {v}")
    return (
        f"python -m raft_sim_tpu run --seed {seed} --batch {batch} "
        f"--ticks {tick + 1} " + " ".join(flags)
    )


def replay_scenario(path: str, context: int) -> int:
    """Replay a scenario repro artifact; 0 = reproduced at the identical tick."""
    from raft_sim_tpu.scenario import shrink as shrink_mod

    art = shrink_mod.load_artifact(path)
    res = shrink_mod.replay_artifact(art, context=context)
    print(json.dumps({
        "found": res["tick"] is not None,
        "reproduced": res["reproduced"],
        "tick": res["tick"],
        "expected_tick": res["expected_tick"],
        "kinds": res["kinds"],
        "expected_kinds": res["expected_kinds"],
        "mutant": art.get("mutant"),
        "segments": art.get("segments"),
    }))
    for t, e in res["events"]:
        marker = " <== VIOLATION TICK" if t == res["tick"] else ""
        print(f"tick {t:>7}  {e}{marker}", file=sys.stderr)
    return 0 if res["reproduced"] else 2


def replay_corpus(directory: str) -> int:
    """Replay every corpus artifact; 0 = all reproduced bit-exactly, 2 = the
    first drifting artifact (named on stderr AND in the summary line)."""
    import glob

    from raft_sim_tpu.scenario import shrink as shrink_mod

    paths = sorted(glob.glob(os.path.join(directory, "*.json")))
    if not paths:
        print(json.dumps({"corpus": directory, "error": "no artifacts"}))
        return 2
    for path in paths:
        name = os.path.basename(path)
        art = shrink_mod.load_artifact(path)
        res = shrink_mod.replay_artifact(art, context=0)
        print(json.dumps({
            "artifact": name,
            "reproduced": res["reproduced"],
            "tick": res["tick"],
            "expected_tick": res["expected_tick"],
            "kinds": res["kinds"],
            "expected_kinds": res["expected_kinds"],
            "mutant": art.get("mutant"),
        }))
        if not res["reproduced"]:
            print(f"corpus DRIFT: {name} (expected tick "
                  f"{res['expected_tick']} {res['expected_kinds']}, got "
                  f"{res['tick']} {res['kinds']})", file=sys.stderr)
            print(json.dumps({
                "corpus": directory, "artifacts": len(paths),
                "drifted": name,
            }))
            return 2
    print(json.dumps({
        "corpus": directory, "artifacts": len(paths), "reproduced": len(paths),
    }))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="repro")
    ap.add_argument("--preset", choices=sorted(PRESETS), default=None)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--ticks", type=int, default=None)
    ap.add_argument("--chunk", type=int, default=512)
    ap.add_argument("--context", type=int, default=30)
    ap.add_argument("--scenario", metavar="FILE", default=None,
                    help="replay a scenario repro artifact instead of "
                         "shrinking a scalar-config run (exit 0 iff the "
                         "violation reproduces at the identical tick)")
    ap.add_argument("--corpus", metavar="DIR", default=None,
                    help="batch-replay every artifact in a corpus directory "
                         "(tests/corpus); exit nonzero naming the first "
                         "drifting artifact")
    from raft_sim_tpu.driver import _add_config_flags, build_config

    _add_config_flags(ap)
    args = ap.parse_args(argv)
    if args.scenario and args.corpus:
        ap.error("--scenario and --corpus are exclusive")
    if args.corpus:
        return replay_corpus(args.corpus)
    if args.scenario:
        return replay_scenario(args.scenario, args.context)
    if args.ticks is None:
        ap.error("--ticks is required (unless replaying with --scenario)")
    cfg, batch = build_config(args)
    if args.batch is not None:
        batch = args.batch

    res = shrink(cfg, args.seed, batch, args.ticks, chunk=args.chunk,
                 context=args.context)
    if res is None:
        print(json.dumps({"found": False, "ticks": args.ticks, "batch": batch}))
        return 0
    events = res.pop("events")
    lines = res.pop("state_lines")
    print(json.dumps({"found": True, **res}))
    print(f"--- state at tick {res['tick']} (cluster {res['cluster']}) ---",
          file=sys.stderr)
    for ln in lines:
        print(ln, file=sys.stderr)
    print("--- events around the violation ---", file=sys.stderr)
    for t, e in events:
        marker = " <== VIOLATION TICK" if t == res["tick"] else ""
        print(f"tick {t:>7}  {e}{marker}", file=sys.stderr)
    return 1  # a violation is a failure condition for scripting


if __name__ == "__main__":
    sys.exit(main())
