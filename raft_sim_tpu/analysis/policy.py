"""The machine-readable form of the repo's dtype and carry-identity policy.

`types.py` states every invariant this package enforces, but states it in
prose: field comments like `# [N, N] index_dtype` carry the narrow-dtype
policy, the docstrings carry the "loop-invariant carry legs stay untouched"
rule (docs/PERF.md, round-4 lesson), and `utils/checkpoint.py`'s version log
carries the bump-on-field-change convention. This module turns each of those
into data the two analysis passes can check against:

  - `parse_types_comments()` parses the `# [shape] dtype` trailing comments of
    the `ClusterState` / `Mailbox` / `StepInfo` field declarations straight
    out of the `types.py` source (so the comments themselves become a checked
    contract, not decoration);
  - `resolve_dtypes()` maps policy names (`index_dtype`, `ack_dtype`) to the
    concrete dtypes `types.py` computes for a given config;
  - `invariant_leaves()` names the scan-carry legs a config's tick must pass
    through UNTOUCHED (the legs XLA elides from the per-tick HBM round trip;
    `tools/traffic_audit.py` prices the same set, imported from here so the
    two can never disagree);
  - `schema_fingerprint()` hashes the serialized-pytree field sets against
    the pin in `utils/checkpoint._SCHEMA_FINGERPRINT`.

Nothing here runs a simulation; the heaviest call is `jax.eval_shape`.
"""

from __future__ import annotations

import ast
import hashlib
import inspect
import math
import re

import jax
import jax.numpy as jnp

from raft_sim_tpu import types as rst_types
from raft_sim_tpu.sim.scan import RunMetrics
from raft_sim_tpu.types import ClusterState, Mailbox, StepInfo
from raft_sim_tpu.utils.config import RaftConfig

# TPU minor-tile sublane multiple by element width (the lane dim is always 128
# wide). Single-sourced here so the cost model (analysis/cost_model.py) and the
# traffic audit (tools/traffic_audit.py) price the batch-minor layout with the
# SAME rules -- a padding-model change is one edit, visible to both. 64-bit
# elements lower as paired 32-bit words on TPU, so they tile like 4-byte
# elements; the 2x price rides on itemsize, which is what cost-carry-bytes
# then flags. Covers every token in CONCRETE_DTYPES, so the cost model can't
# crash on a legal-dtype carry leg.
SUBLANE = {8: 8, 4: 8, 2: 16, 1: 32}


def logical_bytes(shape, itemsize: int) -> int:
    """shape x itemsize; a scalar is one element."""
    return math.prod(shape) * itemsize if shape else itemsize


def padded_bytes(shape, itemsize: int, batch: int) -> float:
    """Physical bytes per cluster in the batch-minor layout: `shape + (B,)`
    with the trailing two dims tiled (sublane x 128 lanes), divided back by B
    so lane padding amortizes across the batch and the reported overhead is
    the sublane padding the layout actually pays per cluster."""
    dims = list(tuple(shape) + (batch,))
    dims[-1] = -(-dims[-1] // 128) * 128
    if len(dims) >= 2:
        sub = SUBLANE[itemsize]
        dims[-2] = -(-dims[-2] // sub) * sub
    return math.prod(dims) * itemsize / batch


# Dtype tokens legal in a types.py field comment: either a concrete dtype or
# the name of a policy function in types.py that picks one per config.
CONCRETE_DTYPES = ("bool", "int8", "int16", "int32", "int64", "uint8", "uint32")
POLICY_DTYPES = ("index_dtype", "ack_dtype", "node_dtype")

# Leading-comment grammar: optional shape (`[N, W]` / `scalar`), one or more
# dtype tokens separated by `/`, optionally a parenthesized policy name,
# optionally a VALUE-RANGE clause `in [lo, hi]` (closed interval; lo/hi are
# integer expressions over the config symbols below -- the value-range audit
# seeds its abstract interpreter from these and proves them inductive, rules
# range-annotation-stale / range-pack-width), then free prose. Examples that
# must parse (all live in types.py today):
#   # [N] int32 (starts at 1, core.clj:34)
#   # [N, W] uint32; bit j of votes[i] = i holds a vote from j
#   # [N, N] index_dtype in [1, cap+1]; leader i's next index for peer j
#   # [N(responder)] int16/int32 (index_dtype) in [0, cap]: acked index ...
#   # scalar int32 global tick counter
#   # bool: two leaders share a term
_DTYPE_TOKEN = "|".join(CONCRETE_DTYPES + POLICY_DTYPES)
_COMMENT_RE = re.compile(
    r"^(?:\[(?P<shape>[^\]]*)\]|(?P<scalar>scalar))?\s*"
    rf"(?P<dtypes>(?:{_DTYPE_TOKEN})(?:/(?:{_DTYPE_TOKEN}))*)"
    rf"(?:\s*\((?P<policy>{'|'.join(POLICY_DTYPES)})\))?"
    r"(?:\s+in\s+\[(?P<lo>[^,\[\]]+),\s*(?P<hi>[^,\[\]]+)\])?"
)

# Symbols legal in a range clause, resolved per config. Kept deliberately
# small: the bounds that motivate the narrow-dtype policy (tile.py widths are
# functions of exactly cap/sat/E).
def _range_symbols(cfg: RaftConfig) -> dict[str, int]:
    return {
        "cap": cfg.log_capacity,
        "sat": cfg.ack_age_sat,
        "E": cfg.max_entries_per_rpc,
        "N": cfg.n_nodes,
        "K": cfg.client_pipeline,
        "NIL": rst_types.NIL,
    }


_RANGE_SYMBOLS = ("cap", "sat", "E", "N", "K", "NIL")


def parse_range_expr(expr: str) -> ast.expr:
    """Validate a range-clause bound: integer arithmetic (+ - *) over int
    literals and the config symbols. Returns the parsed AST; raises
    ValueError (with the offending token) on anything else."""
    try:
        node = ast.parse(expr.strip(), mode="eval").body
    except SyntaxError as e:
        raise ValueError(f"range bound {expr!r} is not an expression: {e}")
    for sub in ast.walk(node):
        if isinstance(sub, ast.BinOp):
            if not isinstance(sub.op, (ast.Add, ast.Sub, ast.Mult)):
                raise ValueError(f"range bound {expr!r}: operator not in + - *")
        elif isinstance(sub, ast.UnaryOp):
            if not isinstance(sub.op, ast.USub):
                raise ValueError(f"range bound {expr!r}: unary op not -")
        elif isinstance(sub, ast.Constant):
            if not isinstance(sub.value, int):
                raise ValueError(f"range bound {expr!r}: non-integer literal")
        elif isinstance(sub, ast.Name):
            if sub.id not in _RANGE_SYMBOLS:
                raise ValueError(
                    f"range bound {expr!r}: unknown symbol {sub.id!r} "
                    f"(legal: {', '.join(_RANGE_SYMBOLS)})"
                )
        elif not isinstance(sub, (ast.Add, ast.Sub, ast.Mult, ast.USub, ast.Load)):
            raise ValueError(f"range bound {expr!r}: {type(sub).__name__} not allowed")
    return node


def resolve_range_expr(expr: str, cfg: RaftConfig) -> int:
    """Evaluate a validated range bound under `cfg`'s symbol values."""
    node = parse_range_expr(expr)
    syms = _range_symbols(cfg)

    def ev(n):
        if isinstance(n, ast.Constant):
            return n.value
        if isinstance(n, ast.Name):
            return syms[n.id]
        if isinstance(n, ast.UnaryOp):
            return -ev(n.operand)
        ops = {ast.Add: lambda a, b: a + b, ast.Sub: lambda a, b: a - b,
               ast.Mult: lambda a, b: a * b}
        return ops[type(n.op)](ev(n.left), ev(n.right))

    return ev(node)


# Capacity-bounded range declarations are the NON-compaction contract: with
# cfg.compaction the same legs carry absolute 1-based indices with no static
# bound (types.index_dtype widens them to int32 for the same reason), so the
# audit neither seeds nor checks these clauses there. Names in carry-leaf
# convention (state bare, mailbox `mb.<f>`).
CAPACITY_RANGE_LEGS = frozenset({
    "next_index", "match_index", "commit_index", "log_len", "dur_len",
    "mb.a_match", "mb.a_hint", "mb.ent_start",
})


def range_applies(leg: str, cfg: RaftConfig) -> bool:
    """Whether `leg`'s declared range clause is in force under `cfg`."""
    if leg in CAPACITY_RANGE_LEGS and cfg.compaction:
        return False
    return True


def declared_ranges(cfg: RaftConfig, specs=None) -> dict[str, tuple[int, int]]:
    """Carry-leg name -> (lo, hi) resolved declared range under `cfg`, for
    every types.py field whose comment carries a range clause that is in
    force (`range_applies`). The value-range audit seeds scan carries from
    this and proves each clause inductive."""
    if specs is None:
        specs, _problems = parse_types_comments()
    out: dict[str, tuple[int, int]] = {}
    for cls, prefix in (("ClusterState", ""), ("Mailbox", "mb.")):
        for f, spec in specs.get(cls, {}).items():
            if spec.lo is None:
                continue
            leg = prefix + f
            if not range_applies(leg, cfg):
                continue
            out[leg] = (
                resolve_range_expr(spec.lo, cfg),
                resolve_range_expr(spec.hi, cfg),
            )
    return out
# Optional `= <default>` between the annotation and the comment: StepInputs'
# reconfiguration-plane fields default to the Python-int NIL sentinel so
# hand-built test inputs stay valid (types.py).
_FIELD_RE = re.compile(r"^\s*(\w+):\s*jax\.Array(?:\s*=\s*[\w.+-]+)?\s*#\s*(.*)$")


class FieldSpec:
    """One parsed field-comment contract: declared ndim (None = unchecked),
    the set of dtype tokens the comment admits, and the optional declared
    value range (lo/hi bound expressions, None = undeclared)."""

    def __init__(self, name: str, line: int, ndim: int | None, dtypes: tuple[str, ...],
                 lo: str | None = None, hi: str | None = None):
        self.name = name
        self.line = line
        self.ndim = ndim
        self.dtypes = dtypes
        self.lo = lo
        self.hi = hi

    def __repr__(self):  # test/debug readability only
        rng = f", in=[{self.lo}, {self.hi}]" if self.lo is not None else ""
        return f"FieldSpec({self.name!r}, ndim={self.ndim}, dtypes={self.dtypes}{rng})"


def parse_types_comments(source: str | None = None):
    """Parse the dtype contracts out of types.py's field comments.

    Returns ({class_name: {field: FieldSpec}}, problems) where `problems` is a
    list of (line, message) for declarations whose comment does NOT parse --
    an unparseable comment is itself a finding (the contract must stay
    machine-readable).
    """
    if source is None:
        source = inspect.getsource(rst_types)
    tree = ast.parse(source)
    lines = source.splitlines()
    out: dict[str, dict[str, FieldSpec]] = {}
    problems: list[tuple[int, str]] = []
    for node in tree.body:
        if not (isinstance(node, ast.ClassDef) and node.name in (
            "ClusterState", "Mailbox", "StepInfo", "StepInputs"
        )):
            continue
        fields: dict[str, FieldSpec] = {}
        for lineno in range(node.lineno, node.end_lineno + 1):
            m = _FIELD_RE.match(lines[lineno - 1])
            if not m:
                continue
            name, comment = m.groups()
            cm = _COMMENT_RE.match(comment.strip())
            if not cm:
                problems.append(
                    (lineno, f"{node.name}.{name}: comment {comment!r} does not "
                             "parse as `[shape] dtype` (see analysis/policy.py)")
                )
                continue
            if cm.group("shape") is not None:
                shape = cm.group("shape")
                ndim = shape.count(",") + 1 if shape.strip() else 0
            elif cm.group("scalar"):
                ndim = 0
            else:
                ndim = None
            dtypes = tuple(cm.group("dtypes").split("/"))
            if cm.group("policy"):
                dtypes = dtypes + (cm.group("policy"),)
            lo, hi = cm.group("lo"), cm.group("hi")
            if lo is not None:
                try:
                    parse_range_expr(lo)
                    parse_range_expr(hi)
                except ValueError as e:
                    problems.append((lineno, f"{node.name}.{name}: {e}"))
                    lo = hi = None
            elif comment.strip()[cm.end():].lstrip().startswith("in ["):
                # A malformed range clause must be a finding, not silently
                # demoted to prose (closed `[lo, hi]` with exactly one comma).
                problems.append(
                    (lineno, f"{node.name}.{name}: range clause in comment "
                             f"{comment!r} does not parse as `in [lo, hi]`")
                )
            fields[name] = FieldSpec(name, lineno, ndim, dtypes, lo=lo, hi=hi)
        out[node.name] = fields
    return out, problems


def resolve_dtypes(spec: FieldSpec, cfg: RaftConfig) -> set[jnp.dtype]:
    """The concrete dtypes a field comment admits under `cfg`. A policy token
    narrows the concrete alternatives to the one the policy picks; concrete
    tokens stand alone."""
    policy = [t for t in spec.dtypes if t in POLICY_DTYPES]
    if policy:
        fns = {
            "index_dtype": rst_types.index_dtype,
            "ack_dtype": rst_types.ack_dtype,
            "node_dtype": rst_types.node_dtype,
        }
        return {jnp.dtype(fns[t](cfg)) for t in policy}
    return {jnp.dtype(t) for t in spec.dtypes}


def state_avals(cfg: RaftConfig):
    """eval_shape'd (ClusterState, StepInputs, StepInfo) for one cluster --
    the actual shapes/dtypes the comment contracts are checked against."""
    from raft_sim_tpu.models import raft
    from raft_sim_tpu.sim import faults

    key = jax.eval_shape(lambda: jax.random.key(0))
    state = jax.eval_shape(lambda k: rst_types.init_state(cfg, k), key)
    inputs = jax.eval_shape(lambda k: faults.make_inputs(cfg, k, jnp.int32(0)), key)
    _, info = jax.eval_shape(lambda s, i: raft.step(cfg, s, i), state, inputs)
    return state, inputs, info


def invariant_leaves(cfg: RaftConfig) -> set[str]:
    """Carry leaves the tick passes through UNTOUCHED for this config. XLA
    elides loop-invariant scan-carry components from the per-tick HBM round
    trip, so rewriting one as fresh values each tick is a measured perf
    regression (docs/PERF.md, round-4 lesson) -- the jaxpr pass fails it
    statically (rule `carry-passthrough`), and `tools/traffic_audit.py`
    excludes the same set from its traffic totals. Names: state fields bare,
    mailbox fields as `mb.<field>`.

    The SAME set governs the scenario (genome-path) scan: a genome tunes only
    inputs, never which carry legs a config's tick touches -- the structural
    gates (pre_vote, compaction, client_redirect, client_interval > 0) stay
    on RaftConfig precisely so this holds, and the jaxpr pass enforces it on
    `scenario_simulate` programs too. The genome itself is scan CONSTS
    (`scenario_genome_leaves`), not carry."""
    inv = set()
    if not cfg.pre_vote:
        inv |= {"mb.pv_grant"}
        if not cfg.read_lease and not cfg.reconfig:
            # heard_clock feeds the pre-vote quiet rule, the lease vote
            # denial, AND the log-carried-config removed-server denial: any
            # gate keeps it live.
            inv |= {"heard_clock"}
    if not cfg.compaction:
        inv |= {
            "mb.req_base", "mb.req_base_term", "mb.req_base_chk",
            "log_base", "base_term", "base_chk",
        }
    if not cfg.client_redirect:
        inv |= {"client_pend", "client_dst"}
    if not cfg.track_offer_ticks:
        # Offer-tick plane off: the latency stamps (log plane, wire window,
        # pipeline stamps) and the dedup frontier are all dead weight the tick
        # must pass through untouched.
        inv |= {"log_tick", "mb.ent_tick", "client_tick", "lat_frontier"}
    elif not cfg.client_redirect:
        # Plane on but no redirect pipeline: stamps never ride client slots
        # (direct acceptance stamps at injection).
        inv |= {"client_tick"}
    # Reconfiguration plane (raft_sim_tpu/reconfig): each extension's state
    # legs are dead weight unless its structural gate is on -- the
    # zero-cost-when-off contract the tentpole inherits from
    # track_offer_ticks/pre_vote/compaction.
    if not cfg.reconfig:
        inv |= {
            "member_old", "member_new", "cfg_epoch", "cfg_pend",
            "log_cfg", "mb.ent_cfg",
        }
    if not (cfg.reconfig and cfg.compaction):
        # The snapshot config context travels (and advances) only when both
        # the config plane AND compaction are live.
        inv |= {
            "base_mold", "base_pend", "base_epoch",
            "mb.req_base_mold", "mb.req_base_pend", "mb.req_base_epoch",
        }
    if not (cfg.leader_transfer and (cfg.reconfig or cfg.read_lease)):
        # The disruptive-RequestVote override is written only when a denial
        # gate exists to read it (transfer x [reconfig | lease]).
        inv |= {"mb.req_disrupt"}
    if not cfg.leader_transfer:
        inv |= {"xfer_to", "mb.xfer_tgt"}
    if not cfg.read_index:
        # The read slot AND its RunMetrics accumulators: scan._add_gated
        # skips the fold when the kernel emits host-constant zeros, so the
        # metric legs are var-identity passthroughs too.
        inv |= {
            "read_idx", "read_tick", "read_acks",
            "metric.reads_served", "metric.read_lat_sum", "metric.read_hist",
        }
    if not cfg.read_lease:
        # The lease staleness anchor is dead weight on plain ReadIndex
        # configs too -- only the lease gate maintains it.
        inv |= {"read_fr"}
    if not cfg.durable_storage:
        # Durable storage plane off (raft_sim_tpu/storage): the watermark
        # triple AND its RunMetrics lag accumulators are dead weight --
        # scan's gated folds skip them when the kernel emits host-constant
        # zeros.
        inv |= {
            "dur_len", "dur_term", "dur_vote",
            "metric.fsync_lag_sum", "metric.fsync_lag_max",
        }
    return inv


def scenario_genome_leaves() -> list[tuple[str, str]]:
    """(leaf name, dtype) of the ScenarioGenome fields, in field order -- the
    scenario engine's input-side surface. Single-sourced here so the traffic
    audit (`tools/traffic_audit.py --scenario`) prices exactly the leaves the
    genome path reads, and a genome field add/rename shows up as an audit
    diff instead of silent unpriced traffic. Each leaf is `[S]` per cluster
    (uint32 thresholds, int32 cadences/spans; 4 bytes either way)."""
    from raft_sim_tpu.scenario.genome import ScenarioGenome, leaf_dtype

    return [
        (f, jnp.dtype(leaf_dtype(f)).name) for f in ScenarioGenome._fields
    ]


def trace_carry_leaf_names() -> list[str]:
    """Leaf names of the TRACE program's tick-loop carry: the (state,
    metrics) template, the window first-violation tick, then the trace
    window/persist legs (trace/ring.py) -- so the cost model's
    `cost-carry-bytes` findings name `trace.ev_kind`, not `extra17`, when a
    trace leg widens."""
    from raft_sim_tpu.trace.ring import TracePersist, TraceWin

    names = carry_leaf_names()
    names.append("first_viol")
    names.extend(f"trace.{f}" for f in TraceWin._fields)
    names.extend(f"trace.{f}" for f in TracePersist._fields)
    return names


def carry_leaf_names() -> list[str]:
    """Flattened leaf names of the batch-minor scan carry (state, metrics), in
    pytree flatten order -- the order of the scan body jaxpr's carry vars.
    State fields bare, mailbox fields `mb.<f>`, metrics `metric.<f>`."""
    names = []
    for f in ClusterState._fields:
        if f == "mailbox":
            names.extend(f"mb.{m}" for m in Mailbox._fields)
        else:
            names.append(f)
    names.extend(f"metric.{m}" for m in RunMetrics._fields)
    return names


# The fingerprint's canonical config: pinned EXPLICITLY (never defaults, so a
# default change cannot silently move the fingerprint) in the int8 index tier.
# Dtype-policy changes in other tiers ride the same code paths, and the
# version log shows every historical bump changed names, rank, or a dtype
# visible in this tier (v8/v13/v17/v18 were exactly such dtype moves).
_FINGERPRINT_CFG = dict(n_nodes=5, log_capacity=32, max_entries_per_rpc=4)


def schema_fingerprint() -> str:
    """sha256 over the serialized-pytree schema: the ordered field names of
    (ClusterState, Mailbox, RunMetrics) -- the exact structures
    `utils/checkpoint.save` iterates -- plus each leaf's rank and dtype under
    the pinned canonical config. Any field add/remove/rename/reorder, any
    rank change, and any dtype move (the v8/v13/v17/v18 class of bump)
    changes this, and the pin in `checkpoint._SCHEMA_FINGERPRINT` must be
    refreshed ALONGSIDE a _FORMAT_VERSION bump (rule `checkpoint-version`)."""
    from raft_sim_tpu.sim import scan

    cfg = RaftConfig(**_FINGERPRINT_CFG)
    key = jax.eval_shape(lambda: jax.random.key(0))
    state = jax.eval_shape(lambda k: rst_types.init_state(cfg, k), key)
    metrics = jax.eval_shape(scan.init_metrics)
    rows = []
    for f in ClusterState._fields:
        if f == "mailbox":
            continue
        v = getattr(state, f)
        rows.append((f, len(v.shape), str(v.dtype)))
    for f in Mailbox._fields:
        v = getattr(state.mailbox, f)
        rows.append((f"mb.{f}", len(v.shape), str(v.dtype)))
    for f in RunMetrics._fields:
        v = getattr(metrics, f)
        rows.append((f"metric.{f}", len(v.shape), str(v.dtype)))
    return hashlib.sha256(repr(rows).encode()).hexdigest()[:16]


# ---------------------------------------------------- donation entry points

class DonatingEntry:
    """One jitted entry point covered by the donation policy.

    `label` is the dotted name Pass C's donation golden pins; `path`/`func`
    locate the definition for the Pass D dataflow lint; `donated_param` is the
    parameter name `donate_argnums` targets (None for input-preserving
    entries); `loops` names the standing-loop functions that call it -- the
    scopes where a retained reference to the donated argument is a
    use-after-donate race; `cost_pinned` says whether the entry appears in the
    Pass C golden (the trace variant shares `_chunk_t_donate`'s donation
    contract but is not separately pinned, so adding it cannot stale the
    golden)."""

    def __init__(self, label: str, path: str, func: str,
                 donated_param: str | None, expected: str,
                 loops: tuple[str, ...] = (), cost_pinned: bool = True):
        self.label = label
        self.path = path
        self.func = func
        self.donated_param = donated_param
        self.expected = expected
        self.loops = loops
        self.cost_pinned = cost_pinned

    def __repr__(self):  # test/debug readability only
        return f"DonatingEntry({self.label!r}, {self.expected!r})"


def donating_entry_points() -> tuple[DonatingEntry, ...]:
    """The single source of truth for which entry points donate their carry.

    Pass C (`cost_model.entry_points`) reads labels + expectations from here
    and pins the lowering-level aliasing marks; Pass D
    (`race_audit`/`sanitizer`) reads paths + donated parameter names from here
    to drive the use-after-donate dataflow lint and the runtime
    donation-poison harness. Adding a donating entry point in code without
    registering it here fails Pass D's coverage check (rule
    `race-unregistered-donation`)."""
    return (
        DonatingEntry(
            "sim.chunked._chunk_donate", "raft_sim_tpu/sim/chunked.py",
            "_chunk_donate", "state", "donated", loops=("run_chunked",)),
        DonatingEntry(
            "sim.telemetry._chunk_t_donate", "raft_sim_tpu/sim/telemetry.py",
            "_chunk_t_donate", "state", "donated",
            loops=("run_chunked_telemetry",)),
        DonatingEntry(
            "sim.telemetry._chunk_t_donate_trace",
            "raft_sim_tpu/sim/telemetry.py", "_chunk_t_donate_trace", "state",
            "donated", loops=("run_chunked_telemetry",), cost_pinned=False),
        DonatingEntry(
            "serve.loop._serve_chunk", "raft_sim_tpu/serve/loop.py",
            "_serve_chunk", "state", "donated",
            loops=("_dispatch", "serve", "drain")),
        DonatingEntry(
            "sim.chunked._chunk", "raft_sim_tpu/sim/chunked.py",
            "_chunk", None, "not-donated"),
        DonatingEntry(
            "sim.scan.simulate", "raft_sim_tpu/sim/scan.py",
            "simulate", None, "not-donated"),
        DonatingEntry(
            "sim.scan.simulate_scenario", "raft_sim_tpu/sim/scan.py",
            "simulate_scenario", None, "not-donated"),
    )


def expected_checkpoint_keys() -> set[str]:
    """The npz key set `checkpoint.save` must produce for its field sets --
    derived the same way save() derives it, so a serializer change that
    drops or renames a key diverges from this and the round-trip check
    (rule `checkpoint-serialization`) names it."""
    keys = {"__version__", "seed", "config_json", "scenario_json", "keys"}
    keys |= {f"state_{f}" for f in ClusterState._fields if f != "mailbox"}
    keys |= {f"mb_{f}" for f in Mailbox._fields}
    keys |= {f"metrics_{f}" for f in RunMetrics._fields}
    return keys
