"""Struct-of-arrays state for batched Raft cluster simulation.

The reference keeps per-node state in a Clojure map (init-node, core.clj:31-38) plus a
log atom {:entries [{:term,:val}] :commit-index} (log.clj:33-34), and exchanges messages
as JSON over HTTP with core.async channels as mailboxes (server.clj:37, client.clj:18).

Here one *cluster* is a pytree of dense arrays over the node axis N; `vmap` lifts every
shape to [batch, N, ...]. Messages live in a dense [N, N] mailbox -- one in-flight slot
per directed edge, indexed [dst, src] -- replacing the reference's buffered(5) channels.
Overwriting an undelivered slot is a legal drop (the reference drops on any HTTP
exception, client.clj:38-40), and requests/responses occupy separate mailboxes because a
request sent at tick t is handled at t+1 and its response lands at t+2, mirroring the
reference's two-tick RPC structure (SURVEY.md section 3.2).

Integers default to int32; the [N, N]-shaped planes ride narrower types (int16 for
log-index bookkeeping and ack ages, int8 for window offsets -- bounds asserted by
RaftConfig) because they dominate HBM traffic at large N, and the purely BOOLEAN
planes (the votes bitmap, the pre-vote grant bits, and the per-tick delivery mask)
pack 32 bits per uint32 word along the source-node axis (ops/bitplane.py:
[N, W = ceil(N/32)] words instead of [N, N] bytes; quorum checks become word
popcounts). Node ids are 0-based with
-1 as nil (the reference uses 1-based ids and `nil`, core.clj:31-38). Log indices are
1-based counts like the reference/spec (entry i lives at array slot i-1; index 0
means "no entry", log.clj:20-23).

The `# [shape] dtype` comment on every field below is a CHECKED CONTRACT, not
decoration: the static analyzer parses them (analysis/policy.py, rule
`dtype-comment`) and verifies shape rank and dtype against the structures
init_state/make_inputs/step actually build, across the policy tiers (the
index_dtype/ack_dtype functions here ARE the policy). Keep them parseable --
leading `[dims] dtype` (or `scalar dtype`), with `/`-separated alternatives
resolved through the named policy function.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

# ACK_AGE_SAT* are re-exported here because state builders read them alongside
# ClusterState; they live in config (the leaf module) for the validator.
from raft_sim_tpu.ops import bitplane
from raft_sim_tpu.utils import config as config_mod
from raft_sim_tpu.utils.config import (
    ACK_AGE_SAT,
    ACK_AGE_SAT_NARROW,
    MAX_LOG_CAPACITY,
    RaftConfig,
)
from raft_sim_tpu.utils.rng import draw_timeouts

# Node roles (reference keywords :follower/:candidate/:leader, core.clj:31-38;
# the reference's misspelled :follwer (core.clj:76) is a documented bug, not carried).
FOLLOWER = 0
CANDIDATE = 1
LEADER = 2
# PreVote probe state (cfg.pre_vote; Raft thesis 9.6 -- BEYOND the reference,
# which has no pre-vote, SURVEY.md 2.3.12): an expired node probes a majority
# at its PROSPECTIVE next term before bumping its real term, so a partitioned
# node cannot inflate its term and depose a stable leader on rejoin.
PRECANDIDATE = 3

# Request mailbox record types (reference URI routing, server.clj:8-12;
# REQ_PREVOTE extends the set -- see PRECANDIDATE above).
REQ_NONE = 0
REQ_VOTE = 1  # :request-vote
REQ_APPEND = 2  # :append-entries
REQ_PREVOTE = 3  # pre-vote probe (carries the prospective term = sender term + 1)
# TimeoutNow (Raft thesis 3.10; cfg.leader_transfer -- BEYOND the reference):
# a transferring leader tells its caught-up target to start an election
# IMMEDIATELY, bypassing both the election timer and pre-vote. The target node
# id rides the Mailbox.xfer_tgt header; only the target acts on the broadcast.
REQ_TIMEOUT_NOW = 4

# Response mailbox record types (client.clj:8-9 keywordizes :type from the HTTP
# body). A pre-vote response's GRANT rides the packed pv_grant bit-plane
# (Mailbox.pv_grant): unlike real votes, one responder may grant SEVERAL
# pre-candidates per tick (grants are non-binding and consume no votedFor), so
# the grant cannot ride the per-responder v_to field.
RESP_NONE = 0
RESP_VOTE = 1  # :vote-response
RESP_APPEND = 2  # :append-response
RESP_PREVOTE = 3  # pre-vote response; the grant bit rides Mailbox.pv_grant

NIL = -1  # nil node id

# Bins of the per-entry commit-latency histogram (StepInfo.lat_hist): bin k
# holds latencies with floor(log2(l)) == k, so 16 bins cover 1 .. 2^16-1 ticks
# with the last bin absorbing anything longer.
LAT_HIST_BINS = 16
# Log value of a leader no-op entry (compaction only): appended on election win so
# a current-term entry exists to pull old-term entries through the spec-5.4.2
# commit gate (models/raft.py phase 6). Reserved: client commands may not use it.
NOOP = -2

# log_capacity ceiling for int8 index planes: the single-pass window-start min
# (models/raft_batched.py phase 8) encodes self as +2K and unresponsive peers as
# +K with K = cap + 1, so the largest encoded value is 2K + cap = 3*cap + 2,
# which must fit the plane dtype. The ceiling is DERIVED from that encoding
# bound (utils/config.max_log_capacity_for, shared with analysis Pass E) so
# widening it without widening the dtype (or the encoding) is impossible by
# construction, not just caught by an assert: (127 - 2) // 3 = 41.
MAX_INT8_LOG_CAPACITY = config_mod.max_log_capacity_for(127)
assert config_mod.window_min_encoding_max(MAX_INT8_LOG_CAPACITY) <= 127  # int8 tier
assert config_mod.window_min_encoding_max(MAX_LOG_CAPACITY) <= 32767  # int16 tier


def ack_dtype(cfg: RaftConfig):
    """Dtype of the ack-age plane: int8 whenever the saturation ceiling fits it
    (cfg.ack_age_sat; +1 per tick before the clamp stays within range)."""
    return jnp.int8 if cfg.ack_age_sat < 127 else jnp.int16


def index_dtype(cfg: RaftConfig):
    """Dtype of the per-edge log-index planes (next/match) and the per-responder
    match/hint wire fields. Log indices are bounded by log_capacity without
    compaction -- int8 up to capacity 41, int16 up to 4095 -- and absolute
    (unbounded) with it: int32."""
    if cfg.compaction:
        return jnp.int32
    return jnp.int8 if cfg.log_capacity <= MAX_INT8_LOG_CAPACITY else jnp.int16


# n_nodes ceiling for int8 node-id wire fields (Mailbox xfer_tgt/v_to/a_ok_to and
# the kernels' grant_to/a_ok_to casts): ids 0..n-1 plus the NIL = -1 sentinel and
# the `n` sentinel the min-select patterns use must all fit the dtype. Derived
# (utils/config.max_nodes_for, shared with analysis Pass E): 127 - 1 = 126
# keeps n itself (the sentinel) a valid int8 value with a slot to spare.
MAX_INT8_NODES = config_mod.max_nodes_for(127)


def node_dtype(cfg: RaftConfig):
    """Dtype of node-id wire fields (Mailbox xfer_tgt/v_to/a_ok_to): int8 up to
    126 nodes, int16 for the giant-N tier (config7x, N=255). Node ids in the
    STATE (voted_for/leader_id) stay int32 -- they are [N]-shaped headers, not
    planes, so narrowing them buys nothing next to the [N, N] traffic."""
    return jnp.int8 if cfg.n_nodes <= MAX_INT8_NODES else jnp.int16


class Mailbox(NamedTuple):
    """In-flight RPC state, one tick deep. TPU-native wire format, v9 (+ the
    round-6 packed pre-vote grant bit-plane, checkpoint v18).

    Both RPCs are logically broadcasts (the reference sends RequestVote and
    AppendEntries to every peer, core.clj:48-67), and after the shared-window prev
    clamp the only genuinely per-edge request datum is a tiny window offset. So the
    wire format carries request HEADERS per sender ([N] -- one record broadcast to
    all peers) and only two per-edge planes, cutting the [N, N]-shaped mailbox
    traffic from ten int32 fields to two int8 planes (the mailbox was the dominant
    HBM traffic of the N=51 tick: ~5x the logical state bytes):

      req_* / ent_* headers: [N(sender)] -- receivers reduce senders over axis 0
        after outer-producting with the per-edge delivery mask.
      req_off:  [sender, receiver] -- AppendEntries per-edge window offset j.
      resp_kind: [receiver, responder] -- RESP_* type of the response on that
        edge; the response payload is per RESPONDER (below).
      pv_grant: [receiver, W] -- the pre-vote grant BITS, bit-packed over the
        responder axis (ops/bitplane.py; 32 responders per uint32 word). The
        only genuinely boolean per-edge response datum: one voter may grant
        several probing pre-candidates in the same tick, so the grant can ride
        neither v_to nor the resp_kind value -- it used to occupy bit 2 of the
        int8 resp_kind plane and now costs W words per receiver instead of a
        byte per edge. All-zero (and carried untouched, so XLA sees a
        loop-invariant component) unless cfg.pre_vote.

    AppendEntries reconstruction at receiver d from sender s (validated against the
    usual prev checks, so spec-equivalent to an explicit per-edge header):
      prev_index = ent_start[s] + req_off[s, d]          (j = req_off in 0..E)
      prev_term  = ent_prev_term[s] if j == 0 else ent_term[s, j-1]
      n_entries  = clip(ent_count[s] - j, 0, E)
      entries    = ent_term/ent_val[s, j :]              (window slot k holds the
                                                          1-based entry ent_start+k+1)
      leaderCommit = req_commit[s]
    The shared E-entry window (reference ships arbitrary per-peer suffixes,
    core.clj:59-67) starts at the minimum prev-index among RESPONSIVE peers (acked
    an AppendEntries within config.ack_timeout_ticks, ClusterState.ack_age; falls
    back to all peers when none are responsive, so a dead peer cannot pin the
    window start and stall replication); each peer's prev is clamped into
    [ent_start, ent_start + E], which is what makes j fit 0..E.

    Responses carry :vote-response {term,vote-granted} (core.clj:95-102) and
    :append-response {term,success,log-index} (core.clj:109-121). The payloads are
    per RESPONDER, not per edge, because one responder's per-tick response surface
    is sparse by construction: it grants at most ONE vote (phase 2's single-grant
    rule) and acks at most ONE AppendEntries sender (phase 3 selects one
    current-term AE; election safety allows only one), and every denial it sends
    shares the same payload (the vote denial carries only resp_term; the AE nack's
    catch-up hint is the responder's log length -- the same value toward every
    sender). So requester q decodes responder r's edge [q, r] as:
      vote:   granted = (v_to[r] == q)
      append: success = (a_ok_to[r] == q);
              match   = a_match[r] if success else a_hint[r]  (nack conflict hint)
    with resp_term[r] the responder's term at send time (same toward every
    requester). This replaces v8's per-edge packed int16/int32 response word --
    less [N, N] traffic, and the acked index is a full int32, so nothing bounds
    committed entries (v8's packed word capped compaction runs at 2^28).
    """

    req_type: jax.Array  # [N(sender)] int32 in [0, 4] (REQ_*): this tick's broadcast, if any
    req_term: jax.Array  # [N] int32: sender's term at send time
    req_commit: jax.Array  # [N] int32: AE leaderCommit
    req_last_index: jax.Array  # [N] int32: RV lastLogIndex
    req_last_term: jax.Array  # [N] int32: RV lastLogTerm
    ent_start: jax.Array  # [N] int32 in [0, cap]: 1-based index before src's shared window (= prev at j=0)
    ent_prev_term: jax.Array  # [N] int32: term of the 1-based entry ent_start (j=0 prev)
    ent_count: jax.Array  # [N] int32 in [0, E]: entries shipped = min(log_len - ent_start, E)
    ent_term: jax.Array  # [N, E] int32: src's shared entry window (terms)
    ent_val: jax.Array  # [N, E] int32: src's shared entry window (values)
    # Offer-tick plane of the shared window (cfg.track_offer_ticks only; zeros
    # and carried untouched otherwise): entry k's offer stamp rides the wire
    # NEXT TO its value, so replication preserves the latency metadata while
    # values stay arbitrary client payloads (VERDICT missing #1: payloads used
    # to BE the offer ticks, so colliding client values corrupted the metric).
    ent_tick: jax.Array  # [N, E] int32: src's shared entry window (offer stamps)
    # Snapshot header (compaction only; zeros otherwise): an AE sender's compaction
    # state (lastIncludedIndex/-Term + the checksum of the compacted prefix). An
    # edge whose req_off is the SNAP sentinel -1 is an InstallSnapshot analogue:
    # the receiver installs (req_base, req_base_term, req_base_chk) instead of
    # appending entries (models/raft.py phase 3).
    req_base: jax.Array  # [N] int32: sender's log_base (snapshot lastIncludedIndex)
    req_base_term: jax.Array  # [N] int32: snapshot lastIncludedTerm
    req_base_chk: jax.Array  # [N] uint32: checksum of the compacted prefix
    # Leadership-transfer header (cfg.leader_transfer only; NIL and carried
    # untouched otherwise): the target of the sender's TimeoutNow broadcast
    # (REQ_TIMEOUT_NOW). Per sender like every request header -- a leader
    # fires at most one transfer per tick.
    xfer_tgt: jax.Array  # [N(sender)] int8/int16 (node_dtype) in [NIL, N-1]: TimeoutNow target node (NIL = none)
    # Disruptive-RequestVote flag (thesis 4.2.3's override, paired with
    # TimeoutNow in 3.10): set on the RequestVote broadcast of a transfer-
    # triggered election, so voters holding the heard-a-leader denial (live
    # under cfg.reconfig -- the removed-server disruption defense -- or
    # cfg.read_lease) still process THIS election: it was sanctioned by the
    # leader being replaced, so denying it would deadlock every transfer.
    # Written only when the flag has a reader (cfg.leader_transfer AND a
    # denial gate); zeros and carried untouched otherwise.
    req_disrupt: jax.Array  # [N(sender)] int8 in [0, 1]: 1 = transfer-sanctioned RequestVote
    # Config-entry plane of the shared window (cfg.reconfig only; zeros and
    # carried untouched otherwise): entry k's config command replicates NEXT
    # TO its value, exactly like the offer-stamp plane -- so a follower's
    # log prefix carries the configuration history its derived membership
    # reads (models/cfglog.py). 0 = not a config entry; +(v+1) = joint entry
    # toggling node v; -(v+1) = final entry completing that toggle.
    ent_cfg: jax.Array  # [N, E] int32: src's shared entry window (config commands)
    # Snapshot config header (compaction AND reconfig; zeros otherwise): the
    # sender's configuration context at its compaction base, installed with
    # the snapshot so the receiver's derived config stays exact when config
    # entries were compacted away (base_mold/base_pend/base_epoch legs).
    req_base_mold: jax.Array  # [N, W] uint32: sender's C_old at its base
    req_base_pend: jax.Array  # [N] int32: sender's pending toggle code at base
    req_base_epoch: jax.Array  # [N] int32: sender's config-entry count at base
    req_off: jax.Array  # [N(sender), N(receiver)] int8 in [-1, E]: AE window offset j; -1 = snapshot
    resp_kind: jax.Array  # [N(receiver), N(responder)] int8 in [0, 3] (RESP_*): response type per edge
    pv_grant: jax.Array  # [N(receiver), W] uint32: packed pre-vote grant bits (bit = responder)
    v_to: jax.Array  # [N(responder)] int8/int16 (node_dtype) in [NIL, N]: candidate granted this tick (NIL = none; N = masked no-sender sentinel)
    a_ok_to: jax.Array  # [N(responder)] int8/int16 (node_dtype) in [NIL, N]: AE sender acked OK this tick (NIL = none; N = masked no-sender sentinel)
    a_match: jax.Array  # [N(responder)] int16/int32 (index_dtype) in [0, cap]: acked index of the successful append
    a_hint: jax.Array  # [N(responder)] int16/int32 (index_dtype) in [0, cap]: nack hint (responder's log length)
    resp_term: jax.Array  # [N(responder)] int32: responder's term at send time


class ClusterState(NamedTuple):
    """Full per-cluster simulator state (the scan carry).

    Maps the reference node map + log atom (SURVEY.md section 2.2) onto arrays:
      role/term/voted_for/leader_id  <- :state/:current-term/:voted-for/:leader-id
      votes [N,W] packed bitmap      <- :votes set (core.clj:38)
      next_index/match_index [N,N]   <- :leader-state maps (core.clj:40-42)
      log_term/log_val/log_len       <- log atom :entries (log.clj:33)
      commit_index                   <- log atom :commit-index
      clock/deadline                 <- async/timeout channels (core.clj:171-174)
    """

    role: jax.Array  # [N] int32 in [0, 3] (FOLLOWER..PRECANDIDATE)
    term: jax.Array  # [N] int32 (starts at 1, core.clj:34)
    voted_for: jax.Array  # [N] int32 in [NIL, N] (NIL = none; N = masked no-candidate sentinel)
    leader_id: jax.Array  # [N] int32 in [NIL, N] (NIL = unknown; N = masked no-sender sentinel)
    # Bit-packed votes bitmap (ops/bitplane.py): bit j of votes[i] set = node i
    # holds a granted vote (or pre-vote grant, while PRECANDIDATE) from node j.
    # The quorum test is a word popcount (bitplane.count >= cfg.quorum), and the
    # plane costs W = ceil(N/32) uint32 words per node instead of N bool bytes
    # (N=51: 2 words = 8 bytes vs 51 bytes carried per node per tick).
    votes: jax.Array  # [N, W] uint32; bit j of votes[i] = i holds a vote from j
    # The three [N, N] leader-bookkeeping planes are the largest state after the
    # mailbox; log indices are capacity-bounded (int8 up to capacity 41, int16 up
    # to 4095 -- index_dtype) and ages saturate (ACK_AGE_SAT), cutting their HBM
    # traffic vs int32. Compaction configs carry absolute (unbounded) indices:
    # int32. Under cfg.compact_planes the CARRY form of these planes (and of
    # req_off/resp_kind/votes/the entry windows/the delivery mask) is the
    # bit-packed flat uint32 layout of ops/tile.py; the comments below state
    # the dense contract the kernels compute on (tile.unpack_state at tick
    # entry, pack_state at exit -- bit-identical trajectories either way).
    next_index: jax.Array  # [N, N] index_dtype in [1, cap+1]; leader i's next index for peer j
    match_index: jax.Array  # [N, N] index_dtype in [0, cap]
    # Ticks since leader i last received an AppendEntries response (success OR
    # failure -- both prove the peer is up) from peer j, saturating at
    # cfg.ack_age_sat (int8 plane whenever that ceiling fits -- ack_dtype);
    # zeroed for the whole row when i wins an election (grace period). Volatile
    # leader bookkeeping like next/match; drives the shared-entry-window
    # responsiveness filter (config.ack_timeout_ticks).
    ack_age: jax.Array  # [N, N] ack_dtype in [0, sat] (int8/int16)
    commit_index: jax.Array  # [N] int32 in [0, cap]
    # Weighted checksum of the committed prefix (log_ops.chk_weights), maintained
    # when config.check_invariants: the "committed entries are immutable" invariant
    # checks one pass over the new log arrays against this instead of re-reading the
    # old arrays every tick. Stays 0 when invariant checking is off. Hand-built
    # states that set commit_index directly must refresh it via
    # types.with_commit_chk (the invariant trips otherwise -- by design).
    commit_chk: jax.Array  # [N] uint32
    # Compaction state (all zeros when cfg.compact_margin == 0). Entries 1..log_base
    # have been compacted away: they exist only as this triple (the snapshot). The
    # Raft persistent set grows to include it (a restart keeps base and resumes with
    # commit = log_base). Invariant: log_base <= commit_index <= log_len and
    # log_len - log_base <= CAP (the retained window fits the ring).
    log_base: jax.Array  # [N] int32: snapshot lastIncludedIndex
    base_term: jax.Array  # [N] int32: snapshot lastIncludedTerm
    base_chk: jax.Array  # [N] uint32: checksum of entries 1..log_base
    # Ring log: 1-based entry i lives at slot (i - 1) mod CAP; live slots hold
    # entries (log_base, log_len]. With compaction off, log_base == 0 and the ring
    # degenerates to the plain prefix layout (entry i at slot i-1, log_len <= CAP).
    log_term: jax.Array  # [N, CAP] int32
    log_val: jax.Array  # [N, CAP] int32
    # Offer-tick plane (cfg.track_offer_ticks; zeros and carried untouched
    # otherwise): slot k holds entry k's offer stamp (offer tick + 1; 0 for
    # no-ops and non-client entries), written at injection and replicated via
    # Mailbox.ent_tick. The commit-latency metric reads THIS plane, so client
    # values are arbitrary int32 payloads -- a value equal to some tick can no
    # longer corrupt the histogram (the round-4 collision caveat). Measurement
    # metadata, not protocol state: excluded from the commit checksum and the
    # log-matching compare, and restart-persistent alongside the log it tags.
    log_tick: jax.Array  # [N, CAP] int32
    log_len: jax.Array  # [N] int32 in [0, cap]
    # Durable storage plane (raft_sim_tpu/storage; all legs zeros/boot values
    # and carried untouched unless cfg.durable_storage). The dissertation's
    # section 3.8 persistent triple -- currentTerm, votedFor, the log -- is
    # durable only up to these watermarks: entries (0, dur_len] have been
    # fsynced, and dur_term/dur_vote are the term/vote as of the last flush.
    # A flush (StepInputs.fsync_fire) snaps all three to the node's live
    # values; a crash-restart REWINDS the node to them (the un-fsynced log
    # suffix is lost, term/vote revert to the durable snapshot), minus any
    # torn tail (StepInputs.torn_drop) the recovery checksum rejects.
    # Truncation clamps dur_len down with log_len (removed entries are no
    # longer durable as log content). v1 excludes compaction (dur_len would
    # have to fold across snapshot installs) -- asserted by RaftConfig.
    dur_len: jax.Array  # [N] int32 in [0, cap]: fsynced log prefix length (<= log_len)
    dur_term: jax.Array  # [N] int32: term at the last flush (boot: 1)
    dur_vote: jax.Array  # [N] int32: votedFor at the last flush (NIL = none)
    clock: jax.Array  # [N] int32 local (skewable) clock
    deadline: jax.Array  # [N] int32 next timer fire on the local clock
    # Local-clock stamp of the last valid leader contact (accepted current-term
    # AppendEntries), driving the thesis-9.6 pre-vote denial rule: a voter
    # denies pre-votes while it heard from a leader within the minimum election
    # timeout. Volatile (restart resets it to "long quiet"). Maintained only
    # when cfg.pre_vote; untouched (loop-invariant) otherwise.
    heard_clock: jax.Array  # [N] int32
    # Reconfiguration plane (cfg.reconfig; zeros and carried untouched
    # otherwise -- raft_sim_tpu/reconfig, thesis chapter 4). LOG-CARRIED,
    # PER-NODE protocol state: configuration changes ride the replicated log
    # as entries (the log_cfg plane below), and each node's effective
    # configuration is DERIVED FROM ITS OWN LOG PREFIX -- applied the moment
    # an entry is appended (never waiting for commit, dissertation ch. 4)
    # and rolled back when a truncation removes it (models/cfglog.py is the
    # single derivation; docs/PROTOCOL.md states the model). These four
    # leaves are the derived cache, recomputed at the end of every tick from
    # the post-append post-compaction log; quorum tests -- election,
    # pre-vote promotion, commit advancement, ReadIndex/lease confirmation
    # -- read the TICK-START values, each node masking by ITS OWN rows (dual
    # popcount of member_old AND member_new while that node's cfg_pend marks
    # an uncompleted joint entry in its prefix). Log-derived state survives
    # restart with the log; crash faults never touch it directly.
    member_old: jax.Array  # [N, W] uint32: node i's C_old from its own log prefix
    member_new: jax.Array  # [N, W] uint32: node i's C_new (== C_old outside joint)
    cfg_epoch: jax.Array  # [N] int32: config entries in node i's prefix (+ base_epoch)
    cfg_pend: jax.Array  # [N] int32: abs index of the governing joint entry (0 = none)
    # Config-entry log plane (cfg.reconfig; zeros otherwise): slot k's config
    # command, written beside log_term/log_val at every append (AE
    # replication via Mailbox.ent_cfg, leader origination at injection) and
    # ZEROED by non-config appends, so a truncated-then-overwritten slot can
    # never leak a stale config entry into the derivation. Encoding:
    # 0 = not a config entry; +(v+1) = joint entry toggling node v's
    # membership; -(v+1) = final entry completing that toggle. Part of the
    # Raft persistent log (restart keeps it).
    log_cfg: jax.Array  # [N, CAP] int32
    # Snapshot config context (compaction AND reconfig; zeros otherwise):
    # the configuration facts at log_base, so the derivation stays exact
    # after committed config entries compact away -- C_old at base, the
    # pending (unmatched-joint) toggle code at base (0 = none), and the
    # config-entry count at/below base. Advances with log_base (the
    # compacted span's entries fold in) and installs from the snapshot
    # header (Mailbox.req_base_mold/...). Restart-persistent with the
    # snapshot triple it extends.
    base_mold: jax.Array  # [N, W] uint32: C_old at log_base
    base_pend: jax.Array  # [N] int32: pending toggle code at base (0 = none)
    base_epoch: jax.Array  # [N] int32: config entries at or below base
    # Leadership-transfer plane (cfg.leader_transfer; NIL and carried
    # untouched otherwise): a transferring leader's pending TimeoutNow target
    # (thesis 3.10). Volatile leader state: cleared on role loss, term
    # adoption, restart, or target unresponsiveness; re-fired each heartbeat
    # while pending and caught up (a dropped TimeoutNow retries).
    xfer_to: jax.Array  # [N] int32 in [NIL, N-1]: pending transfer target (NIL = idle)
    # ReadIndex plane (cfg.read_index; zeros and carried untouched otherwise
    # -- thesis 6.4): one pending read slot per node. read_idx holds the
    # captured commit index + 1 (0 = no pending read) -- capture is gated on
    # the leader having committed a current-term entry; read_acks banks the
    # packed per-peer AppendEntries responses received SINCE capture, and the
    # read is served once they reach a (configuration-aware) majority with
    # the slot's captured index covered by commit. Volatile leader state:
    # wiped on restart, role loss, or term change.
    read_idx: jax.Array  # [N] int32: pending read's captured index + 1 (0 = none)
    read_tick: jax.Array  # [N] int32: offer stamp of the pending read
    read_acks: jax.Array  # [N, W] uint32: packed acks banked since capture
    # Lease-read staleness anchor (cfg.read_lease; zeros and carried
    # untouched otherwise -- thesis 6.4.1): the cluster's committed frontier
    # (lat_frontier semantics: max commit any node ever reached) banked at
    # the pending read's CAPTURE tick. A served read whose captured index
    # falls below this frontier missed writes committed before it was issued
    # -- the exact read_linearizability property the trace checker verifies,
    # here as a per-tick device invariant (StepInfo.viol_read_stale) so the
    # scenario hunt's fitness can see lease violations. Measurement state
    # like lat_frontier, not node state: crash faults never touch it beyond
    # the slot wipe it shares with read_idx.
    read_fr: jax.Array  # [N] int32: frontier at the pending read's capture
    # Client-side state (cfg.client_redirect; NIL/0 otherwise): up to K =
    # cfg.client_pipeline commands the simulated client has in flight and the
    # node each one's next POST targets -- the array form of the reference
    # client chasing HTTP 302 redirects (core.clj:151-160) through a
    # buffered(K) request channel (server.clj:37). Not node state: crash
    # faults never touch it.
    client_pend: jax.Array  # [K] int32 command values in flight (NIL = free slot)
    client_dst: jax.Array  # [K] int32 node each pending command targets
    # Offer stamp of each in-flight slot (redirect mode with the offer-tick
    # plane active; zeros otherwise): latency is measured from the OFFER, so
    # the stamp must survive the 302 bounces alongside the payload -- it used
    # to ride the value itself (tick-encoded payloads), now it rides here.
    client_tick: jax.Array  # [K] int32 offer stamps of the in-flight commands
    # Monotone commit-latency frontier: the highest commit index any node of this
    # cluster has ever reached. The latency metric counts an entry when the live
    # leader's commit first passes it; dedup against this CARRIED maximum (not
    # the restart-mutable per-node commit vector) so a restarted max-commit node
    # regressing to its log_base cannot make a later leader re-count entries
    # already reported (advisor finding, round 4). Measurement state, not node
    # state: crash faults never touch it. Zero unless cfg.track_offer_ticks.
    lat_frontier: jax.Array  # scalar int32
    now: jax.Array  # scalar int32 global tick counter
    mailbox: Mailbox


class StepInputs(NamedTuple):
    """Pure per-tick inputs. Randomness is *materialized outside* the step kernel so the
    same arrays can drive both the jnp kernel and the Python oracle (tests), and so fault
    schedules are plain data (SURVEY.md section 5, failure injection).

    This boundary is what makes the scenario engine free: per-cluster fault
    genomes and phased nemesis programs (raft_sim_tpu/scenario) change only
    how sim/faults.make_inputs FILLS these arrays -- the step kernels consume
    the identical structure either way, so the genome path adds zero step
    lowerings and a homogeneous genome is bit-exact with the scalar path."""

    # Bit-packed delivery mask (ops/bitplane.py), packed over the SOURCE axis:
    # bit s of deliver_mask[d] clear = the message on physical edge [d, s]
    # (addressed to d, sent by s) is dropped this tick. sim/faults.py generates
    # it packed; kernels consume the packed words in the response-side delivery
    # reduction and unpack once for the transposed request orientation; the
    # oracle unpacks (tests/oracle.py). W = ceil(N/32).
    deliver_mask: jax.Array  # [N, W] uint32; bit src of row dst
    skew: jax.Array  # [N] int32 in [0, 2] local-clock increment this tick (normally 1)
    timeout_draw: jax.Array  # [N] int32 election timeout to use on any timer reset
    client_cmd: jax.Array  # scalar int32 command value offered this tick; NIL = none
    # Client routing draws (cfg.client_redirect; zeros otherwise): the node a
    # fresh offer targets, and the random peer each pipeline slot's leaderless
    # redirect bounces to (core.clj:154).
    client_target: jax.Array  # scalar int32 in [0, N-1]
    client_bounce: jax.Array  # [K] int32 in [0, N-1]
    alive: jax.Array  # [N] bool; False = node crashed this tick (silent, frozen)
    restarted: jax.Array  # [N] bool; True = node came back up this tick (volatile wipe)
    # Reconfiguration-plane admin commands (all NIL unless their gate is on;
    # raft_sim_tpu/reconfig). Cluster-scoped offers handled by the lowest-id
    # live member leader, exactly like the direct client's command offer:
    #   reconfig_cmd  toggle node v's voting membership (add if absent,
    #                 remove if present; refused while a joint phase is
    #                 pending or when the removal would leave < 2 voters)
    #   transfer_cmd  ask the current leader to transfer leadership to node v
    #   read_cmd      offer one ReadIndex read (the read-only traffic class)
    # Python-int NIL defaults (not jnp scalars: a module-level jnp array
    # would initialize the backend at import, before driver.select_backend)
    # so hand-built test inputs predating the plane stay valid; make_inputs
    # always materializes real arrays.
    reconfig_cmd: jax.Array = NIL  # scalar int32 in [NIL, N-1]; NIL = none
    transfer_cmd: jax.Array = NIL  # scalar int32 in [NIL, N-1]; NIL = none
    read_cmd: jax.Array = NIL  # scalar int32 in [NIL, 1]: 0/1 flag encoded as value; NIL = none
    # Durable storage plane draws (cfg.durable_storage; all-zero arrays
    # otherwise -- sim/faults._storage_draws). fsync_fire marks the nodes
    # whose disk completes a flush THIS tick (the cadence tick, minus the
    # per-node latency-jitter stall draw); torn_drop is the extra entries a
    # recovery's tail checksum rejects IF the node restarts this tick (the
    # torn-tail write; consumed only on restart ticks, drawn every tick so
    # the key stream is schedule-independent). Python-scalar defaults like
    # the admin commands above; make_inputs always materializes real [N]
    # arrays (the dtype-comment contract fixes the rank per field).
    fsync_fire: jax.Array = False  # [N] bool; True = flush completes this tick
    torn_drop: jax.Array = 0  # [N] int32: torn-tail entries dropped at recovery


class StepInfo(NamedTuple):
    """Small per-tick outputs: on-device safety invariants + observability reductions
    (SURVEY.md section 5, metrics). All scalars per cluster."""

    viol_election_safety: jax.Array  # bool: two leaders share a term
    viol_commit: jax.Array  # bool: commit regressed or exceeds log length
    viol_log_matching: jax.Array  # bool (False unless cfg.check_log_matching)
    leader: jax.Array  # int32: lowest-id current leader, NIL if none
    n_leaders: jax.Array  # int32: number of nodes in LEADER role
    max_term: jax.Array  # int32
    max_commit: jax.Array  # int32
    min_commit: jax.Array  # int32
    msgs_delivered: jax.Array  # int32: request+response records delivered this tick
    cmds_injected: jax.Array  # int32 0/1: an offered command was accepted by a live leader
    # Offer->commit latency, measured at the live leader's commit advancement
    # (the ack point the reference's never-firing commit watch was meant to be,
    # log.clj:83-87): entries carry their offer stamp in the log_tick plane, so
    # newly committed client entries contribute (now - offer_tick) each. Zeros
    # unless cfg.track_offer_ticks.
    lat_sum: jax.Array  # int32: sum of commit latencies of entries committed this tick
    lat_cnt: jax.Array  # int32: number of client entries committed this tick
    # Per-entry latency histogram: bin k counts entries committed this tick whose
    # latency l (in ticks, >= 1) has floor(log2(l)) == k, clamped to the last
    # bin. Fixed log-spaced bins make true fleet p50/p95/p99 recoverable in
    # summarize, where the old accumulators only supported a mean of means.
    # Known undercount (round-5 advisor): the metric attributes entries at a
    # LIVE LEADER's commit advancement, but lat_frontier advances past
    # max(commit) even on leaderless ticks (followers advance commit from a
    # downed leader's final req_commit), so entries whose first commit happens
    # in a leaderless window are permanently excluded from lat_sum/lat_cnt/
    # lat_hist. Under crash churn the histogram is therefore an undercount of
    # committed client entries -- biased toward fault-free windows, never
    # double-counting -- and `lat_excluded` below COUNTS the dropped entries so
    # the coverage gap is measured, not guessed (docs/PERF.md "latency metric
    # coverage" carries the quantified numbers).
    lat_hist: jax.Array  # [LAT_HIST_BINS] int32 (zeros unless track_offer_ticks)
    # Client entries the latency frontier crossed this tick WITHOUT being
    # counted into lat_sum/lat_cnt/lat_hist: the frontier advances to
    # max(commit) every tick, but attribution needs a live leader, so entries
    # first committed in a leaderless window fall through. Counted on the
    # (lowest-id) max-commit node whose commit defines the frontier advance;
    # exact without compaction, conservative (clamped >= 0) with it, where the
    # max-commit node may already have compacted a crossed slot away.
    lat_excluded: jax.Array  # int32 (zero unless track_offer_ticks)
    # Election wins that could NOT append their no-op because the ring held no
    # free slot (compaction only). The no-op reserve guarantees room for
    # max(1, compact_margin // 2) consecutive commit-free elections; a deeper
    # commit-free chain would freeze commit permanently (the 5.4.2 deadlock the
    # no-op exists to break), so any nonzero count here makes that latent
    # livelock visible instead of silent (advisor finding, round 4).
    noop_blocked: jax.Array  # int32: count of win & no-noop-room events this tick
    # Node pairs the compaction-form log-matching check could not compare this
    # tick (one node's base passed the other's commit; their agreement is pinned
    # transitively and via checksums). Measures the ring check's coverage
    # instead of assuming it. Zero unless check_log_matching ran this tick.
    lm_skipped_pairs: jax.Array  # int32: unordered pairs skipped by the check
    # ReadIndex read-traffic metrics (zeros unless cfg.read_index): reads
    # served this tick, their summed offer->serve latency, and the same
    # log2-binned histogram shape the commit-latency metric uses -- so
    # telemetry can report commit-vs-read latency side by side.
    reads_served: jax.Array  # int32: ReadIndex reads served this tick
    read_lat_sum: jax.Array  # int32: summed offer->serve latency of served reads
    read_hist: jax.Array  # [LAT_HIST_BINS] int32 (zeros unless read_index)
    # Lease-read staleness invariant (cfg.read_lease AND check_invariants;
    # a host-constant zero otherwise, with the fold gated like the read
    # metrics -- scan.step_bad): a read was SERVED whose captured index sits
    # below the committed frontier banked at its capture (ClusterState.
    # read_fr). Folds into RunMetrics.violations, so the scenario hunt's
    # fitness sees lease violations the classic viol_* flags cannot -- the
    # device-visible form of the checker's read_linearizability property.
    # Defaulted so hand-built StepInfos predating the lease plane stay valid.
    viol_read_stale: jax.Array = False  # bool: a stale lease read was served
    # Durability lag (cfg.durable_storage; host-constant zeros otherwise, with
    # the folds gated like the read metrics -- scan._accumulate): how far the
    # simulated disks trail the live logs at end of tick, as the sum and max
    # over nodes of (log_len - dur_len). The health plane's durability_lag
    # SLI and the per-window fsync-lag counters read these; a disk that
    # stalls (fsync_jitter_prob) shows up here before it shows up as a
    # replication stall. Defaulted so hand-built StepInfos predating the
    # storage plane stay valid.
    fsync_lag_sum: jax.Array = 0  # int32: sum over nodes of log_len - dur_len
    fsync_lag_max: jax.Array = 0  # int32: max over nodes of log_len - dur_len


def empty_mailbox(cfg: RaftConfig) -> Mailbox:
    n, e = cfg.n_nodes, cfg.max_entries_per_rpc
    i = lambda *s: jnp.zeros(s, jnp.int32)
    return Mailbox(
        req_type=i(n),
        req_term=i(n),
        req_commit=i(n),
        req_last_index=i(n),
        req_last_term=i(n),
        ent_start=i(n),
        ent_prev_term=i(n),
        ent_count=i(n),
        ent_term=i(n, e),
        ent_val=i(n, e),
        ent_tick=i(n, e),
        req_base=i(n),
        req_base_term=i(n),
        req_base_chk=jnp.zeros((n,), jnp.uint32),
        xfer_tgt=jnp.full((n,), NIL, node_dtype(cfg)),
        req_disrupt=jnp.zeros((n,), jnp.int8),
        ent_cfg=i(n, e),
        req_base_mold=jnp.zeros((n, bitplane.n_words(n)), jnp.uint32),
        req_base_pend=i(n),
        req_base_epoch=i(n),
        req_off=jnp.zeros((n, n), jnp.int8),
        resp_kind=jnp.zeros((n, n), jnp.int8),
        pv_grant=jnp.zeros((n, bitplane.n_words(n)), jnp.uint32),
        v_to=jnp.full((n,), NIL, node_dtype(cfg)),
        a_ok_to=jnp.full((n,), NIL, node_dtype(cfg)),
        a_match=jnp.zeros((n,), index_dtype(cfg)),
        a_hint=jnp.zeros((n,), index_dtype(cfg)),
        resp_term=i(n),
    )


def init_state(cfg: RaftConfig, key: jax.Array) -> ClusterState:
    """Fresh cluster: all followers at term 1 with empty logs (init-node core.clj:31-38,
    Log.start log.clj:32-34) and randomized initial election deadlines (the reference
    randomizes per wait-loop iteration, core.clj:174)."""
    n, cap = cfg.n_nodes, cfg.log_capacity
    idt = index_dtype(cfg)
    deadline = draw_timeouts(cfg, key, n)
    state = ClusterState(
        role=jnp.full((n,), FOLLOWER, jnp.int32),
        term=jnp.ones((n,), jnp.int32),
        voted_for=jnp.full((n,), NIL, jnp.int32),
        leader_id=jnp.full((n,), NIL, jnp.int32),
        votes=jnp.zeros((n, bitplane.n_words(n)), jnp.uint32),
        next_index=jnp.ones((n, n), idt),
        match_index=jnp.zeros((n, n), idt),
        ack_age=jnp.full((n, n), cfg.ack_age_sat, ack_dtype(cfg)),
        commit_index=jnp.zeros((n,), jnp.int32),
        commit_chk=jnp.zeros((n,), jnp.uint32),
        log_base=jnp.zeros((n,), jnp.int32),
        base_term=jnp.zeros((n,), jnp.int32),
        base_chk=jnp.zeros((n,), jnp.uint32),
        log_term=jnp.zeros((n, cap), jnp.int32),
        log_val=jnp.zeros((n, cap), jnp.int32),
        log_tick=jnp.zeros((n, cap), jnp.int32),
        log_len=jnp.zeros((n,), jnp.int32),
        # Durable boot state: the empty log is trivially durable, and the
        # boot term-1/no-vote pair counts as flushed (a node that crashes
        # before its first flush recovers to boot state, not to garbage).
        dur_len=jnp.zeros((n,), jnp.int32),
        dur_term=jnp.ones((n,), jnp.int32),
        dur_vote=jnp.full((n,), NIL, jnp.int32),
        clock=jnp.zeros((n,), jnp.int32),
        deadline=deadline,
        # "Quiet since before time began": pre-votes are grantable at boot.
        heard_clock=jnp.full((n,), -cfg.election_min_ticks, jnp.int32),
        # Reconfiguration plane: every node derives the all-voters boot
        # config from its (empty) log prefix when the plane is live --
        # per-node rows, one per node; all-zero dead weight otherwise.
        member_old=(
            jnp.broadcast_to(bitplane.full_row(n), (n, bitplane.n_words(n)))
            if cfg.reconfig
            else jnp.zeros((n, bitplane.n_words(n)), jnp.uint32)
        ),
        member_new=(
            jnp.broadcast_to(bitplane.full_row(n), (n, bitplane.n_words(n)))
            if cfg.reconfig
            else jnp.zeros((n, bitplane.n_words(n)), jnp.uint32)
        ),
        cfg_epoch=jnp.zeros((n,), jnp.int32),
        cfg_pend=jnp.zeros((n,), jnp.int32),
        log_cfg=jnp.zeros((n, cap), jnp.int32),
        base_mold=(
            jnp.broadcast_to(bitplane.full_row(n), (n, bitplane.n_words(n)))
            if cfg.reconfig
            else jnp.zeros((n, bitplane.n_words(n)), jnp.uint32)
        ),
        base_pend=jnp.zeros((n,), jnp.int32),
        base_epoch=jnp.zeros((n,), jnp.int32),
        xfer_to=jnp.full((n,), NIL, jnp.int32),  # NIL = idle, gate on or off
        read_idx=jnp.zeros((n,), jnp.int32),
        read_tick=jnp.zeros((n,), jnp.int32),
        read_acks=jnp.zeros((n, bitplane.n_words(n)), jnp.uint32),
        read_fr=jnp.zeros((n,), jnp.int32),
        client_pend=jnp.full((cfg.client_pipeline,), NIL, jnp.int32),
        client_dst=jnp.zeros((cfg.client_pipeline,), jnp.int32),
        client_tick=jnp.zeros((cfg.client_pipeline,), jnp.int32),
        lat_frontier=jnp.int32(0),
        now=jnp.int32(0),
        mailbox=empty_mailbox(cfg),
    )
    if cfg.compact_planes:
        # Compacted carry layout (ops/tile.py): the per-edge value planes
        # ride bit-packed flat uint32 legs, the narrow word/window planes
        # ride flattened. The field comments above document the DENSE
        # contract (the kernels' working view; the layout tiers are priced
        # by Pass C, not re-declared here).
        from raft_sim_tpu.ops import tile

        state = tile.pack_state(cfg, state)
    return state


def with_commit_chk(state: ClusterState) -> ClusterState:
    """Refresh commit_chk from the current log arrays + commit_index (single-cluster
    state). For tests and state surgery that set commit_index by hand. Ring-aware:
    states with log_base > 0 must carry a correct base_chk already."""
    from raft_sim_tpu.ops import log_ops

    (live,) = log_ops.ring_chk(
        state.log_term, state.log_val, state.log_base, (state.commit_index,)
    )
    return state._replace(commit_chk=state.base_chk + live)


def init_batch(cfg: RaftConfig, key: jax.Array, batch: int) -> ClusterState:
    """[batch, ...] struct-of-arrays over independent clusters, each with its own seed."""
    return jax.vmap(lambda k: init_state(cfg, k))(jax.random.split(key, batch))


def compact_twin(cfg: RaftConfig, on: bool = True) -> RaftConfig:
    """`cfg` with the compacted carry layout toggled (ops/tile.py): the
    layout A/B's one-knob twin -- trajectories are bit-identical either way,
    only the physical carry form (and therefore the priced bytes/tick)
    moves. Single-sourced here for bench, the traffic audit, and the parity
    tests."""
    import dataclasses

    return dataclasses.replace(cfg, compact_planes=on)
