"""Client write-path fidelity: redirect routing + offer->commit latency.

The reference's write path is POST any node -> HTTP 302 redirect to the known
leader, or to a random peer when leaderless (core.clj:151-160, server.clj:62-63),
and its commit watch was meant to ack the client on commit but never fires
(log.clj:83-87, bug 2.3.9). Here `client_redirect=True` reproduces the routing as
pure array state (one command in flight, one tick per bounce) and the latency the
watch should have measured is a first-class metric
(RunMetrics.lat_sum/lat_cnt -> FleetSummary.p50_commit_latency).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_sim_tpu import NIL, RaftConfig
from raft_sim_tpu.parallel import summarize
from raft_sim_tpu.sim import scan
from tests.test_handlers import base_state, make_leader, quiet_inputs, step

CFG_R = RaftConfig(n_nodes=5, log_capacity=8, client_redirect=True)


def offer_inputs(cfg, cmd, target, bounce=0):
    return quiet_inputs(cfg)._replace(
        client_cmd=jnp.int32(cmd),
        client_target=jnp.int32(target),
        client_bounce=jnp.full((cfg.client_pipeline,), bounce, jnp.int32),
    )


def test_offer_at_leader_accepted_same_tick():
    s = make_leader(base_state(CFG_R), 0, 2)
    s2, info = step(CFG_R, s, offer_inputs(CFG_R, 50, target=0))
    assert int(s2.log_len[0]) == 1
    assert int(s2.log_val[0, 0]) == 50
    assert int(s2.client_pend[0]) == NIL
    assert int(info.cmds_injected) == 1


def test_redirect_via_follower_costs_exactly_one_tick():
    """The VERDICT-pinned property: on a reliable net with a known leader, an
    offer targeting a follower lands one tick after a direct offer would -- the
    302 redirect bounce (server.clj:62-63)."""
    s = make_leader(base_state(CFG_R), 0, 2)  # every node knows leader 0
    s2, info = step(CFG_R, s, offer_inputs(CFG_R, 50, target=2))
    # tick 1: the follower redirects; nothing lands anywhere
    assert int(jnp.max(s2.log_len)) == 0
    assert int(info.cmds_injected) == 0
    assert int(s2.client_pend[0]) == 50
    assert int(s2.client_dst[0]) == 0  # redirected to the known leader
    # tick 2: the redirected POST lands on the leader
    s3, info2 = step(CFG_R, s2, quiet_inputs(CFG_R))
    assert int(s3.log_len[0]) == 1
    assert int(s3.log_val[0, 0]) == 50
    assert int(s3.client_pend[0]) == NIL
    assert int(info2.cmds_injected) == 1


def test_leaderless_offer_bounces_to_random_peer():
    """No leader known: redirect to a random peer (core.clj:154) and keep the
    command in flight."""
    s = base_state(CFG_R)  # all followers, leader_id NIL everywhere
    s2, info = step(CFG_R, s, offer_inputs(CFG_R, 50, target=2, bounce=3))
    assert int(info.cmds_injected) == 0
    assert int(s2.client_pend[0]) == 50
    assert int(s2.client_dst[0]) == 3
    assert int(jnp.max(s2.log_len)) == 0


def test_busy_client_drops_fresh_offers():
    """One command in flight at a time: a new offer while one is pending is
    dropped (the one-curl-at-a-time reference client)."""
    s = make_leader(base_state(CFG_R), 0, 2)
    s = s._replace(
        client_pend=jnp.full((1,), 50, jnp.int32),
        client_dst=jnp.zeros((1,), jnp.int32),
    )
    s2, info = step(CFG_R, s, offer_inputs(CFG_R, 60, target=0))
    # the pending 50 lands; the fresh 60 is dropped, not queued
    assert int(s2.log_len[0]) == 1
    assert int(s2.log_val[0, 0]) == 50
    assert int(s2.client_pend[0]) == NIL
    assert int(info.cmds_injected) == 1


def test_dead_target_bounces_instead_of_trusting_its_leader():
    """A POST to a crashed node fails; the client retries a random peer rather
    than following the dead node's stale leader pointer."""
    s = make_leader(base_state(CFG_R), 0, 2)
    inp = offer_inputs(CFG_R, 50, target=2, bounce=4)._replace(
        alive=jnp.ones((CFG_R.n_nodes,), bool).at[2].set(False)
    )
    s2, _ = step(CFG_R, s, inp)
    assert int(s2.client_pend[0]) == 50
    assert int(s2.client_dst[0]) == 4  # bounce, not node 2's leader_id


@pytest.mark.slow  # budget re-tier (PR 12): latency-metric correctness is
# pinned by the test_metrics percentile/histogram rows and the serve
# latency rollups; this direct-vs-redirect comparative soak (two windowed
# compiles) joins the client_path e2e soak in the slow tier -- the redirect
# bounce semantics themselves keep their tier-1 unit rows above.
def test_commit_latency_metric_direct_vs_redirect():
    """p50_commit_latency is live on client workloads and the redirect model pays
    at least the direct model's latency (each bounce costs a tick)."""
    base = dict(n_nodes=5, client_interval=4)
    _, m_direct = scan.simulate(RaftConfig(**base), 0, 32, 300)
    _, m_redir = scan.simulate(RaftConfig(**base, client_redirect=True), 0, 32, 300)
    s_direct = summarize(m_direct)
    s_redir = summarize(m_redir)
    assert s_direct.p50_commit_latency is not None
    assert s_redir.p50_commit_latency is not None
    # commit takes at least a full replicate+ack round trip
    assert s_direct.p50_commit_latency >= 2
    assert s_redir.p50_commit_latency >= s_direct.p50_commit_latency
    # redirect still delivers: commands were accepted and committed fleet-wide
    assert s_redir.total_cmds > 0
    m = jax.device_get(m_redir)
    assert int(np.sum(m.violations)) == 0


def test_no_latency_metric_without_client_traffic():
    _, m = scan.simulate(RaftConfig(n_nodes=5), 0, 8, 100)
    s = summarize(m)
    assert s.p50_commit_latency is None
    assert int(np.sum(jax.device_get(m).lat_cnt)) == 0


def test_session_offer_reports_committed():
    from raft_sim_tpu.driver import Session

    sess = Session(RaftConfig(n_nodes=5), batch=8, seed=0)
    sess.run(60)  # elect leaders everywhere first
    res = sess.offer(777, wait=20)
    assert res["accepted"] == 8
    assert res["committed"] == 8
    assert res["waited"] >= 1  # commit takes a replication round trip


def test_session_offer_reports_committed_under_redirect():
    """Under client_redirect, acceptance lands after the 302 bounces, so the
    same-tick `accepted` undercounts -- the commitment loop must keep stepping
    anyway (code-review finding)."""
    from raft_sim_tpu.driver import Session

    sess = Session(RaftConfig(n_nodes=5, client_redirect=True), batch=8, seed=0)
    sess.run(60)
    res = sess.offer(-7, wait=40)
    assert res["committed"] == 8  # every cluster committed the redirected offer
    assert res["accepted"] < 8  # ~1/5 of targets hit the leader on tick one


def test_session_offer_value_collision_never_false_positives():
    """A value colliding with an already-committed scheduled command must not
    be reported as this offer's commitment. Under the delta-stream ack
    (serve/deltas.py) the watcher's watermark is fast-forwarded past
    everything committed BEFORE the offer, so at wait=0 the old entry cannot
    false-positive -- and unlike the superseded snapshot-diff poll (which
    undercounted this input to 0 forever), a waited offer of the same value
    does ack: tests/test_serve.py pins that half of the contract."""
    from raft_sim_tpu.driver import Session

    sess = Session(RaftConfig(n_nodes=5, client_interval=8), batch=8, seed=0)
    sess.run(200)  # scheduled value 41 (offer tick 40) committed long ago
    res = sess.offer(41, wait=0)
    assert res["committed"] == 0


# ----------------------------------------------- K-deep in-flight pipeline (K > 1)

CFG_P = RaftConfig(n_nodes=5, log_capacity=8, client_redirect=True, client_pipeline=3)


def test_pipeline_queues_offers_instead_of_dropping():
    """With K slots, fresh offers queue while earlier ones are still bouncing;
    only a FULL pipeline drops (the reference's buffered(5) request channel,
    server.clj:37)."""
    s = base_state(CFG_P)  # leaderless: every offer keeps bouncing
    for i, cmd in enumerate((50, 60, 70)):
        s, info = step(CFG_P, s, offer_inputs(CFG_P, cmd, target=2, bounce=3))
        assert int(info.cmds_injected) == 0
    assert [int(x) for x in s.client_pend] == [50, 60, 70]
    # Pipeline full: the fourth offer is dropped, the three stay in flight.
    s, info = step(CFG_P, s, offer_inputs(CFG_P, 80, target=2, bounce=3))
    assert [int(x) for x in s.client_pend] == [50, 60, 70]


def test_pipeline_accepts_one_slot_per_node_per_tick_lowest_first():
    """Two pending slots targeting the same leader: the lowest slot lands this
    tick (the reference dequeues one message per wait iteration); the other
    stays pending and lands next tick."""
    s = make_leader(base_state(CFG_P), 0, 2)
    s = s._replace(
        client_pend=jnp.asarray([50, 60, NIL], jnp.int32),
        client_dst=jnp.zeros((3,), jnp.int32),
    )
    s2, info = step(CFG_P, s, quiet_inputs(CFG_P))
    assert int(s2.log_len[0]) == 1
    assert int(s2.log_val[0, 0]) == 50  # lowest slot first
    assert int(info.cmds_injected) == 1
    assert [int(x) for x in s2.client_pend] == [NIL, 60, NIL]
    s3, info2 = step(CFG_P, s2, quiet_inputs(CFG_P))
    assert int(s3.log_len[0]) == 2
    assert int(s3.log_val[0, 1]) == 60
    assert int(info2.cmds_injected) == 1


@pytest.mark.slow
def test_pipeline_no_drop_and_all_commit_end_to_end():
    """Offers beyond one-in-flight are not lost: a K=4 pipeline under a fast
    offer cadence accepts strictly more than the K=1 client on the same
    trajectory seeds, and everything offered-and-accepted commits (0
    violations). Slow tier (two 600-tick sims; the pipeline unit tests above
    and the oracle-parity pipeline row stay tier-1)."""
    base = dict(
        n_nodes=5, log_capacity=32, compact_margin=8, client_interval=2,
        client_redirect=True,
    )
    _, m1 = scan.simulate(RaftConfig(**base), 0, 32, 600)
    _, m4 = scan.simulate(RaftConfig(**base, client_pipeline=4), 0, 32, 600)
    s1, s4 = summarize(m1), summarize(m4)
    assert s1.total_violations == 0 and s4.total_violations == 0
    assert s4.total_cmds > s1.total_cmds  # the queue absorbs bounce latency
    assert s4.lat_p50 is not None


def test_manual_offer_values_do_not_corrupt_latency_metric():
    """Arbitrary Session.offer payloads must not decode as offer ticks in the
    latency accumulator (code-review finding: a large or negative value would
    skew p50_commit_latency wildly)."""
    from raft_sim_tpu.driver import Session

    sess = Session(RaftConfig(n_nodes=5, client_interval=8), batch=8, seed=0)
    sess.run(100)
    sess.offer(-1000, wait=20)
    sess.run(50)
    after = sess.summary()["p50_commit_latency"]
    assert after is not None
    assert 1 <= after <= 10  # still the ordinary replication round trip
