"""Benchmark: cluster-ticks/sec/chip across the BASELINE fault matrix.

Prints ONE JSON line. The headline fields {"metric", "value", "unit", "vs_baseline"}
are the north-star workload (config3: 100k x 5-node clusters, randomized election
timeouts; target >=1M cluster-ticks/sec/chip, BASELINE.json `north_star`); the
"matrix" field carries one row per BASELINE config (config1 is the
single-cluster 10k-tick correctness reference with log matching checked every
tick, config2 the 1k-cluster vmap row, 3-5 the throughput/fault rows -- config5
now with sampled log matching on) plus three feature rows: config6 (ring
compaction under crash churn), config6r (the same through the 302-redirect
client write path), and config4c (config4's fault mix under client traffic, so
commit latency is measured UNDER faults). Each row carries throughput AND the
quality metrics (p50 ticks-to-stable-leader, mean-based p50 offer->commit
latency, true per-entry lat_p50/p95/p99 from the on-device histogram,
accepted-command / violation / liveness counters). The reference publishes no
numbers of its own (SURVEY.md section 6).

Two timing traps on this machine's TPU stack, both defended here:
  1. it caches identical (program, args) executions, so every timed repeat uses a
     fresh TIME-SALTED seed (a never-before-seen args tuple);
  2. `jax.block_until_ready` can return early (~1 ms) while the program is still
     executing (observed: 0.001 s walls -> 98G "ticks/s"), so each repeat is timed
     to a forced HOST COPY of a per-cluster output -- data on the host cannot lie.
Per-config tick counts keep each XLA call well under the tunnel's execution
watchdog (~60 s).

Usage: python bench.py                      # full matrix (TPU-sized)
       python bench.py --smoke              # CPU-sized shrink of the same matrix
       python bench.py --preset config4     # one config only
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import jax
import numpy as np

from raft_sim_tpu import PRESETS, RaftConfig
from raft_sim_tpu.parallel import summarize
from raft_sim_tpu.sim import scan

NORTH_STAR = 1_000_000.0  # cluster-ticks/sec/chip, BASELINE.json north_star

# config -> ticks per timed call (bounded so one call stays watchdog-safe even at
# full batch; config5's N=51 tick is ~100x a 5-node tick). config1 runs its full
# BASELINE 10k-tick soak (single cluster -- the correctness row, not a
# throughput row). Rows 6/6r exercise the ring-compaction + redirect write
# path, row 4c the config4 fault mix under client traffic, so the standing
# bench carries compaction/redirect throughput and commit latency UNDER faults
# (not only on reliable nets).
MATRIX_TICKS = {
    "config1": 10_000,
    "config2": 2_000,
    "config3": 500,
    "config4": 300,
    "config4c": 300,
    "config5": 200,
    "config6": 5_000,
    "config6r": 5_000,
}
SMOKE_BATCH = {
    "config2": 64,
    "config3": 512,
    "config4": 256,
    "config4c": 256,
    "config5": 16,
    "config6": 64,
    "config6r": 64,
}
SMOKE_TICKS = {"config1": 1_000, "config6": 1_000, "config6r": 1_000}


def _roofline_pins() -> dict:
    """Predicted per-config rooflines from the gated cost model's pins
    (tests/golden_cost_model.json, regenerated via `tools/check.py
    --update-goldens`): bytes/tick x the pinned implied HBM rate. Read-only
    and fully guarded -- bench must still run where the pins are absent
    (installed package, fresh clone); rows then simply omit the
    predicted-vs-measured fields."""
    try:
        from raft_sim_tpu.analysis import cost_model

        with open(cost_model.golden_path()) as f:
            return json.load(f).get("programs", {})
    except Exception:
        return {}


_ROOFLINE_PINS = _roofline_pins()


def _telemetry_window(ticks: int) -> int:
    """A window size that divides the run (the windowed scan requires it):
    the finest of a few round divisors, falling back to one whole-run window."""
    for d in (16, 10, 8, 5, 4, 2):
        if ticks % d == 0:
            return ticks // d
    return ticks


def _pin_applies(config_name: str, batch: int, smoke: bool) -> bool:
    """The pins are priced at the preset's production batch; a --smoke or
    custom-batch row must not carry a headroom number computed against a
    different-batch roofline (it would read as ~100x headroom on CPU).
    `smoke` is checked on its own because a preset whose smoke batch equals
    its production batch (config1: batch 1 both ways) would otherwise slip
    through the batch comparison."""
    return (not smoke and config_name in PRESETS
            and batch == PRESETS[config_name][1])


def bench(cfg: RaftConfig, batch: int, ticks: int, repeats: int = 2,
          quality_seeds: int = 3, telemetry_dir: str | None = None,
          config_name: str = "custom", scenario=None,
          smoke: bool = False) -> dict:
    # `scenario` (a ScenarioProgram) reroutes every run through the
    # scenario-engine input path -- the program's genome broadcast over the
    # fleet -- so the row prices the genome-table reads and the
    # always-traced fault lattice against the scalar path's numbers
    # (docs/PERF.md "scenario path" has the standing verdict).
    if scenario is not None:
        from raft_sim_tpu.scenario import genome as genome_mod

        g = genome_mod.broadcast(scenario.genome, batch)
        seg_len = scenario.seg_len
        sim = lambda seed: scan.simulate_scenario(cfg, seed, batch, ticks, g, seg_len)
    else:
        g = seg_len = None
        sim = lambda seed: scan.simulate(cfg, seed, batch, ticks)
    # Quality runs use FIXED seeds 0..quality_seeds-1 (reproducible across
    # invocations, comparable across commits) and their per-cluster metrics are
    # pooled, so the reported p50s sample quality_seeds x batch clusters instead
    # of one seed's worth. The first doubles as the compile warmup. Timed repeats
    # then use time-salted seeds (capped so seed_base + r stays int32).
    #
    # With telemetry_dir set, the seed-0 quality run goes through the windowed
    # telemetry scan instead and its window records land in
    # telemetry_dir/<config_name>/ under the SAME schema driver.py writes
    # (utils/telemetry_sink.py) -- bit-exact, so the pooled quality metrics are
    # unchanged (tests/test_telemetry.py pins windowed == monolithic).
    pooled = []
    for qs in range(quality_seeds):
        if qs == 0 and telemetry_dir is not None:
            from raft_sim_tpu.sim import telemetry
            from raft_sim_tpu.utils.telemetry_sink import TelemetrySink

            window = _telemetry_window(ticks)
            sink = TelemetrySink(
                os.path.join(telemetry_dir, config_name), cfg, seed=qs,
                batch=batch, window=window, ring=0, source="bench",
            )
            final, m, records, _ = telemetry.simulate_windowed(
                cfg, qs, batch, ticks, window, genome=g,
                seg_len=seg_len if seg_len is not None else 1,
            )
            sink.append_windows(jax.device_get(records))
        else:
            final, m = sim(qs)
        pooled.append(jax.device_get(m))
    q_metrics = type(pooled[0])(
        *(np.concatenate([np.asarray(getattr(m, f)) for m in pooled])
          for f in pooled[0]._fields)
    )

    seed_base = int(time.time_ns() % ((1 << 31) - 1 - repeats))
    best = float("inf")
    for r in range(1, repeats + 1):
        t0 = time.perf_counter()
        final, metrics = sim(seed_base + r)
        # Time to a host copy, not block_until_ready (see module docstring).
        np.asarray(metrics.ticks)
        best = min(best, time.perf_counter() - t0)

    s = summarize(q_metrics)  # pooled fixed-seed quality metrics
    if telemetry_dir is not None:
        # summary.json must describe the SAME run the manifest/windows do
        # (seed 0 alone) -- the pooled 3-seed rollup `s` stays in the bench
        # row, not in the telemetry directory.
        sink.write_summary(summarize(pooled[0])._asdict())
    value = batch * ticks / best
    # Measured throughput vs the PINNED roofline (this program's bytes/tick x
    # the pinned implied HBM rate -- equal to the anchor at pin time by
    # construction, so this is a drift detector against the pins, not a
    # layout-vs-layout bound; those live in tools/traffic_audit.py). ~1.0 =
    # tracking the pins; >1 = slower than pinned (regression, or a non-HBM
    # bottleneck at the pinned rate); <1 = faster than the pins -- they are
    # stale, regenerate after this round's artifact lands.
    pin = _ROOFLINE_PINS.get(f"{config_name}/simulate", {})
    roof = pin.get("roofline_ticks_per_s")
    if not _pin_applies(config_name, batch, smoke):
        roof = None
    row = {
        "cluster_ticks_per_s": round(value, 1),
        "vs_baseline": round(value / NORTH_STAR, 3),
        "batch": batch,
        "n_nodes": cfg.n_nodes,
        "ticks": ticks,
        "wall_s": round(best, 3),
        "p50_stable_tick": s.p50_stable_tick,
        "pct_stable": round(100.0 * s.n_stable / s.n_clusters, 1),
        "p50_commit_latency": s.p50_commit_latency,
        "lat_p50": s.lat_p50,
        "lat_p95": s.lat_p95,
        "lat_p99": s.lat_p99,
        "lat_excluded": s.lat_excluded,
        "total_cmds": s.total_cmds,
        "violations": s.total_violations,
        "noop_blocked": s.noop_blocked,
        "lm_skipped_pairs": s.lm_skipped_pairs,
        "multi_leader": s.multi_leader,
        "quality_seeds": quality_seeds,
    }
    if smoke:
        # Marked so cost_model.bench_anchor can reject the row even when the
        # preset's smoke batch equals its production batch (config1).
        row["smoke"] = True
    if roof and scenario is None:
        row["predicted_roofline_ticks_per_s"] = round(roof, 1)
        row["roofline_headroom"] = round(roof / value, 3)
    return row


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default=None, choices=sorted(PRESETS),
                    help="bench one config instead of the 3/4/5 matrix")
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--ticks", type=int, default=None)
    ap.add_argument("--repeats", type=int, default=2)
    ap.add_argument("--smoke", action="store_true",
                    help="CPU-sized shrink (small batches) of the same matrix")
    ap.add_argument("--telemetry-dir", default=None, metavar="DIR",
                    help="also write each config's seed-0 quality run as a "
                         "telemetry directory (DIR/<config>/, the same schema "
                         "driver.py --telemetry-dir emits)")
    ap.add_argument("--scenario", default=None, metavar="FILE",
                    help="run the benched config(s) through the scenario-"
                         "engine input path under this nemesis program "
                         "(prices the genome-table reads; requires --preset)")
    ap.add_argument("--out", default=None, metavar="PATH",
                    help="write the FULL matrix JSON to PATH and print only a "
                         "short headline line (north-star ratio + per-config "
                         "ticks/s) to stdout -- so a truncated terminal/log "
                         "capture can never clip the primary perf evidence "
                         "again (VERDICT weak #2); the file is the same "
                         "document cost_model.bench_anchor reads (save it as "
                         "BENCH_r<N>.json to anchor the roofline)")
    args = ap.parse_args()

    scenario = None
    if args.scenario:
        if not args.preset:
            ap.error("--scenario requires --preset (one labeled row)")
        from raft_sim_tpu.scenario import program as program_mod

        scenario = program_mod.load(args.scenario, PRESETS[args.preset][0])

    names = (
        [args.preset]
        if args.preset
        else [
            "config1",
            "config2",
            "config3",
            "config4",
            "config4c",
            "config5",
            "config6",
            "config6r",
        ]
    )
    matrix = {}
    for name in names:
        cfg, preset_batch = PRESETS[name]
        smoke_batch = SMOKE_BATCH.get(name, min(preset_batch, 256))
        batch = args.batch or (smoke_batch if args.smoke else preset_batch)
        ticks = args.ticks or (
            SMOKE_TICKS[name]
            if args.smoke and name in SMOKE_TICKS
            else MATRIX_TICKS.get(name, 300)
        )
        print(f"bench {name}: batch={batch} ticks={ticks}...", file=sys.stderr)
        matrix[name] = bench(cfg, batch, ticks, args.repeats,
                             telemetry_dir=args.telemetry_dir, config_name=name,
                             scenario=scenario, smoke=args.smoke)
        if scenario is not None:
            matrix[name]["scenario"] = scenario.name

    # The headline is the north-star workload (config3) whenever it ran; benching a
    # different single preset labels itself via "workload" so vs_baseline is never
    # silently misread as the config3 number.
    headline_name = "config3" if "config3" in matrix else names[0]
    headline = matrix[headline_name]
    doc = {
        "metric": "cluster-ticks/sec/chip",
        "value": headline["cluster_ticks_per_s"],
        "unit": "cluster-ticks/s",
        "vs_baseline": headline["vs_baseline"],
        "workload": headline_name,
        "matrix": matrix,
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
        per_cfg = " ".join(
            f"{name}={row['cluster_ticks_per_s']:g}" for name, row in matrix.items()
        )
        print(
            f"{headline_name} {headline['cluster_ticks_per_s']:g} "
            f"cluster-ticks/s ({headline['vs_baseline']}x north star) | "
            f"{per_cfg} | full matrix: {args.out}"
        )
    else:
        print(json.dumps(doc))


if __name__ == "__main__":
    main()
