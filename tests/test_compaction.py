"""Ring-log compaction + snapshot catch-up (cfg.compact_margin > 0).

The reference's log is an unbounded Clojure vector (log.clj:33, append at
log.clj:61-67): a reference cluster accepts client writes forever. The fixed-CAP
array log must therefore compact its committed prefix (advance log_base) and give
laggards an InstallSnapshot analogue (req_off sentinel -1 installing
base/base_term/base_chk) or long-horizon client workloads would exhaust it. These
tests pin every new transition at the handler level (hand-built states, one tick)
plus a CI-sized unbounded-horizon liveness run; tests/test_oracle_parity.py and
tests/test_batched_parity.py pin the same semantics against the oracle and the
batch-minor kernel across random trajectories.
"""

import jax
import jax.numpy as jnp
import numpy as np

from raft_sim_tpu import FOLLOWER, LEADER, NIL, RaftConfig, StepInputs, init_state
from raft_sim_tpu.ops import bitplane
from raft_sim_tpu.sim import scan
from raft_sim_tpu.types import REQ_APPEND
from tests import oracle as orc
from tests.test_handlers import (
    ae_wire,
    base_state,
    quiet_inputs,
    resp_match_of,
    resp_ok_of,
    step,
)

M32 = (1 << 32) - 1

# Ring of 8 slots, compaction keeps >= 2 free (retain target 6).
CFG = RaftConfig(n_nodes=5, log_capacity=8, compact_margin=2, max_entries_per_rpc=4)


def chk_of(entries, start0=0):
    """Checksum of consecutive (term, val) entries at absolute 0-based indices
    start0... -- stated via the oracle's independent weight formula."""
    acc = 0
    for i, (t, v) in enumerate(entries):
        w_t, w_v = orc.chk_weights(start0 + i)
        acc = (acc + t * w_t + v * w_v) & M32
    return acc


def hist(a, b):
    """The canonical synthetic history: absolute 1-based entry i is (term 1,
    value 1000 + i). Returns entries for indices a+1..b."""
    return [(1, 1000 + i) for i in range(a + 1, b + 1)]


def hist_chk(upto):
    return chk_of(hist(0, upto))


def with_ring_log(s, node, base, entries, commit, base_term=1):
    """Install a compacted ring log on `node`: `entries` are (term, val) for
    absolute indices base+1..base+len(entries); checksums are derived as if the
    compacted prefix were the canonical hist()."""
    cap = CFG.log_capacity
    lt, lv = s.log_term, s.log_val
    for k, (t, v) in enumerate(entries):
        slot = (base + k) % cap
        lt = lt.at[node, slot].set(t)
        lv = lv.at[node, slot].set(v)
    bchk = hist_chk(base)
    cchk = (bchk + chk_of(entries[: commit - base], start0=base)) & M32
    return s._replace(
        log_term=lt,
        log_val=lv,
        log_len=s.log_len.at[node].set(base + len(entries)),
        log_base=s.log_base.at[node].set(base),
        base_term=s.base_term.at[node].set(base_term if base else 0),
        base_chk=s.base_chk.at[node].set(np.uint32(bchk if base else 0)),
        commit_index=s.commit_index.at[node].set(commit),
        commit_chk=s.commit_chk.at[node].set(np.uint32(cchk)),
    )


def snap_wire(s, src, term, L, Lt, Lchk):
    """Broadcast an InstallSnapshot analogue from `src`: an AppendEntries whose
    every edge carries the req_off sentinel -1 plus the snapshot header."""
    mb = s.mailbox._replace(
        req_type=s.mailbox.req_type.at[src].set(REQ_APPEND),
        req_term=s.mailbox.req_term.at[src].set(term),
        req_commit=s.mailbox.req_commit.at[src].set(L),
        req_base=s.mailbox.req_base.at[src].set(L),
        req_base_term=s.mailbox.req_base_term.at[src].set(Lt),
        req_base_chk=s.mailbox.req_base_chk.at[src].set(jnp.uint32(Lchk)),
        req_off=s.mailbox.req_off.at[src, :].set(-1),
    )
    return s._replace(mailbox=mb)


def leader(s, node, term, next_to=None):
    """Minimal leader fixture (wide-index variant of test_handlers.make_leader)."""
    n = CFG.n_nodes
    nxt = int(s.log_len[node]) + 1 if next_to is None else next_to
    return s._replace(
        role=s.role.at[node].set(LEADER),
        term=s.term.at[node].set(term),
        leader_id=jnp.full((n,), node, jnp.int32),
        next_index=s.next_index.at[node].set(
            jnp.full((n,), nxt, s.next_index.dtype)
        ),
        ack_age=s.ack_age.at[node].set(jnp.zeros((n,), s.ack_age.dtype)),
    )


# --------------------------------------------------------------- snapshot install


def test_snapshot_install_wipe():
    """A fresh follower receiving a snapshot adopts it wholesale: log becomes
    logically empty at base = L, commit = L, checksums = the leader's."""
    L, Lchk = 10, hist_chk(10)
    s = base_state(CFG)
    s = s._replace(term=s.term.at[1].set(2))
    s = snap_wire(s, 0, term=2, L=L, Lt=1, Lchk=Lchk)
    s2, info = step(CFG, s)
    assert int(s2.log_base[1]) == L
    assert int(s2.log_len[1]) == L
    assert int(s2.commit_index[1]) == L
    assert int(s2.base_term[1]) == 1
    assert int(np.uint32(s2.base_chk[1])) == Lchk
    assert int(np.uint32(s2.commit_chk[1])) == Lchk
    assert int(s2.leader_id[1]) == 0
    assert resp_ok_of(s2.mailbox, 0, 1)
    assert resp_match_of(s2.mailbox, 0, 1) == L
    assert not bool(info.viol_commit)


def test_snapshot_install_keep_retains_suffix():
    """If the follower's log extends through L with the snapshot's term, the
    suffix past L is retained (Raft fig. 13 rule 6)."""
    s = base_state(CFG)
    s = with_ring_log(s, 1, base=4, entries=hist(4, 12), commit=6)
    s = s._replace(term=s.term.at[1].set(2))
    s = snap_wire(s, 0, term=2, L=8, Lt=1, Lchk=hist_chk(8))
    s2, info = step(CFG, s)
    assert int(s2.log_base[1]) == 8
    assert int(s2.log_len[1]) == 12  # suffix retained
    assert int(s2.commit_index[1]) == 8
    assert int(np.uint32(s2.base_chk[1])) == hist_chk(8)
    # entries 9..12 still live in the ring
    for i in range(9, 13):
        assert int(s2.log_val[1, (i - 1) % CFG.log_capacity]) == 1000 + i
    assert resp_match_of(s2.mailbox, 0, 1) == 8
    assert not bool(info.viol_commit)


def test_snapshot_install_wipe_on_conflict():
    """A conflicting entry at L (different term) discards the whole log."""
    s = base_state(CFG)
    ents = hist(0, 6) + [(2, 99), (2, 98)]  # entries 7, 8 from term 2
    s = with_ring_log(s, 1, base=0, entries=ents, commit=4)
    s = s._replace(term=s.term.at[1].set(3))
    s = snap_wire(s, 0, term=3, L=8, Lt=1, Lchk=hist_chk(8))
    s2, info = step(CFG, s)
    assert int(s2.log_base[1]) == 8
    assert int(s2.log_len[1]) == 8  # suffix discarded (term 2 entry conflicted)
    assert int(s2.commit_index[1]) == 8
    assert int(np.uint32(s2.commit_chk[1])) == hist_chk(8)
    assert not bool(info.viol_commit)


def test_snapshot_below_base_is_plain_ack():
    """L at or below our base installs nothing but still acks (the leader's
    match/next then walk forward past the snapshot)."""
    s = base_state(CFG)
    s = with_ring_log(s, 1, base=8, entries=hist(8, 10), commit=9)
    s = s._replace(term=s.term.at[1].set(2))
    s = snap_wire(s, 0, term=2, L=6, Lt=1, Lchk=hist_chk(6))
    s2, info = step(CFG, s)
    assert int(s2.log_base[1]) == 8  # unchanged
    assert int(s2.log_len[1]) == 10
    assert int(s2.commit_index[1]) == 9
    assert resp_ok_of(s2.mailbox, 0, 1)
    assert resp_match_of(s2.mailbox, 0, 1) == 6
    assert not bool(info.viol_commit)


# ------------------------------------------------------------------- ring appends


def test_ring_append_wraps_past_capacity():
    """Appending at prev == base (boundary consistency via base_term) wraps
    physical slots: entries 7..10 of an 8-ring land at slots 6, 7, 0, 1."""
    s = base_state(CFG)
    s = with_ring_log(s, 1, base=6, entries=[], commit=6)
    s = s._replace(term=s.term.at[1].set(2))
    ents = [(2, 71), (2, 72), (2, 73), (2, 74)]  # abs 7..10
    s = ae_wire(s, 0, term=2, prev_i=6, prev_t=1, commit=6, ents=ents)
    s2, info = step(CFG, s)
    assert int(s2.log_len[1]) == 10
    assert resp_ok_of(s2.mailbox, 0, 1)
    assert resp_match_of(s2.mailbox, 0, 1) == 10
    cap = CFG.log_capacity
    for i, (_, v) in zip(range(7, 11), ents):
        assert int(s2.log_val[1, (i - 1) % cap]) == v
    assert not bool(info.viol_commit)


def test_ring_append_clamped_at_capacity():
    """Entries past base + CAP would evict live slots -> partial accept, partial
    ack; the leader retries the rest after commit/compaction frees room."""
    s = base_state(CFG)
    s = with_ring_log(s, 1, base=2, entries=hist(2, 8), commit=8)
    s = s._replace(term=s.term.at[1].set(2))
    ents = [(2, 91), (2, 92), (2, 93), (2, 94)]  # abs 9..12; ring holds <= 10
    s = ae_wire(s, 0, term=2, prev_i=8, prev_t=1, commit=8, ents=ents)
    s2, _ = step(CFG, s)
    assert int(s2.log_len[1]) == 10  # 9 and 10 accepted, 11 and 12 clamped off
    assert resp_match_of(s2.mailbox, 0, 1) == 10
    cap = CFG.log_capacity
    assert int(s2.log_val[1, 8 % cap]) == 91
    assert int(s2.log_val[1, 9 % cap]) == 92
    # the slots entries 11/12 would have taken still hold live entries 3 and 4
    assert int(s2.log_val[1, 10 % cap]) == 1003
    assert int(s2.log_val[1, 11 % cap]) == 1004


def test_append_below_base_skips_compacted_prefix():
    """prev below the receiver's base is consistent by leader completeness; the
    shipped entries overlapping the compacted prefix are skipped, the rest land."""
    s = base_state(CFG)
    s = with_ring_log(s, 1, base=6, entries=hist(6, 8), commit=8)
    s = s._replace(term=s.term.at[1].set(2))
    # prev = 4 < base = 6; entries abs 5..8. 5 and 6 are compacted (skipped); 7
    # and 8 match the stored terms/values -> nothing changes but the ack covers 8.
    ents = [(1, 1005), (1, 1006), (1, 1007), (1, 1008)]
    s = ae_wire(s, 0, term=2, prev_i=4, prev_t=1, commit=8, ents=ents)
    before = np.asarray(s.log_val[1]).copy()
    s2, info = step(CFG, s)
    assert resp_ok_of(s2.mailbox, 0, 1)
    assert resp_match_of(s2.mailbox, 0, 1) == 8
    assert int(s2.log_len[1]) == 8
    np.testing.assert_array_equal(np.asarray(s2.log_val[1]), before)
    assert not bool(info.viol_commit)


# ------------------------------------------------------- compaction + client path


def test_compaction_advances_base_to_commit_bound():
    """A full ring with a committed prefix rebases: base -> min(commit,
    len - (CAP - margin)), base_term/base_chk follow."""
    s = base_state(CFG)
    s = with_ring_log(s, 1, base=0, entries=hist(0, 8), commit=8)
    s2, info = step(CFG, s)
    # target = min(8, 8 - (8 - 2)) = 2
    assert int(s2.log_base[1]) == 2
    assert int(s2.base_term[1]) == 1
    assert int(np.uint32(s2.base_chk[1])) == hist_chk(2)
    assert int(s2.log_len[1]) == 8
    assert not bool(info.viol_commit)


def test_compaction_never_passes_commit():
    """Uncommitted entries are never compacted: a full ring with a short committed
    prefix only rebases up to commit (and the log then stays full)."""
    s = base_state(CFG)
    s = with_ring_log(s, 1, base=0, entries=hist(0, 8), commit=1)
    s2, info = step(CFG, s)
    assert int(s2.log_base[1]) == 1
    assert not bool(info.viol_commit)


def test_injection_wraps_into_freed_slots():
    """A leader whose ring wrapped keeps accepting commands: the new entry lands
    at slot len mod CAP (previously occupied by a compacted entry)."""
    s = base_state(CFG)
    s = with_ring_log(s, 0, base=4, entries=hist(4, 10), commit=10)
    s = leader(s, 0, term=1)
    inp = quiet_inputs(CFG)._replace(client_cmd=jnp.int32(777))
    s2, info = step(CFG, s, inp)
    assert int(s2.log_len[0]) == 11
    assert int(s2.log_val[0, 10 % CFG.log_capacity]) == 777
    assert int(info.cmds_injected) == 1
    assert not bool(info.viol_commit)


def test_client_injection_respects_noop_reserve():
    """Client commands stop max(1, margin // 2) slots short of the ring so an
    election no-op always finds room (code-review finding: a full ring of
    old-term entries deadlocks commit forever under spec 5.4.2)."""
    s = base_state(CFG)
    # retained = 7 = CAP - reserve (reserve = 1 for margin 2): client blocked.
    s = with_ring_log(s, 0, base=4, entries=hist(4, 11), commit=4)
    s = leader(s, 0, term=1)
    inp = quiet_inputs(CFG)._replace(client_cmd=jnp.int32(777))
    s2, info = step(CFG, s, inp)
    assert int(s2.log_len[0]) == 11  # rejected
    assert int(info.cmds_injected) == 0


def test_election_win_appends_noop_entry():
    """A fresh leader appends a current-term NO-OP so old-term entries can pass
    the spec-5.4.2 commit gate (otherwise a leader whose whole ring is old-term
    entries could never advance commit -- the reviewed deadlock)."""
    from raft_sim_tpu.types import NOOP, RESP_VOTE
    from tests.test_handlers import resp_wire

    s = base_state(CFG)
    s = with_ring_log(s, 0, base=4, entries=hist(4, 10), commit=4)
    s = s._replace(
        role=s.role.at[0].set(1),  # CANDIDATE
        term=s.term.at[0].set(5),
        voted_for=s.voted_for.at[0].set(0),
        votes=bitplane.set_bit(s.votes, 0, 0),
    )
    s = resp_wire(s, 0, 1, RESP_VOTE, term=5, ok=True)
    s = resp_wire(s, 0, 2, RESP_VOTE, term=5, ok=True)
    s2, info = step(CFG, s)
    assert int(s2.role[0]) == LEADER
    assert int(s2.log_len[0]) == 11  # the no-op
    slot = 10 % CFG.log_capacity
    assert int(s2.log_term[0, slot]) == 5
    assert int(s2.log_val[0, slot]) == NOOP
    assert int(info.cmds_injected) == 0  # no-ops are not client commands


def test_same_tick_rebase_and_injection_keeps_checksums_exact():
    """Code-review finding (confirmed by repro): when commit jumps on a full ring,
    compaction frees slots and the same tick's injection reuses one; the checksum
    pass must read the OLD entry under its weight (it runs before phase 6), or
    base_chk silently absorbs the new value under the compacted entry's weight."""
    cap = CFG.log_capacity
    ents = [(3, 200 + i) for i in range(13, 21)]  # abs 13..20, leader's term
    s = base_state(CFG)
    s = with_ring_log(s, 0, base=12, entries=ents, commit=12)  # retained == CAP
    s = leader(s, 0, term=3)
    # quorum already replicated everything: commit jumps 12 -> 20 this tick
    s = s._replace(
        match_index=s.match_index.at[0, 1].set(20).at[0, 2].set(20),
    )
    inp = quiet_inputs(CFG)._replace(client_cmd=jnp.int32(55))
    s2, info = step(CFG, s, inp)
    assert int(s2.commit_index[0]) == 20
    # compaction target: min(20, 20 - (CAP - margin)) = 14
    assert int(s2.log_base[0]) == 14
    assert int(s2.log_len[0]) == 21  # injection went through
    assert int(s2.log_val[0, 20 % cap]) == 55  # ... into just-freed slot 4
    # checksums reflect the ORIGINAL entries 13..14 / 13..20, not the overwrite
    want_base = (hist_chk(12) + chk_of(ents[:2], start0=12)) & M32
    want_commit = (hist_chk(12) + chk_of(ents, start0=12)) & M32
    assert int(np.uint32(s2.base_chk[0])) == want_base
    assert int(np.uint32(s2.commit_chk[0])) == want_commit
    assert not bool(info.viol_commit)
    # and the next tick's carried-checksum verification still passes
    _, info2 = step(CFG, s2)
    assert not bool(info2.viol_commit)


def test_leader_sends_snapshot_sentinel_below_base():
    """A peer whose next_index fell below the leader's base gets req_off = -1 and
    the snapshot header; peers inside the retained window get normal offsets."""
    s = base_state(CFG)
    s = with_ring_log(s, 0, base=6, entries=hist(6, 10), commit=10)
    s = leader(s, 0, term=1)
    # peer 1 lags below the base; peers 2..4 are caught up
    s = s._replace(
        next_index=s.next_index.at[0, 1].set(3),
        deadline=s.deadline.at[0].set(0),  # heartbeat fires this tick
    )
    s2, _ = step(CFG, s)
    mb = s2.mailbox
    assert int(mb.req_type[0]) == REQ_APPEND
    assert int(mb.req_off[0, 1]) == -1
    assert int(mb.req_base[0]) == 6
    assert int(mb.req_base_term[0]) == 1
    assert int(np.uint32(mb.req_base_chk[0])) == hist_chk(6)
    for p in range(2, 5):
        assert int(mb.req_off[0, p]) >= 0


def test_restart_resumes_commit_at_base():
    """The snapshot triple is persistent: a restarted node comes back with
    commit = log_base and commit_chk = base_chk, not zero."""
    s = base_state(CFG)
    s = with_ring_log(s, 1, base=5, entries=hist(5, 9), commit=9)
    n = CFG.n_nodes
    inp = quiet_inputs(CFG)._replace(
        restarted=jnp.zeros((n,), bool).at[1].set(True)
    )
    s2, info = step(CFG, s, inp)
    assert int(s2.role[1]) == FOLLOWER
    assert int(s2.log_base[1]) == 5
    assert int(s2.commit_index[1]) == 5
    assert int(np.uint32(s2.commit_chk[1])) == hist_chk(5)
    assert int(s2.log_len[1]) == 9  # the log itself is persistent
    assert not bool(info.viol_commit)


# ------------------------------------------- completeness across compaction


def test_committed_sequence_across_compaction_boundaries():
    """The end-to-end data audit (tests/test_completeness.py) extended past the
    ring: committed values vanish from the final arrays once compacted, so the
    audit reads each entry AT THE TICK IT COMMITS from a traced run -- newly
    committed entries are always still live then (nothing overwrites a slot
    within CAP of the commit frontier). Every committed index must carry one
    stable value on every node, and the client values must be exactly a
    prefix-ordered subsequence of the offered schedule, NOOPs interleaved."""
    import jax.numpy as jnp

    from raft_sim_tpu.types import NOOP

    cfg = RaftConfig(n_nodes=3, log_capacity=8, compact_margin=4, client_interval=2)
    cap, ticks = cfg.log_capacity, 400
    key = jax.random.key(1)
    k_init, k_run = jax.random.split(key)
    state = init_state(cfg, k_init)
    _, _, (infos, states) = jax.jit(
        lambda s, k: scan.run(cfg, s, k, ticks, trace_states=True)
    )(state, k_run)
    commit = np.asarray(states.commit_index)  # [T, N]
    lv = np.asarray(states.log_val)  # [T, N, CAP]

    vals: dict[int, int] = {}  # absolute 1-based index -> committed value
    n = cfg.n_nodes
    for t in range(ticks):
        for i in range(n):
            c0 = int(commit[t - 1, i]) if t else 0
            for k in range(c0 + 1, int(commit[t, i]) + 1):
                v = int(lv[t, i, (k - 1) % cap])
                assert vals.setdefault(k, v) == v, f"index {k} committed twice with different values"

    maxc = int(commit[-1].max())
    assert maxc > 10 * cap  # the audit really crossed many compaction boundaries
    assert set(vals) >= set(range(1, maxc + 1))  # no committed index unobserved

    seq = [vals[k] for k in range(1, maxc + 1)]
    client_vals = [v for v in seq if v != NOOP]
    offers = {t + 1 for t in range(0, ticks, cfg.client_interval)}
    assert set(client_vals) <= offers  # nothing committed that was never offered
    assert client_vals == sorted(client_vals)  # offer order preserved


# ----------------------------------------------------- unbounded-horizon liveness


def test_unbounded_horizon_commands_survive_ring_exhaustion():
    """The capability the fixed log lacks (pinned by test_handlers.
    test_client_command_rejected_when_log_full): with compaction, a client
    workload many times the physical capacity keeps being accepted and committed,
    under crash + drop faults, with zero invariant violations."""
    cfg = RaftConfig(
        n_nodes=5,
        log_capacity=16,
        compact_margin=8,
        max_entries_per_rpc=4,
        client_interval=2,
        drop_prob=0.1,
        crash_prob=0.3,
        crash_period=32,
        crash_down_ticks=8,
    )
    ticks = 3000
    _, m = scan.simulate(cfg, 0, 8, ticks)
    m = jax.device_get(m)
    assert int(np.sum(m.violations)) == 0
    # every cluster committed far beyond the ring's physical capacity
    assert int(np.min(m.max_commit)) > 20 * cfg.log_capacity
    # and commands kept being accepted throughout (1500 offered per cluster)
    assert int(np.min(m.total_cmds)) > 1000
