"""Regression corpus: every bug the violation hunt ever found stays found.

tests/corpus/ holds shrunk scenario-repro-v2 artifacts (scenario/shrink.py
output, provenance-stamped per farm/corpus.py) -- one per historical hunt
hit, named `<mutant>-<topology>.json`. Three gates per artifact:

  1. BIT-EXACT REPLAY: `tools/repro.py --corpus tests/corpus` replays every
     artifact in one process (shared jitted-replay cache) and exits nonzero
     naming the first drifting artifact -- the same command CI's farm smoke
     runs, so the tier-1 gate and CI cannot diverge. A drifting replay means
     the (genome, seed, kernel) bookkeeping broke; a clean replay of a
     mutant artifact would mean the regression resurfaced the bug's
     preconditions without its effect.
  2. PROVENANCE: the corpus validator (farm/corpus.py) rejects any artifact
     without the v2 provenance block -- who found it, which fitness member,
     which generation/seed, what the shrink ablated, which checker property
     it violates. The corpus is an audit trail, not just replay inputs.
  3. SAFETY SEMANTICS: the six-property whole-history checker
     (trace/checker.py) runs over every artifact's traced replay -- the
     mutant kernel must be REJECTED naming the provenance's recorded
     property, and the REAL kernel under the identical (genome, seed,
     faults) must PASS all six. The corpus regresses safety semantics, not
     just tick-exactness (before the farm, only lease-skew got checker
     treatment, and only in the slow tier/CI).

Artifacts are deliberately SMALL (N=5, short horizons). Seeds: the
weak-quorum election-safety hit, the blind-transfer commit-invariant hit
(PR 10), and the lease-skew read-staleness hit (PR 11) -- hunted, shrunk,
frozen; provenance backfilled by PR 12 (the farm freezes new ones itself).
"""

from __future__ import annotations

import glob
import os
import subprocess
import sys

import pytest

CORPUS_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "corpus")
ARTIFACTS = sorted(glob.glob(os.path.join(CORPUS_DIR, "*.json")))
_IDS = [os.path.basename(p) for p in ARTIFACTS]

# Budget re-tier (ISSUE 13, rolled forward by ISSUE 19): the checker-both-
# ways gates compile ~2 traced replay programs PER artifact (~15-40s each
# on this tier's CPU), and the corpus grew to seven. Tier-1 keeps the
# checker gates for the newest (durable-storage) artifacts -- the ISSUE-19
# acceptance pair, not yet covered anywhere else -- while the older
# artifacts ride the slow tier: their BIT-EXACT replay stays tier-1 via the
# one-command corpus replay below (the "every hunted bug stays found"
# contract), and their checker semantics are re-proven every CI run (trace
# smoke: weak-quorum; reconfig smoke: blind-transfer hunt; lease smoke:
# lease-skew both ways; log-carried smoke: act-on-commit /
# single-server-change).
_TIER1_CHECKED = {"ack-before-fsync-n5.json", "volatile-vote-n5.json"}
_CHECKED_PARAMS = [
    p if os.path.basename(p) in _TIER1_CHECKED
    else pytest.param(p, marks=pytest.mark.slow)
    for p in ARTIFACTS
]


def test_corpus_is_seeded():
    """The corpus exists and carries at least the three seed artifacts."""
    names = {os.path.basename(p) for p in ARTIFACTS}
    assert "weak-quorum-n5.json" in names
    assert "blind-transfer-n5.json" in names
    assert "lease-skew-n5.json" in names


def test_corpus_replays_bit_exactly_in_one_command():
    """tools/repro.py --corpus: the whole corpus in ONE subprocess (one jax
    import, shared replay cache) -- exit 0 iff every artifact reproduces at
    its identical tick with identical kinds."""
    repo = os.path.dirname(CORPUS_DIR.rstrip(os.sep)).rsplit(os.sep, 1)[0]
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "repro.py"),
         "--corpus", CORPUS_DIR],
        capture_output=True,
        text=True,
        timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, (
        f"corpus drifted (exit {proc.returncode}):\n"
        f"{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    )


@pytest.mark.parametrize("artifact", ARTIFACTS, ids=_IDS)
def test_corpus_artifact_has_provenance(artifact):
    """Every frozen artifact is corpus-grade: scenario-repro-v2 with the
    full provenance block (the validator is the farm's freeze gate)."""
    from raft_sim_tpu.farm import corpus as corpus_mod
    from raft_sim_tpu.scenario import shrink as shrink_mod

    art = shrink_mod.load_artifact(artifact)
    assert corpus_mod.validate_artifact(art) == []
    prov = art["provenance"]
    assert prov["checker_property"] in (
        "election_safety", "leader_append_only", "log_matching",
        "leader_completeness", "state_machine_safety", "read_linearizability",
    )


def test_validator_rejects_provenance_free_artifact():
    """A replay-grade v1 artifact (or a stripped v2) must NOT validate as
    corpus-grade: the corpus schema rev exists to make provenance load-
    bearing, not decorative."""
    from raft_sim_tpu.farm import corpus as corpus_mod
    from raft_sim_tpu.scenario import shrink as shrink_mod

    art = shrink_mod.load_artifact(ARTIFACTS[0])
    stripped = {k: v for k, v in art.items() if k != "provenance"}
    problems = corpus_mod.validate_artifact(stripped)
    assert any("provenance" in p for p in problems), problems
    legacy = dict(stripped, schema="scenario-repro-v1")
    problems = corpus_mod.validate_artifact(legacy)
    assert any("schema" in p for p in problems), problems
    # Provenance disagreeing with the artifact's kernel label is corruption.
    lying = dict(art, provenance=dict(art["provenance"], mutant="other"))
    assert any("mutant" in p for p in corpus_mod.validate_artifact(lying))


@pytest.mark.parametrize("artifact", _CHECKED_PARAMS, ids=_IDS)
def test_checker_rejects_mutant_replay_naming_its_property(artifact):
    """The six-property whole-history checker over the artifact's traced
    replay must REJECT the mutant kernel naming the provenance's recorded
    property, on a COMPLETE history (an undecided rejection would be a
    trace-depth bug, not a safety verdict)."""
    from raft_sim_tpu.farm import corpus as corpus_mod
    from raft_sim_tpu.scenario import shrink as shrink_mod

    art = shrink_mod.load_artifact(artifact)
    rep = corpus_mod.check_artifact(art)
    assert rep.complete, rep.problems
    assert art["provenance"]["checker_property"] in rep.violated, (
        f"expected {art['provenance']['checker_property']}, "
        f"checker violated={rep.violated}"
    )
    # The named property carries a minimal witness, not just a verdict.
    assert rep.results[art["provenance"]["checker_property"]].witness


@pytest.mark.parametrize("artifact", _CHECKED_PARAMS, ids=_IDS)
def test_checker_passes_real_kernel_on_same_replay(artifact):
    """The REAL kernel under the identical (genome, seed, faults, horizon)
    must pass all six properties on a complete history: the corpus artifact
    demonstrates the mutant's bug, not an environmental accident."""
    from raft_sim_tpu.farm import corpus as corpus_mod
    from raft_sim_tpu.scenario import shrink as shrink_mod

    art = shrink_mod.load_artifact(artifact)
    rep = corpus_mod.check_artifact(art, real=True)
    assert rep.complete, rep.problems
    assert rep.ok, {n: r.note for n, r in rep.results.items() if not r.ok}
