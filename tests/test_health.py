"""Health plane: SLI math, burn-rate alerting, triage, evidence, bit-exactness.

The load-bearing property mirrors the telemetry tier's: the health plane is a
HOST-SIDE fold over streams the loops already export, so an instrumented run
must be bit-identical to a plain one -- same trajectories, same metrics, same
windows.jsonl bytes -- on both carry layouts. Everything else here is exact
hand-rollup arithmetic: the SLI fold, the log2-bin percentile estimator
(pinned against the mesh report's), the burn-rate state machines on synthetic
error streams, the robust triage ordering, and the evidence-bundle round trip
through its own validator.

Compile budget: the bit-exactness tests are the only ones that touch the
simulator; they run at tiny shapes (batch 4, chunk 16) and the health-armed
session reuses the plain session's jitted programs (health adds no lowerings
-- that is the point), so each layout x path pays one compile.
"""

import copy
import dataclasses
import io
import json
import os
from types import SimpleNamespace

import jax
import numpy as np
import pytest

from raft_sim_tpu import RaftConfig
from raft_sim_tpu.driver import Session
from raft_sim_tpu.health import (
    BurnEngine,
    HealthMonitor,
    HealthWriter,
    load_spec,
    validate_bundle,
    validate_spec,
)
from raft_sim_tpu.health import burn as burn_mod
from raft_sim_tpu.health import evidence as evidence_mod
from raft_sim_tpu.health import sli as sli_mod
from raft_sim_tpu.health import triage as triage_mod
from raft_sim_tpu.health.spec import DEFAULT_SPEC
from raft_sim_tpu.types import LAT_HIST_BINS
from raft_sim_tpu.utils import telemetry_sink

# Kitchen-sink faults so the instrumented runs carry nonzero values in every
# stream the health plane folds (same spirit as test_telemetry.FUZZ_CFG).
HCFG = RaftConfig(
    n_nodes=5,
    log_capacity=16,
    client_interval=4,
    drop_prob=0.2,
    crash_prob=0.3,
    crash_period=32,
    crash_down_ticks=8,
    clock_skew_prob=0.1,
)
HBATCH, HTICKS, HCHUNK, HWINDOW = 4, 64, 16, 8


def tree_eq(a, b, msg=""):
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb), err_msg=msg)


def _spec(**overrides):
    """A minimal valid spec the unit tests mutate: one availability objective
    (budget 0.1) under the default fast/slow rule pair."""
    spec = {
        "schema": "health-slo-v1",
        "eval_windows": 1,
        "worst_k": 3,
        "outlier_score": 3.0,
        "resolve_evals": 2,
        "objectives": {
            "availability": {"sli": "availability", "target": 0.9},
        },
        "rules": [
            {"name": "fast", "short": 1, "long": 2, "burn": 6.0},
        ],
    }
    spec.update(overrides)
    return spec


def _unit(batch=4, start=0, ticks=16, **fields):
    """A synthetic window unit with every counter zeroed unless overridden."""
    u = {
        "start": start,
        "ticks": ticks,
        "violations": np.zeros(batch, np.int64),
        "leaderless": np.zeros(batch, bool),
        "cmds": np.zeros(batch, np.int64),
        "reads": np.zeros(batch, np.int64),
        "lat_sum": np.zeros(batch, np.int64),
        "lat_cnt": np.zeros(batch, np.int64),
        "lat_hist": np.zeros((batch, LAT_HIST_BINS), np.int64),
        "read_hist": np.zeros((batch, LAT_HIST_BINS), np.int64),
        "fsync_lag_sum": np.zeros(batch, np.int64),
        "fsync_lag_max": np.zeros(batch, np.int64),
    }
    u.update(fields)
    return u


# ------------------------------------------------------------------ spec


def test_default_spec_valid_and_load_spec_copies():
    assert validate_spec(DEFAULT_SPEC) == []
    spec = load_spec("default")
    assert spec == DEFAULT_SPEC
    spec["eval_windows"] = 99  # a caller's mutation must not leak back
    assert DEFAULT_SPEC["eval_windows"] == 2


@pytest.mark.parametrize(
    "mutate, fragment",
    [
        (lambda s: s.update(schema="nope"), "schema"),
        (lambda s: s.update(eval_windows=0), "eval_windows"),
        (lambda s: s.update(outlier_score=-1), "outlier_score"),
        (lambda s: s.update(objectives={}), "objectives"),
        (
            lambda s: s["objectives"].update(bad={"sli": "made_up"}),
            "sli 'made_up'",
        ),
        (
            lambda s: s["objectives"].update(
                bad={"sli": "availability", "target": 1.0}
            ),
            "target",
        ),
        (
            lambda s: s["objectives"].update(
                bad={"sli": "commit_latency", "target": 0.9}
            ),
            "threshold_ticks",
        ),
        (
            lambda s: s["objectives"].update(
                bad={"sli": "throughput", "min_ops_per_window": 1, "budget": 0}
            ),
            "budget",
        ),
        (
            lambda s: s["objectives"]["availability"].update(pending_evals=-1),
            "pending_evals",
        ),
        (lambda s: s.update(rules=[]), "rules"),
        (
            lambda s: s.update(
                rules=[{"name": "r", "short": 4, "long": 2, "burn": 1.0}]
            ),
            "short window 4 > long window 2",
        ),
        (
            lambda s: s.update(rules=[
                {"name": "r", "short": 1, "long": 2, "burn": 1.0},
                {"name": "r", "short": 1, "long": 2, "burn": 2.0},
            ]),
            "duplicate rule name",
        ),
    ],
)
def test_spec_rejections(mutate, fragment):
    spec = copy.deepcopy(_spec())
    mutate(spec)
    errors = validate_spec(spec)
    assert errors, f"mutation should have been rejected ({fragment})"
    assert any(fragment in e for e in errors), errors
    with pytest.raises(ValueError):
        load_spec(spec)


# ------------------------------------------------------------- percentiles


def test_hist_percentile_edges():
    empty = np.zeros(LAT_HIST_BINS, np.int64)
    assert sli_mod.hist_percentile(empty, 0.5) is None
    # First-nonempty-bin hits clamp to the bin's lower edge.
    h = np.zeros(LAT_HIST_BINS, np.int64)
    h[3] = 10
    assert sli_mod.hist_percentile(h, 0.5) == float(1 << 3)
    # Interpolation inside a later bin, by hand: bins 1 (10 events) and
    # 5 (2 events); p95 needs 11.4 of 12, so 1.4/2 of bin 5's [32, 64) span.
    h = np.zeros(LAT_HIST_BINS, np.int64)
    h[1], h[5] = 10, 2
    want = 32.0 + (0.95 * 12 - 10) / 2 * (64.0 - 32.0)
    assert sli_mod.hist_percentile(h, 0.95) == pytest.approx(want)
    # q=1.0 lands inside the last nonempty bin, never past it.
    assert 32.0 <= sli_mod.hist_percentile(h, 1.0) <= 64.0


def test_hist_percentile_matches_mesh_report():
    """The health plane and the mesh summaries must never disagree on a
    percentile: pin the two estimators against each other on random hists."""
    from raft_sim_tpu.parallel.mesh import _hist_percentile

    rng = np.random.default_rng(7)
    for _ in range(50):
        h = rng.integers(0, 20, size=LAT_HIST_BINS)
        for q in (0.5, 0.9, 0.95, 0.99):
            assert sli_mod.hist_percentile(h, q) == _hist_percentile(h, q)


def test_fast_bins():
    # Exact at powers of two: bins 0..n-1 cover [1, 2^n).
    assert sli_mod.fast_bins(1) == 0
    assert sli_mod.fast_bins(2) == 1
    assert sli_mod.fast_bins(16) == 4
    # Conservative in between: the partial bin counts bad.
    assert sli_mod.fast_bins(17) == 4
    assert sli_mod.fast_bins(31) == 4
    assert sli_mod.fast_bins(32) == 5
    # Clamped to the histogram width.
    assert sli_mod.fast_bins(1 << (LAT_HIST_BINS + 4)) == LAT_HIST_BINS


# ------------------------------------------------------------------ SLIs


def test_compute_slis_hand_rollup():
    """Every SLI kind against a hand-computed rollup of two synthetic units."""
    spec = _spec(objectives={
        "availability": {"sli": "availability", "target": 0.9},
        "commit_latency": {
            "sli": "commit_latency", "threshold_ticks": 16, "target": 0.99,
        },
        "read_staleness": {
            "sli": "read_staleness", "stale_after_ticks": 4, "target": 0.99,
        },
        "throughput": {
            "sli": "throughput", "min_ops_per_window": 100, "budget": 0.25,
        },
        "safety": {"sli": "safety", "pending_evals": 0},
        "device_wait": {
            "sli": "device_wait_share", "min_share": 0.5, "budget": 0.25,
        },
        "recompile": {"sli": "recompiles", "pending_evals": 0},
    })
    lat0 = np.zeros((4, LAT_HIST_BINS), np.int64)
    lat0[0, 1] = 10  # fast (bin 1 < fast_bins(16)=4)
    lat0[0, 5] = 2   # slow
    lat1 = np.zeros((4, LAT_HIST_BINS), np.int64)
    lat1[1, 3] = 4   # fast
    reads0 = np.zeros((4, LAT_HIST_BINS), np.int64)
    reads0[2, 0] = 5  # fresh (fast_bins(4)=2)
    reads0[2, 2] = 3  # stale
    units = [
        _unit(start=0, leaderless=np.array([True, False, False, False]),
              lat_hist=lat0, read_hist=reads0,
              cmds=np.array([10, 2, 0, 0]), reads=np.array([0, 0, 8, 0])),
        _unit(start=16, leaderless=np.array([True, True, False, False]),
              lat_hist=lat1, cmds=np.array([0, 0, 4, 0]),
              violations=np.array([0, 0, 0, 5])),
    ]
    perf = [
        {"wall_s": 9.0, "device_wait_s": 9.0, "warmup": True},  # excluded
        {"wall_s": 1.0, "device_wait_s": 0.2},
        {"wall_s": 1.0, "device_wait_s": 0.4, "recompiled": True},
    ]
    out = sli_mod.compute_slis(spec, units, perf)
    # availability: 3 leaderless cluster-windows of 4 clusters x 2 windows.
    assert out["errs"]["availability"] == 3 / 8
    assert out["slis"]["availability"]["availability"] == pytest.approx(1 - 3 / 8)
    assert out["budgets"]["availability"] == pytest.approx(0.1)
    np.testing.assert_array_equal(
        out["percluster"]["availability"], [2.0, 1.0, 0.0, 0.0]
    )
    # commit latency: 2 of 16 events land past the 16-tick threshold; the
    # fleet p50 clamps to bin 1's lower edge, p95 interpolates into bin 5.
    cl = out["slis"]["commit_latency"]
    assert (cl["measured"], cl["slow"]) == (16, 2)
    assert out["errs"]["commit_latency"] == 2 / 16
    assert cl["p50"] == 2.0
    assert cl["p95"] == pytest.approx(32.0 + (0.95 * 16 - 14) / 2 * 32.0)
    np.testing.assert_array_equal(
        out["percluster"]["commit_latency"], [2.0, 0.0, 0.0, 0.0]
    )
    # read staleness: 3 of 8 reads served at >= 4 ticks.
    assert out["errs"]["read_staleness"] == 3 / 8
    np.testing.assert_array_equal(
        out["percluster"]["read_staleness"], [0.0, 0.0, 3.0, 0.0]
    )
    # throughput: ops [10, 2, 12, 0] -> 12/window under the floor of 100;
    # triage names the clusters BELOW the fleet mean of 6.
    tp = out["slis"]["throughput"]
    assert tp["ops_per_window"] == pytest.approx(12.0)
    assert out["errs"]["throughput"] == 1.0
    assert out["budgets"]["throughput"] == 0.25
    np.testing.assert_array_equal(
        out["percluster"]["throughput"], [0.0, 4.0, 0.0, 6.0]
    )
    # safety: any violation is a budget-0 page.
    assert out["errs"]["safety"] == 1.0
    assert out["budgets"]["safety"] == 0.0
    np.testing.assert_array_equal(
        out["percluster"]["safety"], [0.0, 0.0, 0.0, 5.0]
    )
    # device-wait share over STEADY rows only: 0.6/2.0 under the 0.5 floor.
    dw = out["slis"]["device_wait"]
    assert dw["share"] == pytest.approx(0.3)
    assert dw["steady_chunks"] == 2
    assert out["errs"]["device_wait"] == 1.0
    assert out["percluster"]["device_wait"] is None
    # recompiles: one steady chunk recompiled -> budget-0 page.
    assert out["slis"]["recompile"]["recompiled_chunks"] == 1
    assert out["errs"]["recompile"] == 1.0


def test_compute_slis_quiet_when_disabled_or_empty():
    """Zero floors disable the binary objectives; empty histograms report
    None percentiles and zero error (no traffic is not an SLO breach)."""
    spec = _spec(objectives={
        "commit_latency": {
            "sli": "commit_latency", "threshold_ticks": 16, "target": 0.99,
        },
        "throughput": {
            "sli": "throughput", "min_ops_per_window": 0, "budget": 0.25,
        },
        "device_wait": {
            "sli": "device_wait_share", "min_share": 0.0, "budget": 0.25,
        },
    })
    out = sli_mod.compute_slis(spec, [_unit()], [])
    assert out["errs"] == {
        "commit_latency": 0.0, "throughput": 0.0, "device_wait": 0.0,
    }
    assert out["slis"]["commit_latency"]["p99"] is None
    assert out["slis"]["device_wait"]["share"] is None


# ------------------------------------------------------------- burn rates


def test_burn_rate_budget_zero():
    assert burn_mod.burn_rate(0.0, 0.0) == 0.0
    assert burn_mod.burn_rate(1e-9, 0.0) == burn_mod.BURN_INF
    assert burn_mod.burn_rate(0.5, 0.1) == pytest.approx(5.0)


def test_burn_clean_stream_stays_ok():
    eng = BurnEngine(_spec())
    for _ in range(10):
        assert eng.update({"availability": 0.0}, {"availability": 0.1}) == []
    assert eng.status() == "ok"
    assert eng.firing() == []


def test_burn_burst_fires_then_resolves():
    """ok -> pending on the first met eval, firing on the 2nd (default
    pending_evals=1), resolved after resolve_evals clean evals."""
    eng = BurnEngine(_spec())
    t0 = eng.update({"availability": 1.0}, {"availability": 0.1})
    assert [tr["state"] for tr in t0] == ["pending"]
    assert t0[0]["burn_short"] == pytest.approx(10.0)
    t1 = eng.update({"availability": 1.0}, {"availability": 0.1})
    assert [tr["state"] for tr in t1] == ["firing"]
    assert eng.status() == "firing"
    assert eng.firing() == [("availability", "fast")]
    # Recovery: 2 clean evals (resolve_evals=2) -> resolved, reads as ok.
    assert eng.update({"availability": 0.0}, {"availability": 0.1}) == []
    t3 = eng.update({"availability": 0.0}, {"availability": 0.1})
    assert [tr["state"] for tr in t3] == ["resolved"]
    assert eng.status() == "ok"


def test_burn_pending_clears_back_to_ok():
    """A one-eval blip never fires: pending drops straight back to ok when
    the condition clears (the short window is the reset clock)."""
    eng = BurnEngine(_spec())
    eng.update({"availability": 1.0}, {"availability": 0.1})
    t1 = eng.update({"availability": 0.0}, {"availability": 0.1})
    assert [tr["state"] for tr in t1] == ["ok"]
    assert eng.status() == "ok"


def test_burn_safety_pages_immediately():
    """pending_evals=0 (the safety/recompile default) fires on the FIRST met
    eval at infinite burn -- no pending stop."""
    spec = _spec(objectives={"safety": {"sli": "safety", "pending_evals": 0}})
    eng = BurnEngine(spec)
    trs = eng.update({"safety": 1.0}, {"safety": 0.0})
    assert [tr["state"] for tr in trs] == ["firing"]
    assert trs[0]["burn_short"] == burn_mod.BURN_INF
    assert trs[0]["burn_long"] == burn_mod.BURN_INF


def test_burn_long_window_gates_firing():
    """Both windows must burn: a single hot eval after a long clean history
    trips the short window but not the long one, so nothing fires."""
    spec = _spec(rules=[{"name": "slow", "short": 1, "long": 4, "burn": 6.0}])
    eng = BurnEngine(spec)
    for _ in range(4):
        eng.update({"availability": 0.0}, {"availability": 0.1})
    # short burn = 10 >= 6, long burn = (1.0/4)/0.1 = 2.5 < 6: not met.
    assert eng.update({"availability": 1.0}, {"availability": 0.1}) == []
    assert eng.status() == "ok"


# ----------------------------------------------------------------- triage


def test_triage_empty_and_all_clean():
    assert triage_mod.outlier_clusters([], 3, 3.0) == []
    assert triage_mod.outlier_clusters([0.0, 0.0, 0.0], 3, 3.0) == []


def test_triage_single_outlier_clamped():
    out = triage_mod.outlier_clusters([0.0, 0.0, 9.0, 0.0], 3, 3.0)
    assert [w["cluster"] for w in out] == [2]
    assert out[0]["value"] == 9.0
    assert out[0]["outlier"] is True
    assert out[0]["score"] == triage_mod.SCORE_CLAMP  # zero-MAD fleet


def test_triage_ordering_ties_and_worst_k():
    # Scores tie for clusters 0 and 1 -> equal raw values -> lower id first;
    # worst_k=2 drops cluster 2 even though its metric is nonzero.
    out = triage_mod.outlier_clusters([5.0, 5.0, 3.0, 0.0], 2, 3.0)
    assert [w["cluster"] for w in out] == [0, 1]
    # Fleet-wide burn: everyone ~0 score, still named, no outlier label.
    out = triage_mod.outlier_clusters([4.0, 4.0, 4.0, 4.0], 3, 3.0)
    assert [w["cluster"] for w in out] == [0, 1, 2]
    assert not any(w["outlier"] for w in out)


def test_triage_cluster_base_shifts_to_fleet_ids():
    out = triage_mod.outlier_clusters([0.0, 7.0], 3, 3.0, cluster_base=10)
    assert [w["cluster"] for w in out] == [11]


# --------------------------------------------------------------- evidence


def test_window_rows_filter_and_base():
    units = [
        _unit(batch=2, start=0, cmds=np.array([3, 4]),
              leaderless=np.array([True, False])),
        _unit(batch=2, start=16, cmds=np.array([5, 6])),
    ]
    # Clusters are fleet-global ids; this monitor's slice starts at 10 and
    # holds 2 clusters, so cluster 99 is silently out of range.
    rows = evidence_mod.window_rows_for(units, [11, 99], 7, cluster_base=10)
    assert [(r["window"], r["cluster"], r["cmds"]) for r in rows] == [
        (7, 11, 4), (8, 11, 6),
    ]
    assert rows[0]["leaderless"] is False


def test_evidence_bundle_round_trip(tmp_path):
    alert = {
        "eval": 3, "scope": "fleet", "objective": "availability",
        "rule": "fast", "state": "firing", "burn_short": 10.0,
        "burn_long": 8.0, "worst_clusters": [], "evidence": "evidence_0000",
    }
    units = [_unit(cmds=np.array([1, 2, 3, 4]))]
    d = str(tmp_path / "evidence_0000")
    evidence_mod.write_bundle(
        d, alert=alert, objective={"sli": "availability", "target": 0.9},
        window_rows=evidence_mod.window_rows_for(units, [0, 2], 6),
        perf_rows=[{"chunk": 1, "wall_s": 0.5}],
        refs={"seed": 7},
    )
    assert validate_bundle(d) == []
    doc = json.load(open(os.path.join(d, "alert.json")))
    assert doc["schema"] == evidence_mod.EVIDENCE_SCHEMA
    assert doc["refs"] == {"seed": 7}
    assert doc["files"] == ["alert.json", "perf.jsonl", "windows.jsonl"]

    # Negatives: an inventoried file gone missing, a wrong schema, and a
    # windows row with a missing/mistyped field all name the problem.
    os.remove(os.path.join(d, "perf.jsonl"))
    assert any("perf.jsonl missing on disk" in e for e in validate_bundle(d))
    with open(os.path.join(d, "windows.jsonl"), "a") as f:
        f.write(json.dumps({"window": "one"}) + "\n")
    errs = validate_bundle(d)
    assert any("'ticks' missing or non-int" in e for e in errs)
    assert any("leaderless must be bool" in e for e in errs)
    doc["schema"] = "nope"
    with open(os.path.join(d, "alert.json"), "w") as f:
        json.dump(doc, f)
    assert any("schema" in e for e in validate_bundle(d))
    assert validate_bundle(str(tmp_path / "nowhere")) == [
        "nowhere: missing alert.json"
    ]


# ---------------------------------------------------- monitor (synthetic)


def test_monitor_end_to_end_synthetic(tmp_path):
    """Drive one monitor through a full incident on synthetic units: pending
    -> firing (evidence captured through the hook) -> resolved, with every
    stream passing the sink validator and the report renderer."""
    d = str(tmp_path)
    spec = _spec(resolve_evals=1, worst_k=2)
    captured = []

    def capture(alert, clusters):
        captured.append((alert["objective"], list(clusters)))
        return {"flights": {}, "refs": {"seed": 0}}

    mon = HealthMonitor(
        spec, batch=4, writer=HealthWriter(d), scope="fleet", capture=capture,
    )
    sick = _unit(leaderless=np.array([True, True, True, False]))
    mon.observe_units([sick])            # eval 0: pending
    assert mon.status == "pending"
    mon.observe_units([sick])            # eval 1: firing + evidence
    assert mon.status == "firing"
    assert mon.status_line() == (
        "health[fleet] eval 2: firing (availability/fast)"
    )
    mon.observe_units([_unit()])         # eval 2: clean -> resolved
    roll = mon.finalize()
    assert roll == {
        "scope": "fleet", "evals": 3, "status": "ok", "alerts": 3,
        "fired_objectives": ["availability"],
    }
    states = [
        json.loads(l)["state"] for l in open(os.path.join(d, "alerts.jsonl"))
    ]
    assert states == ["pending", "firing", "resolved"]
    # The capture hook saw the triaged culprits (fleet-wide burn: worst_k=2
    # named, lowest ids first) and the bundle landed next to the streams.
    assert captured == [("availability", [0, 1])]
    assert os.path.isdir(os.path.join(d, "evidence_0000"))
    assert telemetry_sink.validate_health_files(d) == []
    # The renderer walks the same directory end to end.
    from tools.metrics_report import report_health

    buf = io.StringIO()
    report_health(d, out=buf)
    text = buf.getvalue()
    assert "scope fleet" in text
    assert "firing" in text and "evidence_0000" in text


def test_monitor_writer_truncates_previous_run(tmp_path):
    """Re-arming health (Session.reset discipline) must not inherit the prior
    run's alerts or evidence -- the writer truncates on construction."""
    d = str(tmp_path)
    spec = _spec(resolve_evals=1)
    mon = HealthMonitor(spec, batch=4, writer=HealthWriter(d), scope="fleet")
    sick = _unit(leaderless=np.ones(4, bool))
    mon.observe_units([sick, sick])
    assert os.path.isdir(os.path.join(d, "evidence_0000"))
    HealthWriter(d)
    assert not os.path.isdir(os.path.join(d, "evidence_0000"))
    assert open(os.path.join(d, "health.jsonl")).read() == ""
    assert open(os.path.join(d, "alerts.jsonl")).read() == ""


def test_validate_health_files_negatives(tmp_path):
    d = str(tmp_path)
    # health.jsonl without alerts.jsonl, a bad status, an eval discontinuity.
    rows = [
        {"eval": 0, "scope": "fleet", "window_start": 0, "windows": 1,
         "ticks": 16, "slis": {}, "burn": {}, "status": "ok"},
        {"eval": 2, "scope": "fleet", "window_start": 16, "windows": 1,
         "ticks": 16, "slis": {}, "burn": {}, "status": "on-fire"},
    ]
    with open(os.path.join(d, "health.jsonl"), "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    errs = telemetry_sink.validate_health_files(d)
    assert any("alerts.jsonl missing" in e for e in errs)
    assert any("eval 2 (expected 1)" in e for e in errs)
    assert any("'on-fire'" in e for e in errs)
    # A firing alert must carry evidence; a named dir must exist; an
    # on-disk evidence dir must be named by some alert row.
    alerts = [
        {"eval": 0, "scope": "fleet", "objective": "a", "rule": "fast",
         "state": "firing", "burn_short": 9.0, "burn_long": 9.0,
         "worst_clusters": [], "evidence": None},
        {"eval": 1, "scope": "fleet", "objective": "a", "rule": "fast",
         "state": "resolved", "burn_short": 0.0, "burn_long": 0.0,
         "worst_clusters": [], "evidence": "evidence_0007"},
    ]
    with open(os.path.join(d, "alerts.jsonl"), "w") as f:
        for r in alerts:
            f.write(json.dumps(r) + "\n")
    os.mkdir(os.path.join(d, "evidence_0003"))
    errs = telemetry_sink.validate_health_files(d)
    assert any("firing alert carries no evidence" in e for e in errs)
    assert any("evidence dir evidence_0007 missing" in e for e in errs)
    assert any(
        "evidence_0003: evidence bundle not named" in e for e in errs
    )


def test_monitor_observe_chunk_and_begin_run():
    """The plain-path delta fold: cumulative RunMetrics become per-chunk
    window units, and begin_run() restarts the baseline (run_chunked restarts
    its counters every call) while the absolute tick offset carries on."""
    from raft_sim_tpu.sim import telemetry

    spec = _spec(eval_windows=100)  # never drain: inspect the raw units

    class _Sink:
        directory = None

        def append_health(self, row):
            pass

        def append_alert(self, row):
            pass

    mon = HealthMonitor(spec, batch=2, writer=_Sink(), scope="fleet")

    def metrics(cmds, viol, first):
        return SimpleNamespace(
            violations=np.array(viol), total_cmds=np.array(cmds),
            reads_served=np.zeros(2, np.int64),
            lat_sum=np.zeros(2, np.int64), lat_cnt=np.zeros(2, np.int64),
            lat_hist=np.zeros((2, LAT_HIST_BINS), np.int64),
            read_hist=np.zeros((2, LAT_HIST_BINS), np.int64),
            fsync_lag_sum=np.zeros(2, np.int64),
            fsync_lag_max=np.zeros(2, np.int64),
            first_leader_tick=np.array(first),
        )

    mon.begin_run()
    mon.observe_chunk(16, metrics([5, 0], [0, 0], [3, telemetry.NEVER]))
    mon.observe_chunk(32, metrics([8, 1], [0, 2], [3, 40]))
    mon.begin_run()  # second run(): counters restart from zero
    mon.observe_chunk(16, metrics([2, 2], [0, 0], [3, 40]))
    got = [
        (u["start"], u["ticks"], u["cmds"].tolist(), u["violations"].tolist(),
         u["leaderless"].tolist())
        for u in mon._units
    ]
    assert got == [
        (0, 16, [5, 0], [0, 0], [False, True]),
        (16, 16, [3, 1], [0, 2], [False, False]),
        (32, 16, [2, 2], [0, 0], [False, False]),
    ]


def test_slice_units_are_views():
    from raft_sim_tpu.health.monitor import slice_units

    units = [_unit(cmds=np.arange(4, dtype=np.int64))]

    view = slice_units(units, 1, 3)
    assert view[0]["cmds"].tolist() == [1, 2]
    assert view[0]["start"] == units[0]["start"]
    # A view, not a copy: the serve loop fans one fetch to every tenant.
    units[0]["cmds"][1] = 99
    assert view[0]["cmds"][0] == 99


# ----------------------------------------------- bit-exactness (both kernels)


@pytest.mark.parametrize("compact", [False, True], ids=["dense", "compact"])
def test_health_bit_exact_plain_path(tmp_path, compact):
    """A health-armed plain chunked run equals an unarmed one bit-for-bit --
    state AND metrics -- across TWO run() calls (the begin_run epoch seam),
    on both carry layouts."""
    cfg = dataclasses.replace(HCFG, compact_planes=compact)
    a = Session(cfg, batch=HBATCH, seed=3)
    b = Session(cfg, batch=HBATCH, seed=3)
    b.attach_health(directory=str(tmp_path))
    for s in (a, b):
        s.run(HTICKS, chunk=HCHUNK)
        s.run(HCHUNK * 2, chunk=HCHUNK)
    tree_eq(jax.device_get(a.state), jax.device_get(b.state), "state diverged")
    tree_eq(
        jax.device_get(a.metrics), jax.device_get(b.metrics),
        "metrics diverged",
    )
    roll = b.health.finalize()
    assert roll["evals"] >= 1
    assert telemetry_sink.validate_health_files(str(tmp_path)) == []


@pytest.mark.parametrize("compact", [False, True], ids=["dense", "compact"])
def test_health_bit_exact_telemetry_path(tmp_path, compact):
    """Same contract through the windowed telemetry loop: the health-armed
    session's windows.jsonl is byte-identical to the plain session's, and the
    full sink validator (health streams + evidence included) passes."""
    cfg = dataclasses.replace(HCFG, compact_planes=compact)
    da, db = str(tmp_path / "a"), str(tmp_path / "b")
    a = Session(cfg, batch=HBATCH, seed=3)
    a.attach_telemetry(da, window=HWINDOW, ring=4)
    b = Session(cfg, batch=HBATCH, seed=3)
    b.attach_telemetry(db, window=HWINDOW, ring=4)
    b.attach_health()
    for s in (a, b):
        s.run(HTICKS, chunk=HCHUNK)
    tree_eq(jax.device_get(a.state), jax.device_get(b.state), "state diverged")
    tree_eq(
        jax.device_get(a.metrics), jax.device_get(b.metrics),
        "metrics diverged",
    )
    wa = open(os.path.join(da, "windows.jsonl")).read()
    wb = open(os.path.join(db, "windows.jsonl")).read()
    assert wa == wb, "telemetry stream diverged under health instrumentation"
    assert json.loads(open(os.path.join(db, "health.jsonl")).readline())
    assert telemetry_sink.validate(db) == []
    # reset() re-arms a truncated health plane (same discipline as the sink).
    b.reset()
    assert open(os.path.join(db, "health.jsonl")).read() == ""
    assert b.health is not None


# ------------------------------------------------------- multichip renderer


def test_report_multichip_renders_v2_and_legacy(tmp_path):
    from tools.metrics_report import report_multichip

    v2 = {
        "schema": "multichip-v2", "n_devices": 2, "n_processes": 1,
        "batch": 8, "ticks": 64, "violations": 0, "match": True,
        "throughput_ticks_per_s": 1234.5, "per_device_bytes_per_tick": 99.0,
        "platform": "cpu", "parity_hash": "ab" * 32,
        "reference_ticks_per_s": 2000.0,
    }
    p1 = tmp_path / "MULTICHIP_r06.json"
    p1.write_text(json.dumps(v2))
    assert telemetry_sink.validate_multichip(str(p1)) == []
    p2 = tmp_path / "MULTICHIP_r01.json"
    p2.write_text(json.dumps({"n_devices": 2, "rc": 0, "ok": True}))
    buf = io.StringIO()
    report_multichip([str(p1), str(p2)], out=buf)
    text = buf.getvalue()
    assert "MATCH" in text
    assert "legacy rc-only stub" in text
    assert "abababab" in text  # parity-hash prefix in the notes
    assert "cpu rows never anchor" in text
