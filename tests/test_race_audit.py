"""Pass D's own tests: every concurrency rule must fire on a seeded
violation and stay silent on the blessed idioms, the tree must gate clean,
and the runtime donation-poison sanitizer must (a) catch an injected
use-after-donate naming the deleted buffer and (b) leave armed standing-loop
runs bit-exact against plain runs on BOTH carry layouts.

The negative seeds are the acceptance proof the pass is real: an injected
use-after-donate (direct read, stale view, escaped closure), an in-window
carry mutation, a double-consumed PRNG key, a second sink writer, and an
unregistered donating entry point are each caught naming their rule --
none relies on the race happening to lose at runtime.

The static half is AST-only (no compiles); the sanitizer half runs tiny
2-cluster sessions and shares programs with the rest of the tier-1 suite
where shapes allow.
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np
import pytest

from raft_sim_tpu.analysis import policy, race_audit, run, sanitizer
from raft_sim_tpu.sim import chunked
from raft_sim_tpu.types import init_batch
from raft_sim_tpu.utils.config import RaftConfig

TINY = RaftConfig(n_nodes=3, log_capacity=4, max_entries_per_rpc=1)

SIM_PATH = "raft_sim_tpu/sim/fake_loop.py"
KEY_PATH = "raft_sim_tpu/farm/fake_keys.py"


def rules_of(findings):
    return [f.rule for f in findings]


# ------------------------------------------------------ use-after-donate lint


def test_use_after_donate_direct_read():
    src = (
        "def loop(cfg, state, keys, export):\n"
        "    out = _chunk_donate(cfg, state, keys, 4)\n"
        "    export(state.term)\n"
    )
    got = race_audit.lint_source(src, SIM_PATH)
    assert "race-use-after-donate" in rules_of(got)
    assert any(f.line == 3 for f in got)


def test_use_after_donate_stale_view():
    src = (
        "def loop(cfg, state, keys):\n"
        "    view = state.log_val\n"
        "    state, m = _chunk_donate(cfg, state, keys, 4)\n"
        "    return view.sum()\n"
    )
    got = race_audit.lint_source(src, SIM_PATH)
    assert "race-use-after-donate" in rules_of(got)


def test_use_after_donate_escaped_closure():
    src = (
        "def loop(cfg, state, keys, sink):\n"
        "    snap = lambda: state.term\n"
        "    out = _chunk_donate(cfg, state, keys, 4)\n"
        "    sink(snap)\n"
    )
    got = race_audit.lint_source(src, SIM_PATH)
    assert "race-use-after-donate" in rules_of(got)


def test_use_after_donate_next_iteration():
    # The donated carry is NOT rebound; the loop's next iteration re-reads it.
    src = (
        "def loop(cfg, state, keys, n_ticks):\n"
        "    done = 0\n"
        "    while done < n_ticks:\n"
        "        out = _chunk_donate(cfg, state, keys, 4)\n"
        "        done += 4\n"
        "    return state\n"
    )
    got = race_audit.lint_source(src, SIM_PATH)
    assert "race-use-after-donate" in rules_of(got)


def test_rebind_from_outputs_is_clean():
    src = (
        "def loop(cfg, state, keys):\n"
        "    state = _own_copy(state)\n"
        "    state, m = _chunk_donate(cfg, state, keys, 4)\n"
        "    return state, m\n"
    )
    assert race_audit.lint_source(src, SIM_PATH) == []


def test_unpack_via_raw_output_name_is_clean():
    # telemetry's trace branch: kill via `out = ...`, rebind from `out`.
    src = (
        "def loop(cfg, state, keys, rec, flag):\n"
        "    out = _chunk_t_donate(cfg, state, keys, rec, 4, 4, 0)\n"
        "    if flag:\n"
        "        state, m, recs, rec = out\n"
        "    else:\n"
        "        state, m, recs, rec = out\n"
        "    return state, m\n"
    )
    assert race_audit.lint_source(src, SIM_PATH) == []


def test_fetch_before_donate_is_clean():
    src = (
        "import jax\n"
        "def loop(cfg, state, keys, export):\n"
        "    snap = jax.device_get(state)\n"
        "    state, m = _chunk_donate(cfg, state, keys, 4)\n"
        "    export(snap)\n"
        "    return state\n"
    )
    assert race_audit.lint_source(src, SIM_PATH) == []


# ------------------------------------------------------- overlap window audit


def test_window_mutation_fires():
    src = (
        "import jax\n"
        "def loop(cfg, state, keys, perf):\n"
        "    state, m = _chunk_donate(cfg, state, keys, 4)\n"
        "    state = jax.tree.map(lambda x: x + 1, state)\n"
        "    perf.end(sync=lambda: m.ticks)\n"
    )
    got = race_audit.lint_source(src, SIM_PATH)
    assert "race-window-mutation" in rules_of(got)
    assert any(f.line == 4 for f in got)


def test_window_write_after_sync_is_clean():
    src = (
        "import jax\n"
        "def loop(cfg, state, keys, perf):\n"
        "    state, m = _chunk_donate(cfg, state, keys, 4)\n"
        "    perf.end(sync=lambda: m.ticks)\n"
        "    state = jax.tree.map(lambda x: x + 1, state)\n"
    )
    assert race_audit.lint_source(src, SIM_PATH) == []


def test_disjoint_window_writes_are_clean():
    # The serve-loop shape: in-window host work on NON-carry state, plus the
    # blessed device-stream fetch (begin_rounds/finish_rounds).
    src = (
        "import numpy as np\n"
        "def loop(cfg, state, keys, deltas, perf):\n"
        "    state, m = _chunk_donate(cfg, state, keys, 4)\n"
        "    futs = deltas.begin_rounds(state, 3)\n"
        "    packed = np.zeros(4)\n"
        "    rows = deltas.finish_rounds(futs)\n"
        "    return state, rows, packed\n"
    )
    assert race_audit.lint_source(src, SIM_PATH) == []


def test_overlap_write_sets_exclude_the_carry():
    sets = race_audit.overlap_write_sets()
    serve = sets.get("raft_sim_tpu/serve/loop.py::serve")
    assert serve, f"serve() overlap write-set missing: {sorted(sets)}"
    # The checked fact behind PR 11's overlapped loop: everything the host
    # touches between dispatch and sync is disjoint from the in-flight carry.
    assert "self.state" not in serve


# ------------------------------------------------------- key-stream discipline


def test_key_double_draw_fires():
    src = (
        "import jax\n"
        "def f(key):\n"
        "    a = jax.random.bits(key)\n"
        "    b = jax.random.bits(key)\n"
        "    return a, b\n"
    )
    got = race_audit.lint_source(src, KEY_PATH)
    assert rules_of(got) == ["race-key-reuse"]


def test_key_double_split_fires():
    src = (
        "import jax\n"
        "def f(key):\n"
        "    a, b = jax.random.split(key)\n"
        "    c, d = jax.random.split(key)\n"
        "    return a, b, c, d\n"
    )
    got = race_audit.lint_source(src, KEY_PATH)
    assert rules_of(got) == ["race-key-reuse"]


def test_key_draw_after_split_fires():
    src = (
        "import jax\n"
        "def f(key):\n"
        "    subs = jax.random.split(key, 3)\n"
        "    x = jax.random.uniform(key)\n"
        "    return subs, x\n"
    )
    got = race_audit.lint_source(src, KEY_PATH)
    assert rules_of(got) == ["race-key-reuse"]


def test_key_distinct_streams_are_clean():
    # The faults.py idiom: one split plus fold_ins with distinct salts.
    src = (
        "import jax\n"
        "def f(key, now):\n"
        "    wkey = jax.random.fold_in(key, now)\n"
        "    k1, k2 = jax.random.split(wkey)\n"
        "    tkey = jax.random.fold_in(wkey, 5)\n"
        "    xkey = jax.random.fold_in(wkey, 7)\n"
        "    return jax.random.bits(k1), jax.random.bits(k2), tkey, xkey\n"
    )
    assert race_audit.lint_source(src, KEY_PATH) == []


def test_key_rebind_resets_ledger():
    src = (
        "import jax\n"
        "def f(key):\n"
        "    key, sub = jax.random.split(key)\n"
        "    key, sub2 = jax.random.split(key)\n"
        "    return sub, sub2\n"
    )
    assert race_audit.lint_source(src, KEY_PATH) == []


def test_key_rule_scoped_to_stochastic_dirs():
    src = (
        "import jax\n"
        "def f(key):\n"
        "    a = jax.random.bits(key)\n"
        "    b = jax.random.bits(key)\n"
        "    return a, b\n"
    )
    assert race_audit.lint_source(src, "raft_sim_tpu/obs/fake.py") == []


# ------------------------------------------------------- single-writer sinks


def test_second_sink_writer_fires():
    src = (
        "def rogue(path, rows):\n"
        "    with open(path + '/health.jsonl', 'a') as f:\n"
        "        for r in rows:\n"
        "            f.write(r)\n"
    )
    got = race_audit.lint_source(src, SIM_PATH)
    assert "race-sink-writer" in rules_of(got)
    assert "health.jsonl" in got[0].message


def test_registered_sink_writer_is_clean():
    src = (
        "def append_health(path, rows):\n"
        "    with open(path, 'a') as f:\n"
        "        for r in rows:\n"
        "            f.write(r)\n"
    )
    got = race_audit.lint_source(src, "raft_sim_tpu/health/monitor.py")
    assert "race-sink-writer" not in rules_of(got)


def test_stale_owner_registry_row_fires(monkeypatch):
    monkeypatch.setitem(
        race_audit.APPEND_OWNERS,
        ("raft_sim_tpu/ghost.py", "append_ghost"), "ghost.jsonl",
    )
    got = race_audit.run_pass(run.package_root())
    stale = [f for f in got if f.rule == "race-sink-writer"]
    assert stale and "append_ghost" in stale[0].message


# --------------------------------------------------- donation registry checks


def test_unregistered_donation_fires():
    src = (
        "import functools, jax\n"
        "@functools.partial(jax.jit, donate_argnums=(1,))\n"
        "def _sneaky(cfg, state):\n"
        "    return state\n"
    )
    got = race_audit.lint_source(src, SIM_PATH)
    assert "race-unregistered-donation" in rules_of(got)


def test_registry_entry_without_decorator_fires(monkeypatch):
    ghost = policy.DonatingEntry(
        "sim.chunked._ghost", "raft_sim_tpu/sim/chunked.py", "_ghost",
        "state", "donated")
    real = policy.donating_entry_points()
    monkeypatch.setattr(policy, "donating_entry_points",
                        lambda: real + (ghost,))
    got = race_audit.run_pass(run.package_root())
    bad = [f for f in got if f.rule == "race-unregistered-donation"]
    assert bad and "_ghost" in bad[0].message


def test_registry_covers_every_donating_decorator():
    # The single-sourcing pin: Pass C's cost entries and Pass D's lint/
    # sanitizer all read policy.donating_entry_points; every donated row must
    # resolve a real (path, func, param) triple.
    sigs = race_audit.donating_signatures()
    donated = [e for e in policy.donating_entry_points()
               if e.expected == "donated"]
    assert sorted(sigs) == sorted(e.func for e in donated)
    for e in donated:
        idx, pname, label = sigs[e.func]
        assert pname == e.donated_param and label == e.label


def test_parse_error_is_a_finding():
    got = race_audit.lint_source("def broken(:\n", SIM_PATH)
    assert rules_of(got) == ["race-parse-error"]


# ------------------------------------------------------------ tree gates clean


def test_tree_gates_clean_race_pass():
    from raft_sim_tpu.analysis import findings as F

    found = race_audit.run_pass(run.package_root())
    entries, problems = F.load_waivers(run.DEFAULT_WAIVERS)
    assert not problems
    F.apply_waivers(found, entries)
    unwaived = [f for f in found if not f.waived]
    assert unwaived == [], [
        f"{f.rule} {f.location()}: {f.message}" for f in unwaived]


# ----------------------------------------------------- the runtime sanitizer


def _short_chunked(cfg, ticks=8, chunk=4):
    state = init_batch(cfg, jax.random.key(0), 2)
    keys = jax.random.split(jax.random.key(1), 2)
    return chunked.run_chunked(cfg, state, keys, ticks, chunk=chunk)


@pytest.mark.parametrize("layout", ["dense", "compact"])
def test_sanitizer_armed_runs_bit_exact(layout):
    cfg = TINY if layout == "dense" else dataclasses.replace(
        TINY, compact_planes=True)
    plain = _short_chunked(cfg)
    with sanitizer.armed() as stats:
        armed_out = _short_chunked(cfg)
    assert stats["calls"], "sanitizer never covered the loop"
    assert stats["poisoned"] + stats["pre_deleted"] > 0
    assert sanitizer.mismatched_leaves(plain, armed_out) == []


def test_sanitizer_catches_injected_use_after_donate():
    state = init_batch(TINY, jax.random.key(0), 2)
    keys = jax.random.split(jax.random.key(1), 2)
    with sanitizer.armed():
        carry = chunked._own_copy(state)
        stale = carry  # the injected bug: a retained pre-dispatch alias
        carry, m = chunked._chunk_donate(TINY, carry, keys, 4, None, 1)
        with pytest.raises(RuntimeError, match="deleted"):
            np.asarray(stale.term)
    # the caller's own state was never donated and stays readable
    np.asarray(state.term)


def test_sanitizer_restores_entry_points():
    before = chunked._chunk_donate
    with sanitizer.armed():
        assert chunked._chunk_donate is not before
        assert hasattr(chunked._chunk_donate, "_cache_size")
    assert chunked._chunk_donate is before


def test_dynamic_leg_gates_clean():
    findings, info = sanitizer.run_dynamic()
    assert findings == [], [f"{f.rule}: {f.message}" for f in findings]
    assert set(info["loops"]) == {
        "sim.chunked.run_chunked",
        "sim.telemetry.run_chunked_telemetry",
        "serve.loop.ServeSession.serve",
    }
    for loop_info in info["loops"].values():
        assert loop_info["calls"], "a standing loop escaped coverage"
    assert "farm" in info  # the no-donating-entry rationale is recorded
