"""TEST-ONLY weakened kernel variants: the search loop's ground truth.

A violation hunter that never finds anything proves nothing -- maybe the
kernel is safe, maybe the hunt is blind. These config subclasses weaken the
kernel behind an explicit opt-in (driver `scenario search --mutant`, CI's
scenario smoke job, tests/test_scenario.py) so the search demo has a target
it MUST hit within a bounded generation budget: if the hunt cannot drive a
quorum-off-by-one kernel to an election-safety violation, the hunt is
broken, not the kernel. Never instantiate these outside tests/demos; the
class is deliberately NOT reachable from RaftConfig flags or scenario files.

The weakening rides the config (cfg.quorum feeds both kernels' vote counts
and commit rule), so no second kernel source exists to drift: the mutant
compiles the same step code at a different quorum literal -- one extra jit
compile, zero extra lowered program structures (literal-blind hashes equal;
analysis/jaxpr_audit.py structural_hash).
"""

from __future__ import annotations

from raft_sim_tpu.utils.config import RaftConfig


class WeakQuorumConfig(RaftConfig):
    """quorum - 1: floor(N/2) instead of floor(N/2)+1, so two split-vote
    candidates can both 'win' a term -- the reference's even-N majority bug
    (SURVEY.md quorum note) made unconditional. Election safety violates
    within a few elections once message drop forces vote splits."""

    @property
    def quorum(self) -> int:  # type: ignore[override]
        return self.n_nodes // 2


class JointBypassConfig(RaftConfig):
    """One-step membership change: toggles apply to BOTH configurations
    instantly, no joint phase (cfg.joint_consensus False). Consecutive
    changes under replication lag then produce commit quorums and election
    quorums that do not intersect, so a leader missing committed entries gets
    elected and replicates its short log over them -- the thesis-4.3
    motivating bug. Requires cfg.reconfig (reconfig_interval > 0)."""

    @property
    def joint_consensus(self) -> bool:  # type: ignore[override]
        return False


class StaleReadConfig(RaftConfig):
    """ReadIndex without the confirmation round OR the current-term-commit
    capture gate (cfg.read_confirm False): a deposed leader stranded in a
    minority partition keeps serving reads from its stale commit state --
    reads below the committed frontier, the linearizability break the trace
    checker's read_linearizability property must reject. Requires
    cfg.read_index (read_interval > 0)."""

    @property
    def read_confirm(self) -> bool:  # type: ignore[override]
        return False


class BlindTransferConfig(RaftConfig):
    """TimeoutNow as a coup (cfg.xfer_election False): the leader fires
    without waiting for the target to catch up, and the target assumes
    leadership DIRECTLY -- no vote round, no up-to-date check -- so a behind
    target truncates committed entries off its followers (commit-invariant /
    leader-completeness breaks). Requires cfg.leader_transfer
    (transfer_interval > 0)."""

    @property
    def xfer_election(self) -> bool:  # type: ignore[override]
        return False


class LeaseSkewConfig(RaftConfig):
    """Lease reads judged on a no-skew clock model (cfg.lease_skew_safe
    False): the kernel serves lease reads for election_min_ticks + 2 global
    ticks instead of the configured skew-safe read_lease_ticks. Correct when
    every local clock advances exactly 1/tick; under clock skew a fast
    follower's lease-vote-denial window halves in global time, a new leader
    elects and commits INSIDE the optimistic lease, and the partitioned old
    leader serves a read below the committed frontier -- viol_read_stale on
    device (the hunt's fitness signal, driven by the skew genome axis) and a
    read_linearizability rejection from the trace checker. Requires
    cfg.read_lease (read_lease_ticks > 0)."""

    @property
    def lease_skew_safe(self) -> bool:  # type: ignore[override]
        return False


MUTANTS = {
    "weak-quorum": WeakQuorumConfig,
    "joint-bypass": JointBypassConfig,
    "stale-read": StaleReadConfig,
    "blind-transfer": BlindTransferConfig,
    "lease-skew": LeaseSkewConfig,
}


def mutant_config(name: str, cfg: RaftConfig) -> RaftConfig:
    """Rebuild `cfg` under the named mutant class (same field values)."""
    import dataclasses

    if name not in MUTANTS:
        raise ValueError(f"unknown mutant {name!r} (have {sorted(MUTANTS)})")
    return MUTANTS[name](**dataclasses.asdict(cfg))
