"""The self-growing safety corpus: dedup, provenance, and the checker gate.

tests/corpus/ used to grow by hand: a human ran one hunt, shrank the hit,
and committed the artifact. This module is the farm's freezer -- the policy
that lets the CI job itself grow the corpus without growing noise:

  signature   a hit's identity is (kernel, violation-kinds, mechanism-set):
              which kernel broke, which invariants fired, and which fault
              mechanisms SURVIVED the shrink (the minimal causal set). Two
              hits with the same signature are the same bug reached twice.
  dedup       a new artifact whose mechanism set equals -- or is a
              subset/superset of -- an existing same-kernel same-kinds
              artifact's is REFUSED: a repro needing strictly more
              mechanisms for the same break adds no regression value, and a
              strictly-more-minimal one would just churn the corpus.
  provenance  every frozen artifact records who found it (fitness member,
              generation, seed, the shrink's ablation set, the farm
              manifest hash), so a corpus file is an audit trail, not just
              a replay input (schema rev: scenario-repro-v2; the validator
              REJECTS provenance-free artifacts).
  checker     before freezing, the artifact's fleet slice is replayed
              traced (batch-1, the same trajectory -- batched-parity
              pinned) and the six-property whole-history checker
              (trace/checker.py) must REJECT it naming a property: the
              corpus regresses safety SEMANTICS, not just tick-exactness.
"""

from __future__ import annotations

import dataclasses
import functools
import glob
import json
import math
import os

import jax
import numpy as np

from raft_sim_tpu.scenario import genome as genome_mod
from raft_sim_tpu.scenario import shrink as shrink_mod
from raft_sim_tpu.utils.config import RaftConfig

# The corpus-artifact schema: scenario-repro-v1 plus the REQUIRED provenance
# block. Replay tooling accepts v1 too (shrink.ARTIFACT_SCHEMAS); the corpus
# validator does not.
CORPUS_SCHEMA = "scenario-repro-v2"

PROVENANCE_FIELDS = ("mutant", "fitness", "generation", "seed", "ablated")

CORE_FIELDS = (
    "seed", "batch", "cluster", "seg_len", "ticks", "tick", "kinds",
    "genome_raw",
)


# Per-mechanism GATING fields: the mechanism occurs iff ALL of these are
# nonzero (labels are shrink.ABLATIONS' vocabulary). Partitions need both
# the activation threshold AND a window period -- shrink's halving phase can
# zero `part` while leaving `part_period` standing, and a period without a
# threshold provably fires nothing, so any-field-nonzero would report a
# phantom mechanism and mis-split dedup signatures. crash_down is a span,
# not a gate: it is meaningless without `crash`.
MECHANISM_GATES = {
    "clock skew": ("skew",),
    "client traffic": ("client_interval",),
    "leadership transfers": ("transfer_interval",),
    "reads": ("read_interval",),
    "membership changes": ("reconfig_interval",),
    "message drop": ("drop",),
    "partitions": ("part", "part_period"),
    "crashes": ("crash",),
}
assert set(MECHANISM_GATES) == {label for label, _ in shrink_mod.ABLATIONS}


def mechanisms(art: dict) -> frozenset:
    """The fault mechanisms ACTIVE in an artifact's minimized genome: the
    shrink ablation-group labels whose gating fields ALL survived nonzero
    (MECHANISM_GATES). This is the causal half of the dedup signature: what
    the shrink could not remove."""
    raw = art["genome_raw"]
    out = set()
    for label, gates in MECHANISM_GATES.items():
        if all(f in raw and np.asarray(raw[f]).any() for f in gates):
            out.add(label)
    return frozenset(out)


def signature(art: dict) -> tuple:
    """(kernel, violation-kinds, mechanism-set): the dedup identity."""
    kernel = art.get("mutant") or "real"
    return (kernel, tuple(sorted(art["kinds"])), mechanisms(art))


def load_corpus(directory: str) -> list[tuple[str, dict]]:
    """Every artifact in a corpus directory, sorted by name."""
    return [
        (p, shrink_mod.load_artifact(p))
        for p in sorted(glob.glob(os.path.join(directory, "*.json")))
    ]


def find_duplicate(art: dict, corpus_dir: str) -> dict | None:
    """The existing artifact a new hit duplicates, or None. Same kernel +
    same violation kinds + mechanism sets nested either way = duplicate
    (module docstring has the rationale). Returns {"path", "signature",
    "duplicate_of"} for the farm's dedup ledger."""
    if not os.path.isdir(corpus_dir):
        return None
    kernel, kinds, mech = signature(art)
    for path, old in load_corpus(corpus_dir):
        k2, kinds2, mech2 = signature(old)
        if kernel == k2 and kinds == kinds2 and (mech <= mech2 or mech2 <= mech):
            return {
                "path": path,
                "signature": [kernel, list(kinds), sorted(mech)],
                "duplicate_of": os.path.basename(path),
            }
    return None


def validate_artifact(art: dict) -> list[str]:
    """Problems with a corpus-grade artifact ([] = valid). Replay-grade v1
    artifacts FAIL here: the corpus requires the v2 provenance block --
    tests/test_corpus.py runs this over every frozen file."""
    errs = []
    if art.get("schema") != CORPUS_SCHEMA:
        errs.append(
            f"schema {art.get('schema')!r}: corpus artifacts must be "
            f"{CORPUS_SCHEMA} (provenance-stamped)"
        )
    for k in CORE_FIELDS:
        if k not in art:
            errs.append(f"missing core field {k!r}")
    prov = art.get("provenance")
    if not isinstance(prov, dict):
        errs.append("missing provenance block (who found this, and how?)")
        return errs
    for k in PROVENANCE_FIELDS:
        if k not in prov:
            errs.append(f"provenance: missing field {k!r}")
    if "generation" in prov and not (
        prov["generation"] is None or isinstance(prov["generation"], int)
    ):
        errs.append("provenance: generation must be an int or null")
    if "seed" in prov and not isinstance(prov["seed"], int):
        errs.append("provenance: seed must be an int")
    if "ablated" in prov and not isinstance(prov["ablated"], list):
        errs.append("provenance: ablated must be the shrink ablation list")
    if "mutant" in prov and prov["mutant"] != art.get("mutant"):
        errs.append(
            f"provenance: mutant {prov.get('mutant')!r} disagrees with the "
            f"artifact's kernel label {art.get('mutant')!r}"
        )
    return errs


def stamp(art: dict, provenance: dict) -> dict:
    """A v2 corpus artifact from a shrink output + provenance facts. The
    ablation set defaults to the artifact's own `removed` record."""
    prov = dict(provenance)
    prov.setdefault("mutant", art.get("mutant"))
    prov.setdefault("ablated", list(art.get("removed", [])))
    out = dict(art, schema=CORPUS_SCHEMA, provenance=prov)
    problems = validate_artifact(out)
    if problems:
        raise ValueError(f"artifact failed corpus validation: {problems}")
    return out


# ----------------------------------------------------- the checker gate


@functools.lru_cache(maxsize=16)
def _traced_replay_fn(cfg: RaftConfig, n_ticks: int, window: int,
                      seg_len: int, depth: int):
    """One jitted batch-1 traced windowed replay per shape -- same-shape
    artifacts share it (and the farm's freeze + the tier-1 corpus checker
    test share THIS cache)."""
    from raft_sim_tpu.sim import telemetry
    from raft_sim_tpu.trace.ring import TraceSpec

    spec = TraceSpec(depth=depth)
    fn = jax.jit(
        lambda s, k, g: telemetry.run_batch_minor_telemetry(
            cfg, s, k, n_ticks, window, None, genome=g, seg_len=seg_len,
            trace_spec=spec,
        )
    )
    return fn, spec


def check_artifact(art: dict, real: bool = False, window: int = 64,
                   depth: int = 512):
    """Replay an artifact's cluster TRACED and run the six-property
    whole-history checker over it. `real=False` replays the artifact's own
    kernel (mutant included) -- the freeze gate expects a REJECTION naming a
    property; `real=True` strips the mutant -- the fixed kernel under the
    identical (genome, seed, faults) must PASS all six.

    The replay is the artifact's single fleet slice at batch 1 (bit-exact
    with its batched run -- the parity contract), horizon rounded UP to
    whole windows: running past the violation only gives the checker more
    history. Returns the trace CheckReport."""
    from raft_sim_tpu import init_batch
    from raft_sim_tpu.trace import checker as checker_mod
    from raft_sim_tpu.trace import history as history_mod

    cfg = (
        RaftConfig(**art.get("config", {}))
        if real
        else shrink_mod.artifact_config(art)
    )
    cfg = dataclasses.replace(cfg, track_trace=True)
    n_ticks = int(math.ceil(int(art["ticks"]) / window)) * window
    fn, spec = _traced_replay_fn(
        cfg, n_ticks, window, int(art["seg_len"]), depth
    )
    root = jax.random.key(int(art["seed"]))
    k_init, k_run = jax.random.split(root)
    state = init_batch(cfg, k_init, int(art["batch"]))
    keys = jax.random.split(k_run, int(art["batch"]))
    c = int(art["cluster"])
    state1 = jax.tree.map(lambda v: v[c:c + 1], state)
    g = genome_mod.broadcast(genome_mod.from_raw(art["genome_raw"]), 1)
    out = fn(state1, keys[c:c + 1], g)
    traws = out[4]  # (state, metrics, records, recorder, traws, tp)
    hist = history_mod.from_device(jax.device_get(traws), spec)
    return checker_mod.check_history(hist)


# ------------------------------------------------------------- freezing


def default_name(art: dict) -> str:
    """`<kernel>-n<N>` -- the established corpus naming (weak-quorum-n5)."""
    kernel = art.get("mutant") or "real"
    n = RaftConfig(**art.get("config", {})).n_nodes
    return f"{kernel}-n{n}"


def freeze(
    art: dict,
    corpus_dir: str,
    provenance: dict,
    name: str | None = None,
    window: int = 64,
    depth: int = 512,
) -> tuple[str, dict]:
    """Stamp + checker-gate + write one artifact into the corpus. Raises if
    the checker fails to REJECT the artifact's kernel (a hit the six
    properties cannot see must not enter the safety corpus as if they
    could), or if the stamped artifact fails validation. Dedup is the
    CALLER's gate (find_duplicate) -- freezing is unconditional by then.
    Returns (path, stamped artifact); the rejected property lands in
    provenance["checker_property"]."""
    rep = check_artifact(art, window=window, depth=depth)
    if not rep.violated:
        state = "passed" if rep.ok else "was undecided on"
        raise ValueError(
            f"refusing to freeze: the six-property checker {state} the "
            f"artifact's replay (complete={rep.complete}, problems="
            f"{rep.problems[:2]}) -- the corpus regresses safety semantics, "
            "so a hit the checker cannot name does not belong in it"
        )
    prov = dict(provenance, checker_property=rep.violated[0])
    art2 = stamp(art, prov)
    os.makedirs(corpus_dir, exist_ok=True)
    base = name or default_name(art2)
    path = os.path.join(corpus_dir, f"{base}.json")
    i = 2
    while os.path.exists(path):
        path = os.path.join(corpus_dir, f"{base}-{i}.json")
        i += 1
    shrink_mod.save_artifact(path, art2)
    return path, art2


def backfill_provenance(path: str, provenance: dict) -> dict:
    """Upgrade a v1 artifact file in place to the v2 corpus schema (the
    one-time migration for the hand-frozen seed artifacts; new freezes go
    through freeze())."""
    art = shrink_mod.load_artifact(path)
    art2 = stamp(art, provenance)
    shrink_mod.save_artifact(path, art2)
    return art2
