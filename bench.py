"""Benchmark: cluster-ticks/sec/chip across the BASELINE fault matrix.

Prints ONE JSON line. The headline fields {"metric", "value", "unit", "vs_baseline"}
are the north-star workload (config3: 100k x 5-node clusters, randomized election
timeouts; target >=1M cluster-ticks/sec/chip, BASELINE.json `north_star`); the
"matrix" field carries one row per BASELINE config (config1 is the
single-cluster 10k-tick correctness reference with log matching checked every
tick, config2 the 1k-cluster vmap row, 3-5 the throughput/fault rows -- config5
now with sampled log matching on) plus three feature rows: config6 (ring
compaction under crash churn), config6r (the same through the 302-redirect
client write path), and config4c (config4's fault mix under client traffic, so
commit latency is measured UNDER faults). Each row carries throughput AND the
quality metrics (p50 ticks-to-stable-leader, mean-based p50 offer->commit
latency, true per-entry lat_p50/p95/p99 from the on-device histogram,
accepted-command / violation / liveness counters). The reference publishes no
numbers of its own (SURVEY.md section 6).

Two timing traps on this machine's TPU stack, both defended here:
  1. it caches identical (program, args) executions, so every timed repeat uses a
     fresh TIME-SALTED seed (a never-before-seen args tuple);
  2. `jax.block_until_ready` can return early (~1 ms) while the program is still
     executing (observed: 0.001 s walls -> 98G "ticks/s"), so each repeat is timed
     to a forced HOST COPY of a per-cluster output -- data on the host cannot lie.
Per-config tick counts keep each XLA call well under the tunnel's execution
watchdog (~60 s).

Usage: python bench.py                      # full matrix (TPU-sized)
       python bench.py --smoke              # CPU-sized shrink of the same matrix
       python bench.py --preset config4     # one config only
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import jax
import numpy as np

from raft_sim_tpu import PRESETS, RaftConfig
from raft_sim_tpu.parallel import summarize
from raft_sim_tpu.sim import scan

NORTH_STAR = 1_000_000.0  # cluster-ticks/sec/chip, BASELINE.json north_star

# config -> ticks per timed call (bounded so one call stays watchdog-safe even at
# full batch; config5's N=51 tick is ~100x a 5-node tick). config1 runs its full
# BASELINE 10k-tick soak (single cluster -- the correctness row, not a
# throughput row). Rows 6/6r exercise the ring-compaction + redirect write
# path, row 4c the config4 fault mix under client traffic, so the standing
# bench carries compaction/redirect throughput and commit latency UNDER faults
# (not only on reliable nets).
MATRIX_TICKS = {
    "config1": 10_000,
    "config9": 500,
    "config2": 2_000,
    "config3": 500,
    "config3p": 500,
    "config4": 300,
    "config4c": 300,
    "config5": 200,
    "config5c": 200,
    "config6": 5_000,
    "config6r": 5_000,
}
SMOKE_BATCH = {
    "config2": 64,
    "config8": 64,
    "config10": 64,
    "config9": 64,
    "config3": 512,
    "config3p": 512,
    "config4": 256,
    "config4c": 256,
    "config5": 16,
    "config5c": 16,
    "config6": 64,
    "config6r": 64,
}
SMOKE_TICKS = {"config1": 1_000, "config6": 1_000, "config6r": 1_000}


def _roofline_pins() -> dict:
    """Predicted per-config rooflines from the gated cost model's pins
    (tests/golden_cost_model.json, regenerated via `tools/check.py
    --update-goldens`): bytes/tick x the pinned implied HBM rate. Read-only
    and fully guarded -- bench must still run where the pins are absent
    (installed package, fresh clone); rows then simply omit the
    predicted-vs-measured fields."""
    try:
        from raft_sim_tpu.analysis import cost_model

        with open(cost_model.golden_path()) as f:
            return json.load(f).get("programs", {})
    except Exception:
        return {}


_ROOFLINE_PINS = _roofline_pins()


def _telemetry_window(ticks: int) -> int:
    """A window size that divides the run (the windowed scan requires it):
    the finest of a few round divisors, falling back to one whole-run window."""
    for d in (16, 10, 8, 5, 4, 2):
        if ticks % d == 0:
            return ticks // d
    return ticks


def _pin_applies(config_name: str, cfg: RaftConfig, batch: int,
                 smoke: bool) -> bool:
    """The pins are priced at the preset's production batch AND its exact
    config; a --smoke row, a custom-batch row, or a config-variant row (e.g.
    the measurement pass's serve_ingest=True arm, whose carry the pin does
    not price) must not carry a headroom number computed against a different
    program's roofline. `smoke` is checked on its own because a preset whose
    smoke batch equals its production batch (config1: batch 1 both ways)
    would otherwise slip through the batch comparison."""
    return (not smoke and config_name in PRESETS
            and batch == PRESETS[config_name][1]
            and cfg == PRESETS[config_name][0])


def bench(cfg: RaftConfig, batch: int, ticks: int, repeats: int = 3,
          quality_seeds: int = 3, telemetry_dir: str | None = None,
          config_name: str = "custom", scenario=None,
          smoke: bool = False) -> dict:
    # `scenario` (a ScenarioProgram) reroutes every run through the
    # scenario-engine input path -- the program's genome broadcast over the
    # fleet -- so the row prices the genome-table reads and the
    # always-traced fault lattice against the scalar path's numbers
    # (docs/PERF.md "scenario path" has the standing verdict).
    if scenario is not None:
        from raft_sim_tpu.scenario import genome as genome_mod

        g = genome_mod.broadcast(scenario.genome, batch)
        seg_len = scenario.seg_len
        sim = lambda seed: scan.simulate_scenario(cfg, seed, batch, ticks, g, seg_len)
    else:
        g = seg_len = None
        sim = lambda seed: scan.simulate(cfg, seed, batch, ticks)
    # Quality runs use FIXED seeds 0..quality_seeds-1 (reproducible across
    # invocations, comparable across commits) and their per-cluster metrics are
    # pooled, so the reported p50s sample quality_seeds x batch clusters instead
    # of one seed's worth. The first doubles as the compile warmup. Timed repeats
    # then use time-salted seeds (capped so seed_base + r stays int32).
    #
    # With telemetry_dir set, the seed-0 quality run goes through the windowed
    # telemetry scan instead and its window records land in
    # telemetry_dir/<config_name>/ under the SAME schema driver.py writes
    # (utils/telemetry_sink.py) -- bit-exact, so the pooled quality metrics are
    # unchanged (tests/test_telemetry.py pins windowed == monolithic).
    pooled = []
    for qs in range(quality_seeds):
        if qs == 0 and telemetry_dir is not None:
            from raft_sim_tpu.sim import telemetry
            from raft_sim_tpu.utils.telemetry_sink import TelemetrySink

            window = _telemetry_window(ticks)
            sink = TelemetrySink(
                os.path.join(telemetry_dir, config_name), cfg, seed=qs,
                batch=batch, window=window, ring=0, source="bench",
            )
            final, m, records, _ = telemetry.simulate_windowed(
                cfg, qs, batch, ticks, window, genome=g,
                seg_len=seg_len if seg_len is not None else 1,
            )
            sink.append_windows(jax.device_get(records))
        else:
            final, m = sim(qs)
        pooled.append(jax.device_get(m))
    q_metrics = type(pooled[0])(
        *(np.concatenate([np.asarray(getattr(m, f)) for m in pooled])
          for f in pooled[0]._fields)
    )

    seed_base = int(time.time_ns() % ((1 << 31) - 1 - repeats))
    walls = []
    for r in range(1, repeats + 1):
        t0 = time.perf_counter()
        final, metrics = sim(seed_base + r)
        # Time to a host copy, not block_until_ready (see module docstring).
        np.asarray(metrics.ticks)
        walls.append(time.perf_counter() - t0)
    best = min(walls)
    # Steady-state stats exclude the FIRST timed repeat: the quality runs
    # already paid the compile, but repeat 1 still carries dispatch/cache
    # warmth (and on some stacks a late autotune) -- reconciliation against
    # the cost-model pins must not be polluted by it (obs/reconcile.py reads
    # steady_ticks_per_s first). With repeats == 1 there is nothing to
    # exclude: the single wall is used and repeat_cv is None (unknowable).
    steady_walls = walls[1:] if len(walls) > 1 else walls
    steady_mean = float(np.mean(steady_walls))
    steady_cv = (
        round(float(np.std(steady_walls) / steady_mean), 4)
        if len(steady_walls) > 1 and steady_mean > 0
        else (0.0 if len(steady_walls) > 1 else None)
    )

    s = summarize(q_metrics)  # pooled fixed-seed quality metrics
    if telemetry_dir is not None:
        # summary.json must describe the SAME run the manifest/windows do
        # (seed 0 alone) -- the pooled 3-seed rollup `s` stays in the bench
        # row, not in the telemetry directory.
        sink.write_summary(summarize(pooled[0])._asdict())
    value = batch * ticks / best
    # Measured throughput vs the PINNED roofline (this program's bytes/tick x
    # the pinned implied HBM rate -- equal to the anchor at pin time by
    # construction, so this is a drift detector against the pins, not a
    # layout-vs-layout bound; those live in tools/traffic_audit.py). ~1.0 =
    # tracking the pins; >1 = slower than pinned (regression, or a non-HBM
    # bottleneck at the pinned rate); <1 = faster than the pins -- they are
    # stale, regenerate after this round's artifact lands.
    pin = _ROOFLINE_PINS.get(f"{config_name}/simulate", {})
    roof = pin.get("roofline_ticks_per_s")
    if not _pin_applies(config_name, cfg, batch, smoke):
        roof = None
    row = {
        # Legacy headline: best wall over ALL timed repeats (including the
        # warmup-adjacent first one) -- the exact definition BENCH_r01-r05
        # recorded, kept byte-compatible so old artifacts stay diffable; the
        # "legacy" marker names it so nothing new reads it by accident.
        "cluster_ticks_per_s": round(value, 1),
        "vs_baseline": round(value / NORTH_STAR, 3),
        "legacy": ["cluster_ticks_per_s", "wall_s", "vs_baseline"],
        # Steady-state throughput: warmup repeat excluded, mean-based (the
        # reconciliation input), with per-repeat variance made visible.
        "steady_ticks_per_s": round(batch * ticks / steady_mean, 1),
        "repeat_walls_s": [round(w, 4) for w in walls],
        "repeat_cv": steady_cv,
        "backend": jax.default_backend(),
        # Carry layout of the benched config (cost_model.layout_of): the
        # anchor/reconcile guards key on this so a compacted-layout row can
        # never silently rebase the dense roofline (or vice versa).
        "layout": "compact" if cfg.compact_planes else "dense",
        "batch": batch,
        "n_nodes": cfg.n_nodes,
        "ticks": ticks,
        "wall_s": round(best, 3),
        "p50_stable_tick": s.p50_stable_tick,
        "pct_stable": round(100.0 * s.n_stable / s.n_clusters, 1),
        "p50_commit_latency": s.p50_commit_latency,
        "lat_p50": s.lat_p50,
        "lat_p95": s.lat_p95,
        "lat_p99": s.lat_p99,
        "lat_excluded": s.lat_excluded,
        "total_cmds": s.total_cmds,
        "violations": s.total_violations,
        "noop_blocked": s.noop_blocked,
        "lm_skipped_pairs": s.lm_skipped_pairs,
        "multi_leader": s.multi_leader,
        "quality_seeds": quality_seeds,
    }
    if smoke:
        # Marked so cost_model.bench_anchor can reject the row even when the
        # preset's smoke batch equals its production batch (config1).
        row["smoke"] = True
    if scenario is not None:
        # Marked HERE, not by the CLI layer: every consumer that must refuse
        # scenario-path throughput (cost_model.bench_anchor, obs/reconcile's
        # anchor flag) keys on this field, so a bench() caller that bypasses
        # main() -- the measurement pass's fault-lattice arm -- must not be
        # able to produce an unmarked scenario row.
        row["scenario"] = getattr(scenario, "name", "scenario")
    if roof and scenario is None:
        row["predicted_roofline_ticks_per_s"] = round(roof, 1)
        row["roofline_headroom"] = round(roof / value, 3)
    return row


def serve_bench(preset: str = "config9", batch: int | None = None,
                chunks: int = 8, chunk: int = 256, window: int = 64,
                tenants_n: int = 4, smoke: bool = False) -> dict:
    """The standing serve-throughput row: a multi-tenant ServeSession under
    saturating synthetic load, measured in COMMANDS+READS per second -- the
    service's unit of work -- never ticks/s (ROADMAP item 2's done-bar).

    Load model: `tenants_n` tenants partition the fleet; every tenant's
    source offers one distinct command per (tick, cluster) slot forever and
    demands more reads than the chunk budget can serve (offered one per
    cluster every other tick), so the session runs write- and
    read-saturated for `chunks` chunks. The row carries the PR 8 steady
    rollup (ChunkTimer) and reconciles against the SERVE program's cost pin
    (`<preset>/serve_simulate` -- obs/reconcile.py), with CPU rows
    explicitly non-anchor."""
    import itertools

    import jax as _jax

    from raft_sim_tpu.obs import ChunkTimer
    from raft_sim_tpu.obs import reconcile as _rec
    from raft_sim_tpu.serve import ServeSession, Tenant

    cfg, preset_batch = PRESETS[preset]
    if batch is None:
        batch = min(preset_batch, 64) if smoke else preset_batch
    if not cfg.read_index:
        raise ValueError(f"serve bench needs a read-carrying preset, "
                         f"got {preset}")
    from raft_sim_tpu.serve.tenancy import split_even

    sizes = split_even(batch, tenants_n)
    counter = itertools.count(1)
    tenants = [
        Tenant(f"t{i}", sizes[i],
               source=(next(counter) for _ in itertools.repeat(0)),
               reads=10**9, read_every=2)
        for i in range(tenants_n)
    ]
    perf = ChunkTimer(label="serve-bench", batch=batch)
    sess = ServeSession(cfg, batch=batch, seed=0, chunk=chunk, window=window,
                        sink=None, warmup_ticks=chunk, perf=perf,
                        tenants=tenants)
    stats = sess.serve(chunks=chunks)
    rollup = stats["perf"]
    wall = stats["wall_s"]
    row = {
        "kind": "serve-throughput",
        "unit": "commands+reads/s",
        "config": preset,
        "backend": _jax.default_backend(),
        "smoke": bool(smoke),
        "batch": batch,
        "tenants": tenants_n,
        "chunk": chunk,
        "window": window,
        "chunks": stats["chunks"],
        "ticks": stats["ticks"],
        "commands_acked": stats["commands_acked"],
        "reads_served": stats["reads_served"],
        "ops_done": stats["ops_done"],
        "ops_per_s": round(stats["ops_done"] / wall, 1) if wall else None,
        "commands_per_s": (
            round(stats["commands_acked"] / wall, 1) if wall else None
        ),
        "reads_per_s": (
            round(stats["reads_served"] / wall, 1) if wall else None
        ),
        "violations": stats["violations"],
        "steady_ticks_per_s": rollup["steady_cluster_ticks_per_s"],
        "perf": rollup,
    }
    row["reconciliation"] = _rec.reconcile_row(
        preset, row, _rec.load_pins(), program="serve_simulate"
    )
    return row


# ---------------------------------------------------------- measurement pass

# Schema tag of the MEASUREMENT_r*.json artifact --measurement-pass writes;
# tools/metrics_report.py --perf refuses documents it does not recognize.
MEASUREMENT_SCHEMA = "measurement-pass-v1"

# config3p rides beside config3 so PreVote's cost is a standing measured
# delta (same N/batch/ticks; the only difference is the pre_vote gate).
# config5c rides beside config5 the same way: the compacted-carry-layout
# twin (ops/tile.py) -- the dense-vs-compacted layout A/B is a standing
# measured delta, priced by the config5c cost pins before any chip run.
MATRIX_CONFIGS = (
    "config1", "config2", "config3", "config3p", "config4", "config4c",
    "config5", "config5c", "config6", "config6r",
)


def _matrix_sizing(name: str, smoke: bool) -> tuple[int, int]:
    """(batch, ticks) for one matrix row under the standard sizing rules."""
    _, preset_batch = PRESETS[name]
    batch = SMOKE_BATCH.get(name, min(preset_batch, 256)) if smoke else preset_batch
    ticks = (
        SMOKE_TICKS[name]
        if smoke and name in SMOKE_TICKS
        else MATRIX_TICKS.get(name, 300)
    )
    return batch, ticks


def _next_measurement_path() -> str:
    """MEASUREMENT_r<N+1>.json where N is the highest round any BENCH_r* or
    MEASUREMENT_r* artifact in the repo root records."""
    import re

    root = os.path.dirname(os.path.abspath(__file__))
    rounds = [0]
    for f in os.listdir(root):
        m = re.fullmatch(r"(?:BENCH|MEASUREMENT)_r(\d+)\.json", f)
        if m:
            rounds.append(int(m.group(1)))
    return os.path.join(root, f"MEASUREMENT_r{max(rounds) + 1:02d}.json")


def _bench_trajectory() -> tuple[list[dict], list[str]]:
    """(per-artifact throughput history, notes): one entry per BENCH_r*.json
    in round order, carrying each recoverable row's legacy headline -- the
    BENCH_r01 -> now line the measurement report draws, with the unmeasured
    tail (rounds after the newest artifact) called out."""
    import re

    from raft_sim_tpu.analysis import cost_model

    root = os.path.dirname(os.path.abspath(__file__))
    entries, notes = [], []
    paths = sorted(
        (f for f in os.listdir(root) if re.fullmatch(r"BENCH_r\d+\.json", f)),
        key=lambda p: int(re.search(r"r(\d+)", p).group(1)),
    )
    for name in paths:
        try:
            with open(os.path.join(root, name)) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as ex:
            notes.append(f"{name}: unreadable ({ex}); skipped")
            continue
        rows = cost_model.bench_matrix(doc)
        entries.append({
            "source": name,
            "round": int(re.search(r"r(\d+)", name).group(1)),
            "ticks_per_s": {
                k: v.get("cluster_ticks_per_s")
                for k, v in sorted(rows.items())
                if isinstance(v, dict)
            },
        })
    if entries:
        newest = entries[-1]["round"]
        notes.append(
            f"newest hardware artifact is round {newest}: every perf claim "
            f"since (bit-packing, fault lattice, serve offer-plane, ...) was "
            "priced by the gated cost model but UNMEASURED on hardware until "
            "a chip measurement pass lands"
        )
    else:
        notes.append("no BENCH_r*.json artifacts found: no trajectory to draw")
    return entries, notes


def _ab_pair(label: str, off_row: dict, on_row: dict, notes: list[str]) -> dict:
    """One A/B arm: both rows plus the steady-state THROUGHPUT ratio
    on/off -- < 1 means the feature costs throughput (e.g. the fault
    lattice's documented +66% CPU wall shows up as ~0.6 here), 1.0 = free,
    > 1 = the feature measured faster (run variance or a real win)."""
    off_v = off_row.get("steady_ticks_per_s") or off_row.get("cluster_ticks_per_s")
    on_v = on_row.get("steady_ticks_per_s") or on_row.get("cluster_ticks_per_s")
    return {
        "label": label,
        "off": off_row,
        "on": on_row,
        "on_over_off_ticks_per_s": (
            round(on_v / off_v, 4) if on_v and off_v else None
        ),
        "notes": notes,
    }


def _mesh_scaling_leg(args, smoke: bool, backend: str) -> dict:
    """Strong-scaling sweep over the cluster mesh: the SAME global batch
    sharded across 1/2/4/8 devices through parallel.simulate_windowed_sharded.
    Trajectories are bit-identical at every width (keys split outside the
    sharded region -- tests/test_farm_mesh.py), so the wall-clock ratio prices
    the mesh partition, not the workload. Every row carries `n_devices`:
    reconciliation and `cost_model.bench_anchor` reject D>1 rows the way they
    reject layout mismatches (aggregate mesh throughput must never rebase the
    single-device roofline), and on CPU every row is non-anchor anyway."""
    from raft_sim_tpu.obs import reconcile
    from raft_sim_tpu.parallel import make_mesh
    from raft_sim_tpu.parallel import mesh as mesh_mod

    name = args.mesh_preset
    cfg, _ = PRESETS[name]
    batch, ticks = _matrix_sizing(name, smoke)
    batch = max(8, batch - batch % 8)  # one global batch, divisible at D=8
    window = max(1, ticks // 4)
    ticks = window * 4
    avail = jax.device_count()
    notes = [
        f"fixed global batch {batch}: strong scaling -- the per-device slice "
        "shrinks with D, the work does not",
        "rows carry n_devices; D>1 rows are structurally non-anchor "
        "(obs/reconcile + cost_model.bench_anchor reject them like layout "
        "mismatches)",
    ]
    rows = {}
    for d in (1, 2, 4, 8):
        if d > avail:
            notes.append(f"{d} devices > {avail} available: skipped")
            continue
        print(f"measurement mesh_scaling {name}: {d} devices...",
              file=sys.stderr)
        mesh = make_mesh(d)
        t0 = time.perf_counter()
        out = mesh_mod.simulate_windowed_sharded(cfg, 0, batch, ticks,
                                                 window, mesh)
        jax.block_until_ready(out[:3])
        compile_s = time.perf_counter() - t0
        walls = []
        for _ in range(max(1, args.repeats)):
            t0 = time.perf_counter()
            out = mesh_mod.simulate_windowed_sharded(cfg, 0, batch, ticks,
                                                     window, mesh)
            jax.block_until_ready(out[:3])
            walls.append(time.perf_counter() - t0)
        best = min(walls)
        row = {
            "n_devices": d,
            "batch": batch,
            "ticks": ticks,
            "window": window,
            "smoke": smoke,
            "backend": backend,
            "compile_s": round(compile_s, 3),
            "wall_s": round(best, 4),
            "cluster_ticks_per_s": round(batch * ticks / best, 1),
            "steady_ticks_per_s": round(batch * ticks / best, 1),
        }
        reasons = reconcile.non_anchor_reasons(name, row, backend)
        row["anchor"] = not reasons
        row["non_anchor_reasons"] = reasons
        rows[f"{d}dev"] = row
    base = (rows.get("1dev") or {}).get("cluster_ticks_per_s")
    speedup = {
        k: round(v["cluster_ticks_per_s"] / base, 3) if base else None
        for k, v in rows.items()
    }
    return {
        "label": f"{name}: one global batch across 1/2/4/8 devices",
        "config": name,
        "rows": rows,
        "speedup_vs_1dev": speedup,
        "notes": notes,
    }


def measurement_pass(args) -> int:
    """The owed measurement pass as ONE command (ISSUE 8 / ROADMAP item 1):
    the standing matrix plus the three unpriced deltas, reconciled against
    the gated cost-model pins, written as a schema'd MEASUREMENT_r*.json.

    The three A/Bs:
      bitpack_vs_r05     measured-now vs the archived BENCH_r05 rows -- bit-
                         packing is STRUCTURAL since checkpoint v18 (there is
                         no dense kernel to toggle back to), so the A/B is
                         longitudinal against the last pre-packing chip
                         artifact; cross-backend ratios are refused.
      fault_lattice      the same preset through the plain input path vs the
                         scenario path under its own config's homogeneous
                         genome (bit-exact trajectories; prices the always-
                         traced fault lattice -- the +66%-on-CPU delta
                         docs/SCENARIOS.md expects to compress on chip).
      serve_offer_plane  the preset vs serve_ingest=True (offer-tick plane
                         legs live but no traffic) -- prices the serve-mode
                         carry traffic_audit --serve projects.

    Plus the transfer-during-joint interaction pair on config8 (ROADMAP
    item 4's named follow-up): homogeneous preset cadences vs a genome that
    forces TimeoutNow transfers into nearly every joint-consensus window;
    both rows reconcile in the standing table, marked scenario/non-anchor.

    Plus the durability pair on config10 (ISSUE 19): the fsync/WAL storage
    plane on (the preset) vs structurally off (fsync_interval=0) -- prices
    the durable-watermark carry, the fsync lattice draws, and the recovery
    lanes; both rows reconcile in the standing table.

    On a CPU image the pass auto-shrinks to --smoke sizing (CPU rows can
    never anchor anyway -- reconciliation marks every row non-anchor);
    --full forces production sizing on any backend.
    """
    backend = jax.default_backend()
    smoke = args.smoke or (backend == "cpu" and not args.full)
    configs = (
        [c.strip() for c in args.configs.split(",") if c.strip()]
        if args.configs
        else list(MATRIX_CONFIGS)
    )
    for c in configs:
        if c not in PRESETS:
            raise SystemExit(f"--configs: unknown preset {c!r}")
    ab_preset = args.ab_preset
    if ab_preset not in PRESETS:
        raise SystemExit(f"--ab-preset: unknown preset {ab_preset!r}")
    if args.mesh_preset not in PRESETS:
        raise SystemExit(f"--mesh-preset: unknown preset {args.mesh_preset!r}")

    matrix = {}
    for name in configs:
        batch, ticks = _matrix_sizing(name, smoke)
        print(f"measurement {name}: batch={batch} ticks={ticks}...", file=sys.stderr)
        matrix[name] = bench(
            PRESETS[name][0], batch, ticks, args.repeats,
            config_name=name, smoke=smoke,
        )

    # --- the three unpriced A/Bs ------------------------------------------
    import dataclasses as _dc
    from types import SimpleNamespace

    from raft_sim_tpu.scenario import genome as genome_mod

    ab_cfg = PRESETS[ab_preset][0]
    ab_batch, ab_ticks = _matrix_sizing(ab_preset, smoke)
    if ab_preset in matrix:
        plain = matrix[ab_preset]
    else:
        print(f"measurement A/B baseline {ab_preset}...", file=sys.stderr)
        plain = bench(ab_cfg, ab_batch, ab_ticks, args.repeats,
                      config_name=ab_preset, smoke=smoke)

    print(f"measurement A/B fault lattice ({ab_preset})...", file=sys.stderr)
    lattice = bench(
        ab_cfg, ab_batch, ab_ticks, args.repeats, config_name=ab_preset,
        smoke=smoke,
        scenario=SimpleNamespace(
            genome=genome_mod.from_config(ab_cfg), seg_len=1,
            name="homogeneous-from-config",
        ),
    )
    print(f"measurement A/B serve offer-plane ({ab_preset})...", file=sys.stderr)
    serve_on = bench(
        _dc.replace(ab_cfg, serve_ingest=True), ab_batch, ab_ticks,
        args.repeats, config_name=ab_preset, smoke=smoke,
    )
    # Not the preset's config: say so on the row itself (bench() already
    # refuses to attach the plain preset's roofline pin to it).
    serve_on["config_variant"] = "serve_ingest=True"

    r05_notes = []
    bitpack = {"label": "bitpack_vs_r05", "r05": {}, "measured": {},
               "measured_over_r05": {}, "notes": r05_notes}
    r05_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_r05.json")
    if os.path.isfile(r05_path):
        from raft_sim_tpu.analysis import cost_model

        with open(r05_path) as f:
            r05_rows = cost_model.bench_matrix(json.load(f))
        for name in ("config3", "config4", "config5"):
            old = (r05_rows.get(name) or {}).get("cluster_ticks_per_s")
            new = (matrix.get(name) or {}).get("steady_ticks_per_s")
            bitpack["r05"][name] = old
            bitpack["measured"][name] = new
            if old and new and backend != "cpu" and not smoke:
                bitpack["measured_over_r05"][name] = round(new / old, 4)
        if backend == "cpu" or smoke:
            r05_notes.append(
                "BENCH_r05 rows were measured on chip at production sizing; "
                f"this pass ran backend={backend} smoke={smoke}, so no ratio "
                "is computed -- the bit-packing delta still awaits a chip "
                "session"
            )
        r05_notes.append(
            "bit-packing is structural since checkpoint v18: this A/B is "
            "longitudinal (now vs the last pre-packing artifact), not a "
            "runtime toggle"
        )
    else:
        r05_notes.append("BENCH_r05.json not found: no pre-packing baseline")

    # Dense-vs-compacted layout A/B (ISSUE 14): config5 and its compacted
    # twin config5c run the SAME workload with bit-identical trajectories
    # (tests/test_tile.py), so the throughput ratio prices the node-blocked
    # tiling directly. Both rows ride the standing matrix; the pair is only
    # assembled when both ran (a --configs subset may drop one).
    if "config5" in matrix and "config5c" in matrix:
        layout_ab = _ab_pair(
            "config5: dense vs compacted carry layout (config5c)",
            matrix["config5"], matrix["config5c"],
            ["trajectories are bit-exact across the two arms (the layout is "
             "physical only -- ops/tile.py); the cost pins predict the "
             "compacted arm at ~0.64x the dense bytes/tick on config5 "
             "(tests/golden_cost_model.json config5c/simulate)",
             "neither arm can rebase the OTHER layout's roofline: rows carry "
             "`layout` and the anchor/reconcile guards key on it"],
        )
    else:
        layout_ab = {
            "label": "config5: dense vs compacted carry layout",
            "notes": ["skipped: --configs dropped config5 and/or config5c"],
        }

    # Transfer-during-joint interaction rows (ROADMAP item 4's named
    # follow-up): config8's preset cadences (a membership toggle every 97
    # ticks, a TimeoutNow transfer every 61) overlap a joint-consensus
    # window only occasionally, so the standing rows never price the
    # CONTENDED case -- a transfer in flight during a dual-quorum joint
    # phase (transfer lease refusing client commands + dual majorities +
    # the removed-leader stepdown, all live at once). Both arms run the
    # scenario path so the ratio prices the cadence interaction, not the
    # genome-table reads: the baseline is config8's own homogeneous genome,
    # the interaction arm forces the overlap (toggle every 24 ticks opens
    # joint windows back to back, transfers fire every 5 so nearly every
    # joint phase carries one; faults at config8's own levels).
    print("measurement A/B transfer-during-joint (config8)...", file=sys.stderr)
    xj_cfg = PRESETS["config8"][0]
    xj_batch, xj_ticks = _matrix_sizing("config8", smoke)
    xj_plain = bench(
        xj_cfg, xj_batch, xj_ticks, args.repeats, config_name="config8",
        smoke=smoke,
        scenario=SimpleNamespace(
            genome=genome_mod.from_config(xj_cfg), seg_len=1,
            name="homogeneous-from-config",
        ),
    )
    xj_on = bench(
        xj_cfg, xj_batch, xj_ticks, args.repeats, config_name="config8",
        smoke=smoke,
        scenario=SimpleNamespace(
            genome=genome_mod.from_segments([genome_mod.segment(
                drop_prob=xj_cfg.drop_prob,
                crash_prob=xj_cfg.crash_prob,
                crash_down_ticks=xj_cfg.crash_down_ticks,
                client_interval=xj_cfg.client_interval,
                reconfig_interval=24,
                transfer_interval=5,
                read_interval=xj_cfg.read_interval,
            )]), seg_len=1, name="xfer-joint",
        ),
    )

    # Durability A/B (ISSUE 19): config10's fsync/WAL model vs the SAME
    # preset with the storage plane structurally OFF (fsync_interval=0 and
    # the dependent disk-fault knobs zeroed -- config.py rejects jitter/torn
    # without the gate). The off arm is the zero-cost-when-off claim's priced
    # half: its trajectory is bit-exact vs a pre-plane build (the gated legs
    # are host constants), so the ratio prices the watermark carry + fsync
    # lattice + recovery lanes end to end. Both arms reconcile in the
    # standing table (CPU/smoke rows are non-anchor like every other row).
    print("measurement A/B durability (config10)...", file=sys.stderr)
    dur_cfg = PRESETS["config10"][0]
    dur_batch, dur_ticks = _matrix_sizing("config10", smoke)
    dur_on = bench(
        dur_cfg, dur_batch, dur_ticks, args.repeats, config_name="config10",
        smoke=smoke,
    )
    dur_off = bench(
        _dc.replace(
            dur_cfg, fsync_interval=0, fsync_jitter_prob=0.0,
            torn_tail_prob=0.0, lost_suffix_span=1,
        ),
        dur_batch, dur_ticks, args.repeats, config_name="config10",
        smoke=smoke,
    )
    dur_off["config_variant"] = "fsync_interval=0 (storage plane off)"

    mesh_scaling = _mesh_scaling_leg(args, smoke, backend)

    from raft_sim_tpu.obs import reconcile_matrix

    # The interaction rows reconcile like every standing row (same table,
    # same anchor guards): both carry `scenario`, so neither can ever
    # rebase config8's roofline -- the reconciliation simply reports them.
    reconciliation = reconcile_matrix(
        {"matrix": {
            **matrix,
            "config8": xj_plain,
            "config8/xfer-joint": xj_on,
            "config10": dur_on,
            "config10/durability-off": dur_off,
        }},
        default_backend=backend,
    )
    trajectory, traj_notes = _bench_trajectory()

    doc = {
        "schema": MEASUREMENT_SCHEMA,
        "created_unix": int(time.time()),
        "backend": backend,
        "jax_version": jax.__version__,
        "smoke": smoke,
        "repeats": args.repeats,
        "matrix": matrix,
        "ab": {
            "bitpack_vs_r05": bitpack,
            "fault_lattice": _ab_pair(
                f"{ab_preset}: plain vs scenario-path homogeneous genome",
                plain, lattice,
                ["trajectories are bit-exact across the two arms "
                 "(tests/test_scenario.py pins the homogeneous-genome "
                 "equivalence); the ratio prices the always-traced lattice"],
            ),
            "serve_offer_plane": _ab_pair(
                f"{ab_preset}: plain vs serve_ingest=True (plane legs live, "
                "no offered traffic)",
                plain, serve_on,
                ["prices the v21 offer-tick plane carry the serve mode pays "
                 "(traffic_audit --serve has the static projection)"],
            ),
            "layout_dense_vs_compact": layout_ab,
            "durability": _ab_pair(
                "config10: storage plane off (fsync_interval=0) vs on "
                "(fsync@3 + jitter/torn disk faults)",
                dur_off, dur_on,
                ["the off arm is config10 with the durable-storage gate "
                 "structurally off: the dur watermark legs are carry "
                 "passthroughs and the fsync/recovery lanes compile out "
                 "(tests/test_storage.py pins the disabled-mode goldens "
                 "byte-identical), so the ratio prices the plane itself",
                 "off arm is not the preset's config: the row carries "
                 "config_variant and can never anchor config10's roofline"],
            ),
            "transfer_during_joint": _ab_pair(
                "config8: homogeneous cadences (reconfig@97/transfer@61) vs "
                "forced transfer-during-joint overlap (reconfig@24/"
                "transfer@5)",
                xj_plain, xj_on,
                ["both arms ride the scenario input path, so the ratio "
                 "prices the joint-phase/transfer contention itself "
                 "(dual-quorum counting + transfer lease + stepdown), not "
                 "the genome-table reads",
                 "scenario rows: neither arm can anchor config8's roofline "
                 "(obs/reconcile marks both non-anchor)"],
            ),
        },
        "mesh_scaling": mesh_scaling,
        "reconciliation": reconciliation,
        "trajectory": trajectory,
        "notes": traj_notes,
    }
    out_path = args.out or _next_measurement_path()
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    anchored = ", ".join(reconciliation["anchor_eligible"]) or (
        "NONE (this artifact cannot rebase the roofline)"
    )
    per_cfg = " ".join(
        f"{n}={row.get('steady_ticks_per_s', 0):g}" for n, row in matrix.items()
    )
    print(
        f"measurement pass [{backend}{' smoke' if smoke else ''}]: {per_cfg} | "
        f"anchor-eligible rows: {anchored} | render: "
        f"python tools/metrics_report.py --perf {out_path}"
    )
    return 0


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default=None, choices=sorted(PRESETS),
                    help="bench one config instead of the 3/4/5 matrix")
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--ticks", type=int, default=None)
    ap.add_argument("--repeats", type=int, default=3,
                    help="timed repeats per row; the first is the warmup "
                         "repeat, excluded from steady_ticks_per_s (default 3)")
    ap.add_argument("--smoke", action="store_true",
                    help="CPU-sized shrink (small batches) of the same matrix")
    ap.add_argument("--telemetry-dir", default=None, metavar="DIR",
                    help="also write each config's seed-0 quality run as a "
                         "telemetry directory (DIR/<config>/, the same schema "
                         "driver.py --telemetry-dir emits)")
    ap.add_argument("--scenario", default=None, metavar="FILE",
                    help="run the benched config(s) through the scenario-"
                         "engine input path under this nemesis program "
                         "(prices the genome-table reads; requires --preset)")
    ap.add_argument("--measurement-pass", action="store_true",
                    help="the owed one-command measurement pass (docs/PERF.md "
                         "checklist): standing matrix + the three unpriced "
                         "A/Bs (bit-packing vs r05, fault lattice, serve "
                         "offer-plane) + reconciliation vs the cost-model "
                         "pins, written as MEASUREMENT_r*.json (--out "
                         "overrides the path). Auto-shrinks to smoke sizing "
                         "on CPU; CPU rows are marked non-anchor either way")
    ap.add_argument("--full", action="store_true",
                    help="with --measurement-pass: force production sizing "
                         "even on a CPU backend")
    ap.add_argument("--configs", default=None, metavar="A,B,...",
                    help="with --measurement-pass: matrix subset (default: "
                         "all standing rows)")
    ap.add_argument("--ab-preset", default="config3", metavar="NAME",
                    help="with --measurement-pass: the preset the fault-"
                         "lattice and serve-plane A/Bs run on (default "
                         "config3, the north-star workload)")
    ap.add_argument("--mesh-preset", default="config3", metavar="NAME",
                    help="with --measurement-pass: the preset the "
                         "mesh_scaling leg strong-scales across 1/2/4/8 "
                         "devices at one fixed global batch (default "
                         "config3; D>1 rows are always non-anchor)")
    ap.add_argument("--serve", action="store_true",
                    help="bench ONLY the standing serve-throughput row "
                         "(commands+reads/s over a saturated multi-tenant "
                         "ServeSession; reconciles against the serve "
                         "program's cost pin). The full matrix run appends "
                         "this row automatically")
    ap.add_argument("--serve-preset", default="config9", metavar="NAME",
                    help="read-carrying preset the serve row runs "
                         "(default config9, the lease-read tier)")
    ap.add_argument("--serve-chunks", type=int, default=8,
                    help="serving chunks of the serve row (default 8)")
    ap.add_argument("--out", default=None, metavar="PATH",
                    help="write the FULL matrix JSON to PATH and print only a "
                         "short headline line (north-star ratio + per-config "
                         "ticks/s) to stdout -- so a truncated terminal/log "
                         "capture can never clip the primary perf evidence "
                         "again (VERDICT weak #2); the file is the same "
                         "document cost_model.bench_anchor reads (save it as "
                         "BENCH_r<N>.json to anchor the roofline)")
    args = ap.parse_args()

    if args.measurement_pass:
        if args.preset or args.scenario or args.batch or args.ticks:
            ap.error("--measurement-pass runs the standard matrix sizing; it "
                     "is exclusive with --preset/--scenario/--batch/--ticks "
                     "(use --configs/--ab-preset/--full to steer it)")
        sys.exit(measurement_pass(args))

    if args.serve:
        row = serve_bench(args.serve_preset, batch=args.batch,
                          chunks=args.serve_chunks, smoke=args.smoke)
        print(json.dumps(row))
        return

    scenario = None
    if args.scenario:
        if not args.preset:
            ap.error("--scenario requires --preset (one labeled row)")
        from raft_sim_tpu.scenario import program as program_mod

        scenario = program_mod.load(args.scenario, PRESETS[args.preset][0])

    names = (
        [args.preset]
        if args.preset
        else [
            "config1",
            "config2",
            "config3",
            # The standing PreVote row: config3's exact sizing with pre_vote
            # on, so the probe phases' cost is a measured delta every run
            # (docs/PERF.md "PreVote cost"), not prose.
            "config3p",
            "config4",
            "config4c",
            "config5",
            # The standing compacted-layout row: config5's exact workload
            # under the ops/tile.py carry layout (bit-identical
            # trajectories), so the dense-vs-compacted delta is measured
            # beside its baseline every bench run -- the config3p pattern.
            "config5c",
            "config6",
            "config6r",
        ]
    )
    matrix = {}
    for name in names:
        cfg, preset_batch = PRESETS[name]
        smoke_batch = SMOKE_BATCH.get(name, min(preset_batch, 256))
        batch = args.batch or (smoke_batch if args.smoke else preset_batch)
        ticks = args.ticks or (
            SMOKE_TICKS[name]
            if args.smoke and name in SMOKE_TICKS
            else MATRIX_TICKS.get(name, 300)
        )
        print(f"bench {name}: batch={batch} ticks={ticks}...", file=sys.stderr)
        matrix[name] = bench(cfg, batch, ticks, args.repeats,
                             telemetry_dir=args.telemetry_dir, config_name=name,
                             scenario=scenario, smoke=args.smoke)

    if not args.preset:
        # The standing serve-throughput row rides every full-matrix run:
        # ROADMAP item 2's done-bar is commands+reads/s, not ticks/s.
        # bench_anchor ignores it (no cluster_ticks_per_s key): a service
        # row can never rebase the tick roofline.
        print(f"bench {args.serve_preset}-serve: serve-throughput row...",
              file=sys.stderr)
        matrix[f"{args.serve_preset}-serve"] = serve_bench(
            args.serve_preset, chunks=args.serve_chunks, smoke=args.smoke
        )

    # The headline is the north-star workload (config3) whenever it ran; benching a
    # different single preset labels itself via "workload" so vs_baseline is never
    # silently misread as the config3 number.
    headline_name = "config3" if "config3" in matrix else names[0]
    headline = matrix[headline_name]
    doc = {
        "metric": "cluster-ticks/sec/chip",
        "value": headline["cluster_ticks_per_s"],
        "unit": "cluster-ticks/s",
        "vs_baseline": headline["vs_baseline"],
        "workload": headline_name,
        "matrix": matrix,
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
        per_cfg = " ".join(
            f"{name}={row['cluster_ticks_per_s']:g}"
            if "cluster_ticks_per_s" in row
            else f"{name}={row.get('ops_per_s', 0):g}ops/s"
            for name, row in matrix.items()
        )
        print(
            f"{headline_name} {headline['cluster_ticks_per_s']:g} "
            f"cluster-ticks/s ({headline['vs_baseline']}x north star) | "
            f"{per_cfg} | full matrix: {args.out}"
        )
    else:
        print(json.dumps(doc))


if __name__ == "__main__":
    main()
