"""Adversarial scenario engine: per-cluster fault genomes, phased nemesis
programs, and a violation-hunting search + shrink loop.

The fifth subsystem (alongside models/sim/parallel/analysis). The simulator's
fault knobs stop being Python floats baked into one compiled program per
point in fault space and become DATA:

  genome.py   ScenarioGenome -- a pytree of per-cluster, per-segment fault
              parameters (uint32 threshold-compare encoding), threaded
              through sim/faults.make_inputs so one compiled program
              evaluates a heterogeneous fleet: 100k different fault settings
              per step instead of one per ~15-40s compile.
  program.py  Phased nemesis timelines: S segments with per-segment genomes
              compiled to dense [S] tables indexed by now // seg_len on
              device, loadable from a declarative JSON scenario file
              ("partition 200 ticks -> heal -> crash churn").
  search.py   A host-side cross-entropy loop over genome populations: the
              fleet IS the population, fitness comes from the telemetry
              window counters (PR 2), and each generation is ONE device
              call. Every evaluation is replayable from (genome, seed).
  shrink.py   Minimizes a violating (genome, seed, horizon) triple to a
              small repro artifact that tools/repro.py --scenario replays
              bit-exactly and the flight recorder renders.
  mutation.py TEST-ONLY deliberately-weakened kernel variants (quorum
              off-by-one) proving the hunt actually hunts.

Layering: scenario/ sits ABOVE sim/ (it imports faults/scan/telemetry; sim/
duck-types the genome and never imports back). docs/SCENARIOS.md is the
user-facing guide.
"""

from raft_sim_tpu.scenario.genome import ScenarioGenome
from raft_sim_tpu.scenario.program import ScenarioProgram

__all__ = ["ScenarioGenome", "ScenarioProgram"]
