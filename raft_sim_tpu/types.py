"""Struct-of-arrays state for batched Raft cluster simulation.

The reference keeps per-node state in a Clojure map (init-node, core.clj:31-38) plus a
log atom {:entries [{:term,:val}] :commit-index} (log.clj:33-34), and exchanges messages
as JSON over HTTP with core.async channels as mailboxes (server.clj:37, client.clj:18).

Here one *cluster* is a pytree of dense arrays over the node axis N; `vmap` lifts every
shape to [batch, N, ...]. Messages live in a dense [N, N] mailbox -- one in-flight slot
per directed edge, indexed [dst, src] -- replacing the reference's buffered(5) channels.
Overwriting an undelivered slot is a legal drop (the reference drops on any HTTP
exception, client.clj:38-40), and requests/responses occupy separate mailboxes because a
request sent at tick t is handled at t+1 and its response lands at t+2, mirroring the
reference's two-tick RPC structure (SURVEY.md section 3.2).

All integers are int32; node ids are 0-based with -1 as nil (the reference uses 1-based
ids and `nil`, core.clj:31-38). Log indices are 1-based counts like the reference/spec
(entry i lives at array slot i-1; index 0 means "no entry", log.clj:20-23).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from raft_sim_tpu.utils.config import RaftConfig
from raft_sim_tpu.utils.rng import draw_timeouts

# Node roles (reference keywords :follower/:candidate/:leader, core.clj:31-38;
# the reference's misspelled :follwer (core.clj:76) is a documented bug, not carried).
FOLLOWER = 0
CANDIDATE = 1
LEADER = 2

# Request mailbox record types (reference URI routing, server.clj:8-12).
REQ_NONE = 0
REQ_VOTE = 1  # :request-vote
REQ_APPEND = 2  # :append-entries

# Response mailbox record types (client.clj:8-9 keywordizes :type from the HTTP body).
RESP_NONE = 0
RESP_VOTE = 1  # :vote-response
RESP_APPEND = 2  # :append-response

NIL = -1  # nil node id


class Mailbox(NamedTuple):
    """One in-flight RPC slot per directed edge. Index orientation is chosen so that
    every outbox write is transpose-free (transposing ten [N, N, batch] fields per
    tick was ~15% of the N=51 tick):

      req_*  fields: [sender, receiver]   -- a sender broadcasts along its row;
                                             receivers reduce over axis 0.
      resp_* fields: [receiver, responder] -- a responder answers the request slot
                                             [q, r] it was addressed in, so the
                                             response to q lands at [q, r] directly;
                                             requesters reduce over axis 1.

    The AppendEntries entry payload is *shared per sender* (src-indexed).

    Request header fields overlay both message types (reference wire formats
    core.clj:51-54 and core.clj:62-67):
      REQ_VOTE:   prev_index = last-log-index, prev_term = last-log-term
      REQ_APPEND: prev_index/prev_term/commit/n_ent as named

    Entry transport (TPU-native wire-format deviation from the reference, which ships
    an arbitrary per-peer log suffix, core.clj:59-67): a sender broadcasts ONE shared
    E-entry window of its log per tick -- `ent_term/ent_val` [N(src), E] starting at
    1-based index `ent_start[src] + 1` -- positioned at the minimum prev-index among
    its RESPONSIVE peers (those that acked an AppendEntries within
    config.ack_timeout_ticks, tracked in ClusterState.last_ack; falls back to all
    peers when none are responsive, so a dead peer cannot pin the window start and
    stall replication). Each receiver rebases into the shared window at offset
    (own prev_index - ent_start); the per-edge `req_n_ent` header already counts only
    the entries available to that receiver. Spec-equivalent (AppendEntries may carry
    any window the receiver validates against prev_index/prev_term) but the mailbox
    payload is O(N*E) instead of O(N^2*E) -- at N=51 the per-edge form was ~70% of all
    mailbox bytes and the dominant HBM traffic of the whole tick.

    Response fields overlay :vote-response {term,vote-granted} (core.clj:95-102) and
    :append-response {term,success,log-index} (core.clj:109-121): `ok` is
    granted/success, `match` is the acknowledged log index for successful appends.
    """

    req_type: jax.Array  # [N(sender), N(receiver)] int32 (REQ_*)
    req_term: jax.Array  # [sender, receiver] int32
    req_prev_index: jax.Array  # [sender, receiver] int32
    req_prev_term: jax.Array  # [sender, receiver] int32
    req_commit: jax.Array  # [sender, receiver] int32
    req_n_ent: jax.Array  # [sender, receiver] int32
    ent_start: jax.Array  # [N] int32: 0-based slot where src's shared window starts
    ent_term: jax.Array  # [N, E] int32: src's shared entry window (terms)
    ent_val: jax.Array  # [N, E] int32: src's shared entry window (values)
    resp_type: jax.Array  # [N(receiver), N(responder)] int32 (RESP_*)
    resp_term: jax.Array  # [receiver, responder] int32
    resp_ok: jax.Array  # [receiver, responder] bool
    resp_match: jax.Array  # [receiver, responder] int32


class ClusterState(NamedTuple):
    """Full per-cluster simulator state (the scan carry).

    Maps the reference node map + log atom (SURVEY.md section 2.2) onto arrays:
      role/term/voted_for/leader_id  <- :state/:current-term/:voted-for/:leader-id
      votes [N,N] bool bitmap        <- :votes set (core.clj:38)
      next_index/match_index [N,N]   <- :leader-state maps (core.clj:40-42)
      log_term/log_val/log_len       <- log atom :entries (log.clj:33)
      commit_index                   <- log atom :commit-index
      clock/deadline                 <- async/timeout channels (core.clj:171-174)
    """

    role: jax.Array  # [N] int32
    term: jax.Array  # [N] int32 (starts at 1, core.clj:34)
    voted_for: jax.Array  # [N] int32 (NIL = none)
    leader_id: jax.Array  # [N] int32 (NIL = unknown)
    votes: jax.Array  # [N, N] bool; votes[i, j] = i holds a granted vote from j
    next_index: jax.Array  # [N, N] int32; leader i's next index for peer j
    match_index: jax.Array  # [N, N] int32
    # Tick at which leader i last received an AppendEntries response (success OR
    # failure -- both prove the peer is up) from peer j; stamped to the current tick
    # for the whole row when i wins an election (grace period). Volatile leader
    # bookkeeping like next/match; drives the shared-entry-window responsiveness
    # filter (config.ack_timeout_ticks).
    last_ack: jax.Array  # [N, N] int32
    commit_index: jax.Array  # [N] int32
    log_term: jax.Array  # [N, CAP] int32
    log_val: jax.Array  # [N, CAP] int32
    log_len: jax.Array  # [N] int32
    clock: jax.Array  # [N] int32 local (skewable) clock
    deadline: jax.Array  # [N] int32 next timer fire on the local clock
    now: jax.Array  # scalar int32 global tick counter
    mailbox: Mailbox


class StepInputs(NamedTuple):
    """Pure per-tick inputs. Randomness is *materialized outside* the step kernel so the
    same arrays can drive both the jnp kernel and the Python oracle (tests), and so fault
    schedules are plain data (SURVEY.md section 5, failure injection)."""

    deliver_mask: jax.Array  # [N, N] bool; False = message on edge [dst, src] dropped
    skew: jax.Array  # [N] int32 local-clock increment this tick (normally 1)
    timeout_draw: jax.Array  # [N] int32 election timeout to use on any timer reset
    client_cmd: jax.Array  # scalar int32 command value offered to the leader; NIL = none
    alive: jax.Array  # [N] bool; False = node crashed this tick (silent, frozen)
    restarted: jax.Array  # [N] bool; True = node came back up this tick (volatile wipe)


class StepInfo(NamedTuple):
    """Small per-tick outputs: on-device safety invariants + observability reductions
    (SURVEY.md section 5, metrics). All scalars per cluster."""

    viol_election_safety: jax.Array  # bool: two leaders share a term
    viol_commit: jax.Array  # bool: commit regressed or exceeds log length
    viol_log_matching: jax.Array  # bool (False unless cfg.check_log_matching)
    leader: jax.Array  # int32: lowest-id current leader, NIL if none
    n_leaders: jax.Array  # int32: number of nodes in LEADER role
    max_term: jax.Array  # int32
    max_commit: jax.Array  # int32
    min_commit: jax.Array  # int32
    msgs_delivered: jax.Array  # int32: request+response records delivered this tick
    cmds_injected: jax.Array  # int32 0/1: an offered command was accepted by a live leader


def empty_mailbox(cfg: RaftConfig) -> Mailbox:
    n, e = cfg.n_nodes, cfg.max_entries_per_rpc
    i = lambda *s: jnp.zeros(s, jnp.int32)
    return Mailbox(
        req_type=i(n, n),
        req_term=i(n, n),
        req_prev_index=i(n, n),
        req_prev_term=i(n, n),
        req_commit=i(n, n),
        req_n_ent=i(n, n),
        ent_start=i(n),
        ent_term=i(n, e),
        ent_val=i(n, e),
        resp_type=i(n, n),
        resp_term=i(n, n),
        resp_ok=jnp.zeros((n, n), bool),
        resp_match=i(n, n),
    )


def init_state(cfg: RaftConfig, key: jax.Array) -> ClusterState:
    """Fresh cluster: all followers at term 1 with empty logs (init-node core.clj:31-38,
    Log.start log.clj:32-34) and randomized initial election deadlines (the reference
    randomizes per wait-loop iteration, core.clj:174)."""
    n, cap = cfg.n_nodes, cfg.log_capacity
    deadline = draw_timeouts(cfg, key, n)
    return ClusterState(
        role=jnp.full((n,), FOLLOWER, jnp.int32),
        term=jnp.ones((n,), jnp.int32),
        voted_for=jnp.full((n,), NIL, jnp.int32),
        leader_id=jnp.full((n,), NIL, jnp.int32),
        votes=jnp.zeros((n, n), bool),
        next_index=jnp.ones((n, n), jnp.int32),
        match_index=jnp.zeros((n, n), jnp.int32),
        last_ack=jnp.zeros((n, n), jnp.int32),
        commit_index=jnp.zeros((n,), jnp.int32),
        log_term=jnp.zeros((n, cap), jnp.int32),
        log_val=jnp.zeros((n, cap), jnp.int32),
        log_len=jnp.zeros((n,), jnp.int32),
        clock=jnp.zeros((n,), jnp.int32),
        deadline=deadline,
        now=jnp.int32(0),
        mailbox=empty_mailbox(cfg),
    )


def init_batch(cfg: RaftConfig, key: jax.Array, batch: int) -> ClusterState:
    """[batch, ...] struct-of-arrays over independent clusters, each with its own seed."""
    return jax.vmap(lambda k: init_state(cfg, k))(jax.random.split(key, batch))
