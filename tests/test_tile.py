"""Compacted carry layout (ops/tile.py, cfg.compact_planes): word-boundary
exactness, dense-vs-compacted bit-equality, entry-channel backpressure, and
the layout-keyed anchor guard (ISSUE 14).

The layout is physical only -- both kernels unpack at tick entry and repack
at exit -- so the load-bearing claims are (1) pack/unpack is the identity on
every in-range value at every word-boundary N, (2) whole trajectories are
bit-identical between the layouts (states, metrics, StepInfo), including
across the compacted entry channel under truncation-heavy fault churn, and
(3) a bench row measured under one layout can never rebase the other
layout's roofline anchor."""

from __future__ import annotations

import dataclasses

import jax
import numpy as np
import pytest

from raft_sim_tpu import RaftConfig, init_state
from raft_sim_tpu.models import raft
from raft_sim_tpu.ops import tile
from raft_sim_tpu.sim import faults, scan
from raft_sim_tpu.types import compact_twin
from tests import oracle

# Word-boundary cluster sizes: around the 32-bit word edge (31/32/33), the
# small reference size, and the config5 width (51; 64 rides the slow tier
# with the full-width sim equality).
WORD_NS = (5, 31, 32, 33, 51, 64)


def _leg_cases(cfg):
    """(label, max_value, bits, bias) for every packed leg of `cfg`."""
    cases = [
        ("ack_age", cfg.ack_age_sat, tile.age_bits(cfg), 0),
        ("req_off", cfg.max_entries_per_rpc + 1, tile.off_bits(cfg), 1),
        ("resp_kind", 3, tile.RESP_BITS, 0),
    ]
    if not cfg.compaction:
        cases += [
            ("next_index", cfg.log_capacity + 1, tile.index_bits(cfg), 0),
            ("match_index", cfg.log_capacity, tile.index_bits(cfg), 0),
        ]
    return cases


@pytest.mark.parametrize("n", WORD_NS)
def test_pack_roundtrip_word_boundaries(n):
    """pack_words/unpack_words is the identity on every in-range value for
    every packed leg, at edge counts that straddle word boundaries -- and
    the oracle's independently restated unpacking agrees bit-for-bit."""
    cfg = RaftConfig(n_nodes=min(n, 126), log_capacity=16)
    rng = np.random.default_rng(n)
    for label, vmax, bits, bias in _leg_cases(cfg):
        vals = rng.integers(-bias, vmax + 1, size=(n * n,), dtype=np.int64)
        # Extremes present regardless of the draw: the word-straddle bug
        # class lives at the ends of the range.
        vals[0], vals[-1] = -bias, vmax
        packed = np.asarray(tile.pack_words(
            (vals + bias).astype(np.int32), bits
        ))
        assert packed.shape == (tile.words_for(n * n, bits),), label
        back = np.asarray(
            tile.unpack_words(packed, bits, n * n, np.int32)
        ).astype(np.int64) - bias
        np.testing.assert_array_equal(back, vals, err_msg=f"{label} n={n}")
        # The oracle's restatement (tests/oracle.py unpack_values) must
        # decode the SAME words: the parity suite's comparison domain
        # depends on the two layouts never drifting.
        orc = oracle.unpack_values(packed, bits, n * n) - bias
        np.testing.assert_array_equal(orc, vals, err_msg=f"oracle {label} n={n}")


def test_oracle_bit_width_restatement_pinned():
    """The oracle's independently restated bit widths equal ops/tile.py's
    for every structurally distinct tier (the tests/test_constants.py
    convention: restate, then pin)."""
    for cfg in (
        RaftConfig(),  # cap 32
        RaftConfig(log_capacity=16),
        RaftConfig(log_capacity=2048, client_interval=8),  # int16 index tier
        RaftConfig(ack_timeout_ticks=500),  # wide ack tier
    ):
        assert oracle._bits_for(cfg.log_capacity + 2) == tile.index_bits(cfg)
        assert oracle._bits_for(cfg.ack_age_sat + 1) == tile.age_bits(cfg)
        assert oracle._bits_for(cfg.max_entries_per_rpc + 2) == tile.off_bits(cfg)


def _assert_states_equal(dense_state, compact_state, cfg_c, msg=""):
    du = tile.unpack_state(cfg_c, compact_state)
    for f in dense_state._fields:
        if f == "mailbox":
            for mf in dense_state.mailbox._fields:
                np.testing.assert_array_equal(
                    np.asarray(getattr(dense_state.mailbox, mf)),
                    np.asarray(getattr(du.mailbox, mf)),
                    err_msg=f"{msg} mb.{mf}",
                )
        else:
            np.testing.assert_array_equal(
                np.asarray(getattr(dense_state, f)),
                np.asarray(getattr(du, f)),
                err_msg=f"{msg} {f}",
            )


def _fault_cfg(n, **kw):
    base = dict(
        n_nodes=n,
        log_capacity=8,
        max_entries_per_rpc=2,
        client_interval=2,
        drop_prob=0.25,
        crash_prob=0.4,
        crash_period=16,
        crash_down_ticks=8,
    )
    base.update(kw)
    return RaftConfig(**base)


def _run_both(cfg_d, ticks, seed=0):
    """(dense_state, compact_state, infos_equal) after `ticks` jitted
    raft.step ticks from the same seed -- step-level jits keep the compile
    cost far under a scan-shaped program's."""
    cfg_c = compact_twin(cfg_d)
    key = jax.random.key(seed)
    k_init, k_run = jax.random.split(key)
    sd = init_state(cfg_d, k_init)
    sc = init_state(cfg_c, k_init)
    # One jitted step per layout; inputs jitted separately (same draws both
    # layouts except the mask's flat shipping shape). Info equality is
    # asserted at the final tick only -- the per-tick info stream folds into
    # the metrics the batch-minor lockstep test compares in full.
    step_d = jax.jit(lambda s, i: raft.step(cfg_d, s, i))
    step_c = jax.jit(lambda s, i: raft.step(cfg_c, s, i))
    inp_fn_d = jax.jit(lambda now: faults.make_inputs(cfg_d, k_run, now))
    inp_fn_c = jax.jit(lambda now: faults.make_inputs(cfg_c, k_run, now))
    info_d = info_c = None
    for t in range(ticks):
        sd, info_d = step_d(sd, inp_fn_d(sd.now))
        sc, info_c = step_c(sc, inp_fn_c(sc.now))
    for f in info_d._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(info_d, f)), np.asarray(getattr(info_c, f)),
            err_msg=f"final tick: info.{f}",
        )
    _assert_states_equal(sd, sc, cfg_c, msg=f"after {ticks} ticks")
    return sd, sc


@pytest.mark.slow  # budget re-tier (ISSUE 14): tier-1 already pins the
# compacted layout's sim equality THREE ways for less wall -- the
# n5-compact-crashes ORACLE parity row (per-tick, kernel vs the
# independently restated layout, crashes + truncations), the roundtrip
# property at every word-boundary width, and the backpressure sim below;
# the CI layout-smoke job re-proves the batched full-config5 width
# (scan.simulate dense vs compacted, fault-fuzzed) on every PR.
def test_dense_vs_compact_bitexact():
    """Dense and compacted trajectories are bit-identical (states + final
    StepInfo) under fault churn at the reference width."""
    _run_both(_fault_cfg(5), ticks=80, seed=5)


@pytest.mark.slow  # redundant-with-siblings word widths (the tier-1
# roundtrip property pins the word arithmetic at every boundary width and
# the n=5 row + CI layout smoke pin the sim wiring) -- each param is a
# step-compile pair the 870s tier-1 budget cannot absorb.
@pytest.mark.parametrize("n", [31, 32, 33, 51, 64])
def test_dense_vs_compact_bitexact_wide(n):
    kw = dict()
    if n >= 51:
        kw = dict(log_capacity=16, partition_period=10, partition_prob=0.5,
                  crash_prob=0.0)
    _run_both(_fault_cfg(n, **kw), ticks=30, seed=n)


@pytest.mark.slow  # one extra step-jit pair: the reconfig-plane interaction
# (ent_cfg riding the FLATTENED entry window with its gate LIVE, log-carried
# config toggles + transfers + reads under fault churn) -- the gated-leg
# pack path the tier-1 rows exercise only for ent_tick.
def test_dense_vs_compact_bitexact_reconfig_plane():
    _run_both(
        _fault_cfg(
            5, log_capacity=16, max_entries_per_rpc=4,
            reconfig_interval=11, transfer_interval=13, read_interval=5,
        ),
        ticks=120, seed=99,
    )


@pytest.mark.slow  # two scan-shaped compiles; the same batched-lockstep
# claim is re-proven EVERY PR by the CI layout-smoke job at the full
# config5 width (scan.simulate dense vs compacted, 16x128 fault-fuzzed),
# and the per-tick oracle tier rides tier-1's n5-compact-crashes parity row.
def test_dense_vs_compact_batch_minor_lockstep():
    """The batched kernel's compacted boundary (step_b through
    scan.simulate): dense and compacted batch-minor runs are bit-identical
    in final states AND metrics -- the batched-lockstep tier of the layout
    contract (the per-tick oracle tier rides test_oracle_parity's
    n5-compact-crashes row)."""
    cfg_d = _fault_cfg(5)
    cfg_c = compact_twin(cfg_d)
    fd, md = scan.simulate(cfg_d, 3, 8, 96)
    fc, mc = scan.simulate(cfg_c, 3, 8, 96)
    du = jax.vmap(lambda s: tile.unpack_state(cfg_c, s))(fc)
    for f in fd._fields:
        if f == "mailbox":
            for mf in fd.mailbox._fields:
                np.testing.assert_array_equal(
                    np.asarray(getattr(fd.mailbox, mf)),
                    np.asarray(getattr(du.mailbox, mf)), err_msg=f"mb.{mf}",
                )
        else:
            np.testing.assert_array_equal(
                np.asarray(getattr(fd, f)), np.asarray(getattr(du, f)),
                err_msg=f,
            )
    for f in md._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(md, f)), np.asarray(getattr(mc, f)),
            err_msg=f"metric.{f}",
        )


def test_entry_channel_overflow_is_backpressure_not_loss():
    """E smaller than the outstanding entry backlog saturates the compacted
    entry channel (offsets clamp into the shared window) but loses nothing:
    on a reliable net every accepted command still commits, in order, and
    the dense layout agrees bit-for-bit."""
    cfg_d = RaftConfig(
        n_nodes=3, log_capacity=32, max_entries_per_rpc=2, client_interval=1,
    )
    sd, sc = _run_both(cfg_d, ticks=90)
    cfg_c = compact_twin(cfg_d)
    du = tile.unpack_state(cfg_c, sc)
    # The 1-command-per-tick firehose outruns E=2 replication per RPC; the
    # window start walks forward anyway. All nodes converge on a deep
    # committed prefix: nothing was dropped by channel overflow.
    commit = np.asarray(du.commit_index)
    assert commit.min() >= 20, commit
    lens = np.asarray(du.log_len)
    vals = np.asarray(du.log_val)
    # Committed prefixes agree across nodes (no lost/reordered entries).
    depth = int(commit.min())
    for node in range(1, 3):
        np.testing.assert_array_equal(vals[0, :depth], vals[node, :depth])


def test_init_and_checkpoint_round_trip_compact(tmp_path):
    """init_state builds the packed layout directly; checkpoint save/load
    round-trips the packed leaves bit-for-bit (shapes ride the arrays --
    no schema change, no version bump: the canonical fingerprint config is
    dense)."""
    from raft_sim_tpu.sim.scan import init_metrics_batch
    from raft_sim_tpu.utils import checkpoint

    cfg = compact_twin(_fault_cfg(5))
    key = jax.random.key(1)
    state = jax.vmap(lambda k: init_state(cfg, k))(jax.random.split(key, 2))
    assert state.next_index.ndim == 2  # [B, W]: packed flat per cluster
    path = str(tmp_path / "compact.npz")
    checkpoint.save(path, cfg, state, jax.random.split(key, 2),
                    init_metrics_batch(2), seed=1)
    cfg2, state2, _keys, _metrics, _seed, _scn = checkpoint.load(path)
    assert cfg2 == cfg
    for f in state._fields:
        if f == "mailbox":
            continue
        np.testing.assert_array_equal(
            np.asarray(getattr(state, f)), np.asarray(getattr(state2, f)),
            err_msg=f,
        )


# ------------------------------------------------- layout-keyed anchor guard


def test_bench_anchor_rejects_layout_mismatched_rows(tmp_path):
    """A bench row measured under the compacted layout, keyed by a DENSE
    preset's name, must never rebase that preset's roofline anchor (the
    PR 5/PR 8 smoke-row trap class, closed for layouts) -- and vice versa a
    dense row cannot anchor config5c. Rows without a layout field (pre-r14
    artifacts) are dense by definition and still anchor dense presets."""
    import json

    from raft_sim_tpu.analysis import cost_model

    doc = {
        "matrix": {
            # compacted row mislabeled under the dense preset: refused.
            "config5": {"cluster_ticks_per_s": 9e6, "batch": 10_000,
                        "layout": "compact"},
            # dense row under the compacted preset: refused.
            "config5c": {"cluster_ticks_per_s": 8e6, "batch": 10_000,
                         "layout": "dense"},
            # correctly-keyed rows: accepted.
            "config3": {"cluster_ticks_per_s": 40e6, "batch": 100_000},
            "config4": {"cluster_ticks_per_s": 23e6, "batch": 100_000,
                        "layout": "dense"},
        }
    }
    (tmp_path / "BENCH_r99.json").write_text(json.dumps(doc))
    anchors, source, notes = cost_model.bench_anchor(str(tmp_path))
    assert "config5" not in anchors and "config5c" not in anchors
    assert anchors == {"config3": 40e6, "config4": 23e6}
    assert any("config5 row" in n and "layout" in n for n in notes)
    assert any("config5c row" in n and "layout" in n for n in notes)


def test_reconcile_marks_layout_mismatch_non_anchor():
    from raft_sim_tpu.obs import reconcile

    row = {"steady_ticks_per_s": 9e6, "batch": 10_000, "layout": "compact"}
    reasons = reconcile.non_anchor_reasons("config5", row, "tpu")
    assert any("layout" in r for r in reasons)
    # The correctly-keyed compacted row has no layout objection.
    ok = reconcile.non_anchor_reasons("config5c", row, "tpu")
    assert not any("layout" in r for r in ok)
    # Pre-r14 rows (no layout field) are dense: fine for dense presets.
    legacy = {"steady_ticks_per_s": 9e6, "batch": 10_000}
    assert not any(
        "layout" in r for r in reconcile.non_anchor_reasons("config5", legacy, "tpu")
    )


def test_dense_base_twin_resolution():
    from raft_sim_tpu.analysis import cost_model
    from raft_sim_tpu.utils.config import PRESETS

    assert cost_model.dense_base("config5c") == "config5"
    assert cost_model.dense_base("config5") is None
    assert cost_model.layout_of(PRESETS["config5c"][0]) == "compact"
    assert cost_model.layout_of(PRESETS["config5"][0]) == "dense"


def test_compacted_pin_meets_the_roofline_bar():
    """ISSUE-14 acceptance, as a test: the gated pin for config5c/simulate
    prices the compacted config5 tick at <= ~48 KB padded, which the
    r05-implied HBM rate prices at >= 3M cluster-ticks/s (the ROADMAP
    item-1 bar packing alone provably cannot reach -- docs/PERF.md)."""
    import json
    import os

    from raft_sim_tpu.analysis import cost_model

    with open(cost_model.golden_path()) as f:
        golden = json.load(f)
    pin = golden["programs"]["config5c/simulate"]
    dense = golden["programs"]["config5/simulate"]
    assert pin["bytes_per_tick_padded"] <= 48_000, pin
    # At the pinned implied rate (borrowed from the dense base's anchor --
    # `layout_base` records the borrow) the predicted roofline clears 3M.
    assert pin.get("layout_base") == "config5"
    assert pin["roofline_ticks_per_s"] >= 3_000_000, pin
    # And the compacted carry genuinely undercuts the dense pin (not a
    # padding artifact): logical bytes shrink too.
    assert pin["bytes_per_tick_logical"] < dense["bytes_per_tick_logical"]
