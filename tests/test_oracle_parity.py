"""Kernel-vs-oracle parity: the vectorized `where`-lattice in models/raft.py must agree
bit-for-bit, tick by tick, with the scalar Python oracle (tests/oracle.py) across
randomized trajectories including faults -- the mitigation SURVEY.md section 7.3 calls
for against branch-precedence bugs."""

import jax
import numpy as np
import pytest

from raft_sim_tpu import RaftConfig, init_state
from raft_sim_tpu.models import raft
from raft_sim_tpu.sim import faults
from tests import oracle


def assert_state_equal(got: dict, want: dict, tick: int):
    for f, g in got.items():
        if f == "mailbox":
            for mf, mg in g.items():
                np.testing.assert_array_equal(
                    mg, want["mailbox"][mf], err_msg=f"tick {tick}: mailbox.{mf}"
                )
        else:
            np.testing.assert_array_equal(g, want[f], err_msg=f"tick {tick}: {f}")


CONFIGS = [
    pytest.param(RaftConfig(n_nodes=3, log_capacity=8, client_interval=3), 0, id="n3"),
    pytest.param(
        RaftConfig(n_nodes=5, log_capacity=8, max_entries_per_rpc=2, client_interval=2),
        1,
        id="n5-narrow-rpc",
    ),
    pytest.param(
        RaftConfig(
            n_nodes=5,
            log_capacity=6,  # tiny: exercises capacity clipping
            client_interval=1,
            drop_prob=0.25,
            clock_skew_prob=0.2,
        ),
        2,
        id="n5-faults",
    ),
    pytest.param(
        RaftConfig(
            n_nodes=4,  # even cluster size: quorum = 3
            log_capacity=8,
            client_interval=4,
            drop_prob=0.15,
            partition_period=10,
            partition_prob=0.7,
        ),
        3,
        id="n4-partitions",
    ),
    pytest.param(
        RaftConfig(
            n_nodes=5,
            log_capacity=8,
            client_interval=3,
            drop_prob=0.1,
            crash_prob=0.5,
            crash_period=20,
            crash_down_ticks=10,
        ),
        4,
        id="n5-crashes",
    ),
    pytest.param(
        RaftConfig(
            n_nodes=5,
            log_capacity=8,
            client_interval=2,
            drop_prob=0.5,  # heavy loss: peers regularly fall out of the ack window
            ack_timeout_ticks=7,  # the tightest legal horizon (heartbeat 3 + 4)
            crash_prob=0.4,
            crash_period=16,
            crash_down_ticks=12,
        ),
        5,
        id="n5-ack-window",  # exercises responsiveness exclusion + re-admission in
        # the shared-window start (the no-responsive fallback needs a deterministic
        # scenario: test_handlers.test_window_fallback_when_no_peer_responsive)
    ),
    pytest.param(
        RaftConfig(
            n_nodes=5,
            log_capacity=8,
            max_entries_per_rpc=2,  # narrow window: offsets/backpressure live
            client_interval=1,
            drop_prob=0.3,
            crash_prob=0.5,
            crash_period=20,
            crash_down_ticks=10,
            compact_planes=True,
        ),
        17,
        id="n5-compact-crashes",  # the compacted carry layout (ops/tile.py)
        # vs the oracle's independently restated unpacking, with crashes +
        # heavy drop so conflict TRUNCATIONS and snapshot-free catch-up cross
        # the compacted entry channel (bit-packed req_off offsets, flattened
        # ent windows) every few ticks
    ),
    pytest.param(
        RaftConfig(n_nodes=3, log_capacity=8, compact_margin=4, client_interval=1),
        6,
        id="n3-compaction",  # 150 commands through an 8-slot ring: continuous
        # rebase + wrapped appends, absolute indices far past CAP
    ),
    pytest.param(
        RaftConfig(
            n_nodes=5,
            log_capacity=8,
            compact_margin=4,
            max_entries_per_rpc=2,
            client_interval=1,
            drop_prob=0.2,
            crash_prob=0.5,
            crash_period=20,
            crash_down_ticks=12,
        ),
        7,
        id="n5-compaction-snap",  # crashed nodes fall below the leader's base and
        # catch up via the InstallSnapshot sentinel (keep AND wipe paths)
    ),
    pytest.param(
        RaftConfig(
            n_nodes=5,
            log_capacity=8,
            client_interval=2,
            client_redirect=True,
            drop_prob=0.2,
            crash_prob=0.4,
            crash_period=16,
            crash_down_ticks=8,
        ),
        8,
        id="n5-redirect",  # the 302 write path: random targets, redirect bounces,
        # leaderless random-peer fallback, busy-client drops -- under faults
    ),
    pytest.param(
        RaftConfig(
            n_nodes=5,
            log_capacity=8,
            compact_margin=4,
            client_interval=2,
            client_redirect=True,
            drop_prob=0.2,
            crash_prob=0.4,
            crash_period=16,
            crash_down_ticks=8,
        ),
        9,
        id="n5-redirect-compaction",  # routing state and election no-ops riding
        # the compaction ring (the full round-4 feature interaction)
    ),
    pytest.param(
        RaftConfig(
            n_nodes=5,
            log_capacity=8,
            compact_margin=4,
            client_interval=1,
            client_redirect=True,
            client_pipeline=4,
            drop_prob=0.2,
            crash_prob=0.4,
            crash_period=16,
            crash_down_ticks=8,
        ),
        10,
        id="n5-redirect-pipeline",  # K = 4 commands in flight: slot fill/free
        # churn, per-node lowest-slot acceptance, parallel accepts at
        # split-brain leaders, per-slot bounce draws
    ),
    pytest.param(
        RaftConfig(
            n_nodes=5,
            log_capacity=8,
            client_interval=3,
            pre_vote=True,
            drop_prob=0.25,
            crash_prob=0.4,
            crash_period=16,
            crash_down_ticks=8,
        ),
        11,
        id="n5-prevote",  # thesis-9.6 probes under churn: precandidate rounds,
        # per-edge grant bits, promotions, prospective-term non-adoption
    ),
    pytest.param(
        RaftConfig(
            n_nodes=5,
            log_capacity=8,
            compact_margin=4,
            client_interval=2,
            pre_vote=True,
            drop_prob=0.25,
            crash_prob=0.4,
            crash_period=16,
            crash_down_ticks=8,
        ),
        12,
        id="n5-prevote-compaction",  # the pre_vote x compaction interaction
        # (VERDICT weak #3): precandidate probes judged against ring logs whose
        # last-entry position wraps, election no-ops burning ring reserve while
        # probes defer the term bump, snapshot catch-up of crashed probers
    ),
    pytest.param(
        RaftConfig(
            n_nodes=5,
            log_capacity=8,
            client_interval=2,
            reconfig_interval=11,
            transfer_interval=13,
            read_interval=3,
            drop_prob=0.2,
            crash_prob=0.4,
            crash_period=16,
            crash_down_ticks=8,
        ),
        13,
        id="n5-reconfig-plane",  # all three thesis extensions at once, under
        # drop + crash churn: joint entry/exit + dual quorums + removed-leader
        # stepdown, transfer lease/fire/receipt elections, read capture/
        # confirm/serve -- the full raft_sim_tpu/reconfig surface vs the oracle
    ),
    pytest.param(
        RaftConfig(
            n_nodes=5,
            log_capacity=8,
            compact_margin=4,
            client_interval=1,
            reconfig_interval=11,
            transfer_interval=13,
            read_interval=3,
            pre_vote=True,
            drop_prob=0.2,
            crash_prob=0.4,
            crash_period=16,
            crash_down_ticks=8,
        ),
        14,
        id="n5-reconfig-prevote-compaction",  # the reconfiguration plane
        # crossed with BOTH other structural gates: TimeoutNow's pre-vote
        # bypass, masked pre-quorums, ring-log current-term read captures
        marks=pytest.mark.slow,  # budget re-tier (ISSUE 13): the triple
        # interaction is the largest program in this file, and its pairwise
        # surfaces stay tier-1 (n5-reconfig-plane, n5-prevote-compaction,
        # n5-reconfig-truncation) -- the full cross rides the slow tier to
        # pay for the two new log-carried corpus replays.
    ),
    pytest.param(
        RaftConfig(
            n_nodes=5,
            log_capacity=8,
            client_interval=2,
            reconfig_interval=5,
            transfer_interval=2,
            drop_prob=0.25,
            crash_prob=0.4,
            crash_period=16,
            crash_down_ticks=8,
        ),
        15,
        # Slow tier (tier-1 budget): the deterministic transfer-during-joint
        # interaction is pinned step by step in tier-1
        # (tests/test_reconfig.py::test_transfer_fires_and_elects_during_
        # joint_phase), and the n5-reconfig-plane row keeps the
        # transfer x membership machinery oracle-swept every tier-1 run;
        # this row adds the denser randomized interleaving sweep.
        marks=pytest.mark.slow,
        id="n5-transfer-during-joint",  # PR 10's named follow-up: a dense
        # transfer cadence (every 2 ticks) against a 5-tick membership
        # cadence under churn keeps TimeoutNow transfers pending, firing,
        # and received WHILE joint phases are open -- dual-quorum elections
        # of transfer targets, transfer aborts at removed-leader stepdown,
        # lease handoffs across epoch bumps (the deterministic interaction
        # is pinned in tests/test_reconfig.py; this row sweeps it vs the
        # oracle under randomized fault interleavings)
    ),
    pytest.param(
        RaftConfig(
            n_nodes=5,
            log_capacity=8,
            client_interval=1,
            reconfig_interval=3,
            drop_prob=0.25,
            partition_period=8,
            partition_prob=0.8,
            crash_prob=0.5,
            crash_period=14,
            crash_down_ticks=8,
        ),
        11,
        id="n5-reconfig-truncation",  # the log-carried config rollback
        # surface: a dense membership cadence under partition + crash churn
        # keeps minority leaders appending config entries that the healed
        # majority then truncates -- per-node derived configs must diverge
        # (86 of 150 ticks at this seed), roll back with the truncation
        # (cfg_epoch decreases mid-run), and re-derive bit-for-bit against
        # the oracle every tick (ISSUE 13 acceptance row)
    ),
    pytest.param(
        RaftConfig(
            n_nodes=5,
            log_capacity=8,
            compact_margin=4,
            election_min_ticks=12,
            election_range_ticks=6,
            client_interval=2,
            read_interval=3,
            read_lease_ticks=4,
            drop_prob=0.2,
            clock_skew_prob=0.3,
            crash_prob=0.4,
            crash_period=16,
            crash_down_ticks=8,
        ),
        16,
        id="n5-lease-reads",  # the ISSUE-11 lease plane vs the oracle under
        # skew + drop + crash churn: the lease serve predicate over ack_age
        # quorums, the thesis-4.2.3 vote denial on skewed local clocks, and
        # the read_fr staleness anchor riding capture/serve/cancel/restart
    ),
    pytest.param(
        RaftConfig(
            n_nodes=5,
            log_capacity=8,
            client_interval=2,
            fsync_interval=3,
            fsync_jitter_prob=0.25,
            torn_tail_prob=0.3,
            lost_suffix_span=3,
            drop_prob=0.2,
            crash_prob=0.5,
            crash_period=16,
            crash_down_ticks=8,
        ),
        18,
        id="n5-durable-crashes",  # the storage plane vs the oracle under
        # crash churn: fsync watermark advance (with jitter stalls), the ack
        # clamp + durable leader self-match, the vote-exposure gate with its
        # late-grant completion responses, and crash recovery truncating the
        # torn un-fsynced suffix back to the durable floor
    ),
    pytest.param(
        RaftConfig(
            n_nodes=5,
            log_capacity=8,
            max_entries_per_rpc=2,
            client_interval=1,
            fsync_interval=4,
            fsync_jitter_prob=0.3,
            torn_tail_prob=0.4,
            lost_suffix_span=4,
            pre_vote=True,
            drop_prob=0.25,
            crash_prob=0.5,
            crash_period=14,
            crash_down_ticks=8,
            compact_planes=True,
        ),
        19,
        id="n5-durable-prevote-compact",  # durability x pre_vote x the
        # compacted carry layout: late-grant responses racing prevote
        # promotions and AE responses on the same edges, recovery truncation
        # of logs carried bit-packed, narrow-RPC catch-up after torn tails
    ),
]


def run_parity(cfg, state, k_run, ticks):
    step = jax.jit(lambda s, i: raft.step(cfg, s, i)[0])
    s_oracle = oracle.state_to_dict(state, cfg)
    for t in range(ticks):
        inp = faults.make_inputs(cfg, k_run, state.now)
        inp_np = {f: np.asarray(v) for f, v in zip(inp._fields, inp)}
        state = step(state, inp)
        s_oracle = oracle.oracle_step(cfg, s_oracle, inp_np)
        assert_state_equal(oracle.state_to_dict(state, cfg), s_oracle, t)
    return state


@pytest.mark.parametrize("cfg,seed", CONFIGS)
def test_trajectory_parity(cfg, seed):
    key = jax.random.key(seed)
    k_init, k_run = jax.random.split(key)
    run_parity(cfg, init_state(cfg, k_init), k_run, ticks=150)


def test_parity_at_int16_index_boundary():
    """CAP-scale log indices riding the narrow planes: next/match and the
    per-responder match/hint wire fields (int16) near the MAX_LOG_CAPACITY = 4095
    ceiling. The small-CAP rows above never push an index past 8; here every node
    starts with ~3980 committed-prefix entries, so election bookkeeping, append
    acks, and capacity rejection all run with indices in the 3980..4095 range --
    checked against the oracle bit-for-bit, including commit_chk over the 3970-deep
    prefix."""
    import jax.numpy as jnp

    from raft_sim_tpu.types import with_commit_chk
    from raft_sim_tpu.utils.config import MAX_LOG_CAPACITY

    cfg = RaftConfig(
        n_nodes=5,
        log_capacity=MAX_LOG_CAPACITY,
        max_entries_per_rpc=8,
        client_interval=1,
    )
    key = jax.random.key(6)
    k_init, k_run = jax.random.split(key)
    state = init_state(cfg, k_init)

    # Identical 3980-entry term-1 logs on every node, 3970 of them committed.
    pre = 3980
    n = cfg.n_nodes
    lt = state.log_term.at[:, :pre].set(1)
    lv = state.log_val.at[:, :pre].set(
        jnp.broadcast_to(jnp.arange(pre, dtype=jnp.int32), (n, pre))
    )
    state = with_commit_chk(
        state._replace(
            log_term=lt,
            log_val=lv,
            log_len=jnp.full((n,), pre, jnp.int32),
            commit_index=jnp.full((n,), pre - 10, jnp.int32),
        )
    )

    final = run_parity(cfg, state, k_run, ticks=60)
    # The run must actually have driven indices past the prefill: a leader exists
    # and appended client commands toward the capacity ceiling.
    assert int(np.max(np.asarray(final.log_len))) > pre
    assert int(np.max(np.asarray(final.match_index))) > pre - 10
