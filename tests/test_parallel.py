"""Multi-chip tier on the 8-virtual-CPU-device mesh (conftest.py sets
xla_force_host_platform_device_count=8; SURVEY.md section 4, distributed tests)."""

import jax
import numpy as np
import pytest

from raft_sim_tpu import RaftConfig
from raft_sim_tpu.parallel import make_mesh, simulate_sharded, summarize
from raft_sim_tpu.sim import scan


def test_eight_virtual_devices():
    assert len(jax.devices()) == 8


def test_sharded_matches_single_device():
    """Same (seed, batch) must produce bit-identical trajectories at any device count
    (SURVEY.md section 4: vmap/pmap parity)."""
    cfg = RaftConfig(n_nodes=5, client_interval=8)
    batch, ticks = 64, 120

    f1, m1 = scan.simulate(cfg, 3, batch, ticks)
    mesh = make_mesh()
    f8, m8 = simulate_sharded(cfg, 3, batch, ticks, mesh)

    for a, b in zip(jax.tree.leaves(jax.device_get(m1)), jax.tree.leaves(jax.device_get(m8))):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(jax.tree.leaves(jax.device_get(f1)), jax.tree.leaves(jax.device_get(f8))):
        np.testing.assert_array_equal(a, b)


@pytest.mark.slow
def test_sharded_matches_single_device_compaction_redirect():
    """Device-count invariance holds for the full round-4 feature surface: ring
    compaction (wide index planes, snapshot wire header) + redirect routing."""
    cfg = RaftConfig(
        n_nodes=5,
        log_capacity=8,
        compact_margin=4,
        client_interval=2,
        client_redirect=True,
        drop_prob=0.2,
        crash_prob=0.4,
        crash_period=16,
        crash_down_ticks=8,
    )
    batch, ticks = 32, 150
    f1, m1 = scan.simulate(cfg, 5, batch, ticks)
    f8, m8 = simulate_sharded(cfg, 5, batch, ticks, make_mesh())
    for a, b in zip(jax.tree.leaves(jax.device_get(m1)), jax.tree.leaves(jax.device_get(m8))):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(jax.tree.leaves(jax.device_get(f1)), jax.tree.leaves(jax.device_get(f8))):
        np.testing.assert_array_equal(a, b)
    # compaction really ran (absolute indices far past the ring)
    assert int(np.max(np.asarray(jax.device_get(f8).log_base))) > cfg.log_capacity


def test_sharded_output_is_sharded():
    cfg = RaftConfig(n_nodes=3)
    mesh = make_mesh()
    final, metrics = simulate_sharded(cfg, 0, 16, 30, mesh)
    shard_devs = {s.device for s in final.role.addressable_shards}
    assert len(shard_devs) == 8


def test_summarize_under_faults():
    cfg = RaftConfig(n_nodes=5, drop_prob=0.2)
    mesh = make_mesh()
    _, metrics = simulate_sharded(cfg, 1, 64, 200, mesh)
    s = summarize(metrics)
    assert s.n_clusters == 64
    assert s.total_violations == 0
    # Most clusters should still stabilize under 20% drop.
    assert s.n_stable > 32


@pytest.mark.slow
def test_session_sharded_matches_unsharded():
    """Session(devices=8) must equal Session(devices=None) bit-for-bit: the driver's
    sharded chunked path (jit propagating the input sharding) preserves trajectories
    at any device count."""
    from raft_sim_tpu.driver import Session

    cfg = RaftConfig(n_nodes=5, client_interval=8, drop_prob=0.1)
    a = Session(cfg, batch=64, seed=7)
    b = Session(cfg, batch=64, seed=7, devices=8)
    a.run(150, chunk=64)
    b.run(150, chunk=64)
    for x, y in zip(jax.tree.leaves(jax.device_get(a.state)), jax.tree.leaves(jax.device_get(b.state))):
        np.testing.assert_array_equal(x, y)
    assert a.summary() == b.summary()
    # The sharded session's state is actually spread over all 8 devices.
    assert len({s.device for s in b.state.role.addressable_shards}) == 8
