"""The fuzzing farm: portfolio hunts from one compiled program.

`scenario search` is one CE loop with one fitness function; the farm is the
orchestration layer that makes the fleet's scale count (ROADMAP item 5):

  1. PORTFOLIO -- the batch axis is partitioned among fitness members
     (farm/portfolio.py) the way serve/tenancy.py partitions tenants: each
     member owns a contiguous cluster slice and its own CE distribution, and
     one generation = ONE `telemetry.simulate_windowed` call for the whole
     portfolio (genome rows are traced data; the compiled program never sees
     the partition, so the jit cache is pinned flat across member counts).
  2. COVERAGE-GUIDED MUTATION -- members propose through
     `search.propose_coverage_guided` against a FARM-WIDE seen-bit union:
     genomes that lit unseen (role x kind)/(kind -> kind) transition bits
     anywhere in the portfolio become mutation parents everywhere,
     deterministic per (genome, seed).
  3. AUTO-CORPUS -- hits are shrunk (scenario/shrink.py; bounded: the
     first violating cluster per member per generation, the rest counted
     in the hunt stream), deduped against the existing corpus by (kernel,
     violation-kinds, mechanism-set) signature, provenance-stamped,
     checker-gated, and frozen into tests/corpus/ by the farm itself
     (farm/corpus.py). A
     budget exhausted without a hit ends in a PINNED NEGATIVE RESULT with
     coverage numbers (negative.json) -- "we hunted this space to N
     generations and lit B bits" is an artifact, not a shrug.

Driver: `python -m raft_sim_tpu scenario farm` (docs/SCENARIOS.md "Running
the farm"). Out-dir streams: farm_manifest.json, members/<name>/hunt.jsonl
(one row per generation per member), negative.json (hitless budgets), and
perf.jsonl (PR 8 ChunkTimer rows, one per generation -- the sink's perf
schema, so `tools/metrics_report.py --perf` renders a farm like any loop).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os

import numpy as np

from raft_sim_tpu.farm import corpus as corpus_mod
from raft_sim_tpu.farm import portfolio as portfolio_mod
from raft_sim_tpu.scenario import genome as genome_mod
from raft_sim_tpu.scenario import search as search_mod
from raft_sim_tpu.scenario import shrink as shrink_mod
from raft_sim_tpu.serve.tenancy import split_even
from raft_sim_tpu.sim import telemetry
from raft_sim_tpu.utils.config import RaftConfig

FARM_MANIFEST_SCHEMA = "farm-manifest-v1"
FARM_NEGATIVE_SCHEMA = "farm-negative-v1"

# Required integer fields of a members/<name>/hunt.jsonl row
# (validate_farm_dir; floats carry the fitness statistics).
HUNT_INT_FIELDS = ("gen", "seed", "violating_clusters")
HUNT_FLOAT_FIELDS = ("best_fitness", "mean_fitness")


@dataclasses.dataclass(frozen=True)
class FarmSpec:
    """Farm hyperparameters. `population` is the TOTAL fleet batch, split
    contiguously among the portfolio members (tenancy's split_even policy);
    every member's sub-population shares the one compiled program."""

    portfolio: tuple[str, ...] = ("scalar", "coverage")
    budget_gens: int = 8
    population: int = 64
    ticks: int = 512
    window: int = 64
    elite_frac: float = 0.25
    seed: int = 0
    init_sigma: float = 0.35
    min_sigma: float = 0.05
    smoothing: float = 0.6
    carry_best: bool = True
    trace_depth: int = 32
    # Coverage-guided mutation (search.propose_coverage_guided) for every
    # member, against the farm-wide seen set. Forces the trace-variant
    # program even for scalar-only portfolios (the novelty signal needs the
    # bitmap); False + a trace-free portfolio runs untraced.
    guided: bool = True
    guided_frac: float = 0.5
    # When to stop early: "hit" = first processed hit (found + shrunk +
    # dedup'd), "frozen" = only a NEWLY FROZEN artifact stops the hunt
    # (dedup-rejected re-finds keep hunting), "budget" = never early.
    stop_on: str = "hit"
    knobs: tuple = None  # None -> search.default_knobs(cfg)

    def __post_init__(self):
        if self.stop_on not in ("hit", "frozen", "budget"):
            raise ValueError(
                f"stop_on {self.stop_on!r} (have: hit, frozen, budget)"
            )
        if self.ticks % self.window:
            raise ValueError(
                f"ticks {self.ticks} must divide by window {self.window}"
            )


@dataclasses.dataclass
class FarmResult:
    """One farm run's outcome: the manifest dict (what farm_manifest.json
    holds), the per-generation member rows, processed hits, frozen artifact
    paths, and the dedup ledger."""

    manifest: dict
    generations: list[dict]
    hits: list[dict]
    frozen: list[str]
    dedup_rejected: list[dict]

    @property
    def negative(self) -> bool:
        return not self.hits


def _nondefault_config(cfg: RaftConfig) -> dict:
    return {
        f.name: getattr(cfg, f.name)
        for f in dataclasses.fields(RaftConfig)
        if getattr(cfg, f.name) != f.default
    }


def manifest_hash(identity: dict) -> str:
    """Stable short hash of the farm's identity (config, mutant, portfolio,
    budget, seed): the provenance key tying a frozen artifact back to the
    exact hunt that produced it."""
    blob = json.dumps(identity, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


class FarmSink:
    """Writer half of the farm's out-dir schema (module docstring). Creating
    one truncates the streams, telemetry-sink style; it also speaks the
    ChunkTimer sink protocol (append_perf), so the PR 8 timer streams
    perf.jsonl rows here directly. append_hunt/append_perf are this scope's
    REGISTERED single writers (analysis Pass D, rule `race-sink-writer`):
    a second code path appending to these streams is a gated finding."""

    def __init__(self, directory: str, members: list[dict]):
        import shutil

        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        for stale in ("farm_manifest.json", "negative.json", "perf.jsonl"):
            p = os.path.join(directory, stale)
            if os.path.exists(p):
                os.remove(p)
        # A reused out-dir must not keep a previous run's member streams: an
        # orphan members/<old-name>/hunt.jsonl would read as this run's data.
        keep = {m["name"] for m in members}
        mdir = os.path.join(directory, "members")
        if os.path.isdir(mdir):
            for name in os.listdir(mdir):
                if name not in keep:
                    shutil.rmtree(os.path.join(mdir, name))
        self._hunt_paths = {}
        for m in members:
            d = os.path.join(mdir, m["name"])
            os.makedirs(d, exist_ok=True)
            path = os.path.join(d, "hunt.jsonl")
            open(path, "w").close()
            self._hunt_paths[m["name"]] = path

    def append_hunt(self, member: str, row: dict) -> None:
        with open(self._hunt_paths[member], "a") as f:
            f.write(json.dumps(row) + "\n")

    def append_perf(self, rows: list[dict]) -> int:
        with open(os.path.join(self.directory, "perf.jsonl"), "a") as f:
            for row in rows:
                f.write(json.dumps(row) + "\n")
        return len(rows)

    def write_manifest(self, manifest: dict) -> str:
        path = os.path.join(self.directory, "farm_manifest.json")
        with open(path, "w") as f:
            json.dump(manifest, f, indent=1, sort_keys=True)
            f.write("\n")
        return path

    def write_negative(self, doc: dict) -> str:
        path = os.path.join(self.directory, "negative.json")
        with open(path, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        return path


@dataclasses.dataclass
class _Member:
    """One portfolio member's host-side hunt state."""

    name: str
    fitness: str
    lo: int
    hi: int
    mu: np.ndarray
    sigma: np.ndarray
    rng: np.random.Generator
    best_x: np.ndarray | None = None
    best_fit: float = -np.inf
    prev_xs: np.ndarray | None = None
    prev_novelty: np.ndarray | None = None

    @property
    def b(self) -> int:
        return self.hi - self.lo


def _member_names(portfolio: tuple[str, ...]) -> list[str]:
    """Unique stream names for possibly-duplicated members (scalar, scalar2)."""
    seen: dict[str, int] = {}
    names = []
    for f in portfolio:
        seen[f] = seen.get(f, 0) + 1
        names.append(f if seen[f] == 1 else f"{f}{seen[f]}")
    return names


def run_farm(
    cfg: RaftConfig,
    spec: FarmSpec | None = None,
    mutant: str | None = None,
    out_dir: str | None = None,
    corpus_dir: str | None = None,
    freeze: bool = False,
    perf=None,
    mesh=None,
    health=None,
) -> FarmResult:
    """Run the portfolio hunt. `cfg` must already be the kernel under test
    (mutant_config-applied for mutant hunts; `mutant` labels artifacts and
    provenance, exactly like shrink). `corpus_dir` arms the auto-corpus
    policy: hits are shrunk and dedup'd against it, and `freeze=True` lets
    the farm write NEW artifacts into it (checker-gated). `perf` is an
    obs.ChunkTimer; with an `out_dir` and no timer, the farm makes its own
    and streams perf.jsonl there.

    Concurrency posture (analysis Pass D): the farm is the one standing loop
    WITHOUT a donating entry point -- members evaluate genomes through the
    non-donating `telemetry.simulate_windowed` / mesh variants and fetch
    metrics by `jax.device_get`, so there is no dispatch->sync carry window
    to race. The registry rows in `policy.donating_entry_points` pin that
    as `not-donated`; the key-stream discipline lint (`race-key-reuse`)
    covers this package's PRNG handling instead.

    Hit processing is BOUNDED, not exhaustive: each generation, each
    member's FIRST violating cluster is shrunk (one ablation ladder per
    member-generation); the remaining violating clusters are counted in the
    hunt rows and the manifest's violating_clusters_total -- a reported
    number, never a silence. Under stop_on="budget" a reliably-broken
    kernel therefore re-pays one shrink per member per generation only to
    be dedup-rejected again; the default stop_on="hit" avoids that, and a
    per-run signature memo is the named follow-up if long mutant soaks
    become a workflow.

    `health` (a health SLO spec: "default", a path, or a dict) folds the
    streaming evaluator into the per-generation record fetch the farm already
    does -- one scope ("farm") over the whole portfolio population, streams
    under `out_dir` beside the hunt files. Host-side only: hunts are
    bit-identical with it armed.

    `mesh` (a parallel.make_mesh 1-D cluster mesh) shards each generation's
    evaluation over the devices (parallel.simulate_windowed_sharded):
    population must divide by the device count. Trajectories -- and
    therefore hits, coverage, and the manifest hash -- are BIT-IDENTICAL to
    the unsharded farm at any device count (keys split outside the sharded
    region), so the mesh is deliberately NOT part of the hashed identity:
    provenance names the hunt, not the hardware it ran on."""
    spec = spec or FarmSpec()
    if mesh is not None and spec.population % mesh.devices.size:
        raise ValueError(
            f"population {spec.population} must divide over the mesh's "
            f"{mesh.devices.size} devices"
        )
    portfolio = portfolio_mod.parse_portfolio(spec.portfolio)
    knobs = spec.knobs or search_mod.default_knobs(cfg)
    dim = len(knobs)
    needs_trace = spec.guided or any(
        portfolio_mod.FITNESS[f][1] for f in portfolio
    )
    run_cfg = cfg
    trace_spec = None
    seen = None
    if needs_trace:
        from raft_sim_tpu.trace.ring import COV_WORDS, TraceSpec

        run_cfg = dataclasses.replace(cfg, track_trace=True)
        trace_spec = TraceSpec(depth=spec.trace_depth, coverage=True)
        seen = np.zeros(COV_WORDS, np.uint32)

    sizes = split_even(spec.population, len(portfolio))
    names = _member_names(portfolio)
    members: list[_Member] = []
    lo = 0
    for i, (fname, b) in enumerate(zip(portfolio, sizes)):
        members.append(_Member(
            name=names[i], fitness=fname, lo=lo, hi=lo + b,
            mu=np.full(dim, 0.5), sigma=np.full(dim, spec.init_sigma),
            rng=np.random.default_rng([spec.seed, i]),
        ))
        lo += b

    identity = {
        "config": _nondefault_config(cfg),
        "mutant": mutant,
        "portfolio": list(portfolio),
        "population": spec.population,
        "ticks": spec.ticks,
        "window": spec.window,
        "budget_gens": spec.budget_gens,
        "seed": spec.seed,
        "guided": spec.guided,
        # The CE knobs change the hunt's trajectory, so they are part of
        # the hashed identity -- two hunts differing only in elite_frac
        # must not share a provenance key.
        "spec": {
            "elite_frac": spec.elite_frac,
            "smoothing": spec.smoothing,
            "init_sigma": spec.init_sigma,
            "min_sigma": spec.min_sigma,
            "guided_frac": spec.guided_frac,
            "trace_depth": spec.trace_depth,
            "stop_on": spec.stop_on,
        },
    }
    mhash = manifest_hash(identity)
    member_docs = [
        {"name": m.name, "fitness": m.fitness, "lo": m.lo, "hi": m.hi}
        for m in members
    ]
    sink = FarmSink(out_dir, member_docs) if out_dir else None
    if sink is not None and perf is None:
        from raft_sim_tpu.obs import ChunkTimer

        perf = ChunkTimer(label="farm", batch=spec.population, sink=sink)
    if mesh is not None:
        from raft_sim_tpu.parallel import mesh as mesh_mod

        evaluate = lambda g, s: mesh_mod.simulate_windowed_sharded(
            run_cfg, s, spec.population, spec.ticks, spec.window, mesh,
            genome=g, trace=trace_spec,
        )
        probe = ("parallel.simulate_windowed_sharded",
                 mesh_mod.simulate_windowed_sharded)
    else:
        evaluate = lambda g, s: telemetry.simulate_windowed(
            run_cfg, s, spec.population, spec.ticks, spec.window,
            genome=g, trace=trace_spec,
        )
        probe = ("telemetry.simulate_windowed", telemetry.simulate_windowed)
    if perf is not None:
        perf.add_probe(*probe)
    monitor = None
    if health is not None:
        if out_dir is None:
            raise ValueError(
                "health monitoring needs an out_dir: the health/alert streams "
                "and evidence bundles live there"
            )
        from raft_sim_tpu.health import HealthMonitor, HealthWriter, load_spec

        refs = {"farm": mhash, "mutant": mutant, "seed": spec.seed}
        monitor = HealthMonitor(
            load_spec(health), batch=spec.population,
            writer=HealthWriter(out_dir), scope="farm", perf=perf,
            capture=lambda alert, clusters: {"refs": refs},
        )
    # perf.jsonl keying for reconciliation (obs/reconcile.py): farm rows are
    # self-describing about what measured them -- a mesh-sharded generation's
    # aggregate throughput must never read as a single-device number.
    run_devices = mesh.devices.size if mesh is not None else 1

    gens: list[dict] = []
    hits: list[dict] = []
    frozen: list[str] = []
    dedup_rejected: list[dict] = []
    cov_by_gen: list[int] = []
    n_elite_of = lambda b: max(2, int(round(spec.elite_frac * b)))
    stop = False

    for gen in range(spec.budget_gens):
        # --- propose: per-member CE draws, coverage-guided when armed.
        xs = np.zeros((spec.population, dim))
        for m in members:
            if spec.guided:
                mx = search_mod.propose_coverage_guided(
                    m.rng, m.mu, m.sigma, m.b, m.prev_xs, m.prev_novelty,
                    spec.seed, frac=spec.guided_frac,
                )
            else:
                mx = search_mod.propose_gaussian(m.rng, m.mu, m.sigma, m.b)
            if spec.carry_best and m.best_x is not None:
                mx[0] = m.best_x
            xs[m.lo:m.hi] = mx
        rows = [search_mod.decode_row(cfg, knobs, x) for x in xs]
        g = genome_mod.stack_rows(rows)
        genome_mod.validate(cfg, g)
        sim_seed = spec.seed + search_mod.SEED_STRIDE * gen

        # --- evaluate: the WHOLE portfolio in one device call.
        if perf is not None:
            perf.begin(spec.ticks)
        if trace_spec is None:
            _, metrics, records, _ = evaluate(g, sim_seed)
            tp = None
        else:
            _, metrics, records, _, _, tp = evaluate(g, sim_seed)
        import jax

        if perf is not None:
            perf.dispatched()
            perf.annotate(
                n_devices=run_devices, backend=jax.default_backend(),
            )
            perf.end(sync=lambda: np.asarray(metrics.ticks))
        metrics = jax.device_get(metrics)
        records = jax.device_get(records)
        if monitor is not None:
            monitor.observe_records(records)
        cov = np.asarray(tp.cov) if tp is not None else None

        # --- score + CE-update each member against the shared baseline.
        viol_all = np.asarray(metrics.violations)
        gen_rows = []
        for m in members:
            take = lambda x: jax.tree.map(lambda v: np.asarray(v)[m.lo:m.hi], x)
            m_rec, m_met = take(records), take(metrics)
            novelty = None
            if cov is not None:
                novelty = search_mod.coverage_novelty(cov[:, m.lo:m.hi], seen)
            fit = portfolio_mod.FITNESS[m.fitness][0](m_rec, m_met, novelty)
            order = np.argsort(-fit)
            elites = xs[m.lo:m.hi][order[:n_elite_of(m.b)]]
            a = spec.smoothing
            m.mu = a * elites.mean(axis=0) + (1 - a) * m.mu
            m.sigma = np.maximum(
                a * elites.std(axis=0) + (1 - a) * m.sigma, spec.min_sigma
            )
            if fit[order[0]] > m.best_fit:
                m.best_fit = float(fit[order[0]])
                m.best_x = xs[m.lo + order[0]].copy()
            m.prev_xs, m.prev_novelty = xs[m.lo:m.hi], novelty
            row = {
                "gen": gen,
                "seed": int(sim_seed),
                "member": m.name,
                "fitness": m.fitness,
                "best_fitness": float(fit[order[0]]),
                "mean_fitness": float(fit.mean()),
                "violating_clusters": int((viol_all[m.lo:m.hi] > 0).sum()),
                "novelty_bits": (
                    int(novelty.sum()) if novelty is not None else None
                ),
                "best_genome": genome_mod.decode(rows[m.lo + order[0]])[0],
            }
            gen_rows.append(row)
        # Union AFTER every member scored: scoring is member-order-free and
        # the seen set grows monotonically (tests/test_farm.py pins both).
        if cov is not None:
            seen = search_mod.seen_union(cov, seen)
            total_bits = int(search_mod._popcount_words(seen[:, None])[0])
            for row in gen_rows:
                row["cov_total_bits"] = total_bits
            cov_by_gen.append(total_bits)
        if sink is not None:
            for row in gen_rows:
                sink.append_hunt(row["member"], row)
        gens.extend(gen_rows)

        # --- bank hits: first violating cluster per member this generation.
        for m in members:
            violating = np.flatnonzero(viol_all[m.lo:m.hi] > 0)
            if not violating.size:
                continue
            c = m.lo + int(violating[0])
            fv = np.asarray(records.first_viol_tick)[c]
            hit = {
                "seed": int(sim_seed),
                "batch": int(spec.population),
                "cluster": c,
                "ticks": int(spec.ticks),
                "seg_len": 1,
                "first_viol_tick": int(fv[fv < telemetry.NEVER].min()),
                "genome_raw": genome_mod.to_raw(rows[c]),
                "segments": genome_mod.decode(rows[c]),
                "member": m.name,
                "fitness": m.fitness,
                "gen": gen,
            }
            hits.append(hit)
            if corpus_dir is not None:
                art = shrink_mod.shrink(cfg, hit, mutant=mutant)
                dup = corpus_mod.find_duplicate(art, corpus_dir)
                if dup is not None:
                    dedup_rejected.append(dict(dup, member=m.name, gen=gen))
                elif freeze:
                    path, _ = corpus_mod.freeze(
                        art, corpus_dir,
                        provenance={
                            "mutant": mutant,
                            "fitness": m.fitness,
                            "member": m.name,
                            "generation": gen,
                            "seed": int(sim_seed),
                            "farm": mhash,
                        },
                    )
                    frozen.append(path)
                    if spec.stop_on == "frozen":
                        stop = True
                else:
                    hit["unfrozen"] = True  # new signature, freezing off
            if spec.stop_on == "hit":
                stop = True
        if stop:
            break

    manifest = {
        "schema": FARM_MANIFEST_SCHEMA,
        **identity,
        "manifest_hash": mhash,
        "members": member_docs,
        "generations_run": (gens[-1]["gen"] + 1) if gens else 0,
        "evaluations": ((gens[-1]["gen"] + 1) if gens else 0) * spec.population,
        # Hit processing is BOUNDED (one shrink ladder per member per
        # generation: the first violating cluster); the full violating-
        # cluster count is reported here and per generation in the hunt
        # rows, so unprocessed hits are a number, never a silence.
        "violating_clusters_total": sum(
            g["violating_clusters"] for g in gens
        ),
        "cov_bits_total": cov_by_gen[-1] if cov_by_gen else None,
        "hits": [
            {k: h[k] for k in ("member", "fitness", "gen", "seed", "cluster",
                               "first_viol_tick")}
            for h in hits
        ],
        "frozen": [os.path.basename(p) for p in frozen],
        "dedup_rejected": dedup_rejected,
        "negative": not hits,
    }
    if monitor is not None:
        manifest["health"] = monitor.finalize()
    if sink is not None:
        sink.write_manifest(manifest)
        if not hits:
            sink.write_negative({
                "schema": FARM_NEGATIVE_SCHEMA,
                "manifest_hash": mhash,
                **identity,
                "generations": manifest["generations_run"],
                "evaluations": manifest["evaluations"],
                "cov_bits_total": manifest["cov_bits_total"],
                "cov_bits_by_gen": cov_by_gen,
                "knobs": [dataclasses.asdict(k) for k in knobs],
            })
    return FarmResult(
        manifest=manifest, generations=gens, hits=hits, frozen=frozen,
        dedup_rejected=dedup_rejected,
    )


def validate_farm_dir(directory: str) -> list[str]:
    """Schema-check a farm out-dir ([] = valid): manifest fields, per-member
    hunt.jsonl rows with contiguous generations, the negative artifact when
    the manifest claims one, and perf.jsonl rows against the telemetry
    sink's perf field tuples (one shared perf schema repo-wide)."""
    errors = []
    man_path = os.path.join(directory, "farm_manifest.json")
    if not os.path.isfile(man_path):
        return [f"missing farm_manifest.json in {directory}"]
    try:
        with open(man_path) as f:
            man = json.load(f)
    except (OSError, json.JSONDecodeError) as ex:
        return [f"farm_manifest.json unreadable: {ex}"]
    for k in ("schema", "config", "portfolio", "members", "manifest_hash",
              "population", "budget_gens", "seed", "generations_run",
              "hits", "frozen", "dedup_rejected", "negative"):
        if k not in man:
            errors.append(f"farm_manifest.json: missing field {k!r}")
    if man.get("schema") != FARM_MANIFEST_SCHEMA:
        errors.append(
            f"farm_manifest.json: schema {man.get('schema')!r}, expected "
            f"{FARM_MANIFEST_SCHEMA}"
        )
    for m in man.get("members", []):
        path = os.path.join(directory, "members", m.get("name", "?"), "hunt.jsonl")
        if not os.path.isfile(path):
            errors.append(f"missing members/{m.get('name')}/hunt.jsonl")
            continue
        prev_gen = -1
        with open(path) as f:
            for ln, raw in enumerate(f, 1):
                try:
                    row = json.loads(raw)
                except json.JSONDecodeError as ex:
                    errors.append(f"{m['name']}/hunt.jsonl:{ln}: not JSON: {ex}")
                    continue
                for k in HUNT_INT_FIELDS:
                    if not isinstance(row.get(k), int) or row.get(k) is True:
                        errors.append(
                            f"{m['name']}/hunt.jsonl:{ln}: field {k!r} "
                            "missing or non-int"
                        )
                for k in HUNT_FLOAT_FIELDS:
                    if not isinstance(row.get(k), (int, float)):
                        errors.append(
                            f"{m['name']}/hunt.jsonl:{ln}: field {k!r} "
                            "missing or non-numeric"
                        )
                if isinstance(row.get("gen"), int):
                    if row["gen"] != prev_gen + 1:
                        errors.append(
                            f"{m['name']}/hunt.jsonl:{ln}: gen {row['gen']} "
                            f"(expected {prev_gen + 1})"
                        )
                    prev_gen = row["gen"]
        # Contiguity alone passes a tail-truncated stream; the manifest
        # knows how many generations actually ran.
        if (
            isinstance(man.get("generations_run"), int)
            and prev_gen + 1 != man["generations_run"]
        ):
            errors.append(
                f"{m['name']}/hunt.jsonl: {prev_gen + 1} generation rows, "
                f"manifest claims {man['generations_run']} -- stream "
                "truncated"
            )
    if man.get("negative") and not os.path.isfile(
        os.path.join(directory, "negative.json")
    ):
        errors.append("manifest claims a negative result but negative.json missing")
    perf_path = os.path.join(directory, "perf.jsonl")
    if os.path.isfile(perf_path):
        from raft_sim_tpu.utils.telemetry_sink import (
            PERF_BOOL_FIELDS, PERF_FLOAT_FIELDS, PERF_INT_FIELDS,
        )

        with open(perf_path) as f:
            for ln, raw in enumerate(f, 1):
                try:
                    row = json.loads(raw)
                except json.JSONDecodeError as ex:
                    errors.append(f"perf.jsonl:{ln}: not JSON: {ex}")
                    continue
                for k in PERF_INT_FIELDS:
                    if not isinstance(row.get(k), int) or row.get(k) is True:
                        errors.append(f"perf.jsonl:{ln}: field {k!r} missing or non-int")
                for k in PERF_BOOL_FIELDS:
                    if not isinstance(row.get(k), bool):
                        errors.append(f"perf.jsonl:{ln}: field {k!r} missing or non-bool")
                for k in PERF_FLOAT_FIELDS:
                    v = row.get(k)
                    if not isinstance(v, (int, float)) or isinstance(v, bool) or v < 0:
                        errors.append(
                            f"perf.jsonl:{ln}: field {k!r} missing or not a "
                            "non-negative number"
                        )
    # Health streams ride farm out-dirs too (run_farm health=): same schema,
    # same checker, as a telemetry directory's.
    from raft_sim_tpu.utils.telemetry_sink import validate_health_files

    errors.extend(validate_health_files(directory))
    return errors
