"""Reconcile measured runtime against the gated Pass C predictions.

Pass C (analysis/cost_model.py) pins, per config tier x program, the
bytes/cluster-tick of the lowered run loop, the implied HBM rate, the
resulting roofline ticks/s, and the live-set peak -- all *predictions*, gated
in CI. Bench rows and perf.jsonl streams are *measurements*. This module is
the join:

    achieved bytes/s    = measured cluster-ticks/s x pinned bytes/tick
    roofline fraction   = measured / pinned roofline ticks/s
                          (~1.0 = tracking the pins; <1 = headroom the pins
                          say should exist; >1 = the pins are stale --
                          regenerate after the artifact lands)
    live occupancy      = observed device bytes at chunk boundaries vs the
                          pinned live-set peak (the pin is priced at the
                          AUDIT shape, not the production batch -- a trend
                          fence, not an absolute byte budget; see
                          docs/OBSERVABILITY.md)

The load-bearing guard is the **anchor flag**: a reconciled row is
anchor-eligible ONLY when it was measured on a non-CPU backend, at the
preset's production batch, not under --smoke, and not through the scenario
input path. Everything else is explicitly `anchor: false` with the reason
spelled out -- a CPU measurement pass can be *reconciled* (that is its whole
point on this image) but can never *rebase* the roofline, the same trap
class PR 5 closed for smoke rows on the cost-model side
(`cost_model.bench_anchor` enforces the mirror-image rejection when reading
BENCH artifacts).
"""

from __future__ import annotations

import json
import os

from raft_sim_tpu.utils.config import PRESETS


def load_pins(path: str | None = None) -> dict:
    """The golden cost-model document (tests/golden_cost_model.json), or {}
    when absent/unreadable (installed package, fresh clone) -- reconciliation
    then reports measurements only, with a note, instead of failing."""
    if path is None:
        from raft_sim_tpu.analysis import cost_model

        path = cost_model.golden_path()
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return {}


def _measured(row: dict) -> tuple[float | None, str]:
    """(cluster-ticks/s, source) from a bench row: the warmup-excluded steady
    value when the row carries one (bench >= r06), else the legacy
    best-of-repeats headline (BENCH_r01-r05 artifacts)."""
    v = row.get("steady_ticks_per_s")
    if v:
        return float(v), "steady"
    v = row.get("cluster_ticks_per_s")
    if v:
        return float(v), "legacy-best"
    return None, "missing"


def non_anchor_reasons(config_name: str, row: dict,
                       backend: str | None) -> list[str]:
    """Why this measured row must NOT rebase the roofline ([] = eligible).
    Mirrors (and extends with the backend rule) what
    `cost_model.bench_anchor` rejects when reading BENCH artifacts."""
    reasons = []
    if backend is None:
        reasons.append("backend unrecorded: treated non-anchor (pre-r06 row)")
    elif backend == "cpu":
        reasons.append("cpu backend: a CPU run can never rebase the roofline")
    if row.get("smoke"):
        reasons.append("--smoke row")
    if row.get("scenario"):
        reasons.append(f"scenario input path ({row['scenario']})")
    prod = PRESETS.get(config_name)
    if prod and row.get("batch") is not None and row["batch"] != prod[1]:
        reasons.append(
            f"batch {row['batch']} != production {prod[1]}"
        )
    if prod is not None:
        # Layout keying (the PR 5/PR 8 trap class, closed for layouts): a
        # row measured under one carry layout must never rebase the other
        # layout's roofline -- a compacted A/B row labeled with the dense
        # preset's name (or vice versa) reconciles but cannot anchor.
        # Rows without a layout field (pre-r14) are all dense.
        from raft_sim_tpu.analysis.cost_model import layout_of

        row_layout = row.get("layout") or "dense"
        if row_layout != layout_of(prod[0]):
            reasons.append(
                f"{row_layout} layout row vs the preset's "
                f"{layout_of(prod[0])} layout: a layout A/B row can never "
                "rebase the other layout's roofline"
            )
    # Device-count keying (the same trap class as layouts, closed for the
    # mesh-scaling leg): the pins price per-device bytes/tick, so a row
    # measured across D devices reports aggregate throughput that a
    # single-device roofline must never be rebased onto. Rows without an
    # n_devices field (every pre-mesh artifact) are all single-device.
    if (row.get("n_devices") or 1) != 1:
        reasons.append(
            f"row measured across {row['n_devices']} devices: aggregate "
            "mesh throughput can never rebase the single-device roofline"
        )
    if prod is None:
        reasons.append(f"{config_name!r} is not a preset: no pins to rebase")
    return reasons


def reconcile_row(config_name: str, row: dict, pins: dict,
                  default_backend: str | None = None,
                  observed_live_bytes: int | None = None,
                  program: str = "simulate") -> dict:
    """Join one measured bench row against its config's pinned program
    (`simulate` for the tick matrix; the serve-throughput row passes
    `serve_simulate` so its ticks/s reconcile against the SERVE program's
    bytes/tick -- the offer/read planes and window folds included)."""
    backend = row.get("backend") or default_backend
    measured, source = _measured(row)
    pin = (pins.get("programs") or {}).get(f"{config_name}/{program}") or {}
    notes = []
    out = {
        "config": config_name,
        "backend": backend,
        "measured_ticks_per_s": measured,
        "measured_source": source,
        "repeat_cv": row.get("repeat_cv"),
        "predicted_roofline_ticks_per_s": pin.get("roofline_ticks_per_s"),
        "bytes_per_tick_padded": pin.get("bytes_per_tick_padded"),
        "achieved_bytes_per_s": None,
        "roofline_fraction": None,
        "implied_hbm_bytes_per_s": pin.get("implied_hbm_bytes_per_s"),
        "live_peak_pin": pin.get("live_peak"),
        "observed_live_bytes": observed_live_bytes,
        "live_occupancy_vs_pin": None,
    }
    if source == "legacy-best":
        notes.append(
            "measured from the legacy best-of-repeats field (row carries no "
            "steady stats: pre-r06 artifact)"
        )
    if not pin:
        notes.append(
            f"no cost-model pin for {config_name}/{program}: "
            "measurements only"
        )
    if measured and pin.get("bytes_per_tick_padded"):
        out["achieved_bytes_per_s"] = round(
            measured * pin["bytes_per_tick_padded"], 1
        )
    if measured and pin.get("roofline_ticks_per_s"):
        frac = measured / pin["roofline_ticks_per_s"]
        out["roofline_fraction"] = round(frac, 4)
        if frac > 1.0:
            notes.append(
                "measured above the pinned roofline: the pins are stale -- "
                "regenerate via tools/check.py --update-goldens after this "
                "artifact lands"
            )
    elif measured and pin:
        notes.append(
            "pin carries no roofline (config outside the anchored set): "
            "achieved bytes/s only"
        )
    if observed_live_bytes is not None and pin.get("live_peak"):
        out["live_occupancy_vs_pin"] = round(
            observed_live_bytes / pin["live_peak"], 3
        )
        notes.append(
            "live-peak pin is priced at the audit shape, not the production "
            "batch: occupancy ratio is a trend fence, not a byte budget"
        )
    reasons = non_anchor_reasons(config_name, row, backend)
    out["anchor"] = not reasons
    out["non_anchor_reasons"] = reasons
    out["notes"] = notes
    return out


def reconcile_matrix(doc: dict, pins: dict | None = None,
                     default_backend: str | None = None) -> dict:
    """Reconcile every row of a bench matrix document ({"matrix": {...}},
    i.e. bench.py --out / BENCH_r*.json parsed form) against the pins."""
    if pins is None:
        pins = load_pins()
    notes = []
    if not pins:
        notes.append(
            "golden cost-model pins unavailable: reporting measurements only"
        )
    rows = [
        reconcile_row(name, row, pins, default_backend=default_backend)
        for name, row in sorted((doc.get("matrix") or {}).items())
        if isinstance(row, dict)
    ]
    anchored = [r["config"] for r in rows if r["anchor"]]
    if not anchored:
        notes.append(
            "no anchor-eligible rows: this artifact must not be saved as a "
            "BENCH_r*.json roofline anchor"
        )
    return {
        "pins_jax_version": pins.get("jax_version"),
        "pins_anchor_source": pins.get("anchor_source"),
        "anchor_eligible": anchored,
        "rows": rows,
        "notes": notes,
    }


def _preset_name(config_dict: dict) -> str | None:
    """Match a manifest's full config dict back to a named preset (the pins
    are keyed by preset name)."""
    import dataclasses

    for name, (cfg, _batch) in PRESETS.items():
        if dataclasses.asdict(cfg) == config_dict:
            return name
    return None


def reconcile_perf_dir(directory: str, pins: dict | None = None) -> dict:
    """Reconcile a directory's perf.jsonl stream: steady-state throughput
    recomputed from the rows themselves (not trusted from any summary),
    joined against the directory config's pins. Telemetry directories carry
    a full manifest.json; farm out-dirs (scenario farm / driver sfarm) carry
    farm_manifest.json instead -- their identity (config, population) comes
    from it, and backend/n_devices come from the rows themselves (the farm's
    timer annotates each generation, so a mesh-sharded hunt's aggregate
    throughput is keyed non-anchor like any multi-device row). A CPU perf
    run reconciles but never anchors, either way."""
    import dataclasses as _dc

    from raft_sim_tpu.obs.timer import summarize_rows
    from raft_sim_tpu.utils import telemetry_sink
    from raft_sim_tpu.utils.config import RaftConfig

    rows = read_perf(directory)
    if not rows:
        raise ValueError(f"{directory}: no perf.jsonl rows to reconcile")
    farm_path = os.path.join(directory, "farm_manifest.json")
    if os.path.isfile(os.path.join(directory, "manifest.json")):
        man = telemetry_sink.read_manifest(directory)
        batch = int(man.get("batch", 1))
        label = man.get("source", "run")
        config_dict = man.get("config") or {}
        backend = man.get("backend")
        farm = False
    elif os.path.isfile(farm_path):
        with open(farm_path) as f:
            man = json.load(f)
        batch = int(man.get("population", 1))
        label = "farm"
        # The farm manifest stores only non-default fields (hunt identity);
        # defaults reconstruct the full config for preset matching.
        try:
            config_dict = _dc.asdict(RaftConfig(**(man.get("config") or {})))
        except (TypeError, AssertionError):
            config_dict = {}
        # The mesh is deliberately not part of the farm's hashed identity,
        # so runtime keying comes from the rows (ChunkTimer annotations).
        backend = next(
            (r["backend"] for r in reversed(rows) if r.get("backend")), None
        )
        farm = True
    else:
        raise ValueError(
            f"{directory}: neither manifest.json nor farm_manifest.json -- "
            "not a reconcilable perf directory"
        )
    summary = summarize_rows(rows, label=label, batch=batch)
    name = _preset_name(config_dict)
    n_devices = max(
        (r["n_devices"] for r in rows
         if isinstance(r.get("n_devices"), int)), default=1,
    )
    pseudo = {
        "steady_ticks_per_s": summary["steady_cluster_ticks_per_s"],
        "batch": batch,
        "backend": backend,
        "n_devices": n_devices,
    }
    if pins is None:
        pins = load_pins()
    rec = reconcile_row(
        name or "custom", pseudo, pins, default_backend=backend,
        observed_live_bytes=summary["live_bytes_peak"],
    )
    if name is None:
        rec["notes"].append(
            "manifest config matches no preset: no pins to join against"
        )
    if farm:
        rec["notes"].append(
            "farm out-dir: one row per CE generation (whole-portfolio "
            "evaluations), batch = the portfolio population"
        )
    rec["notes"].append(
        "measured through the chunked loop (per-chunk sync points), not the "
        "monolithic bench program the pin prices: same tick body, slightly "
        "more host traffic -- compare fractions, not absolutes, against "
        "bench rows"
    )
    return {"summary": summary, "reconciliation": rec}


def read_perf(directory: str) -> list[dict]:
    """Load perf.jsonl rows from a telemetry directory ([] when absent)."""
    path = os.path.join(directory, "perf.jsonl")
    if not os.path.isfile(path):
        return []
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]
