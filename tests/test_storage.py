"""Durable storage plane (raft_sim_tpu/storage; ISSUE 19): lost-suffix
recovery truncation at word-edge cluster sizes, durability x compacted-carry
bit-exactness, checkpoint v25, and the durability_lag SLI.

The oracle-parity rows in test_oracle_parity.py carry the per-tick
correctness claim (n5-durable-* rows, both kernels); this file pins the
plane's EDGES: the recovery arithmetic through the real kernel at N
straddling the 32-bit vote-plane word boundary (31/32/33 -- elections over
packed vote words are live around every recovery), the layout-independence
of the dur watermark legs, and the persistence/health surfaces."""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_sim_tpu import RaftConfig, init_state
from raft_sim_tpu.models import raft
from raft_sim_tpu.ops import tile
from raft_sim_tpu.sim import faults, scan
from raft_sim_tpu.storage import plane
from raft_sim_tpu.types import NIL, compact_twin
from raft_sim_tpu.utils import checkpoint
from raft_sim_tpu.utils.config import PRESETS


def _dur_cfg(n, **kw):
    base = dict(
        n_nodes=n,
        log_capacity=16,
        client_interval=2,
        fsync_interval=3,
        fsync_jitter_prob=0.25,
        torn_tail_prob=0.5,
        lost_suffix_span=5,
    )
    base.update(kw)
    return RaftConfig(**base)


# ------------------------------------------------- plane helpers vs numpy


@pytest.mark.parametrize("n", [31, 32, 33])
def test_plane_helpers_match_numpy_reference(n):
    """recover/covered/flush restated independently in numpy, fuzzed over
    word-edge-sized vectors including the extremes (torn 0, torn > log_len,
    dur_len == log_len)."""
    cfg = _dur_cfg(n)
    rng = np.random.default_rng(n)
    log_len = rng.integers(0, 17, n).astype(np.int32)
    dur_len = np.minimum(rng.integers(0, 17, n), log_len).astype(np.int32)
    torn = rng.integers(0, 20, n).astype(np.int32)  # may exceed log_len
    torn[0], torn[-1] = 0, 19
    rs = rng.integers(0, 2, n).astype(bool)
    term = rng.integers(1, 6, n).astype(np.int32)
    dur_term = np.minimum(rng.integers(1, 6, n), term).astype(np.int32)
    vote = rng.integers(-1, n, n).astype(np.int32)
    dur_vote = rng.integers(-1, n, n).astype(np.int32)

    rec = np.maximum(dur_len, log_len - torn)
    np.testing.assert_array_equal(
        np.asarray(plane.recovered_log_len(
            jnp.asarray(dur_len), jnp.asarray(log_len), jnp.asarray(torn))),
        rec,
    )
    r_term, r_vote, r_len = plane.recover(
        cfg, jnp.asarray(rs), jnp.asarray(torn),
        jnp.asarray(dur_len), jnp.asarray(dur_term), jnp.asarray(dur_vote),
        jnp.asarray(term), jnp.asarray(vote), jnp.asarray(log_len),
    )
    np.testing.assert_array_equal(np.asarray(r_len), np.where(rs, rec, log_len))
    np.testing.assert_array_equal(
        np.asarray(r_term), np.where(rs, dur_term, term))
    np.testing.assert_array_equal(
        np.asarray(r_vote), np.where(rs, dur_vote, vote))
    np.testing.assert_array_equal(
        np.asarray(plane.covered(
            jnp.asarray(dur_term), jnp.asarray(dur_vote),
            jnp.asarray(term), jnp.asarray(vote))),
        (dur_term == term) & (dur_vote == vote) & (vote != NIL),
    )


# --------------------------------------- kernel recovery at word edges


@pytest.mark.parametrize("n", [31, 32, 33])
def test_kernel_lost_suffix_truncation_word_edges(n):
    """One real-kernel tick with forced restarts and torn-tail draws at N
    straddling the vote-plane word boundary: every restarted node's log is
    truncated to max(dur_len, log_len - torn_drop) -- the fsync watermark
    FLOORS the recovered length (the durable prefix never tears) -- and
    non-restarted logs are untouched. fsync_fire is forced off so the
    watermarks themselves only clamp, never advance."""
    cfg = _dur_cfg(n)
    key = jax.random.key(n)
    k_init, k_run = jax.random.split(key)
    s = init_state(cfg, k_init)
    ar = np.arange(n)
    log_len = ((ar * 7) % 17).astype(np.int32)
    dur_len = (log_len // 2).astype(np.int32)
    s = s._replace(
        log_len=jnp.asarray(log_len),
        dur_len=jnp.asarray(dur_len),
    )
    inp = faults.make_inputs(cfg, k_run, s.now)
    restarted = jnp.asarray(ar % 2 == 0)
    torn = jnp.asarray((ar % 7).astype(np.int32))  # 0..6 spans, some > tail
    inp = inp._replace(
        restarted=restarted,
        alive=jnp.ones(n, bool),
        torn_drop=torn,
        fsync_fire=jnp.zeros(n, bool),
        client_cmd=jnp.int32(NIL),
    )
    s2, _ = jax.jit(lambda st, i: raft.step(cfg, st, i))(s, inp)
    rs = np.asarray(restarted)
    expect = np.where(rs, np.maximum(dur_len, log_len - np.asarray(torn)),
                      log_len)
    np.testing.assert_array_equal(np.asarray(s2.log_len), expect)
    # The watermark only clamped: dur_len' = min(dur_len, recovered log).
    np.testing.assert_array_equal(
        np.asarray(s2.dur_len), np.minimum(dur_len, expect))
    assert bool(np.all(np.asarray(s2.dur_len) <= np.asarray(s2.log_len)))


# ------------------------------------- durability x compacted carry layout


def test_durability_compact_planes_bitexact():
    """Dense and compacted trajectories are bit-identical with the storage
    plane LIVE under crash/torn churn: the dur watermark legs ride the carry
    unpacked in both layouts, and recovery truncation of bit-packed logs
    lands on the same lengths (the layout is physical only)."""
    cfg_d = _dur_cfg(
        5, log_capacity=8, max_entries_per_rpc=2, client_interval=1,
        drop_prob=0.3, crash_prob=0.5, crash_period=20, crash_down_ticks=10,
        lost_suffix_span=3,
    )
    cfg_c = compact_twin(cfg_d)
    key = jax.random.key(21)
    k_init, k_run = jax.random.split(key)
    sd = init_state(cfg_d, k_init)
    sc = init_state(cfg_c, k_init)
    step_d = jax.jit(lambda s, i: raft.step(cfg_d, s, i)[0])
    step_c = jax.jit(lambda s, i: raft.step(cfg_c, s, i)[0])
    inp_d = jax.jit(lambda now: faults.make_inputs(cfg_d, k_run, now))
    inp_c = jax.jit(lambda now: faults.make_inputs(cfg_c, k_run, now))
    for _ in range(80):
        sd = step_d(sd, inp_d(sd.now))
        sc = step_c(sc, inp_c(sc.now))
    du = tile.unpack_state(cfg_c, sc)
    for f in sd._fields:
        if f == "mailbox":
            for mf in sd.mailbox._fields:
                np.testing.assert_array_equal(
                    np.asarray(getattr(sd.mailbox, mf)),
                    np.asarray(getattr(du.mailbox, mf)), err_msg=f"mb.{mf}")
        else:
            np.testing.assert_array_equal(
                np.asarray(getattr(sd, f)), np.asarray(getattr(du, f)),
                err_msg=f)
    # The run actually exercised the plane: some disk lagged its log.
    assert int(np.max(np.asarray(sd.dur_len))) > 0


# --------------------------------------------------------- checkpoint v25


def test_checkpoint_v25_round_trips_durable_state(tmp_path):
    """A mid-run config10 fleet (watermarks advanced, fsync-lag metrics
    accumulated) saves and loads bit-identically."""
    from raft_sim_tpu.types import init_batch

    cfg, _ = PRESETS["config10"]
    root = jax.random.key(11)
    k_init, k_run = jax.random.split(root)
    state = init_batch(cfg, k_init, 2)
    keys = jax.random.split(k_run, 2)
    state, metrics = scan.run_batch_minor(cfg, state, keys, 120)
    assert int(np.max(np.asarray(state.dur_len))) > 0  # flushes happened
    assert int(np.sum(np.asarray(metrics.fsync_lag_sum))) > 0  # lag observed
    path = checkpoint.save(str(tmp_path / "ck"), cfg, state, keys, metrics,
                           seed=11)
    cfg2, state2, keys2, metrics2, seed2, scenario = checkpoint.load(path)
    assert cfg2 == cfg and seed2 == 11 and scenario is None
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(state2)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(metrics), jax.tree.leaves(metrics2)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    assert np.array_equal(
        jax.random.key_data(keys), jax.random.key_data(keys2))


def test_checkpoint_v24_file_refused_with_migration_error(tmp_path, monkeypatch):
    """A pre-v25 checkpoint must be REFUSED with the migration-pointing
    error, not half-loaded into the watermark-bearing schema."""
    cfg = RaftConfig(n_nodes=3, log_capacity=8)
    s = init_state(cfg, jax.random.key(0))
    state = jax.tree.map(lambda x: jnp.stack([x]), s)
    keys = jax.random.split(jax.random.key(1), 1)
    metrics = scan.init_metrics_batch(1)
    monkeypatch.setattr(checkpoint, "_FORMAT_VERSION", 24)
    path = checkpoint.save(str(tmp_path / "old"), cfg, state, keys, metrics)
    monkeypatch.undo()
    with pytest.raises(ValueError, match="v24.*v25|format v24"):
        checkpoint.load(path)


# ------------------------------------------------- health + fleet surfaces


def test_durability_lag_sli_and_spec():
    """The durability_lag SLI: worst instantaneous per-node lag vs the
    ceiling (binary budget objective; ceiling 0 = disabled), with the
    per-cluster max as the triage metric."""
    from raft_sim_tpu.health import sli
    from raft_sim_tpu.health.spec import load_spec

    def unit(lag_max, lag_sum):
        b = len(lag_max)
        from raft_sim_tpu.types import LAT_HIST_BINS
        return {
            "start": 0, "ticks": 16,
            "violations": np.zeros(b, np.int64),
            "leaderless": np.zeros(b, bool),
            "cmds": np.zeros(b, np.int64), "reads": np.zeros(b, np.int64),
            "lat_sum": np.zeros(b, np.int64), "lat_cnt": np.zeros(b, np.int64),
            "lat_hist": np.zeros((b, LAT_HIST_BINS), np.int64),
            "read_hist": np.zeros((b, LAT_HIST_BINS), np.int64),
            "fsync_lag_sum": np.asarray(lag_sum, np.int64),
            "fsync_lag_max": np.asarray(lag_max, np.int64),
        }

    spec = load_spec({
        "schema": "health-slo-v1", "eval_windows": 1, "worst_k": 1,
        "outlier_score": 3.0, "resolve_evals": 1,
        "objectives": {
            "durability": {"sli": "durability_lag", "max_lag": 4,
                           "budget": 0.25},
        },
        "rules": [{"name": "fast", "short": 1, "long": 2, "burn": 6.0}],
    })
    units = [unit([2, 7, 0], [8, 40, 0]), unit([1, 3, 0], [4, 12, 0])]
    out = sli.compute_slis(spec, units, [])
    assert out["slis"]["durability"]["max_lag"] == 7
    assert out["errs"]["durability"] == 1.0  # 7 > ceiling 4
    assert out["budgets"]["durability"] == 0.25
    np.testing.assert_array_equal(out["percluster"]["durability"],
                                  [2.0, 7.0, 0.0])
    # Ceiling respected / disabled.
    spec["objectives"]["durability"]["max_lag"] = 8
    assert sli.compute_slis(spec, units, [])["errs"]["durability"] == 0.0
    spec["objectives"]["durability"]["max_lag"] = 0
    assert sli.compute_slis(spec, units, [])["errs"]["durability"] == 0.0
    # Spec validation rejects a bad ceiling.
    from raft_sim_tpu.health.spec import validate_spec
    bad = {**spec, "objectives": {
        "durability": {"sli": "durability_lag", "max_lag": -1, "budget": 0.25}}}
    assert any("max_lag" in e for e in validate_spec(bad))


def test_fleet_summary_fsync_rollup():
    """FleetSummary's durability readouts: fleet total, worst instantaneous
    lag, and percentiles over per-cluster MEAN lag (lag_sum / ticks),
    skipping clusters that ran zero ticks."""
    from types import SimpleNamespace

    from raft_sim_tpu.parallel.mesh import _fsync_lag_rollup

    m = SimpleNamespace(
        ticks=np.array([10, 20, 0]),
        fsync_lag_sum=np.array([50, 20, 0]),
        fsync_lag_max=np.array([7, 3, 0]),
    )
    out = _fsync_lag_rollup(m)
    assert out["fsync_lag_total"] == 70
    assert out["fsync_lag_max"] == 7
    assert out["fsync_lag_p50"] == pytest.approx(3.0)  # means [5.0, 1.0]
    assert out["fsync_lag_p95"] == pytest.approx(4.8)
    empty = _fsync_lag_rollup(SimpleNamespace(
        ticks=np.zeros(2, int), fsync_lag_sum=np.zeros(2, int),
        fsync_lag_max=np.zeros(2, int)))
    assert empty["fsync_lag_p50"] is None and empty["fsync_lag_total"] == 0


# ------------------------------------------------------------ config gates


def test_config_gate_validation():
    """Structural-gate asserts: disk-fault knobs without the fsync gate are
    refused, as is the v1 compaction overlap."""
    with pytest.raises(AssertionError, match="fsync"):
        RaftConfig(n_nodes=3, log_capacity=8, fsync_jitter_prob=0.2)
    with pytest.raises(AssertionError, match="fsync"):
        RaftConfig(n_nodes=3, log_capacity=8, torn_tail_prob=0.2)
    with pytest.raises(AssertionError, match="compact_margin|fsync"):
        RaftConfig(n_nodes=3, log_capacity=8, compact_margin=4,
                   fsync_interval=3)
    cfg = _dur_cfg(3)
    assert cfg.durable_storage and cfg.durable_acks and cfg.persist_vote
    off = dataclasses.replace(cfg, fsync_interval=0, fsync_jitter_prob=0.0,
                              torn_tail_prob=0.0, lost_suffix_span=1)
    assert not off.durable_storage


# ------------------------------------------------------- portfolio member


def test_durability_portfolio_member_gradient():
    """fit_durability (farm/portfolio.py): exposure = per-window commit
    advance weighted by the window's fsync lag -- the committing-while-
    volatile cluster MUST outscore both the idle-disk committer (lag 0)
    and the partition-dead churner (no commits), and a device violation
    dominates all of it. The pure-distress members anti-select the bug's
    preconditions; this member is why the CI durability smoke re-finds
    ack-before-fsync within its generation budget."""
    from types import SimpleNamespace

    from raft_sim_tpu.farm.portfolio import fit_durability

    # Three clusters x four windows: [0] commits under lag, [1] commits on a
    # prompt disk, [2] churns leaderless without committing anything.
    max_commit = np.array([[2, 5, 9, 12], [2, 5, 9, 12], [0, 0, 0, 0]],
                          np.int64)
    lag = np.array([[3, 4, 6, 5], [0, 0, 0, 0], [9, 9, 9, 9]], np.int64)
    records = SimpleNamespace(metrics=SimpleNamespace(
        max_commit=max_commit, fsync_lag_max=lag))
    metrics = SimpleNamespace(violations=np.array([0, 0, 0], np.int64),
                              max_term=np.array([3, 3, 40], np.int64))
    fit = fit_durability(records, metrics, None)
    assert fit[0] > fit[1], fit  # lag-exposed commits beat prompt-disk ones
    assert fit[0] > fit[2], fit  # ...and beat commit-free churn
    # A violation dominates lexicographically in every member.
    metrics_v = SimpleNamespace(violations=np.array([0, 0, 1], np.int64),
                                max_term=metrics.max_term)
    fit_v = fit_durability(records, metrics_v, None)
    assert fit_v[2] > fit_v[0] and fit_v[2] > 1e5, fit_v
