"""Findings schema + waiver engine for the static analyzer.

Follows the telemetry-sink idiom (utils/telemetry_sink.py): the schema IS a
field tuple plus a dependency-free `validate()`, everything serializes as
plain JSON with integer-exact values, and incompatible format changes bump a
schema version that consumers refuse.

A *finding* is one rule violation, anchored either to a source line
(`path:line`, the AST pass) or to a lowered program (`path` like
`jaxpr:config5/step_b`, line 0, the jaxpr pass). Intentional exceptions live
in an annotated waiver file (`analysis/waivers.json`): each entry names the
rule, the path, an optional `contains` substring of the message, and a
one-line human justification. `tools/check.py` exits nonzero on any UNWAIVED
finding; waived findings still appear in the JSON report (with their
justification) so CI artifacts show what is being tolerated and why.
"""

from __future__ import annotations

import dataclasses
import json
import os

FINDINGS_SCHEMA_VERSION = 1

# Required fields of one serialized finding (validate() enforces).
FINDING_FIELDS = ("rule", "path", "line", "message", "waived", "waiver_reason")

# Required fields of a waiver entry. `contains` is optional.
WAIVER_FIELDS = ("rule", "path", "reason")


@dataclasses.dataclass
class Finding:
    """One rule violation. `line` 0 = not anchored to a source line (jaxpr
    findings anchor to a program name in `path` instead)."""

    rule: str
    path: str
    message: str
    line: int = 0
    waived: bool = False
    waiver_reason: str = ""

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": int(self.line),
            "message": self.message,
            "waived": bool(self.waived),
            "waiver_reason": self.waiver_reason,
        }

    def location(self) -> str:
        return f"{self.path}:{self.line}" if self.line else self.path


def load_waivers(path: str) -> tuple[list[dict], list[str]]:
    """Read a waiver file; returns (entries, problems). A missing file is an
    empty waiver set (not an error); a malformed one is all problems -- a
    typo'd waiver must fail loudly, not silently stop waiving."""
    if not os.path.isfile(path):
        return [], []
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as ex:
        return [], [f"{path}: unreadable: {ex}"]
    problems = []
    if doc.get("schema_version") != FINDINGS_SCHEMA_VERSION:
        problems.append(
            f"{path}: schema_version {doc.get('schema_version')!r}, "
            f"expected {FINDINGS_SCHEMA_VERSION}"
        )
    entries = doc.get("waivers")
    if not isinstance(entries, list):
        return [], problems + [f"{path}: 'waivers' must be a list"]
    ok = []
    for i, w in enumerate(entries):
        if not isinstance(w, dict):
            problems.append(f"{path}: waiver[{i}]: must be an object, got {type(w).__name__}")
            continue
        for k in WAIVER_FIELDS:
            if not isinstance(w.get(k), str) or not w.get(k):
                problems.append(f"{path}: waiver[{i}]: field {k!r} missing or empty")
        if "contains" in w and not isinstance(w["contains"], str):
            problems.append(f"{path}: waiver[{i}]: 'contains' must be a string")
        ok.append(w)
    return ok, problems


def apply_waivers(findings: list[Finding], waivers: list[dict]) -> list[dict]:
    """Mark findings matched by a waiver (rule + path equal, and the optional
    `contains` substring in the message). Returns the waiver entries that
    matched NOTHING -- stale waivers are surfaced so the file cannot silently
    accumulate dead exceptions."""
    used = [False] * len(waivers)
    for f in findings:
        for i, w in enumerate(waivers):
            if w.get("rule") != f.rule or w.get("path") != f.path:
                continue
            if w.get("contains") and w["contains"] not in f.message:
                continue
            f.waived = True
            f.waiver_reason = w.get("reason", "")
            used[i] = True
            break
    return [w for w, u in zip(waivers, used) if not u]


def report(findings: list[Finding], *, unused_waivers=(), extras=None) -> dict:
    """The full JSON report document (the CI artifact)."""
    import jax

    unwaived = [f for f in findings if not f.waived]
    doc = {
        "schema_version": FINDINGS_SCHEMA_VERSION,
        "jax_version": jax.__version__,
        "n_findings": len(findings),
        "n_unwaived": len(unwaived),
        "n_waived": len(findings) - len(unwaived),
        "unused_waivers": list(unused_waivers),
        "findings": [f.to_json() for f in findings],
    }
    if extras:
        doc.update(extras)
    return doc


def validate(doc: dict) -> list[str]:
    """Check a report document against the schema. Returns human-readable
    problems ([] = valid). Dependency-free, like telemetry_sink.validate."""
    errors = []
    if doc.get("schema_version") != FINDINGS_SCHEMA_VERSION:
        errors.append(
            f"schema_version {doc.get('schema_version')!r}, "
            f"expected {FINDINGS_SCHEMA_VERSION}"
        )
    for k in ("n_findings", "n_unwaived", "n_waived"):
        if not isinstance(doc.get(k), int):
            errors.append(f"field {k!r} missing or non-int")
    rows = doc.get("findings")
    if not isinstance(rows, list):
        return errors + ["'findings' must be a list"]
    for i, row in enumerate(rows):
        for k in FINDING_FIELDS:
            if k not in row:
                errors.append(f"findings[{i}]: missing field {k!r}")
        if not isinstance(row.get("line"), int):
            errors.append(f"findings[{i}]: 'line' must be an int")
        if not isinstance(row.get("waived"), bool):
            errors.append(f"findings[{i}]: 'waived' must be a bool")
    if isinstance(doc.get("n_findings"), int) and doc["n_findings"] != len(rows):
        errors.append("n_findings does not match len(findings)")
    if isinstance(doc.get("n_unwaived"), int):
        actual = sum(1 for r in rows if not r.get("waived", False))
        if doc["n_unwaived"] != actual:
            errors.append("n_unwaived does not match the findings list")
    return errors
