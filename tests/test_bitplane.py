"""Unit tier for ops/bitplane.py: the packed boolean planes under the kernels.

Word-boundary N values matter most -- 31/32 (one word, full and not), 33 (first
bit of a second word), 51 (config5's wide cluster), 64 (two full words) -- and
the canonicality invariant (padding bits zero) that makes popcount quorum
counts exact.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_sim_tpu.ops import bitplane

NS = [1, 5, 31, 32, 33, 51, 64]


@pytest.mark.parametrize("n", NS)
def test_pack_unpack_roundtrip(n):
    rng = np.random.default_rng(n)
    x = jnp.asarray(rng.random((n, n)) < 0.4)
    p = bitplane.pack(x, axis=1)
    assert p.shape == (n, bitplane.n_words(n))
    assert p.dtype == jnp.uint32
    np.testing.assert_array_equal(np.asarray(bitplane.unpack(p, n, axis=1)), x)
    # axis 0 too (the alive-mask orientation in the batched kernel).
    p0 = bitplane.pack(x, axis=0)
    np.testing.assert_array_equal(np.asarray(bitplane.unpack(p0, n, axis=0)), x)


@pytest.mark.parametrize("n", NS)
def test_popcount_matches_bool_sum(n):
    rng = np.random.default_rng(100 + n)
    x = rng.random((n, n)) < 0.5
    p = bitplane.pack(jnp.asarray(x), axis=1)
    got = np.asarray(bitplane.count(p, axis=1))
    np.testing.assert_array_equal(got, x.sum(axis=1))
    assert got.dtype == np.int32


@pytest.mark.parametrize("n", [31, 33, 51])
def test_pack_is_canonical(n):
    """Padding bits (positions >= n of the last word) stay zero, and stay zero
    under the word algebra the kernels use (AND/OR/andnot-with-canonical)."""
    ones = bitplane.pack(jnp.ones((n, n), bool), axis=1)
    w = bitplane.n_words(n)
    valid = (1 << (n - 32 * (w - 1))) - 1  # valid-bit mask of the last word
    last = np.asarray(ones)[:, -1]
    assert (last == valid).all()
    mixed = bitplane.andnot(ones, bitplane.eye(n))
    assert (np.asarray(mixed)[:, -1] & ~np.uint32(valid) == 0).all()
    # count() is exact on the all-true plane (no phantom padding bits).
    assert (np.asarray(bitplane.count(ones, axis=1)) == n).all()


def test_eye_and_rows():
    n = 51
    np.testing.assert_array_equal(
        np.asarray(bitplane.unpack(bitplane.eye(n), n, axis=1)), np.eye(n, dtype=bool)
    )
    np.testing.assert_array_equal(
        np.asarray(bitplane.unpack(bitplane.full_row(n), n)), np.ones(n, bool)
    )
    br = np.asarray(bitplane.unpack(bitplane.bit_row(40, n), n))
    assert br[40] and br.sum() == 1


def test_set_and_get_bit():
    n = 33
    plane = jnp.zeros((n, bitplane.n_words(n)), jnp.uint32)
    plane = bitplane.set_bit(plane, 2, 32)  # first bit of the second word
    assert bool(bitplane.get_bit(plane, 2, 32))
    assert not bool(bitplane.get_bit(plane, 2, 31))
    assert int(bitplane.count(plane, axis=1).sum()) == 1
    cleared = bitplane.set_bit(plane, 2, 32, value=False)
    assert int(bitplane.count(cleared, axis=1).sum()) == 0


def test_batch_minor_and_vmap_forms_agree():
    """The same functions serve [N, N] (vmap-lifted) and [N, N, B] (batch-minor)
    planes; both must produce identical words."""
    n, b = 51, 7
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.random((b, n, n)) < 0.5)  # [B, N, N] batch-leading
    per_cluster = jax.vmap(lambda p: bitplane.pack(p, axis=1))(x)  # [B, N, W]
    minor = bitplane.pack(jnp.moveaxis(x, 0, -1), axis=1)  # [N, W, B]
    np.testing.assert_array_equal(
        np.asarray(per_cluster), np.asarray(jnp.moveaxis(minor, -1, 0))
    )
    back = bitplane.unpack(minor, n, axis=1)  # [N, N, B]
    np.testing.assert_array_equal(
        np.asarray(jnp.moveaxis(back, -1, 0)), np.asarray(x)
    )


def test_matches_oracle_numpy_forms():
    """tests/oracle.py restates pack/unpack independently (it may not import
    the package); pin the two layouts against each other so they cannot
    drift."""
    from tests import oracle

    n = 51
    rng = np.random.default_rng(3)
    x = rng.random((n, n)) < 0.5
    np.testing.assert_array_equal(
        np.asarray(bitplane.pack(jnp.asarray(x), axis=1)), oracle.pack_plane(x)
    )
    np.testing.assert_array_equal(
        oracle.unpack_plane(np.asarray(bitplane.pack(jnp.asarray(x), axis=1)), n), x
    )
