"""Fleet-as-a-service: the standing-fleet serve subsystem (ISSUE 6).

Three pieces (docs/SERVE.md has the architecture):
  ingest.py  -- host command sources packed into per-chunk offer planes
  loop.py    -- the double-buffered served scan + ServeSession driver
  deltas.py  -- device-side commit-delta extraction (the streaming apply/ack
                surface replacing the host snapshot-diff poll)
"""

from raft_sim_tpu.serve.deltas import DeltaStream, extract
from raft_sim_tpu.serve.ingest import CommandSource, jsonl_commands, pack_chunk
from raft_sim_tpu.serve.loop import ServeSession, serve_config, simulate_serve

__all__ = [
    "CommandSource",
    "DeltaStream",
    "ServeSession",
    "extract",
    "jsonl_commands",
    "pack_chunk",
    "serve_config",
    "simulate_serve",
]
