"""Static analysis for raft_sim_tpu: the invariants the docstrings state,
checked by machine.

The simulator's perf and correctness story rides on conventions nothing used
to enforce: the narrow-dtype policy of the [N, N] planes (types.index_dtype /
ack_dtype), the integer-only protocol path, the loop-invariant scan-carry legs
XLA must be allowed to elide (docs/PERF.md, round-4 lesson), the
bump-_FORMAT_VERSION-on-field-change checkpoint convention, and the tier-1
compile budget (~15-40 s per distinct scan program on CPU). This package
checks all of them statically -- lowering is tracing only, no XLA compile, so
the full gate runs in well under two minutes on CPU:

  Pass A (`jaxpr_audit`)  lowers the real step/scan programs per config tier
                          and walks the jaxprs (float ops, plane widening,
                          carry passthrough + dtypes, large constants, the
                          recompile-fork guard).
  Pass B (`ast_lint`)     AST rules over the package source (traced branches,
                          float literals) plus the contract cross-checks
                          (types.py dtype comments, checkpoint fingerprint and
                          serialization round trip).
  Pass C (`cost_model`)   prices the same lowered programs equation by
                          equation -- scan-carry bytes/tick (per-leg, derived
                          from the run loop's jaxpr), live-set peak, jit
                          entry-point donation, roofline at the pinned HBM
                          rate -- against tests/golden_cost_model.json, the
                          roofline as a CI invariant instead of a hand table.

Findings are schema'd JSON (`findings`, same idiom as the telemetry sink);
intentional exceptions carry one-line justifications in
`analysis/waivers.json`. CLI: `python tools/check.py --all` (CI runs it before
the tier-1 tests); rule catalogue and how-to-add-a-rule: docs/ANALYSIS.md.
"""

from raft_sim_tpu.analysis import (
    ast_lint, cost_model, findings, jaxpr_audit, policy, run,
)

__all__ = ["ast_lint", "cost_model", "findings", "jaxpr_audit", "policy", "run"]
