"""Batch-minor Raft tick kernel: the hot path for TPU execution.

Semantics are EXACTLY models/raft.py (same nine phases, same citations) -- this module
exists purely for memory layout. The vmap form puts the cluster batch LEADING
([B, N, ...]), which leaves each array's two minor dims at (N, N) or (N, CAP); TPU
tiles the two minor dims to (8, 128), so a [B, 5, 5] int32 array physically occupies
~40x its logical bytes and every tick is HBM-bound on padding (measured ~700KB moved
per cluster-tick vs ~3KB of logical state). Here the batch axis B is MINOR on every
array ([N, B], [N, N, B], [N, CAP, B]), so B rides the 128-wide lane tile and padding
is bounded by the second-minor dim (N or E or CAP -> at most 8/5).

Parity with the vmap form is enforced bit-for-bit by tests/test_batched_parity.py;
parity with the scalar oracle therefore transfers. Keep the two kernels in sync: any
semantic change lands in raft.py first (with its unit tests), then here.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_sim_tpu.models import cfglog
from raft_sim_tpu.ops import bitplane, log_ops
from raft_sim_tpu.storage import plane as storage_plane
from raft_sim_tpu.types import (
    CANDIDATE,
    FOLLOWER,
    LAT_HIST_BINS,
    LEADER,
    NIL,
    NOOP,
    PRECANDIDATE,
    REQ_APPEND,
    REQ_PREVOTE,
    REQ_TIMEOUT_NOW,
    REQ_VOTE,
    RESP_APPEND,
    RESP_PREVOTE,
    RESP_VOTE,
    ClusterState,
    Mailbox,
    StepInfo,
    StepInputs,
    node_dtype,
)
from raft_sim_tpu.utils.config import RaftConfig


class NodeShardCtx(NamedTuple):
    """Node-axis sharding context for `_step_b`/`_step_info_b` (built inside
    parallel/nodeshard.py's shard_map body; never seen by single-chip callers).

    The node axis is partitioned row-wise by RECEIVER over `n_dev` devices of a
    named mesh axis: the global node count is padded to n_pad = n_dev * nl and
    every state/mailbox leg carries this device's `nl` rows (peer/sender axes
    stay full at n_pad). Pad rows are permanently-dead nodes (alive=False every
    tick), which makes them tick fixed points; the pad hazards that are NOT
    inert by liveness alone (the phase-8 window-start min and the n<=cap
    quorum count) are masked explicitly where they arise -- see pad_self /
    valid_peer below and docs/DESIGN.md "Node-axis sharding"."""

    axis: str  # mesh axis name the node rows are sharded over
    nl: int  # node rows per device (static)
    n_pad: int  # padded node-axis length = n_devices * nl (static)
    row0: jax.Array  # first global row of this shard (traced: axis_index * nl)


def _loc(x, sh: NodeShardCtx):
    """This device's node rows of a full [n_pad, ...] per-node array."""
    return lax.dynamic_slice_in_dim(x, sh.row0, sh.nl, axis=0)


def _gather_mailbox(cfg: RaftConfig, mb: Mailbox, sh: NodeShardCtx) -> Mailbox:
    """THE hot-loop collective: all_gather the outbound mailbox over the node
    axis and reorient the per-edge planes into the receiver view _step_b reads.

    The sharded carry stores every mailbox leg WRITER-major (rows = this
    device's senders/responders), so one tiled all_gather materializes the full
    sender/responder axis and every delivery reduction after it is local:
      req_* / ent_* headers [nl, ...] -> [n_pad, ...] (the broadcast row)
      req_off [nl(snd), n_pad(rcv)]  -> gathered, then receivers keep their
                                        local columns (dense orientation
                                        [sender, receiver(local)])
      resp_kind carried TRANSPOSED [nl(responder), n_pad(receiver)] -> gathered
                                        to [n_pad, n_pad], swapped back to the
                                        dense [receiver(local), responder] view
      pv_grant carried [nl(voter), W(candidate bits)] -> unpacked over the
                                        candidate axis, transposed, local
                                        candidate rows repacked over the voter
                                        axis (the dense [cand, W(voter)] view)
    Legs whose structural gate is off in the sharded v1 surface (transfer,
    reconfig, and -- when their own flags are off -- compaction/track/pre_vote
    legs) stay the LOCAL loop-invariant carry: they are never read, and not
    gathering them keeps the ICI bytes at the cost model's header-row figure."""
    npd = sh.n_pad
    ag = lambda x: lax.all_gather(x, sh.axis, axis=0, tiled=True)
    comp, track = cfg.compaction, cfg.track_offer_ticks
    if cfg.pre_vote:
        pv = bitplane.unpack(ag(mb.pv_grant), npd, axis=1)  # [voter, cand, B]
        pv = _loc(jnp.swapaxes(pv, 0, 1), sh)  # [nl(cand), n_pad(voter), B]
        pv_grant = bitplane.pack(pv, axis=1)
    else:
        pv_grant = mb.pv_grant
    return mb._replace(
        req_type=ag(mb.req_type),
        req_term=ag(mb.req_term),
        req_commit=ag(mb.req_commit),
        req_last_index=ag(mb.req_last_index),
        req_last_term=ag(mb.req_last_term),
        ent_start=ag(mb.ent_start),
        ent_prev_term=ag(mb.ent_prev_term),
        ent_count=ag(mb.ent_count),
        ent_term=ag(mb.ent_term),
        ent_val=ag(mb.ent_val),
        ent_tick=ag(mb.ent_tick) if track else mb.ent_tick,
        req_base=ag(mb.req_base) if comp else mb.req_base,
        req_base_term=ag(mb.req_base_term) if comp else mb.req_base_term,
        req_base_chk=ag(mb.req_base_chk) if comp else mb.req_base_chk,
        req_off=lax.dynamic_slice_in_dim(ag(mb.req_off), sh.row0, sh.nl, axis=1),
        resp_kind=_loc(jnp.swapaxes(ag(mb.resp_kind), 0, 1), sh),
        pv_grant=pv_grant,
        v_to=ag(mb.v_to),
        a_ok_to=ag(mb.a_ok_to),
        a_match=ag(mb.a_match),
        a_hint=ag(mb.a_hint),
        resp_term=ag(mb.resp_term),
    )


def to_batch_minor(tree):
    """[B, ...]-leading pytree -> [..., B]-trailing (transpose once per run, not per
    tick)."""
    return jax.tree.map(lambda x: jnp.moveaxis(x, 0, -1), tree)


def from_batch_minor(tree):
    return jax.tree.map(lambda x: jnp.moveaxis(x, -1, 0), tree)


def step_b(
    cfg: RaftConfig, s: ClusterState, inp: StepInputs, sh: NodeShardCtx | None = None
) -> tuple[ClusterState, StepInfo]:
    """One tick for B clusters at once; every array carries a trailing batch axis.

    Mirrors raft.step phase by phase; see that function for the reference
    citations -- and for the TRACE DELTA CONTRACT (raft_sim_tpu/trace reads
    role/term/voted_for/commit_index/log_len deltas of this kernel too; the
    phase-order properties documented there bind both kernels, which
    tests/test_trace.py pins by re-deriving the batched path's device events
    from the unbatched kernel's stacked states).

    Under cfg.compact_planes this boundary mirrors raft.step's: unpack the
    compacted carry (ops/tile.py; trailing batch axes ride along), run the
    identical dense tick, repack with gated-off legs passed through
    verbatim.
    """
    if not cfg.compact_planes:
        return _step_b(cfg, s, inp, sh)
    assert sh is None  # sharded carries run dense (parallel/nodeshard.py)
    from raft_sim_tpu.ops import tile

    s2, info = _step_b(
        cfg, tile.unpack_state(cfg, s), tile.unpack_inputs(cfg, inp)
    )
    return tile.pack_state(cfg, s2, reuse=s), info


def _step_b(
    cfg: RaftConfig, s: ClusterState, inp: StepInputs, sh: NodeShardCtx | None = None
) -> tuple[ClusterState, StepInfo]:
    """The dense batch-minor tick body (layout-independent semantics).

    `sh` (NodeShardCtx) switches to node-sharded execution inside a shard_map
    over sh.axis: `s` carries this device's nl node rows (peer axes padded to
    n_pad), `inp` carries the FULL padded per-node inputs (every device draws
    them redundantly from the same keys -- zero communication), and the only
    cross-device traffic per tick is the mailbox all_gather plus the
    pmin/pmax/psum folds of the per-cluster [B] reductions. sh=None (every
    single-chip caller) lowers a byte-identical program to the pre-sharding
    kernel: the folds degenerate to identity and the orientation aliases below
    collapse onto the one square eye."""
    n, e, cap = cfg.n_nodes, cfg.max_entries_per_rpc, cfg.log_capacity
    comp = cfg.compaction  # static: ring-log compaction + snapshot catch-up active
    track = cfg.track_offer_ticks  # static: offer-tick plane + latency metric active
    rcf = cfg.reconfig  # static: joint-consensus membership plane active
    xfr = cfg.leader_transfer  # static: TimeoutNow transfer plane active
    rdx = cfg.read_index  # static: ReadIndex read traffic class active
    rdl = cfg.read_lease  # static: lease-based reads (thesis 6.4.1) active
    dur = cfg.durable_storage  # static: fsync/WAL durability plane active
    b = s.role.shape[-1]
    # All iota-style constants are built at their final rank (log_ops.iota): Mosaic
    # cannot lower unit-dim-appending reshapes, and this module doubles as the
    # pallas_engine kernel body.
    iota = log_ops.iota
    if sh is None:
        nl = npd = n  # local self rows / padded peer-axis length: the full square
        ids2 = iota((n, 1), 0)  # [N, 1] node id column
        eye3 = iota((n, n, 1), 0) == iota((n, n, 1), 1)  # [N, N, 1]
        # Orientation aliases -- ONE array unsharded, distinct shapes sharded:
        # eye_sr = [sender, receiver(local)] (delivery), eye_ls = [self(local),
        # peer] (bookkeeping planes), pad_self = self-or-pad peer (the phase-8
        # window min and anything else that must skip pad peers).
        eye_sr = eye_ls = pad_self = eye3
        eye_p3 = bitplane.eye(n)[:, :, None]  # [N, W, 1] packed self-bit rows
        snd_ids = iota((n, n, 1), 0)  # [sender, receiver, 1] -> sender id
        gmax = gmin = gsum = gany = lambda x: x  # node-axis folds: already local
        alive_full = inp.alive
    else:
        # Sharded v1 feature surface: planes whose semantics span the node axis
        # in ways the gather does not cover (client redirect routing, log-
        # carried reconfig, transfer coups, ReadIndex/lease quorums, the O(N^2
        # CAP) log-matching pairs) are excluded -- parallel/nodeshard.py raises
        # a friendly error before tracing ever gets here.
        assert not (
            rcf or xfr or rdx or rdl or dur
            or cfg.client_redirect or cfg.check_log_matching
        )
        nl, npd = sh.nl, sh.n_pad
        ids2 = sh.row0 + iota((nl, 1), 0)  # [nl, 1] GLOBAL ids of local rows
        peer3 = iota((nl, npd, 1), 1)  # [nl, n_pad, 1] -> peer id
        eye_ls = ids2[:, :, None] == peer3
        pad_self = eye_ls | (peer3 >= n)  # pad peers masked like self
        eye_sr = iota((npd, nl, 1), 0) == (sh.row0 + iota((npd, nl, 1), 1))
        eye_p3 = _loc(bitplane.eye(npd), sh)[:, :, None]  # [nl, W, 1]
        snd_ids = iota((npd, nl, 1), 0)  # [sender, receiver(local), 1]
        gmax = lambda x: lax.pmax(x, sh.axis)
        gmin = lambda x: lax.pmin(x, sh.axis)
        gsum = lambda x: lax.psum(x, sh.axis)
        gany = lambda x: lax.psum(x.astype(jnp.int32), sh.axis) > 0
        # Per-node inputs: keep the full alive vector (delivery gates need the
        # SENDER side), localize the rest so the body below reads local rows.
        alive_full = inp.alive
        inp = inp._replace(
            alive=_loc(inp.alive, sh),
            restarted=_loc(inp.restarted, sh),
            skew=_loc(inp.skew, sh),
            timeout_draw=_loc(inp.timeout_draw, sh),
        )
    zw = jnp.uint32(0)

    # ---- phase -1: restart (crash fault) -----------------------------------------
    # The snapshot triple is persistent: commit resumes at log_base (raft.py).
    rs = inp.restarted  # [N, B]
    rs2 = rs[:, None, :]
    s = s._replace(
        role=jnp.where(rs, FOLLOWER, s.role),
        leader_id=jnp.where(rs, NIL, s.leader_id),
        votes=jnp.where(rs2, zw, s.votes),
        next_index=jnp.where(rs2, 1, s.next_index),
        match_index=jnp.where(rs2, 0, s.match_index),
        ack_age=jnp.where(rs2, cfg.ack_age_sat, s.ack_age),
        commit_index=jnp.where(rs, s.log_base, s.commit_index),
        commit_chk=jnp.where(rs, s.base_chk, s.commit_chk),
        deadline=jnp.where(rs, s.clock + inp.timeout_draw, s.deadline),
    )
    if dur:
        # Crash recovery (raft.py phase -1; storage/plane.recover is
        # elementwise, so the [N, B] orientation broadcasts through).
        r_term, r_vote, r_len = storage_plane.recover(
            cfg, rs, inp.torn_drop,
            s.dur_len, s.dur_term, s.dur_vote,
            s.term, s.voted_for, s.log_len,
        )
        s = s._replace(term=r_term, voted_for=r_vote, log_len=r_len)
    if cfg.pre_vote or rdl or rcf:
        # A restarted node remembers no leader contact: "quiet" immediately
        # (pre-votes grantable, and -- under the lease or log-carried-config
        # denial gates -- real votes too: raft.py phase -1).
        s = s._replace(
            heard_clock=jnp.where(
                rs, s.clock - cfg.election_min_ticks, s.heard_clock
            )
        )
    if xfr:
        # A pending transfer is volatile leader state (raft.py phase -1).
        s = s._replace(xfer_to=jnp.where(rs, NIL, s.xfer_to))
    if rdx:
        # Pending reads die with the process too (raft.py phase -1).
        s = s._replace(
            read_idx=jnp.where(rs, 0, s.read_idx),
            read_tick=jnp.where(rs, 0, s.read_tick),
            read_acks=jnp.where(rs2, zw, s.read_acks),
        )
        if rdl:
            # The staleness anchor dies with the slot it anchors.
            s = s._replace(read_fr=jnp.where(rs, 0, s.read_fr))
    # In sharded mode the carry mailbox is writer-major local rows; the gather
    # below is THE intra-tick collective (one tiled all_gather per leg), after
    # which `mb` has the exact orientations the dense body reads.
    mb = s.mailbox if sh is None else _gather_mailbox(cfg, s.mailbox, sh)
    base, bterm, bchk = s.log_base, s.base_term, s.base_chk  # [N, B]
    if rcf:
        # Snapshot config context (raft.py): carried untouched without comp.
        bmold, bpend, bepoch = s.base_mold, s.base_pend, s.base_epoch

    # Reconfiguration plane: log-carried, PER-NODE configuration masking
    # (raft.py): member rows are each node's derived view of its own log
    # prefix, [N, W, B]; every quorum test masks by the TESTING node's rows,
    # dual while that node's cfg_pend marks an open joint entry.
    if rcf:
        m_old, m_new = s.member_old, s.member_new  # [N, W, B]
        joint = s.cfg_pend > 0  # [N, B]
        maj_old = bitplane.count(m_old, axis=1) // 2 + 1  # [N, B]
        maj_new = bitplane.count(m_new, axis=1) // 2 + 1
        # Node i's own-membership bit (raft.py: the removed-server
        # disruption surface when a log misses its removal entry).
        member_b = jnp.any(((m_old | m_new) & eye_p3) != 0, axis=1)  # [N, B]

        def packed_quorum(rows):
            """[N, W, B] packed grant rows -> [N, B] own-config quorum."""
            ok = bitplane.count(rows & m_old, axis=1) >= maj_old
            return ok & (
                ~joint | (bitplane.count(rows & m_new, axis=1) >= maj_new)
            )
    else:

        def packed_quorum(rows):
            return bitplane.count(rows, axis=1) >= cfg.quorum

    # ---- phase 0: delivery -------------------------------------------------------
    # Input mask is per physical edge [to, from]; requests ([sender, receiver]) read
    # it transposed, responses ([receiver, responder]) directly (raft.py phase 0).
    # The mask arrives bit-packed over the source axis (raft.py phase 0): the
    # response orientation runs its AND-chain on the packed words and unpacks
    # once; the request orientation unpacks and transposes in bool space.
    dst_up = inp.alive & ~inp.restarted  # alive now AND at send time (last tick)
    # Receiver-row slices of the (full, redundantly drawn) delivery mask; the
    # packed source words cover all n_pad senders either way (pad bits are
    # canonical zeros -- bitplane's contract).
    dmask_rcv = inp.deliver_mask if sh is None else _loc(inp.deliver_mask, sh)
    resp_del_p = jnp.where(
        dst_up[:, None, :],
        dmask_rcv & ~eye_p3 & bitplane.pack(alive_full, axis=0)[None, :, :],
        zw,
    )  # [nl, W, B]
    deliver_resp = bitplane.unpack(resp_del_p, npd, axis=1)
    dreq = jnp.swapaxes(bitplane.unpack(inp.deliver_mask, npd, axis=1), 0, 1)
    if sh is not None:
        # [sender, receiver]: receivers keep their local columns.
        dreq = lax.dynamic_slice_in_dim(dreq, sh.row0, nl, axis=1)
    deliver_req = (
        dreq
        & ~eye_sr
        & alive_full[:, None, :]
        & dst_up[None, :, :]
    )  # [n_pad, nl, B]
    req_in = deliver_req & (mb.req_type != 0)[:, None, :]
    resp_in = deliver_resp & (mb.resp_kind != 0)

    # Heard-a-leader denial window (thesis 4.2.3; raft.py for the full
    # argument): shared by the log-carried membership defense (rcf) and the
    # lease vote denial (rdl), bypassed by the transfer override flag.
    if rcf or rdl:
        heard_recent = (
            (s.clock + inp.skew) - s.heard_clock < cfg.election_min_ticks
        )  # [N, B]
        if xfr:
            rv_denied = (
                heard_recent[None, :, :] & ~(mb.req_disrupt != 0)[:, None, :]
            )
        else:
            rv_denied = jnp.broadcast_to(heard_recent[None, :, :], (n, n, b))

    # ---- phase 1: term adoption (PreVote probes carry a PROSPECTIVE term:
    # never adopted -- raft.py phase 1) -------------------------------------------
    if cfg.pre_vote:
        term_req = req_in & (mb.req_type != REQ_PREVOTE)[:, None, :]
    else:
        term_req = req_in
    if rcf:
        # 4.2.3 in full: denied RequestVotes are not PROCESSED -- no term
        # adoption either (the removed-server disruption defense; raft.py).
        term_req = term_req & ~(
            (mb.req_type == REQ_VOTE)[:, None, :] & rv_denied
        )
    in_term = jnp.maximum(
        jnp.max(jnp.where(term_req, mb.req_term[:, None, :], 0), axis=0),
        jnp.max(jnp.where(resp_in, mb.resp_term[None, :, :], 0), axis=1),
    )  # [N, B]
    saw_higher = in_term > s.term
    term = jnp.maximum(s.term, in_term)
    role = jnp.where(saw_higher, FOLLOWER, s.role)
    voted_for = jnp.where(saw_higher, NIL, s.voted_for)
    leader_id = jnp.where(saw_higher, NIL, s.leader_id)
    votes = jnp.where(saw_higher[:, None, :], zw, s.votes)

    if comp:
        my_last_idx = s.log_len
        my_last_term = log_ops.term_at_rb(s.log_term, base, bterm, s.log_len)
    else:
        my_last_idx, my_last_term = log_ops.last_index_term_b(s.log_term, s.log_len)

    # ---- phase 2: RequestVote requests -------------------------------------------
    is_rv = req_in & (mb.req_type == REQ_VOTE)[:, None, :]  # [candidate, voter, B]
    cur_rv = is_rv & (mb.req_term[:, None, :] == term[None, :, :])
    up_to_date = (mb.req_last_term[:, None, :] > my_last_term[None, :, :]) | (
        (mb.req_last_term[:, None, :] == my_last_term[None, :, :])
        & (mb.req_last_index[:, None, :] >= my_last_idx[None, :, :])
    )
    can_grant = cur_rv & up_to_date
    if rcf or rdl:
        # Heard-a-leader vote denial (thesis 4.2.3; raft.py phase 2), with
        # the transfer override folded into rv_denied.
        can_grant = can_grant & ~rv_denied
    lowest = jnp.min(jnp.where(can_grant, snd_ids, n), axis=0)  # [N, B]
    # Boolean arithmetic instead of where-on-bools: Mosaic cannot lower vector
    # selects with i1 operands.
    has_vote = (voted_for != NIL)[None, :, :]
    grant = (has_vote & can_grant & (snd_ids == voted_for[None, :, :])) | (
        ~has_vote & can_grant & (snd_ids == lowest[None, :, :])
    )
    granted_any = jnp.any(grant, axis=0)  # [N, B]
    voted_for = jnp.where((voted_for == NIL) & granted_any, lowest, voted_for)
    vr_out = is_rv  # [candidate, voter] = response orientation [receiver, responder]
    # Grant target = post-update voted_for (raft.py phase 2: no reduction needed).
    grant_to = jnp.where(granted_any, voted_for, NIL).astype(node_dtype(cfg))  # [N, B]

    # ---- phase 3: AppendEntries requests ------------------------------------------
    is_ae = req_in & (mb.req_type == REQ_APPEND)[:, None, :]  # [leader, follower, B]
    cur_ae = is_ae & (mb.req_term[:, None, :] == term[None, :, :])
    ae_src = jnp.min(jnp.where(cur_ae, snd_ids, n), axis=0)  # [N, B]
    has_ae = ae_src < n
    sel = cur_ae & (snd_ids == ae_src[None, :, :])  # one-hot [sender, receiver, B]

    # Reconstruct the per-edge AE header from the selected sender's broadcast record
    # plus this edge's window offset j (Mailbox docstring; raft.py phase 3). All
    # selections are one-hot sums (no gather); when no sender is selected everything
    # is zeros and gated by has_ae/ae_ok downstream.
    pick_h = lambda h: jnp.sum(jnp.where(sel, h[:, None, :], 0), axis=0)  # [N, B]
    j_in = jnp.sum(jnp.where(sel, mb.req_off, 0), axis=0).astype(jnp.int32)  # [N, B] in 0..E
    # InstallSnapshot analogue: offset sentinel -1 (raft.py phase 3).
    if comp:
        snap = has_ae & (j_in < 0)
        ae_norm = has_ae & ~snap
    else:
        snap = jnp.zeros_like(has_ae)
        ae_norm = has_ae
    # Well-formed mailboxes keep the one-hot sum in [-1, E]; the clip bounds the
    # fully-masked garbage lane (and routes snap's -1 to 0, gated by ae_norm), as
    # in raft.py. Keeps prev_i provably within the idx dtype on wide-N tiers.
    j_nn = jnp.clip(j_in, 0, e)
    ws_in = pick_h(mb.ent_start)
    lcommit = pick_h(mb.req_commit)
    prev_i = jnp.where(ae_norm, ws_in + j_nn, 0)
    n_ent = jnp.where(ae_norm, jnp.clip(pick_h(mb.ent_count) - j_nn, 0, e), 0)
    # One masked reduction selects EVERY window plane (same one-hot mask):
    # terms and values -- plus offer stamps / config commands when their
    # planes are live -- ride a single [N, N, kE, B] pass, split after.
    planes = [mb.ent_term, mb.ent_val]
    if track:
        planes.append(mb.ent_tick)
    if rcf:
        planes.append(mb.ent_cfg)
    ent_tv = jnp.concatenate(planes, axis=1)  # [N, kE, B]
    w_tv = jnp.sum(jnp.where(sel[:, :, None, :], ent_tv[:, None], 0), axis=0)
    w_term_in = w_tv[:, :e]  # [N, E, B]
    w_val_in = w_tv[:, e:2 * e]
    off_w = 2 * e
    if track:
        w_tick_in = w_tv[:, off_w:off_w + e]
        off_w += e
    else:
        w_tick_in = None
    w_cfg_in = w_tv[:, off_w:off_w + e] if rcf else None
    # prev term via ext[k] = term of 1-based entry ws+k: k=0 is the sender's
    # ent_prev_term, k>=1 the shared window slots; one-hot over the E+1 offsets.
    ext = jnp.concatenate(
        [pick_h(mb.ent_prev_term)[:, None, :], w_term_in], axis=1
    )  # [N, E+1, B]
    oh_j = iota((1, e + 1, 1), 1) == j_nn[:, None, :]
    prev_t = jnp.sum(jnp.where(oh_j, ext, 0), axis=1)  # [N, B]
    # This receiver's entries start at window slot j (slot k holds entry ws+k+1).
    off = jnp.clip(j_nn, 0, e - 1)  # j = E only when n_ent = 0 (fully masked)
    ent_term_in = log_ops.window_b(w_term_in, off, e)  # [N, E, B]
    ent_val_in = log_ops.window_b(w_val_in, off, e)
    ent_tick_in = log_ops.window_b(w_tick_in, off, e) if track else None
    ent_cfg_in = log_ops.window_b(w_cfg_in, off, e) if rcf else None

    if cfg.pre_vote:
        stepdown = (role == CANDIDATE) | (role == PRECANDIDATE)
    else:
        stepdown = role == CANDIDATE
    role = jnp.where(has_ae & stepdown, FOLLOWER, role)
    leader_id = jnp.where(has_ae, ae_src, leader_id)

    if comp:
        prev_stored_term = log_ops.term_at_rb(s.log_term, base, bterm, prev_i)
        # prev below the local base is committed-and-compacted: consistent by
        # leader completeness; at prev == base the check is against base_term.
        consistent = (
            (prev_i == 0)
            | (prev_i < base)
            | ((prev_i <= s.log_len) & (prev_stored_term == prev_t))
        )
    else:
        prev_stored_term = log_ops.term_at_b(s.log_term, prev_i)
        consistent = (prev_i == 0) | (
            (prev_i <= s.log_len) & (prev_stored_term == prev_t)
        )
    ae_ok = ae_norm & consistent

    ks_e = iota((1, e, 1), 1)  # [1, E, 1]
    gidx0 = prev_i[:, None, :] + ks_e  # [N, E, B] 0-based entry indices
    if comp:
        # Skip already-compacted entries, accept only what the ring can hold
        # (raft.py phase 3).
        lo = jnp.clip(base - prev_i, 0, e)  # [N, B]
        n_acc = jnp.minimum(n_ent, jnp.maximum(base + cap - prev_i, 0))
        in_ent = (ks_e >= lo[:, None, :]) & (ks_e < n_acc[:, None, :])
        stored = log_ops.window_rb(s.log_term, prev_i, e)  # [N, E, B]
        appended_len = prev_i + n_acc
    else:
        n_acc = n_ent
        in_ent = ks_e < n_ent[:, None, :]
        stored = log_ops.window_b(s.log_term, prev_i, e)  # [N, E, B]
        appended_len = jnp.minimum(prev_i + n_ent, cap)
    exists = gidx0 < s.log_len[:, None, :]
    mismatch = in_ent & exists & (stored != ent_term_in)
    any_mismatch = jnp.any(mismatch, axis=1)  # [N, B]
    new_len = jnp.where(any_mismatch, appended_len, jnp.maximum(s.log_len, appended_len))
    log_len = jnp.where(ae_ok, new_len, s.log_len)
    if dur:
        # Durable watermark after the AE conflict truncation (raft.py phase 3).
        dur_mid = jnp.minimum(s.dur_len, log_len)
    if comp:
        log_term_arr = log_ops.write_window_rb(
            s.log_term, prev_i, ent_term_in, ae_ok, lo, n_acc
        )
        log_val_arr = log_ops.write_window_rb(
            s.log_val, prev_i, ent_val_in, ae_ok, lo, n_acc
        )
        if track:
            log_tick_arr = log_ops.write_window_rb(
                s.log_tick, prev_i, ent_tick_in, ae_ok, lo, n_acc
            )
        if rcf:
            # Same masks as the value plane: non-config entries ship 0 and
            # scrub stale config commands off reused slots (raft.py).
            log_cfg_arr = log_ops.write_window_rb(
                s.log_cfg, prev_i, ent_cfg_in, ae_ok, lo, n_acc
            )
    else:
        log_term_arr = log_ops.write_window_b(s.log_term, prev_i, ent_term_in, ae_ok, n_ent)
        log_val_arr = log_ops.write_window_b(s.log_val, prev_i, ent_val_in, ae_ok, n_ent)
        if track:
            log_tick_arr = log_ops.write_window_b(
                s.log_tick, prev_i, ent_tick_in, ae_ok, n_ent
            )
        if rcf:
            log_cfg_arr = log_ops.write_window_b(
                s.log_cfg, prev_i, ent_cfg_in, ae_ok, n_ent
            )
    if not track:
        log_tick_arr = s.log_tick  # untouched: loop-invariant carry leg
    if not rcf:
        log_cfg_arr = s.log_cfg  # untouched: loop-invariant carry leg

    # The floor at 0 is a no-op on the ae_ok path (prev_i/n_acc are
    # non-negative for a real AE) but bounds the masked-garbage lane so the
    # int8/int16 a_match narrowing below is provably in range (Pass E).
    last_new = jnp.maximum(jnp.minimum(prev_i + n_acc, log_len), 0)
    commit = jnp.where(
        ae_ok,
        jnp.maximum(s.commit_index, jnp.minimum(lcommit, last_new)),
        s.commit_index,
    )

    # Snapshot install (raft.py phase 3): adopt the sender's compaction state,
    # retaining our suffix when it extends through L with the snapshot's term.
    if comp:
        L = jnp.where(snap, pick_h(mb.req_base), 0)
        Lt = pick_h(mb.req_base_term)
        Lchk = jnp.sum(jnp.where(sel, mb.req_base_chk[:, None, :], jnp.uint32(0)), axis=0)
        apply_snap = snap & (L > base)
        keep = (
            apply_snap
            & (L <= s.log_len)
            & (log_ops.term_at_rb(s.log_term, base, bterm, L) == Lt)
        )
        wipe = apply_snap & ~keep
        bterm = jnp.where(apply_snap, Lt, bterm)
        bchk = jnp.where(apply_snap, Lchk, bchk)
        base = jnp.where(apply_snap, L, base)
        log_len = jnp.where(wipe, L, log_len)
        commit = jnp.where(apply_snap, jnp.maximum(commit, L), commit)
        if rcf:
            # Snapshot config context installs with the snapshot (raft.py).
            Lmold = jnp.sum(
                jnp.where(
                    sel[:, :, None, :], mb.req_base_mold[:, None], jnp.uint32(0)
                ),
                axis=0,
            )  # [N, W, B]
            bmold = jnp.where(apply_snap[:, None, :], Lmold, bmold)
            bpend = jnp.where(apply_snap, pick_h(mb.req_base_pend), bpend)
            bepoch = jnp.where(apply_snap, pick_h(mb.req_base_epoch), bepoch)
    else:
        apply_snap = snap

    # [leader, follower] is already the response orientation [receiver, responder]
    # (snapshot installs always ack, with match = the snapshot index); the payload
    # is per responder -- at most one success target, one shared nack hint
    # (raft.py phase 3, Mailbox docstring).
    ar_out = is_ae
    if comp:
        a_ok = ae_ok | snap
        out_a_match = jnp.where(snap, L, jnp.where(ae_ok, last_new, 0))
    else:
        a_ok = ae_ok
        out_a_match = jnp.where(ae_ok, last_new, 0)
    idt = s.next_index.dtype
    out_a_ok_to = jnp.where(a_ok, ae_src, NIL).astype(node_dtype(cfg))  # NIL = no success
    out_a_match = out_a_match.astype(idt)  # bounded by the responder's log length
    out_a_hint = log_len.astype(idt)  # post-append, pre-injection (phase 6 rebinds)

    # ---- phase 3.5: PreVote requests (thesis 9.6; raft.py) -----------------------
    if cfg.pre_vote or rdl or rcf:
        # heard_clock serves the pre-vote quiet rule, the lease vote denial,
        # and the log-carried-config removed-server denial (phase 2) -- any
        # gate keeps the leg live (raft.py).
        clock_pv = s.clock + inp.skew  # phase 7's clock; duplicated, CSE'd
        heard = jnp.where(has_ae, clock_pv, s.heard_clock)  # [N, B]
    else:
        heard = s.heard_clock
    if cfg.pre_vote:
        is_pv = req_in & (mb.req_type == REQ_PREVOTE)[:, None, :]  # [cand, voter, B]
        quiet = (clock_pv - heard >= cfg.election_min_ticks) & (role != LEADER)
        pv_grant = (
            is_pv
            & (mb.req_term[:, None, :] >= term[None, :, :])
            & up_to_date
            & quiet[None, :, :]
        )
        pv_out = is_pv

    # ---- phase 3.7: TimeoutNow receipt (thesis 3.10; raft.py) --------------------
    if xfr:
        rcv_ids = iota((1, n, 1), 1)  # [1, N(receiver), 1]
        is_tn = req_in & (mb.req_type == REQ_TIMEOUT_NOW)[:, None, :]
        tn_cur = (
            is_tn
            & (mb.xfer_tgt[:, None, :] == rcv_ids)
            & (mb.req_term[:, None, :] == term[None, :, :])
        )
        xfer_elect = jnp.any(tn_cur, axis=0) & inp.alive & (role != LEADER)
        if rcf:
            xfer_elect = xfer_elect & member_b  # non-voters never campaign
        if not cfg.xfer_election:
            # TEST-ONLY mutant: transfer as a coup (raft.py phase 3.7).
            coup = xfer_elect
            term = term + coup
            role = jnp.where(coup, LEADER, role)
            leader_id = jnp.where(coup, ids2, leader_id)
            xfer_elect = jnp.zeros_like(coup)
        else:
            coup = jnp.zeros_like(xfer_elect)

    # ---- phase 4: responses ------------------------------------------------------
    vresp = resp_in & (mb.resp_kind == RESP_VOTE)
    new_votes = (
        vresp
        & (mb.v_to[None, :, :] == ids2[:, None, :])
        & (mb.resp_term[None, :, :] == term[:, None, :])
        & (role == CANDIDATE)[:, None, :]
    )
    votes = votes | bitplane.pack(new_votes, axis=1)
    # Packed-quorum test: word popcount over [N, W, B] instead of a bool-plane
    # sum over [N, N, B] (raft.py phase 4); configuration-masked (dual during
    # joint phases) when the reconfiguration plane is live.
    win = (role == CANDIDATE) & packed_quorum(votes) & inp.alive
    if rcf:
        win = win & member_b  # a removed node cannot win on banked votes
    if xfr and not cfg.xfer_election:
        win = win | coup  # mutant coups ride the fresh-leader bookkeeping
    role = jnp.where(win, LEADER, role)
    leader_id = jnp.where(win, ids2, leader_id)
    # Log indices are capacity-bounded (config caps log_capacity): the [N, N, B]
    # bookkeeping planes and their intermediates ride int8/int16, cutting their
    # HBM cost 4x/2x vs int32. Compaction carries absolute indices: int32
    # (types.index_dtype).
    len_i = log_len.astype(s.next_index.dtype)
    next_index = jnp.where(win[:, None, :], (len_i + 1)[:, None, :], s.next_index)
    match_index = jnp.where(win[:, None, :], 0, s.match_index)

    # ---- phase 4.5: PreVote responses + promotion (thesis 9.6; raft.py) ----------
    if cfg.pre_vote:
        # Grant bits ride the packed pv_grant plane (raft.py phase 4.5).
        pvresp = resp_in & (mb.resp_kind == RESP_PREVOTE)
        new_pv = jnp.where(
            (role == PRECANDIDATE)[:, None, :],
            bitplane.pack(pvresp, axis=1) & mb.pv_grant,
            zw,
        )
        votes = votes | new_pv
        pre_win = (role == PRECANDIDATE) & packed_quorum(votes) & inp.alive
        if rcf:
            pre_win = pre_win & member_b
        term = term + pre_win
        role = jnp.where(pre_win, CANDIDATE, role)
        voted_for = jnp.where(pre_win, ids2, voted_for)
        # votes is uint32 now: a plain select (the i1-select Mosaic caveat that
        # forced boolean arithmetic here no longer applies to this plane).
        votes = jnp.where(pre_win[:, None, :], eye_p3, votes)
    else:
        pre_win = jnp.zeros_like(win)

    aresp = (
        resp_in
        & (mb.resp_kind == RESP_APPEND)
        & (role == LEADER)[:, None, :]
        & (mb.resp_term[None, :, :] == term[:, None, :])
    )
    ok_mine = mb.a_ok_to[None, :, :] == ids2[:, None, :]
    a_succ = aresp & ok_mine
    a_fail = aresp & ~ok_mine
    am = mb.a_match[None, :, :]  # already index_dtype (bounded by log length)
    ah = mb.a_hint[None, :, :]
    match_index = jnp.where(a_succ, jnp.maximum(match_index, am), match_index)
    next_index = jnp.where(a_succ, jnp.maximum(next_index, am + 1), next_index)
    # Failure: back off to min(next-1, hint+1) (conflict-index hint; raft.py).
    next_index = jnp.where(
        a_fail, jnp.maximum(jnp.minimum(next_index - 1, ah + 1), 1), next_index
    )
    # Responsiveness ages for the shared-window filter (phase 8; see raft.py).
    ack_age = jnp.minimum(s.ack_age + 1, cfg.ack_age_sat)
    ack_age = jnp.where(win[:, None, :] | aresp, 0, ack_age)

    # ---- phase 5: leader commit advancement --------------------------------------
    is_leader = role == LEADER
    if dur and cfg.durable_acks:
        # A leader's own vote for a replication quorum is its DURABLE length
        # (raft.py phase 5: the leader's disk is a follower too).
        dmi = dur_mid.astype(len_i.dtype)
        match_with_self = jnp.where(eye_ls, dmi[:, None, :], match_index)
    else:
        match_with_self = jnp.where(eye_ls, len_i[:, None, :], match_index)  # [N, N, B]
    # quorum-th largest match without a sort (TPU sorts along a non-minor axis are
    # slow). Two equivalent counting forms; pick per static shapes:
    #   cap < n  (config5: N=51, CAP=16): match values are bounded by CAP, so count
    #     how many matches reach each threshold v in 1..CAP; cnt_ge is non-increasing
    #     in v, so the quorum-th order statistic is the number of thresholds reached
    #     by >= quorum matches. O(N*CAP) compares per leader.
    #   n <= cap (configs 1-4, CAP up to 2048): threshold over the N match values
    #     themselves -- the quorum-th largest is the largest element v with
    #     count(match >= v) >= quorum. O(N^2) compares per leader, independent of CAP
    #     (the CAP-threshold form would do ~6x the work at N=5, CAP=32 and ~400x at
    #     config1's CAP=2048).
    if rcf:
        # Per-leader configuration-masked quorum match (raft.py phase 5):
        # candidates range over the members' own match values under EACH
        # leader's OWN derived member rows; dual (min of both configs)
        # while that leader's prefix is joint.
        mws = match_with_self
        ge_m = mws[:, None, :, :] >= mws[:, :, None, :]  # [i, j(cand), k, B]

        def masked_qmatch(mask_b, maj):
            # mask_b [N(i), N(k), B]: node i's member view; maj [N(i), B].
            cnt = jnp.sum(ge_m & mask_b[:, None, :, :], axis=2)  # [N, N, B]
            ok = (cnt >= maj[:, None, :]) & mask_b
            return jnp.max(jnp.where(ok, mws, 0), axis=1).astype(jnp.int32)

        mem_old_b = bitplane.unpack(m_old, n, axis=1)  # [N, N, B]
        mem_new_b = bitplane.unpack(m_new, n, axis=1)
        qm_old = masked_qmatch(mem_old_b, maj_old)
        quorum_match = jnp.where(
            joint,
            jnp.minimum(qm_old, masked_qmatch(mem_new_b, maj_new)),
            qm_old,
        )
    elif cap < n and not comp:
        # Thresholds 1..CAP only bound match values when indices are capacity-
        # bounded; compaction's absolute indices use the value-threshold form.
        vth = (iota((1, 1, cap, 1), 2) + 1).astype(match_with_self.dtype)  # 1..CAP
        cnt_ge = jnp.sum(match_with_self[:, :, None, :] >= vth, axis=1)  # [N, CAP, B]
        quorum_match = jnp.sum(cnt_ge >= cfg.quorum, axis=1).astype(jnp.int32)  # [N, B]
    else:
        ge = (
            match_with_self[:, None, :, :] >= match_with_self[:, :, None, :]
        )  # [N, j(candidate), k(counted), B]
        if sh is not None:
            # Pad peers carry match 0 and every candidate is >= 0: unmasked
            # they would inflate the count by (n_pad - n) for every candidate.
            ge = ge & (iota((1, 1, npd, 1), 2) < n)
        ok = jnp.sum(ge, axis=2) >= cfg.quorum  # [N, N, B]
        quorum_match = jnp.max(jnp.where(ok, match_with_self, 0), axis=1)  # [N, B]
    if comp:
        quorum_term = log_ops.term_at_rb(log_term_arr, base, bterm, quorum_match)
    else:
        quorum_term = log_ops.term_at_b(log_term_arr, quorum_match)
    commit = jnp.where(
        is_leader & inp.alive & (quorum_match > commit) & (quorum_term == term),
        quorum_match,
        commit,
    )

    # ---- phase 5.2: reconfiguration transitions moved INTO the log --------------
    # (Log-carried membership: no admin transition block. Joint entry/exit
    # are LOG APPENDS -- phase 6 originates them, phase 3 replicates them --
    # and each node's configuration re-derives from its own prefix at end of
    # tick; raft.py for the full rationale.)
    if xfr:
        tgt_oh_x = iota((1, n, 1), 1) == jnp.clip(s.xfer_to, 0, n - 1)[:, None, :]
        age_t = jnp.sum(jnp.where(tgt_oh_x, ack_age, 0), axis=1)  # one-hot gather
        keep_x = is_leader & (s.xfer_to != NIL) & (age_t <= cfg.ack_timeout_ticks)
        xfer_to = jnp.where(keep_x, s.xfer_to, NIL)
        t_x = inp.transfer_cmd  # [B]
        ld_ok_x = is_leader & inp.alive
        if rcf:
            ld_ok_x = ld_ok_x & member_b
            # Target must be a voter of the LEADER's own target config
            # (per-node derived rows; tick-start like every config read).
            t_voter = jnp.any(
                (m_new & bitplane.one_bit(t_x, n)[None]) != 0, axis=1
            )  # [N, B]
        else:
            t_voter = jnp.bool_(True)
        ldx = jnp.min(jnp.where(ld_ok_x, ids2, n), axis=0)  # [B]
        can_x = (
            (t_x != NIL)[None, :]
            & t_voter
            & (ids2 == ldx[None, :])
            & ld_ok_x
            & (t_x[None, :] != ids2)
            & (xfer_to == NIL)
        )
        xfer_to = jnp.where(can_x, t_x[None, :], xfer_to)
        xfer_pend = xfer_to != NIL
    if rdx:
        pend0 = s.read_idx > 0  # [N, B]
        keep_r = is_leader & pend0
        read_acks = jnp.where(
            keep_r[:, None, :], s.read_acks | bitplane.pack(aresp, axis=1), zw
        )
        if cfg.read_confirm:
            serve = keep_r & inp.alive & packed_quorum(read_acks | eye_p3)
        else:
            serve = keep_r & inp.alive  # TEST-ONLY mutant: no confirmation
        if rdl:
            # Lease fast path on the global-tick ack_age plane; the
            # lease_skew_safe mutant widens the window to the no-skew bound
            # election_min_ticks + 2 (raft.py phase 5 for the argument).
            lease_w = (
                cfg.read_lease_ticks
                if cfg.lease_skew_safe
                else cfg.election_min_ticks + 2
            )
            fresh_p = bitplane.pack(ack_age <= lease_w, axis=1)  # [N, W, B]
            lease_ok = packed_quorum(fresh_p | eye_p3)
            if xfr:
                # Transfer handoff covers the read path (raft.py phase 5).
                lease_ok = lease_ok & ~xfer_pend
            serve = serve | (keep_r & inp.alive & lease_ok)
        lat_r = jnp.maximum(s.now[None, :] + 1 - s.read_tick, 1)  # [N, B]
        reads_served = jnp.sum(serve, axis=0).astype(jnp.int32)
        read_lat_sum = jnp.sum(jnp.where(serve, lat_r, 0), axis=0).astype(jnp.int32)
        bin_r = log_ops.log2_bin(lat_r, LAT_HIST_BINS)
        oh_r = (
            iota((1, LAT_HIST_BINS, 1), 1) == bin_r[:, None, :]
        ) & serve[:, None, :]
        read_hist = jnp.sum(oh_r, axis=0).astype(jnp.int32)  # [BINS, B]
        if comp:
            cur_committed = (
                log_ops.term_at_rb(log_term_arr, base, bterm, commit) == term
            )
        else:
            cur_committed = log_ops.term_at_b(log_term_arr, commit) == term
        can_cap = (inp.read_cmd != NIL)[None, :] & is_leader & inp.alive & ~pend0
        if cfg.read_confirm:
            can_cap = can_cap & cur_committed
        if xfr:
            can_cap = can_cap & ~xfer_pend
        low_cap = jnp.min(jnp.where(can_cap, ids2, n), axis=0)  # [B]
        cap_r = can_cap & (ids2 == low_cap[None, :])
        cleared = serve | (pend0 & ~keep_r)
        read_idx = jnp.where(cap_r, commit + 1, jnp.where(cleared, 0, s.read_idx))
        read_tick = jnp.where(
            cap_r, s.now[None, :] + 1, jnp.where(cleared, 0, s.read_tick)
        )
        read_acks = jnp.where((cap_r | serve)[:, None, :], zw, read_acks)
        if rdl:
            # Staleness anchor + device invariant (raft.py phase 5).
            fr_now = jnp.maximum(s.lat_frontier, jnp.max(commit, axis=0))  # [B]
            read_fr = jnp.where(
                cap_r, fr_now[None, :], jnp.where(cleared, 0, s.read_fr)
            )
            if cfg.check_invariants:
                viol_read_stale = jnp.any(
                    serve & (s.read_idx - 1 < s.read_fr), axis=0
                )
            else:
                viol_read_stale = np.zeros((b,), np.bool_)
        else:
            viol_read_stale = np.zeros((b,), np.bool_)
    else:
        # Constants, not jnp.zeros: keep the disabled-mode lowered program
        # byte-identical (see raft.py).
        reads_served = np.zeros((b,), np.int32)
        read_lat_sum = np.zeros((b,), np.int32)
        read_hist = np.zeros((LAT_HIST_BINS, b), np.int32)
        viol_read_stale = np.zeros((b,), np.bool_)

    # ---- offer->commit latency (client workloads only; raft.py) ------------------
    if track:
        sl = iota((1, cap, 1), 1)
        if comp:
            abs1 = base[:, None, :] + (sl - base[:, None, :]) % cap + 1
        else:
            abs1 = sl + 1
        # Carried-frontier dedup; stamps read from the offer-tick plane, never
        # from values (raft.py).
        newly = (abs1 > s.lat_frontier[None, None, :]) & (abs1 <= commit[:, None, :])
        cli = (log_tick_arr >= 1) & (log_tick_arr <= s.now[None, None, :])
        lm = (is_leader & inp.alive)[:, None, :] & newly & cli
        lats = jnp.where(lm, s.now[None, None, :] - log_tick_arr + 1, 0)  # [N, CAP, B]
        lat_sum = gsum(jnp.sum(lats, axis=(0, 1)).astype(jnp.int32))
        lat_cnt = gsum(jnp.sum(lm, axis=(0, 1)).astype(jnp.int32))
        # Coverage gap counter: crossed-but-unattributed client entries, read
        # on the lowest-id max-commit node (raft.py for the full rationale).
        is_maxc = commit == gmax(jnp.max(commit, axis=0))[None, :]
        hnode = gmin(jnp.min(jnp.where(is_maxc, ids2, n), axis=0))  # [B]
        crossed = (ids2 == hnode[None, :])[:, None, :] & newly & cli
        lat_excluded = jnp.maximum(
            gsum(jnp.sum(crossed, axis=(0, 1)).astype(jnp.int32)) - lat_cnt, 0
        )
        # Histogram bin = floor(log2(l)) (log_ops.log2_bin; raft.py).
        bin_ = log_ops.log2_bin(lats, LAT_HIST_BINS)
        oh_b = (iota((1, 1, LAT_HIST_BINS, 1), 2) == bin_[:, :, None, :]) & lm[:, :, None, :]
        lat_hist = gsum(jnp.sum(oh_b, axis=(0, 1)).astype(jnp.int32))  # [BINS, B]
        lat_frontier = jnp.maximum(s.lat_frontier, gmax(jnp.max(commit, axis=0)))
    else:
        lat_sum = jnp.zeros_like(s.now)
        lat_cnt = jnp.zeros_like(s.now)
        lat_hist = jnp.zeros((LAT_HIST_BINS, b), jnp.int32)
        lat_excluded = jnp.zeros_like(s.now)
        lat_frontier = s.lat_frontier

    # ---- phase 5.5: log compaction (raft.py) -------------------------------------
    base_mid, bchk_mid = base, bchk  # post-install, pre-advance (checksum anchor)
    if comp:
        target = jnp.minimum(commit, log_len - (cap - cfg.compact_margin))
        base2 = jnp.maximum(base, target)
        bterm = log_ops.term_at_rb(log_term_arr, base, bterm, base2)  # = bterm if unchanged
        if rcf:
            # Fold the compacted span's config entries into the snapshot
            # context (cfglog.fold_span; anchored at the PRE-advance base,
            # same aliasing rule as the checksum pass -- raft.py phase 5.5).
            bmold, bpend, bepoch = cfglog.fold_span(
                cfg, log_cfg_arr, base, base2, bmold, bpend, bepoch,
                batched=True,
            )
        base = base2

    # ---- committed-prefix checksum, compaction form (raft.py: anchored at
    # base_mid, MUST run before phase 6 -- an injection into a slot freed by this
    # tick's rebase would alias under the anchored slot->index map; maintained
    # even with invariant checking off, since base_chk is load-bearing wire
    # state). The non-compaction form has no aliasing hazard and stays at its
    # original post-outbox position (placement affects XLA fusion of the hot
    # configs).
    if comp:
        co = jnp.maximum(s.commit_index, base_mid)  # snap installs skip the check
        s_co, s_bf, s_cn = log_ops.ring_chk_b(
            log_term_arr, log_val_arr, base_mid, (co, base, commit)
        )
        if cfg.check_invariants:
            chk_ok = (bchk_mid + s_co == s.commit_chk) | apply_snap
        else:
            chk_ok = jnp.ones_like(s.commit_index, dtype=bool)
        bchk = bchk_mid + s_bf
        chk_new = bchk_mid + s_cn

    # ---- phase 6: client command injection, redirect routing, election-win
    # no-op (raft.py phase 6) --------------------------------------------------------
    if comp:
        reserve = max(1, cfg.compact_margin // 2)
        noop = win & (log_len - base < cap)
        room = log_len - base < cap - reserve
        # Win with no no-op room: surfaced as a liveness metric (raft.py).
        noop_blocked = gsum(
            jnp.sum(win & ~(log_len - base < cap), axis=0).astype(jnp.int32)
        )
    else:
        noop = jnp.zeros_like(is_leader)
        room = log_len - base < cap
        noop_blocked = jnp.zeros_like(s.now)
    # ---- config-entry origination (log-carried membership; raft.py phase 6
    # for the full rationale: joint entry on the admin toggle, final entry
    # once the governing joint entry commits on the leader, both judged on
    # the leader's OWN tick-start derived configuration, sharing the
    # one-append-per-node slot at priority no-op > config > client) ---------------
    if rcf:
        t_r = inp.reconfig_cmd  # [B]
        tbit = bitplane.one_bit(t_r, n)  # [W, B]; all-zero column for NIL
        toggled = m_new ^ tbit[None]  # [N, W, B]: each node's view of the result
        ld_ok = is_leader & inp.alive & member_b & room & ~noop  # [N, B]
        ldj = jnp.min(jnp.where(ld_ok & ~joint, ids2, n), axis=0)  # [B]
        accept_j = (
            (t_r != NIL)[None, :]
            & (ids2 == ldj[None, :])
            & ld_ok
            & ~joint
            & (bitplane.count(tbit, axis=0) > 0)[None, :]
            & (bitplane.count(toggled, axis=1) >= 2)
        )
        if cfg.joint_consensus:
            # Pending toggle of this node's open joint phase: the one bit
            # its member_old and member_new rows differ on.
            pvbits = bitplane.unpack(m_old ^ m_new, n, axis=1)  # [N, N, B]
            pend_v = jnp.min(
                jnp.where(pvbits, iota((1, n, 1), 1), n), axis=1
            )  # [N, B]
            accept_f = ld_ok & joint & (commit >= s.cfg_pend)
            cfg_code = jnp.where(
                accept_j, t_r[None, :] + 1, jnp.where(accept_f, -(pend_v + 1), 0)
            ).astype(jnp.int32)
            cfg_write = accept_j | accept_f
        else:
            # TEST-ONLY mutant (single-server change; raft.py phase 6).
            cfg_code = jnp.where(accept_j, t_r[None, :] + 1, 0).astype(jnp.int32)
            cfg_write = accept_j
    if cfg.client_redirect:
        # K-deep in-flight pipeline: first free slot takes a fresh offer, at
        # most one slot accepted per node per tick, lowest slot first
        # (raft.py phase 6).
        kdim = cfg.client_pipeline
        kk3 = iota((kdim, 1, 1), 0)  # [K, 1, 1]
        free = s.client_pend == NIL  # [K, B]
        first_free = free & (jnp.cumsum(free, axis=0) == 1)
        fresh = (inp.client_cmd != NIL)[None, :] & first_free
        pend = jnp.where(fresh, inp.client_cmd[None, :], s.client_pend)  # [K, B]
        tgt = jnp.where(fresh, inp.client_target[None, :], s.client_dst)
        # Offer stamp rides the slot beside the payload (raft.py phase 6).
        ptick = (
            jnp.where(fresh, (s.now + 1)[None, :], s.client_tick) if track else None
        )
        active = pend != NIL
        tgt_oh = active[:, None, :] & (tgt[:, None, :] == iota((1, n, 1), 1))  # [K, N, B]
        low_k = jnp.min(jnp.where(tgt_oh, kk3, kdim), axis=0)  # [N, B]
        node_ok = is_leader & inp.alive & room & ~noop  # [N, B]
        if rcf:
            node_ok = node_ok & ~cfg_write  # the slot holds a config entry
        if xfr:
            node_ok = node_ok & ~xfer_pend  # transfer lease handoff (raft.py)
        client_ok = (low_k < kdim) & node_ok  # [N, B] nodes accepting a slot
        sel_k = tgt_oh & (kk3 == low_k[None, :, :]) & node_ok[None, :, :]  # [K, N, B]
        wval_cl = jnp.sum(jnp.where(sel_k, pend[:, None, :], 0), axis=0)  # [N, B]
        wtick_cl = (
            jnp.sum(jnp.where(sel_k, ptick[:, None, :], 0), axis=0) if track else None
        )
        accepted_k = jnp.any(sel_k, axis=1)  # [K, B]
        cmds_cnt = jnp.sum(accepted_k, axis=0).astype(jnp.int32)  # [B]
        tgt_ld = jnp.max(jnp.where(tgt_oh, leader_id[None, :, :], NIL), axis=1)  # [K, B]
        tgt_up = jnp.any(tgt_oh & inp.alive[None, :, :], axis=1)
        pend_on = active & ~accepted_k
        client_pend = jnp.where(pend_on, pend, NIL)
        client_dst = jnp.where(
            pend_on, jnp.where(tgt_up & (tgt_ld != NIL), tgt_ld, inp.client_bounce), 0
        )
        client_tick = jnp.where(pend_on, ptick, 0) if track else s.client_tick
    else:
        client_ok = (inp.client_cmd[None, :] != NIL) & is_leader & inp.alive & room & ~noop
        if rcf:
            client_ok = client_ok & ~cfg_write  # the slot holds a config entry
        if xfr:
            client_ok = client_ok & ~xfer_pend  # transfer lease handoff
        wval_cl = jnp.broadcast_to(inp.client_cmd[None, :], (nl, b))
        # Direct mode accepts on the offer tick: stamp = now + 1 (raft.py).
        wtick_cl = (
            jnp.broadcast_to((s.now + 1)[None, :], (nl, b)) if track else None
        )
        cmds_cnt = gany(jnp.any(client_ok, axis=0)).astype(jnp.int32)  # offers, not appends
        client_pend = s.client_pend
        client_dst = s.client_dst
        client_tick = s.client_tick
    do_write = (noop | cfg_write | client_ok) if rcf else (noop | client_ok)
    wval = jnp.where(noop, NOOP, wval_cl)  # [N, B]
    if rcf:
        # Config entries carry value 0 (the command rides the log_cfg plane).
        wval = jnp.where(cfg_write, 0, wval)
    # cap matches no slot -> masked-off writes dropped.
    inj_pos = jnp.where(do_write, log_len % cap if comp else log_len, cap)  # [N, B]
    inj_oh = iota((1, cap, 1), 1) == inj_pos[:, None, :]  # [N, CAP, B]
    log_term_arr = jnp.where(inj_oh, term[:, None, :], log_term_arr)
    log_val_arr = jnp.where(inj_oh, wval[:, None, :], log_val_arr)
    if track:
        # No-op entries carry stamp 0 (protocol filler, never a client offer).
        wtick = jnp.where(noop, 0, wtick_cl)  # [N, B]
        if rcf:
            wtick = jnp.where(cfg_write, 0, wtick)  # config entries too
        log_tick_arr = jnp.where(inj_oh, wtick[:, None, :], log_tick_arr)
    if rcf:
        # EVERY append writes the config plane (0 for non-config entries):
        # a slot reused after truncation must never leak its old command.
        log_cfg_arr = jnp.where(
            inj_oh, jnp.where(cfg_write, cfg_code, 0)[:, None, :], log_cfg_arr
        )
    log_len = log_len + do_write

    # ---- phase 7: timers ---------------------------------------------------------
    clock = s.clock + inp.skew
    reset_election = granted_any | has_ae | saw_higher
    deadline = jnp.where(reset_election, clock + inp.timeout_draw, s.deadline)
    deadline = jnp.where(win, clock + cfg.heartbeat_ticks, deadline)
    if cfg.pre_vote:
        deadline = jnp.where(pre_win, clock + inp.timeout_draw, deadline)
    expired = (clock >= deadline) & inp.alive

    heartbeat = expired & is_leader
    deadline = jnp.where(heartbeat, clock + cfg.heartbeat_ticks, deadline)

    if cfg.pre_vote:
        # Expiry starts a PRE-vote probe: no term bump, votedFor untouched
        # (raft.py phase 7); real elections start at promotions (phase 4.5).
        start_prevote = expired & ~is_leader
        if rcf:
            # Non-voters never campaign, judged on the node's OWN derived
            # config (raft.py phase 7: the disruption surface when a log
            # misses its removal entry).
            start_prevote = start_prevote & member_b
        if xfr:
            start_prevote = start_prevote & ~xfer_elect  # thesis-3.10 bypass
        role = jnp.where(start_prevote, PRECANDIDATE, role)
        leader_id = jnp.where(start_prevote, NIL, leader_id)
        votes = jnp.where(start_prevote[:, None, :], eye_p3, votes)
        deadline = jnp.where(start_prevote, clock + inp.timeout_draw, deadline)
        start_election = pre_win
        if xfr:
            # TimeoutNow election (raft.py phase 7): the real-election start
            # minus the pre-quorum; ~is_leader re-checked (a phase-4 win may
            # have promoted the target this very tick).
            xe = xfer_elect & ~pre_win & ~is_leader
            term = term + xe
            role = jnp.where(xe, CANDIDATE, role)
            voted_for = jnp.where(xe, ids2, voted_for)
            leader_id = jnp.where(xe, NIL, leader_id)
            votes = jnp.where(xe[:, None, :], eye_p3, votes)
            deadline = jnp.where(xe, clock + inp.timeout_draw, deadline)
            start_election = pre_win | xe
    else:
        start_prevote = jnp.zeros_like(expired)
        start_election = expired & ~is_leader
        if rcf:
            start_election = start_election & member_b  # non-voters never campaign
        if xfr:
            xe = xfer_elect & ~is_leader
            start_election = start_election | xe
        term = term + start_election
        role = jnp.where(start_election, CANDIDATE, role)
        voted_for = jnp.where(start_election, ids2, voted_for)
        leader_id = jnp.where(start_election, NIL, leader_id)
        votes = jnp.where(start_election[:, None, :], eye_p3, votes)
        deadline = jnp.where(start_election, clock + inp.timeout_draw, deadline)

    # ---- phase 7.5: fsync flush + durability gates (raft.py phase 7.5) -----------
    if dur:
        fs_fire = inp.fsync_fire & inp.alive  # dead disks never flush
        dur2_len, dur2_term, dur2_vote = storage_plane.flush(
            fs_fire, dur_mid, s.dur_term, s.dur_vote, log_len, term, voted_for
        )
        if cfg.durable_acks:
            # Gate 1 (ack durability): AE acks reflect only the fsynced
            # prefix (raft.py phase 7.5).
            out_a_match = jnp.minimum(
                out_a_match.astype(jnp.int32), dur2_len
            ).astype(idt)
            # Gate 2 (vote durability): a grant is exposed only once the
            # durable snapshot covers it; the covering flush emits the
            # withheld response (late_grant -> outbox overlay below).
            covered0 = storage_plane.covered(s.dur_term, s.dur_vote, term, voted_for)
            covered2 = storage_plane.covered(dur2_term, dur2_vote, term, voted_for)
            grant_to = jnp.where(covered2, voted_for, NIL).astype(
                node_dtype(cfg)
            )
            late_grant = covered2 & ~covered0 & ~granted_any

    # ---- phase 8: outbox ---------------------------------------------------------
    send_append = win | heartbeat
    if comp:
        new_last_idx = log_len
        new_last_term = log_ops.term_at_rb(log_term_arr, base, bterm, log_len)
    else:
        new_last_idx, new_last_term = log_ops.last_index_term_b(log_term_arr, log_len)

    # Request headers are per sender (both RPCs are broadcasts); only the AE window
    # offset is per edge (Mailbox docstring; raft.py phase 8).
    ae_edge = send_append[:, None, :] & ~eye_ls
    out_req_type = jnp.where(
        start_election, REQ_VOTE, jnp.where(send_append, REQ_APPEND, 0)
    )  # [N, B]
    if cfg.pre_vote:
        out_req_type = jnp.where(start_prevote, REQ_PREVOTE, out_req_type)
        rv_like = start_election | start_prevote
    else:
        rv_like = start_election
    out_req_term = jnp.where(out_req_type != 0, term, 0)
    if cfg.pre_vote:
        out_req_term = jnp.where(start_prevote, term + 1, out_req_term)  # prospective
    if xfr:
        # TimeoutNow fire (raft.py phase 8): replaces the heartbeat slot on
        # catch-up; AE window fields stay populated (receivers gate on
        # req_type == REQ_APPEND).
        tgt_oh8 = iota((1, n, 1), 1) == jnp.clip(xfer_to, 0, n - 1)[:, None, :]
        t_match = jnp.sum(
            jnp.where(tgt_oh8, match_index, 0), axis=1, dtype=jnp.int32
        )
        if cfg.xfer_election:
            caught = t_match >= log_len
        else:
            caught = jnp.ones_like(log_len, bool)  # TEST-ONLY mutant: no wait
        fire = send_append & (xfer_to != NIL) & caught
        out_req_type = jnp.where(fire, REQ_TIMEOUT_NOW, out_req_type)
        out_xfer_tgt = jnp.where(fire, xfer_to, NIL).astype(node_dtype(cfg))
    else:
        out_xfer_tgt = mb.xfer_tgt  # NIL, loop-invariant carry component
    if xfr and (rcf or rdl):
        # The disruptive-RequestVote override (thesis 3.10/4.2.3; raft.py
        # phase 8): written only when a denial gate can read it.
        out_req_disrupt = jnp.where(xe, 1, 0).astype(jnp.int8)
    else:
        out_req_disrupt = mb.req_disrupt  # zeros, loop-invariant component
    prev_out = jnp.clip(next_index - 1, 0, len_i[:, None, :])  # [src, dst, B]
    # Shared window start: minimum prev over RESPONSIVE peers, falling back to all
    # peers when none are (see raft.py phase 8 for the liveness argument).
    responsive = ack_age <= cfg.ack_timeout_ticks
    if comp:
        big = jnp.int32(2**31 - 1)
        ws_resp = jnp.min(jnp.where(pad_self | ~responsive, big, prev_out), axis=1)  # [N, B]
        ws_all = jnp.min(jnp.where(pad_self, big, prev_out), axis=1)
        ws = jnp.where(ws_resp == big, ws_all, ws_resp)
    else:
        # Single [N, N, B] min instead of two: unresponsive peers ride +K and
        # self +2K with K = cap + 1, so the min is the responsive minimum when
        # one exists, else K + the all-peers minimum (self cannot win it:
        # 2K > K + cap, and with n >= 2 some non-self edge is <= K + cap). The
        # largest encoded value, 3*cap + 2, fits the index dtype by construction
        # (types.MAX_INT8_LOG_CAPACITY / config.MAX_LOG_CAPACITY). Same values
        # as the two-pass form, one full reduction cheaper.
        K = jnp.asarray(cap + 1, len_i.dtype)
        z = jnp.asarray(0, len_i.dtype)
        # Pad peers ride the self (+2K) lane: a leader's win resets the whole
        # ack_age row, so they would otherwise pose as responsive (prev_out =
        # len-at-win) and drag the window start (pad_self == eye3 dense).
        off = prev_out + jnp.where(pad_self, K + K, jnp.where(responsive, z, K))
        m = jnp.min(off, axis=1)  # [N, B]
        # Both where-branches are non-negative under their conditions; the
        # explicit floor makes that a local (range-provable) fact.
        ws = jnp.maximum(jnp.where(m >= K, m - K, m), z)
    ws = jnp.minimum(ws, len_i)  # narrow dtype throughout; widened at header writes
    if comp:
        # The window cannot start below the compaction base; peers whose prev fell
        # below it get the InstallSnapshot sentinel (raft.py phase 8).
        ws = jnp.maximum(ws, base)
        snap_edge = ae_edge & (prev_out < base[:, None, :])
    # Clamp prev into [ws, ws+E] (see raft.py): the per-edge request payload then
    # reduces to the offset j = prev - ws in 0..E; receivers reconstruct prev,
    # prev_term, and n_entries from it and the per-sender header.
    # j = clip(prev, ws, ws+E) - ws == clip(prev - ws, 0, E): the latter form
    # bounds the offset *syntactically* (Pass E), where the subtract-after-clip
    # form only bounds it relationally.
    off_j = jnp.clip(prev_out - ws[:, None, :], 0, e)
    prev_out = ws[:, None, :] + off_j
    out_req_off = jnp.where(ae_edge, off_j, 0).astype(jnp.int8)
    if comp:
        out_req_off = jnp.where(snap_edge, jnp.int8(-1), out_req_off)
        wt = log_ops.window_rb(log_term_arr, ws, e)  # [N, E, B] shared window terms
        wv = log_ops.window_rb(log_val_arr, ws, e)
    else:
        wt = log_ops.window_b(log_term_arr, ws, e)
        wv = log_ops.window_b(log_val_arr, ws, e)
    n_ship = jnp.clip(log_len - ws, 0, e)  # [N, B]
    ship_used = send_append[:, None, :] & (iota((1, e, 1), 1) < n_ship[:, None, :])
    out_ent_term = jnp.where(ship_used, wt, 0)
    out_ent_val = jnp.where(ship_used, wv, 0)
    if track:
        wtk = (log_ops.window_rb if comp else log_ops.window_b)(log_tick_arr, ws, e)
        out_ent_tick = jnp.where(ship_used, wtk, 0)
    else:
        out_ent_tick = mb.ent_tick  # zeros, loop-invariant carry component
    if rcf:
        wcf = (log_ops.window_rb if comp else log_ops.window_b)(log_cfg_arr, ws, e)
        out_ent_cfg = jnp.where(ship_used, wcf, 0)
    else:
        out_ent_cfg = mb.ent_cfg  # zeros, loop-invariant carry component

    # Responses [receiver, responder]: the edge plane carries only the response
    # TYPE; payloads (grant target, ack target, match, hint, term) are per
    # responder (Mailbox response decode). The outbox is transpose-free and
    # broadcast-free: nothing [N, N]-shaped is written beyond the offset and
    # response-kind planes, both int8.
    out_resp_kind = (
        jnp.where(vr_out, RESP_VOTE, 0) + jnp.where(ar_out, RESP_APPEND, 0)
    ).astype(jnp.int8)
    if cfg.pre_vote:
        # The grant bit rides the packed pv_grant plane (raft.py phase 8).
        out_resp_kind = out_resp_kind + jnp.where(pv_out, RESP_PREVOTE, 0).astype(
            jnp.int8
        )
        if sh is None:
            out_pv_grant = bitplane.pack(pv_grant, axis=1)  # [cand, W(bit=voter), B]
        else:
            # Writer-major carry: the voter rows are local, candidates ride the
            # packed bits; _gather_mailbox reorients on read.
            out_pv_grant = bitplane.pack(jnp.swapaxes(pv_grant, 0, 1), axis=1)
    else:
        out_pv_grant = mb.pv_grant  # zeros, loop-invariant carry component
    if dur and cfg.durable_acks:
        # Late vote-completion response (phase 7.5 gate 2; raft.py for the
        # full argument and the AE-response collision guard).
        vfc = jnp.clip(voted_for, 0, n - 1)
        late_edge = (ids2[:, :, None] == vfc[None, :, :]) & late_grant[None, :, :]
        out_resp_kind = jnp.where(
            late_edge & (out_resp_kind == 0),
            jnp.int8(RESP_VOTE),
            out_resp_kind,
        )
    if comp:
        pterm = log_ops.term_at_rb(log_term_arr, base, bterm, ws)
    else:
        pterm = log_ops.term_at_b(log_term_arr, ws)

    new_mb = Mailbox(
        req_type=out_req_type,
        req_term=out_req_term,
        req_commit=jnp.where(send_append, commit, 0),
        req_last_index=jnp.where(rv_like, new_last_idx, 0),
        req_last_term=jnp.where(rv_like, new_last_term, 0),
        ent_start=jnp.where(send_append, ws.astype(jnp.int32), 0),
        ent_prev_term=jnp.where(send_append, pterm, 0),
        ent_count=jnp.where(send_append, n_ship, 0),
        ent_term=out_ent_term,
        ent_val=out_ent_val,
        ent_tick=out_ent_tick,
        # Without compaction the snapshot header is dead weight: pass the zeros
        # through untouched so XLA sees a loop-invariant carry component (raft.py).
        req_base=jnp.where(send_append, base, 0) if comp else mb.req_base,
        req_base_term=jnp.where(send_append, bterm, 0) if comp else mb.req_base_term,
        req_base_chk=(
            jnp.where(send_append, bchk, jnp.uint32(0)) if comp else mb.req_base_chk
        ),
        xfer_tgt=out_xfer_tgt,
        req_disrupt=out_req_disrupt,
        ent_cfg=out_ent_cfg,
        req_base_mold=(
            jnp.where(send_append[:, None, :], bmold, jnp.uint32(0))
            if (comp and rcf) else mb.req_base_mold
        ),
        req_base_pend=(
            jnp.where(send_append, bpend, 0) if (comp and rcf)
            else mb.req_base_pend
        ),
        req_base_epoch=(
            jnp.where(send_append, bepoch, 0) if (comp and rcf)
            else mb.req_base_epoch
        ),
        req_off=out_req_off,
        # Sharded carries are writer-major: the responder rows are local, so the
        # [resp-receiver, responder] plane is stored transposed (read path
        # reorients in _gather_mailbox).
        resp_kind=out_resp_kind if sh is None else jnp.swapaxes(out_resp_kind, 0, 1),
        pv_grant=out_pv_grant,
        v_to=grant_to,
        a_ok_to=out_a_ok_to,
        a_match=out_a_match,
        a_hint=out_a_hint,
        resp_term=term,
    )

    # Committed-prefix checksum, non-compaction form (log_ops module comment).
    if not comp:
        if cfg.check_invariants:
            chk_old, chk_new = log_ops.prefix_chk2_b(
                log_term_arr, log_val_arr, s.commit_index, commit
            )
            chk_ok = chk_old == s.commit_chk
        else:
            chk_new = s.commit_chk
            chk_ok = jnp.ones_like(s.commit_index, dtype=bool)

    # ---- end-of-tick config derivation (log-carried membership; raft.py) ---------
    if rcf:
        d_mold, d_mnew, d_pend, d_epoch, d_hi = cfglog.derive(
            cfg, log_cfg_arr, log_len, commit, base, bmold, bpend, bepoch,
            batched=True,
        )
        if not cfg.truncation_rollback:
            # TEST-ONLY mutant (ignore-truncation-rollback; raft.py).
            rolled = d_epoch < s.cfg_epoch
            d_mold = jnp.where(rolled[:, None, :], s.member_old, d_mold)
            d_mnew = jnp.where(rolled[:, None, :], s.member_new, d_mnew)
            d_pend = jnp.where(rolled, s.cfg_pend, d_pend)
            d_epoch = jnp.where(rolled, s.cfg_epoch, d_epoch)
        # Removed-server stepdown + candidacy kill (raft.py end-of-tick).
        self_in = jnp.any(((d_mold | d_mnew) & eye_p3) != 0, axis=1)  # [N, B]
        is_cand = (role == CANDIDATE) | (role == PRECANDIDATE)
        demote = ~self_in & (
            ((role == LEADER) & (commit >= d_hi)) | is_cand
        )
        role = jnp.where(demote, FOLLOWER, role)
        leader_id = jnp.where(demote, NIL, leader_id)

    new_state = ClusterState(
        role=role,
        term=term,
        voted_for=voted_for,
        leader_id=leader_id,
        votes=votes,
        next_index=next_index,
        match_index=match_index,
        ack_age=ack_age,
        commit_index=commit,
        commit_chk=chk_new,
        log_base=base,
        base_term=bterm,
        base_chk=bchk,
        log_term=log_term_arr,
        log_val=log_val_arr,
        log_tick=log_tick_arr,
        log_len=log_len,
        dur_len=dur2_len if dur else s.dur_len,
        dur_term=dur2_term if dur else s.dur_term,
        dur_vote=dur2_vote if dur else s.dur_vote,
        clock=clock,
        deadline=deadline,
        heard_clock=heard,
        member_old=d_mold if rcf else s.member_old,
        member_new=d_mnew if rcf else s.member_new,
        cfg_epoch=d_epoch if rcf else s.cfg_epoch,
        cfg_pend=d_pend if rcf else s.cfg_pend,
        log_cfg=log_cfg_arr,
        base_mold=bmold if (rcf and comp) else s.base_mold,
        base_pend=bpend if (rcf and comp) else s.base_pend,
        base_epoch=bepoch if (rcf and comp) else s.base_epoch,
        xfer_to=xfer_to if xfr else s.xfer_to,
        read_idx=read_idx if rdx else s.read_idx,
        read_tick=read_tick if rdx else s.read_tick,
        read_acks=read_acks if rdx else s.read_acks,
        read_fr=read_fr if rdl else s.read_fr,
        client_pend=client_pend,
        client_dst=client_dst,
        client_tick=client_tick,
        lat_frontier=lat_frontier,
        now=s.now + 1,
        mailbox=new_mb,
    )

    # Durability-lag reductions (host-constant zeros when the plane is off).
    if dur:
        lag = log_len - dur2_len  # [N, B] >= 0 (flush snaps to log_len)
        fsync_lag_sum = jnp.sum(lag, axis=0).astype(jnp.int32)
        fsync_lag_max = jnp.max(lag, axis=0).astype(jnp.int32)
    else:
        fsync_lag_sum = np.zeros((b,), np.int32)
        fsync_lag_max = np.zeros((b,), np.int32)

    info = _step_info_b(
        cfg, s, new_state, req_in, resp_in, inp.alive, cmds_cnt, chk_ok,
        lat_sum, lat_cnt, lat_hist, lat_excluded, noop_blocked,
        reads_served, read_lat_sum, read_hist, viol_read_stale,
        fsync_lag_sum, fsync_lag_max, sh,
    )
    return new_state, info


def _step_info_b(
    cfg: RaftConfig,
    old: ClusterState,
    new: ClusterState,
    req_in: jax.Array,
    resp_in: jax.Array,
    alive: jax.Array,
    cmds_cnt: jax.Array,
    chk_ok: jax.Array,
    lat_sum: jax.Array,
    lat_cnt: jax.Array,
    lat_hist: jax.Array,
    lat_excluded: jax.Array,
    noop_blocked: jax.Array,
    reads_served: jax.Array,
    read_lat_sum: jax.Array,
    read_hist: jax.Array,
    viol_read_stale: jax.Array,
    fsync_lag_sum: jax.Array,
    fsync_lag_max: jax.Array,
    sh: NodeShardCtx | None = None,
) -> StepInfo:
    """Batched phase 9; see raft._step_info. All outputs [B]."""
    n = cfg.n_nodes
    b = new.role.shape[-1]
    iota = log_ops.iota
    is_leader = new.role == LEADER
    live_leader = is_leader & alive  # see raft._step_info: leadership metrics are live-only
    f = jnp.zeros((b,), bool)
    if sh is None:
        eye3 = iota((n, n, 1), 0) == iota((n, n, 1), 1)
        ids1 = iota((n, 1), 0)
        gmax = gmin = gsum = gany = lambda x: x  # node-axis folds: already local
    else:
        ids1 = sh.row0 + iota((sh.nl, 1), 0)
        gmax = lambda x: lax.pmax(x, sh.axis)
        gmin = lambda x: lax.pmin(x, sh.axis)
        gsum = lambda x: lax.psum(x, sh.axis)
        gany = lambda x: lax.psum(x.astype(jnp.int32), sh.axis) > 0

    if cfg.check_invariants:
        if sh is None:
            pair_bad = (
                is_leader[:, None, :]
                & is_leader[None, :, :]
                & (new.term[:, None, :] == new.term[None, :, :])
                & ~eye3
            )
        else:
            # One extra [n_pad, B] gather: leaders encoded by term (terms start
            # at 1, so 0 reads as non-leader; pad rows never lead). Tiny next
            # to the mailbox gather, and only paid when invariants are on.
            lv = lax.all_gather(
                jnp.where(is_leader, new.term, 0), sh.axis, axis=0, tiled=True
            )  # [n_pad, B]
            pair_bad = (
                (lv[:, None, :] > 0)
                & (lv[:, None, :] == lv[None, :, :])
                & ~(
                    iota((sh.n_pad, sh.n_pad, 1), 0)
                    == iota((sh.n_pad, sh.n_pad, 1), 1)
                )
            )
        viol_election = jnp.any(pair_bad, axis=(0, 1))
        # Committed-prefix immutability via the carried checksum (raft._step_info),
        # plus the compaction bounds (base <= commit, retained window <= CAP).
        viol_commit = gany(
            jnp.any(
                (new.commit_index < old.commit_index)
                | (new.commit_index > new.log_len)
                | (new.commit_index < new.log_base)
                | (new.log_len - new.log_base > cfg.log_capacity)
                | ~chk_ok,
                axis=0,
            )
        )
    else:
        viol_election = f
        viol_commit = f

    if cfg.check_log_matching:

        def _check(_):
            minc = jnp.minimum(
                new.commit_index[:, None, :], new.commit_index[None, :, :]
            )
            differ = (new.log_term[:, None] != new.log_term[None, :]) | (
                new.log_val[:, None] != new.log_val[None, :]
            )  # [N, N, CAP, B]
            if not cfg.compaction:
                both = iota((1, 1, cfg.log_capacity, 1), 2) < minc[:, :, None, :]
                return jnp.any(both & differ, axis=(0, 1, 2)), jnp.zeros_like(new.now)
            # Ring form (see raft._step_info): slots live in BOTH rings over
            # (max base, min commit] compare directly; the shared prefix below
            # max(base_i, base_j) compares via checksums-at-mb; incomparable
            # pairs are counted (lm_skipped_pairs).
            cap = cfg.log_capacity
            bb = new.log_base  # [N, B]
            sl = iota((1, cap, 1), 1)
            abs0 = bb[:, None, :] + (sl - bb[:, None, :]) % cap  # [N, CAP, B]
            mb_ = jnp.maximum(bb[:, None, :], bb[None, :, :])  # [N, N, B]
            comparable = minc >= mb_
            in_i = (abs0[:, None, :, :] >= mb_[:, :, None, :]) & (
                abs0[:, None, :, :] < minc[:, :, None, :]
            )
            in_j = (abs0[None, :, :, :] >= mb_[:, :, None, :]) & (
                abs0[None, :, :, :] < minc[:, :, None, :]
            )
            viol_suffix = jnp.any(
                comparable[:, :, None, :] & in_i & in_j & differ, axis=(0, 1, 2)
            )
            w_t, w_v = log_ops.chk_weights_at(abs0)
            contrib = (
                new.log_term.astype(jnp.uint32) * w_t
                + new.log_val.astype(jnp.uint32) * w_v
            )  # [N, CAP, B]
            chk_at_mb = new.base_chk[:, None, :] + jnp.sum(
                jnp.where(
                    abs0[:, None, :, :] < mb_[:, :, None, :],
                    contrib[:, None, :, :],
                    jnp.uint32(0),
                ),
                axis=2,
                dtype=jnp.uint32,
            )  # [N(i), N(j), B]
            viol_prefix = jnp.any(
                comparable & (chk_at_mb != jnp.swapaxes(chk_at_mb, 0, 1)), axis=(0, 1)
            )
            skipped = (
                jnp.sum(~comparable & ~eye3, axis=(0, 1)) // 2
            ).astype(jnp.int32)
            return viol_suffix | viol_prefix, skipped

        if cfg.log_matching_interval == 1:
            viol_match, lm_skipped = _check(None)
        else:
            # Lockstep cadence: now[0] is the whole batch's tick (config.py), a
            # scalar pred, so lax.cond skips the check entirely off-cadence.
            viol_match, lm_skipped = jax.lax.cond(
                new.now.reshape(-1)[0] % cfg.log_matching_interval == 0,
                _check,
                lambda _: (f, jnp.zeros_like(new.now)),
                None,
            )
    else:
        viol_match, lm_skipped = f, jnp.zeros_like(new.now)

    leader = gmin(jnp.min(jnp.where(live_leader, ids1, n), axis=0))  # [B]
    if sh is None:
        min_commit = jnp.min(new.commit_index, axis=0)
    else:
        # Pad rows sit at commit 0 forever; mask them to the max-int sentinel
        # (a live row always exists, so the sentinel never wins).
        min_commit = gmin(
            jnp.min(
                jnp.where(ids1 < n, new.commit_index, jnp.int32(2**31 - 1)), axis=0
            )
        )
    return StepInfo(
        viol_election_safety=viol_election,
        viol_commit=viol_commit,
        viol_log_matching=viol_match,
        leader=jnp.where(leader < n, leader, NIL).astype(jnp.int32),
        n_leaders=gsum(jnp.sum(live_leader, axis=0).astype(jnp.int32)),
        max_term=gmax(jnp.max(new.term, axis=0)),
        max_commit=gmax(jnp.max(new.commit_index, axis=0)),
        min_commit=min_commit,
        msgs_delivered=gsum(
            (jnp.sum(req_in, axis=(0, 1)) + jnp.sum(resp_in, axis=(0, 1))).astype(
                jnp.int32
            )
        ),
        cmds_injected=cmds_cnt,  # offers accepted, not appends; see raft.py phase 6
        lat_sum=lat_sum,
        lat_cnt=lat_cnt,
        lat_hist=lat_hist,
        lat_excluded=lat_excluded,
        noop_blocked=noop_blocked,
        lm_skipped_pairs=lm_skipped,
        reads_served=reads_served,
        read_lat_sum=read_lat_sum,
        read_hist=read_hist,
        viol_read_stale=viol_read_stale,
        fsync_lag_sum=fsync_lag_sum,
        fsync_lag_max=fsync_lag_max,
    )
