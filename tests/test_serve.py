"""The serve subsystem: streaming ingest, arbitrary payloads, commit-delta export.

ISSUE 6's acceptance surface, pinned:
  - Arbitrary-payload parity: values chosen to COLLIDE with the old tick
    encoding produce bit-exact state (minus the value planes themselves),
    latency histograms, and telemetry windows vs non-colliding values, on both
    kernels -- payload/latency decoupling (checkpoint v21) means the metric
    reads the offer-tick plane, never the payload.
  - The device-side commit-delta stream exactly equals the host snapshot-diff
    reconstruction on a fuzzed run, and ApplyLogWriter's per-node export.
  - A multi-chunk ServeSession compiles NOTHING after its first chunk
    (command values are traced data).
  - Session.offer acks via the delta stream (VERDICT missing #2), with the
    superseded snapshot-diff poll kept as a cross-check.

Compile budget: one served scan (`simulate_serve`, shared by the parity and
export tests), one scheduled scan (the cadence-equivalence anchor), one serve
chunk program (`_serve_chunk`, shared by every ServeSession test via the
module fixture), and one unbatched step -- everything else is host-side or
reuses programs other test modules compile.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_sim_tpu import NIL, RaftConfig
from raft_sim_tpu.types import NOOP
from raft_sim_tpu.models import raft
from raft_sim_tpu.serve import (
    CommandSource,
    DeltaStream,
    ServeSession,
    jsonl_commands,
    pack_chunk,
    serve_config,
    simulate_serve,
)
from raft_sim_tpu.serve import deltas as deltas_mod
from raft_sim_tpu.serve import ingest, loop
from raft_sim_tpu.sim import faults, scan
from raft_sim_tpu.types import init_batch, init_state
from raft_sim_tpu.utils import checkpoint

# The scheduled twin (client_interval=1) and its serve-mode variant: ONE served
# scan program covers the parity, export, and cadence-equivalence tests.
BASE = RaftConfig(n_nodes=3, log_capacity=32, client_interval=1)
SCFG = serve_config(BASE)
BATCH, T, WINDOW = 4, 64, 16

# The fuzzed standing-fleet config (module fixture `served`): every fault class
# the serve loop must stream through without losing an exported entry.
FCFG = serve_config(
    RaftConfig(
        n_nodes=3,
        log_capacity=64,
        drop_prob=0.2,
        crash_prob=0.3,
        crash_period=24,
        crash_down_ticks=8,
    )
)
FB, FCHUNK, FW = 4, 32, 16

# Payloads that COLLIDE with the old tick encoding (small positive ints in
# (0, now]) vs arbitrary ones -- same offer ticks, different values only.
COLLIDING = [7, 1, 2, 3, 9, 5]
ARBITRARY = [2**31 - 1, -(2**31), -1000, 10**9, -7, 123456789]
OFFER_AT = 32  # first offer tick: leaders are long elected by then


def _plane(values, start=OFFER_AT, ticks=T, batch=BATCH):
    """[T, B] offer plane with `values` at consecutive ticks from `start`,
    broadcast across the batch (the pre-tenancy one-client-over-the-fleet
    form) -- pack_chunk's contiguous packing, shifted to a post-election
    window."""
    col = np.full((ticks,), NIL, np.int32)
    col[start : start + len(values)] = pack_chunk(values, len(values))
    return jnp.asarray(np.broadcast_to(col[:, None], (ticks, batch)))


def assert_equal_except_values(a, b):
    """Bit-exact on every leaf EXCEPT the payload planes and their checksums
    (log_val, mailbox.ent_val, the value-weighted commit/base checksums, and
    redirect-pipeline payload slots): the decoupling contract -- values
    influence nothing but themselves."""
    skip = {"log_val", "commit_chk", "base_chk", "client_pend"}
    mb_skip = {"ent_val", "req_base_chk"}
    for f in a._fields:
        if f in skip:
            continue
        if f == "mailbox":
            for mf in a.mailbox._fields:
                if mf in mb_skip:
                    continue
                np.testing.assert_array_equal(
                    np.asarray(getattr(a.mailbox, mf)),
                    np.asarray(getattr(b.mailbox, mf)),
                    err_msg=f"mailbox.{mf} diverged under a value-only change",
                )
        else:
            np.testing.assert_array_equal(
                np.asarray(getattr(a, f)),
                np.asarray(getattr(b, f)),
                err_msg=f"state.{f} diverged under a value-only change",
            )


def assert_trees_equal(a, b, what):
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb), err_msg=what)


# --------------------------------------------------------------- ingest units


def test_serve_config_forces_external_ingest():
    assert SCFG.client_interval == 0
    assert SCFG.serve_ingest
    assert SCFG.track_offer_ticks
    assert serve_config(SCFG) is SCFG  # idempotent: already serve-mode
    # The structural gate it exists for: without it, an interval-0 config
    # carries the offer-tick plane as dead weight.
    assert not RaftConfig(n_nodes=3).track_offer_ticks


def test_check_value_and_pack_chunk():
    for v in (0, 7, -3, 2**31 - 1, -(2**31)):
        assert ingest.check_value(v) == v
    for bad in (NIL, NOOP):
        with pytest.raises(ValueError, match="sentinel"):
            ingest.check_value(bad)
    with pytest.raises(ValueError, match="int32"):
        ingest.check_value(2**31)
    plane = pack_chunk([5, -9], 4)
    assert plane.dtype == np.int32
    assert list(plane) == [5, -9, NIL, NIL]
    with pytest.raises(ValueError, match="fit"):
        pack_chunk([1, 2, 3], 2)


def test_jsonl_source_and_parse(tmp_path):
    p = tmp_path / "cmds.jsonl"
    p.write_text('7\n# comment\n\n{"value": -3, "tag": "x"}\n2147483647\n')
    assert list(jsonl_commands(str(p))) == [7, -3, 2**31 - 1]
    with pytest.raises(ValueError, match="value"):
        ingest.parse_line('{"tag": "x"}')
    with pytest.raises(ValueError, match="integer"):
        ingest.parse_line("true")
    src = CommandSource(jsonl_commands(str(p)))
    first = src.next_chunk(2)
    assert list(first) == [7, -3] and not src.exhausted
    rest = src.next_chunk(8)
    assert list(rest) == [2**31 - 1] + [NIL] * 7 and src.exhausted
    assert src.offered == 3


# ----------------------------------------------- arbitrary-payload parity


def test_arbitrary_payload_parity_batched():
    """ISSUE-6 acceptance: colliding vs arbitrary payloads -- bit-exact
    telemetry windows, metrics (latency histogram included), and state minus
    the value planes, through ONE compiled served scan (values are data)."""
    sa, ma, ra = simulate_serve(SCFG, 0, BATCH, _plane(COLLIDING), WINDOW)
    sb, mb_, rb = simulate_serve(SCFG, 0, BATCH, _plane(ARBITRARY), WINDOW)
    assert_trees_equal(ma, mb_, "metrics diverged under a value-only change")
    assert_trees_equal(ra, rb, "windows diverged under a value-only change")
    assert_equal_except_values(sa, sb)
    # The stamps themselves: identical between runs, offer tick + 1 at the
    # slots the offers landed in (node 0's committed prefix).
    np.testing.assert_array_equal(np.asarray(sa.log_tick), np.asarray(sb.log_tick))
    commit0 = int(np.asarray(sa.commit_index)[0, 0])
    assert commit0 == len(COLLIDING)  # reliable net: everything offered commits
    np.testing.assert_array_equal(
        np.asarray(sa.log_tick)[0, 0, :commit0],
        OFFER_AT + 1 + np.arange(len(COLLIDING)),
    )
    np.testing.assert_array_equal(
        np.asarray(sb.log_val)[0, 0, :commit0], ARBITRARY
    )
    # Latency was measured (not silently skipped) and covered every commit.
    assert int(np.asarray(ma.lat_cnt).sum()) >= len(COLLIDING)
    assert int(np.asarray(ma.lat_excluded).sum()) == 0


@pytest.mark.slow
def test_arbitrary_payload_parity_unbatched_kernel():
    """The same A/B on the UNBATCHED kernel (raft.step): every StepInfo leaf --
    latency histogram included -- and all non-value state bit-exact. Slow
    tier: the batched A/B above is the tier-1 gate, and the unbatched kernel's
    offer-tick plane is already oracle-checked every tick by the parity
    matrix (tests/test_oracle_parity.py client rows)."""
    step = jax.jit(lambda s, i, c: raft.step(SCFG, s, i._replace(client_cmd=c)))
    key = jax.random.key(3)
    k_init, k_run = jax.random.split(key)

    def drive(values):
        # The unbatched kernel takes one scalar offer per tick: one column of
        # the (broadcast) [T, B] plane.
        plane = np.asarray(_plane(values, ticks=48, batch=1))[:, 0]
        s = init_state(SCFG, k_init)
        infos = []
        for t in range(48):
            inp = faults.make_inputs(SCFG, k_run, s.now)
            s, info = step(s, inp, jnp.int32(plane[t]))
            infos.append(jax.device_get(info))
        return s, infos

    sa, ia = drive(COLLIDING)
    sb, ib = drive(ARBITRARY)
    for t, (a, b) in enumerate(zip(ia, ib)):
        assert_trees_equal(a, b, f"StepInfo diverged at tick {t}")
    assert_equal_except_values(sa, sb)
    assert sum(int(i.lat_cnt) for i in ia) == len(COLLIDING)
    assert sum(int(i.lat_excluded) for i in ia) == 0


@pytest.mark.slow
def test_scheduled_cadence_equals_explicit_plane():
    """The scheduled client cadence IS a served offer plane: client_interval=1
    traffic (value = tick+1, faults.make_inputs) replayed through pack_chunk as
    an explicit plane on the serve-mode variant reproduces the scheduled run
    bit-for-bit -- state (values included), metrics, latency. One packing
    helper, one semantics (the scenario-genome cadence pins the same identity
    against the scheduled path in tests/test_scenario.py, closing the
    genome -> scheduled -> served chain)."""
    s_sched, m_sched = scan.simulate(BASE, 0, BATCH, T)
    col = pack_chunk([t + 1 for t in range(T)], T)
    cmds = jnp.asarray(np.broadcast_to(col[:, None], (T, BATCH)))
    s_srv, m_srv, _ = simulate_serve(SCFG, 0, BATCH, cmds, WINDOW)
    assert_trees_equal(s_sched, s_srv, "scheduled vs explicit-plane state")
    assert_trees_equal(m_sched, m_srv, "scheduled vs explicit-plane metrics")


# ------------------------------------------------------- commit-delta export


def test_delta_export_acks_every_offer_bit_exactly():
    """Every offered command's ack arrives through the delta stream with the
    value round-tripped bit-exactly -- including int32 extremes and values that
    used to collide with the tick encoding -- and stamps carry the offer
    ticks. Shares the parity test's compiled program."""
    values = [7, 1, 2**31 - 1, -(2**31), -1000, 9]
    final, _, _ = simulate_serve(SCFG, 0, BATCH, _plane(values), WINDOW)
    stream = DeltaStream(BATCH, depth=2)  # depth < len: forces drain rounds
    rows = stream.drain(final)
    for c in range(BATCH):
        assert deltas_mod.applied_values(rows, c) == values
        ticks = [t for row in rows if row["cluster"] == c for t in row["ticks"]]
        assert ticks == [OFFER_AT + 1 + k for k in range(len(values))]
    assert stream.exported == BATCH * len(values)
    assert stream.gap_entries == 0
    assert stream.drain(final) == []  # watermark caught up: stream is dry


def test_extract_reports_compaction_gap():
    """Entries compacted past node 0's base before export surface as a gap
    count, and the stream resumes at the base (hand-built ring state)."""
    state = init_batch(SCFG, jax.random.key(0), 2)
    lv = state.log_val.at[0, 0, 4:6].set(jnp.asarray([44, 55], jnp.int32))
    lt = state.log_tick.at[0, 0, 4:6].set(jnp.asarray([10, 11], jnp.int32))
    state = state._replace(
        log_val=lv,
        log_tick=lt,
        log_base=state.log_base.at[0, 0].set(4),
        commit_index=state.commit_index.at[0, 0].set(6),
        log_len=state.log_len.at[0, 0].set(6),
    )
    d = deltas_mod.extract(state, jnp.zeros((2,), jnp.int32), 8)
    assert int(d.gap[0]) == 4 and int(d.count[0]) == 2
    assert list(np.asarray(d.values)[0, :2]) == [44, 55]
    assert list(np.asarray(d.ticks)[0, :2]) == [10, 11]
    assert int(d.watermark[0]) == 6
    assert int(d.count[1]) == 0 and int(d.gap[1]) == 0


def test_validate_deltas_catches_stream_holes(tmp_path):
    p = str(tmp_path / "deltas.jsonl")
    rows = [
        {"cluster": 0, "start": 1, "gap": 0, "values": [5, 6], "ticks": [2, 3]},
        {"cluster": 0, "start": 3, "gap": 0, "values": [7], "ticks": [4]},
    ]
    deltas_mod.append_delta_rows(p, rows)
    assert deltas_mod.validate_deltas(p) == []
    deltas_mod.append_delta_rows(
        p, [{"cluster": 0, "start": 9, "gap": 0, "values": [8], "ticks": [9]}]
    )
    errs = deltas_mod.validate_deltas(p)
    assert any("not dense" in e for e in errs)
    deltas_mod.append_delta_rows(
        p, [{"cluster": 1, "start": 1, "gap": 0, "values": [1, 2], "ticks": [3]}]
    )
    assert any("length mismatch" in e for e in deltas_mod.validate_deltas(p))


# ------------------------------------------------- the standing-fleet session


@pytest.fixture(scope="module")
def served(tmp_path_factory):
    """ONE fuzzed multi-chunk ServeSession (drop + crash faults, sink attached,
    ApplyLogWriter shadowing cluster 0) shared by the session-level tests --
    one compiled chunk program for the whole module."""
    from raft_sim_tpu.utils.apply_log import ApplyLogWriter
    from raft_sim_tpu.utils.telemetry_sink import TelemetrySink

    sink_dir = str(tmp_path_factory.mktemp("serve_sink"))
    sink = TelemetrySink(
        sink_dir, FCFG, seed=7, batch=FB, window=FW, ring=0, source="serve"
    )
    sess = ServeSession(
        FCFG, batch=FB, seed=7, chunk=FCHUNK, window=FW, delta_depth=4,
        sink=sink, warmup_ticks=FCHUNK,
    )
    writer = ApplyLogWriter(str(tmp_path_factory.mktemp("apply")), FCFG)
    cache_sizes = []

    def progress(_stats):
        cache_sizes.append(loop._serve_chunk._cache_size())
        writer.update(sess.state)

    cmds = [7, 1, 2, 2**31 - 1, -(2**31), -1000, 9, 9] + list(range(100, 120))
    stats = sess.serve(CommandSource(iter(cmds)), drain_chunks=3, progress=progress)
    return {
        "sess": sess, "stats": stats, "writer": writer, "sink_dir": sink_dir,
        "cache_sizes": cache_sizes, "cmds": cmds,
    }


def test_fuzzed_stream_equals_snapshot_diff(served):
    """ISSUE-6 acceptance: on a fuzzed run the streamed deltas exactly equal
    the host snapshot-diff -- node 0's committed prefix (values AND stamps),
    per cluster, reconstructed from the final fleet state."""
    sess = served["sess"]
    st = jax.device_get(sess.state)
    wm = np.asarray(sess.deltas.watermark)
    total = 0
    for c in range(FB):
        # Node 0's commit INDEX is restart-mutable (a crashed node rebuilds it
        # from the leader), but the committed entries themselves never change:
        # the stream must equal the log prefix up to its own watermark -- the
        # highest commit it ever observed -- bit for bit.
        n_exp = int(wm[c])
        assert n_exp >= int(np.asarray(st.commit_index)[c, 0])
        want_vals = list(np.asarray(st.log_val)[c, 0, :n_exp])
        want_ticks = list(np.asarray(st.log_tick)[c, 0, :n_exp])
        got_vals = [v for r in sess.delta_rows if r["cluster"] == c for v in r["values"]]
        got_ticks = [t for r in sess.delta_rows if r["cluster"] == c for t in r["ticks"]]
        assert got_vals == want_vals, f"cluster {c}: delta values != committed log"
        assert got_ticks == want_ticks, f"cluster {c}: delta stamps != log_tick plane"
        total += n_exp
    assert total > 0  # the fault mix let clusters commit
    assert sess.deltas.exported == total
    assert sess.deltas.gap_entries == 0  # no compaction: nothing lost


def test_fuzzed_stream_matches_apply_log_writer(served):
    """The delta stream and the per-chunk ApplyLogWriter shadow agree on
    cluster 0's apply stream (the single-cluster exporter it generalizes)."""
    assert served["writer"].values(0) == served["sess"].acked_values(0)


def test_serve_session_zero_recompiles(served):
    """ISSUE-6 acceptance: after the first chunk the session compiles NOTHING
    -- varying command values, empty drain chunks, and the warmup plane all
    share one chunk executable."""
    sizes = served["cache_sizes"]
    assert len(sizes) >= 4
    assert len(set(sizes)) == 1, f"serve chunk recompiled mid-session: {sizes}"


def test_serve_sink_streams_validate(served):
    from raft_sim_tpu.utils import telemetry_sink

    sink_dir = served["sink_dir"]
    assert deltas_mod.validate_deltas(os.path.join(sink_dir, "deltas.jsonl")) == []
    assert telemetry_sink.validate(sink_dir) == []
    # The streamed file holds exactly the rows the session drained.
    with open(os.path.join(sink_dir, "deltas.jsonl")) as f:
        n_rows = sum(1 for _ in f)
    assert n_rows == len(served["sess"].delta_rows)


def test_serve_state_checkpoints_v21(served, tmp_path):
    """The offer-tick plane rides the v21 checkpoint: a served fleet's state
    (nonzero log_tick, serve_ingest config) round-trips bit-exactly."""
    sess = served["sess"]
    path = checkpoint.save(
        str(tmp_path / "ck"), sess.cfg, sess.state, sess.keys, sess.metrics, seed=7
    )
    cfg2, state2, keys2, metrics2, seed2, scen = checkpoint.load(path)
    assert cfg2 == sess.cfg and cfg2.serve_ingest and seed2 == 7
    assert scen is None
    assert np.asarray(state2.log_tick).any()  # the plane is live and persisted
    assert_trees_equal(state2, sess.state, "checkpoint round trip")
    assert_trees_equal(metrics2, sess.metrics, "metrics round trip")


def test_session_offer_acks_via_delta_stream_with_poll_cross_check():
    """Session.offer's ack = the commit-delta stream (VERDICT missing #2
    closed): a value equal to a long-committed scheduled command still acks
    (the superseded snapshot-diff poll reported 0 forever on this input), and
    the poll -- kept as the cross-check -- agrees with every ack after the
    fact."""
    from raft_sim_tpu.driver import Session

    sess = Session(RaftConfig(n_nodes=5, client_interval=8), batch=8, seed=0)
    sess.run(100)  # scheduled value 65 (offer tick 64, leaders long elected)
    assert sess._committed_mask(65).all()  # the collision is real pre-offer
    res = sess.offer(65, wait=40)
    assert res["accepted"] == 8
    assert res["committed"] == 8  # the delta stream sees the NEW entry
    # Cross-check: the snapshot poll agrees on a fresh (non-colliding) value.
    res2 = sess.offer(-424242, wait=40)
    assert res2["committed"] == 8
    assert sess._committed_mask(-424242).all()
