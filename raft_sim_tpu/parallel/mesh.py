"""Multi-chip execution: shard the independent-cluster batch axis over a device mesh.

The reference's "distributed backend" is point-to-point HTTP between one OS process per
Raft node (server.clj:37-39, client.clj:34-40). In the rebuild, *intra-cluster* traffic
is the dense mailbox inside the step kernel (types.py); *across chips* the workload is
embarrassingly parallel -- clusters are independent -- so ICI carries only the batch
sharding installed here plus small psum metric reductions. No NCCL analogue is needed
beyond XLA's collectives (SURVEY.md section 5, distributed communication backend).

Design: per-cluster PRNG keys are split OUTSIDE the sharded region, so a run is
bit-identical for the same (seed, batch) at any device count -- the distributed parity
property tested in tests/test_parallel.py. `shard_map` (not bare jit-with-shardings) is
used so the compiled program provably contains no accidental cross-device traffic in the
hot loop; the only cross-device movement is the host-side gather in `summarize`, which
pulls the small per-cluster RunMetrics off device for the fleet rollup.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from raft_sim_tpu.sim import scan
from raft_sim_tpu.types import init_state
from raft_sim_tpu.utils.config import RaftConfig

AXIS = "clusters"


def _shard_map(f, mesh, in_specs, out_specs):
    """Version-portable shard_map: jax >= 0.6 exposes it top-level with the
    varying-manual-axes check named `check_vma`; jax 0.4/0.5 (this image) has it
    in jax.experimental with the same check named `check_rep`. The check is
    disabled either way: the scan carry mixes axis-invariant constants
    (init_metrics zeros) with per-cluster varying state, and the body has no
    cross-device communication to validate."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
        )
    from jax.experimental.shard_map import shard_map

    return shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
    )


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> int:
    """Multi-host bootstrap: join this host's chips into the global device mesh.

    The reference's cross-node transport is point-to-point HTTP between OS
    processes (server.clj/client.clj); here multi-HOST scaling is pure
    orchestration -- clusters are independent, so a pod just shards the batch
    axis over every chip of every host. This wraps `jax.distributed.initialize`
    (args fall back to the standard JAX env vars / TPU pod auto-detection; DCN
    carries only this control plane, never tick traffic). Call once per host
    process before any computation; afterwards `jax.devices()` is the global
    device list, `make_mesh()` builds the global 1-D mesh, `simulate_sharded`
    runs with each host touching only its addressable shards, and
    `summarize`/`gather_metrics` all-gather the per-cluster metrics so every
    process sees the fleet rollup. Exercised end to end by
    tools/multihost_check.py (two cooperating OS processes on one machine --
    the reference's deployment shape, core.clj:197-203 -- verified bit-for-bit
    against a single-process run; tests/test_multihost.py runs it in CI).
    Returns this host's process index.
    """
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return jax.process_index()


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D mesh over the flat device list; the single named axis shards the batch of
    independent clusters (the rebuild's only data-parallel axis, SURVEY.md section 2)."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(f"requested {n_devices} devices, only {len(devices)} available")
        devices = devices[:n_devices]
    import numpy as np

    return Mesh(np.asarray(devices), (AXIS,))


def _run_shard(cfg: RaftConfig, n_ticks: int, keys_init, keys_run):
    """Body executed per shard: init + scan the local slice of clusters (batch-minor
    hot path)."""
    state = jax.vmap(lambda k: init_state(cfg, k))(keys_init)
    return scan.run_batch_minor(cfg, state, keys_run, n_ticks)


@functools.partial(jax.jit, static_argnums=(0, 2, 3, 4))
def simulate_sharded(cfg: RaftConfig, seed, batch: int, n_ticks: int, mesh: Mesh):
    """Batched simulation sharded over `mesh`. Returns (final_state, RunMetrics), both
    with the leading batch axis sharded over the mesh.

    Bit-identical to `scan.simulate` for the same (cfg, seed, batch, n_ticks): the
    per-cluster key split happens before sharding, so device count does not perturb
    any cluster's trajectory.
    """
    n_dev = mesh.devices.size
    if batch % n_dev:
        raise ValueError(f"batch {batch} must divide over {n_dev} devices")
    root = jax.random.key(seed)
    k_init, k_run = jax.random.split(root)
    keys_init = jax.random.split(k_init, batch)
    keys_run = jax.random.split(k_run, batch)

    sharded = _shard_map(
        functools.partial(_run_shard, cfg, n_ticks),
        mesh=mesh,
        in_specs=(P(AXIS), P(AXIS)),
        out_specs=P(AXIS),
    )
    keys_init = _constrain_keys(keys_init, mesh)
    keys_run = _constrain_keys(keys_run, mesh)
    return sharded(keys_init, keys_run)


def _run_shard_windowed(cfg, n_ticks, window, seg_len, trace_spec,
                        keys_init, keys_run, genome):
    """Per-shard body for `simulate_windowed_sharded`: init + the windowed
    telemetry scan over the local cluster slice. The recorder leg is always
    None here (the farm never rings) and is dropped from the return -- a
    dead leg has no shard spec."""
    from raft_sim_tpu.sim import telemetry

    state = jax.vmap(lambda k: init_state(cfg, k))(keys_init)
    out = telemetry.run_batch_minor_telemetry(
        cfg, state, keys_run, n_ticks, window, None,
        genome=genome, seg_len=seg_len, trace_spec=trace_spec,
    )
    if trace_spec is None:
        final, metrics, recs, _ = out
        return final, metrics, recs
    final, metrics, recs, _, traws, tp = out
    return final, metrics, recs, traws, tp


@functools.partial(jax.jit, static_argnums=(0, 2, 3, 4, 5, 7, 8))
def simulate_windowed_sharded(
    cfg: RaftConfig, seed, batch: int, n_ticks: int, window: int, mesh: Mesh,
    genome=None, seg_len: int = 1, trace=None,
):
    """`telemetry.simulate_windowed` sharded over the cluster axis of `mesh`
    -- the farm's per-generation evaluator (farm/core.py): one shard_map'ped
    windowed scan for the whole CE portfolio, the population divided over
    the devices. Same return shape as simulate_windowed (the recorder slot
    is always None: rings are a debugging tool, the farm never arms one),
    plus the trace legs when `trace` is given.

    Bit-identical to the unsharded call at ANY device count: per-cluster
    keys are split OUTSIDE the sharded region (the simulate_sharded
    invariance pattern), so a hunt's trajectory -- and therefore its hits,
    its manifest hash, its corpus artifacts -- never depends on the mesh it
    ran on. Genome rows stay traced DATA ([B, S] leaves sharded over their
    leading cluster axis): new genome values reuse the compiled program, so
    the jit cache holds exactly one entry per (config, mesh) and stays flat
    across generations and device counts (tests/test_farm.py pins this)."""
    n_dev = mesh.devices.size
    if batch % n_dev:
        raise ValueError(f"batch {batch} must divide over {n_dev} devices")
    root = jax.random.key(seed)
    k_init, k_run = jax.random.split(root)
    keys_init = _constrain_keys(jax.random.split(k_init, batch), mesh)
    keys_run = _constrain_keys(jax.random.split(k_run, batch), mesh)

    body = functools.partial(
        _run_shard_windowed, cfg, n_ticks, window, seg_len, trace
    )
    args = (keys_init, keys_run)
    in_specs = [P(AXIS), P(AXIS)]
    if genome is None:
        fn = lambda ki, kr: body(ki, kr, None)
    else:
        fn = body
        args += (genome,)
        in_specs.append(P(AXIS))  # [B, S] leaves: clusters lead, S replicated
    # Batch-leading outputs shard on axis 0; the trace legs stay batch-minor
    # (leaves [n_windows, ..., B] / [..., B]), so their specs put the cluster
    # axis LAST -- ranks read off an eval_shape of the unsharded body.
    out_specs = [P(AXIS), P(AXIS), P(AXIS)]
    if trace is not None:
        shapes = jax.eval_shape(fn, *args)
        minor = lambda t: jax.tree.map(
            lambda s: P(*([None] * (s.ndim - 1)), AXIS), t
        )
        out_specs += [minor(shapes[3]), minor(shapes[4])]
    sharded = _shard_map(
        fn, mesh=mesh, in_specs=tuple(in_specs), out_specs=tuple(out_specs)
    )
    out = sharded(*args)
    if trace is None:
        return out[0], out[1], out[2], None
    return out[0], out[1], out[2], None, out[3], out[4]


def _constrain_keys(keys, mesh: Mesh):
    """Batch-shard a typed PRNG key array. The constraint is applied to the raw
    key DATA ([B, 2] uint32) and the keys re-wrapped: older jax (0.4.x) fails
    to extend a rank-1 sharding spec over the key dtype's hidden trailing dim
    ("tile assignment dimensions different than input rank" at compile time),
    while the data route lowers identically on every supported version. Values
    are untouched -- only placement metadata is attached."""
    kd = jax.random.key_data(keys)
    spec = P(AXIS, *([None] * (kd.ndim - 1)))
    kd = jax.lax.with_sharding_constraint(kd, NamedSharding(mesh, spec))
    return jax.random.wrap_key_data(kd)


class FleetSummary(NamedTuple):
    """Host-side rollup of per-cluster RunMetrics across the whole fleet. The
    per-cluster metric arrays are tiny ([batch] int32s), so this is a plain
    device_get + numpy reduction, not an on-device collective."""

    n_clusters: int
    total_violations: int
    n_stable: int  # clusters that ended with a continuously-held leader
    p50_stable_tick: float | None  # median ticks-to-stable-leader; None if no cluster stabilized
    max_term: int
    total_msgs: int
    total_cmds: int  # client commands accepted fleet-wide (offered vs committed audit)
    # LEGACY: fleet p50 of per-cluster MEAN offer->commit latency (ticks) -- a
    # mean-of-means, superseded by the true per-entry percentiles below
    # (lat_p50/p95/p99 from the on-device histogram). Kept for continuity with
    # the BENCH_* history; both are derived in ONE pass (_latency_rollup) from
    # the same gathered metrics, so the two readouts cannot drift apart. None
    # when no cluster committed any client entry (e.g. client_interval == 0).
    p50_commit_latency: float | None
    # TRUE per-entry latency percentiles, recovered from the fleet-summed
    # log2-bin histogram (RunMetrics.lat_hist) with linear interpolation inside
    # the hit bin -- the tail visibility the mean-of-means above lacks. None
    # when no entry committed.
    lat_p50: float | None
    lat_p95: float | None
    lat_p99: float | None
    # Latency coverage gap (RunMetrics.lat_excluded): client entries whose
    # first commit fell in a leaderless window -- crossed by the dedup frontier
    # but never attributed into the histogram. The percentiles above cover
    # lat_cnt / (lat_cnt + lat_excluded) of committed client entries
    # (docs/PERF.md "latency metric coverage").
    lat_excluded: int
    # Liveness/coverage counters (RunMetrics): election wins that found no
    # no-op slot (compaction livelock early-warning), and node pairs the ring
    # log-matching check could not compare.
    noop_blocked: int
    lm_skipped_pairs: int
    # Split-brain exposure (RunMetrics.multi_leader): fleet-total ticks with
    # >= 2 concurrent LEADER roles. Legal under partitions (a deposed leader
    # that has not heard the news); the graded precursor the scenario search
    # climbs toward election-safety violations (docs/SCENARIOS.md).
    multi_leader: int
    # ReadIndex read traffic (RunMetrics.reads_served/read_hist; zeros unless
    # cfg.read_index): reads served fleet-wide and their true per-read
    # latency percentiles -- the commit-vs-read comparison the read traffic
    # class exists to expose (docs/PROTOCOL.md).
    reads_served: int
    read_p50: float | None
    read_p95: float | None
    read_p99: float | None
    # Durable storage plane (RunMetrics.fsync_lag_sum/fsync_lag_max; zeros
    # unless cfg.durable_storage): how far disks trail the logs. The
    # percentiles are over PER-CLUSTER mean lag (lag_sum / ticks, i.e.
    # node-summed entries-behind per tick) -- the fleet's "typical cluster"
    # durability debt -- and fsync_lag_max is the worst instantaneous
    # per-node lag seen anywhere (the burn plane's page signal feeds on the
    # per-window form of the same counters, health/spec.py durability_lag).
    fsync_lag_total: int
    fsync_lag_max: int
    fsync_lag_p50: float | None
    fsync_lag_p95: float | None


def gather_metrics(metrics):
    """Make a batched RunMetrics fully addressable on every process.

    Single-process metrics pass through untouched. Under multi-host execution the
    shard_map outputs are global arrays whose remote shards this process cannot
    read; a jitted identity with replicated out-shardings inserts the cross-host
    all-gather (every process must call this -- standard multi-controller SPMD),
    after which the host-side rollup below works unchanged. The metrics are a few
    int32s per cluster, so the DCN traffic is negligible (SURVEY.md section 5:
    DCN carries orchestration and metric collection only).
    """
    leaves = jax.tree.leaves(metrics)
    x0 = leaves[0]
    if not (hasattr(x0, "sharding") and not x0.is_fully_addressable):
        return metrics
    mesh = x0.sharding.mesh
    rep = NamedSharding(mesh, P())
    return jax.device_get(jax.jit(lambda t: t, out_shardings=rep)(metrics))


def _hist_percentile(hist, q: float) -> float | None:
    """The q-quantile latency from a summed log2-bin histogram: bin k holds
    latencies in [2^k, 2^(k+1)), linearly interpolated inside the hit bin.
    None for an empty histogram.

    The interpolation assumes uniform spread inside the bin, which biases
    upward by as much as the bin width; when the hit bin is the FIRST nonempty
    one the quantile is clamped to the bin's lower edge instead -- an
    all-1-tick run reports lat_p50 = 1.0, not 1.5 (the distribution's minimum
    is a hard lower bound on every quantile, and with no mass below the bin
    there is nothing to interpolate against). Tail granularity above the first
    bin remains up to 2x -- inherent to log2 binning."""
    total = int(hist.sum())
    if total == 0:
        return None
    need = q * total
    cum = 0
    for k, c in enumerate(int(x) for x in hist):
        if c and cum + c >= need:
            lo, hi = float(1 << k), float(1 << (k + 1))
            if cum == 0:
                return lo  # first nonempty bin: clamp to its lower edge
            return lo + (need - cum) / c * (hi - lo)
        cum += c
    return float(1 << len(hist))


def _latency_rollup(m) -> dict:
    """All four latency readouts (legacy mean-of-means p50 AND the true
    histogram percentiles) plus the coverage-gap counter, from ONE host-side
    pass over the same gathered metrics -- the single code path that keeps the
    legacy and histogram numbers from drifting (they answer the same question
    at different fidelities, so they must always be computed together)."""
    import numpy as np

    committed = m.lat_cnt > 0
    p50_lat = (
        float(np.median(m.lat_sum[committed] / m.lat_cnt[committed]))
        if np.any(committed)
        else None
    )
    hist = np.sum(np.asarray(m.lat_hist, dtype=np.int64), axis=0)  # [BINS]
    rhist = np.sum(np.asarray(m.read_hist, dtype=np.int64), axis=0)  # [BINS]
    return {
        "p50_commit_latency": p50_lat,  # legacy (see FleetSummary docstring)
        "lat_p50": _hist_percentile(hist, 0.50),
        "lat_p95": _hist_percentile(hist, 0.95),
        "lat_p99": _hist_percentile(hist, 0.99),
        "lat_excluded": int(np.sum(m.lat_excluded, dtype=np.int64)),
        "reads_served": int(np.sum(m.reads_served, dtype=np.int64)),
        "read_p50": _hist_percentile(rhist, 0.50),
        "read_p95": _hist_percentile(rhist, 0.95),
        "read_p99": _hist_percentile(rhist, 0.99),
    }


def summarize(metrics) -> FleetSummary:
    """Fleet-level rollup of a batched RunMetrics. The p50 quantile is computed
    host-side from the (small, [batch]-shaped) stable-tick vector. Handles
    multi-host (non-addressable) metrics via gather_metrics."""
    metrics = gather_metrics(metrics)
    stable = jax.device_get(scan.stable_leader_ticks(metrics))
    import numpy as np

    reached = stable[stable < scan.NEVER]
    # None (JSON null) rather than inf: json.dumps(inf) emits non-standard `Infinity`.
    p50 = float(np.median(reached)) if reached.size else None
    m = jax.device_get(metrics)
    return FleetSummary(
        n_clusters=int(m.ticks.shape[0]),
        total_violations=int(np.sum(m.violations)),
        n_stable=int(reached.size),
        p50_stable_tick=p50,
        max_term=int(np.max(m.max_term)),
        total_msgs=int(np.sum(m.total_msgs, dtype=np.int64)),
        total_cmds=int(np.sum(m.total_cmds, dtype=np.int64)),
        noop_blocked=int(np.sum(m.noop_blocked, dtype=np.int64)),
        lm_skipped_pairs=int(np.sum(m.lm_skipped_pairs, dtype=np.int64)),
        multi_leader=int(np.sum(m.multi_leader, dtype=np.int64)),
        **_fsync_lag_rollup(m),
        **_latency_rollup(m),
    )


def _fsync_lag_rollup(m) -> dict:
    """Fleet durability-lag readouts (FleetSummary docstring). Per-cluster
    mean lag = lag_sum / ticks (node-summed entries-behind per tick); the
    percentiles are None when no tick ran. All-zero with the storage plane
    off -- the gated metric legs never accumulate."""
    import numpy as np

    ticks = np.asarray(m.ticks, dtype=np.int64)
    ran = ticks > 0
    if np.any(ran):
        mean_lag = np.asarray(m.fsync_lag_sum, np.int64)[ran] / ticks[ran]
        p50 = float(np.percentile(mean_lag, 50))
        p95 = float(np.percentile(mean_lag, 95))
    else:
        p50 = p95 = None
    return {
        "fsync_lag_total": int(np.sum(m.fsync_lag_sum, dtype=np.int64)),
        "fsync_lag_max": int(np.max(m.fsync_lag_max)),
        "fsync_lag_p50": p50,
        "fsync_lag_p95": p95,
    }
