"""Scalar-Python oracle for the Raft tick semantics.

An independent re-implementation of raft_sim_tpu.models.raft.step using plain Python
loops and if/else over numpy state -- the `cond`-cascade form of the handlers (the shape
the reference writes them in, core.clj:91-169) -- used to cross-check the vectorized
`jnp.where` lattice, whose branch precedence is the hard part of the rebuild
(SURVEY.md section 7.3). Deliberately written for clarity, not speed; every phase
mirrors the kernel's documented phase order:

  deliver -> adopt terms -> vote requests -> append requests -> responses ->
  leader commit -> client inject -> timers -> outbox

The oracle operates on dicts of numpy arrays (the device ClusterState pulled host-side)
so the parity test can compare entire states bit-for-bit after every tick.
"""

from __future__ import annotations

import numpy as np

FOLLOWER, CANDIDATE, LEADER = 0, 1, 2
PRECANDIDATE = 3  # cfg.pre_vote probe state (thesis 9.6)
REQ_NONE, REQ_VOTE, REQ_APPEND, REQ_PREVOTE = 0, 1, 2, 3
REQ_TIMEOUT_NOW = 4  # cfg.leader_transfer (thesis 3.10)
RESP_NONE, RESP_VOTE, RESP_APPEND, RESP_PREVOTE = 0, 1, 2, 3
NIL = -1
# Independently-stated copies of the implementation's constants (the oracle must not
# import from raft_sim_tpu); tests/test_constants.py pins them against the originals
# so they cannot drift silently.
# raft_sim_tpu.utils.config ACK_AGE_SAT / ACK_AGE_SAT_NARROW + the ack_age_sat
# property, restated: ages saturate at the int8 ceiling when the responsiveness
# horizon fits under it, else at the int16 ceiling.
ACK_AGE_SAT = 30000
ACK_AGE_SAT_NARROW = 120
NOOP = -2  # raft_sim_tpu.types.NOOP (leader no-op entry value, compaction only)


def ack_age_sat(cfg) -> int:
    if cfg.ack_timeout_ticks < ACK_AGE_SAT_NARROW:
        return ACK_AGE_SAT_NARROW
    return ACK_AGE_SAT


def unpack_plane(words: np.ndarray, n: int) -> np.ndarray:
    """Independent numpy restatement of ops/bitplane.py's layout: uint32 words
    along the LAST axis, bit j of word w = source index 32*w + j. The oracle
    operates on plain [.., n] bool planes; the packed wire/state forms
    (ClusterState.votes, Mailbox.pv_grant, StepInputs.deliver_mask) are
    unpacked at the boundary."""
    words = np.asarray(words, np.uint32)
    k = np.arange(n)
    return ((words[..., k // 32] >> (k % 32)) & 1).astype(bool)


def pack_plane(bools: np.ndarray) -> np.ndarray:
    """Inverse of unpack_plane (last axis -> ceil(n/32) uint32 words)."""
    b = np.asarray(bools, bool)
    n = b.shape[-1]
    w = (n + 31) // 32
    out = np.zeros(b.shape[:-1] + (w,), np.uint32)
    for k in range(n):
        out[..., k // 32] |= b[..., k].astype(np.uint32) << (k % 32)
    return out


def chk_weights(k: int) -> tuple[int, int]:
    """(term weight, value weight) of 0-based log slot k for the committed-prefix
    checksum -- the oracle's statement of log_ops.chk_weights (mod 2^32)."""
    m = (1 << 32) - 1
    w_t = ((k * 2654435761 + 0x9E3779B9) | 1) & m
    w_v = ((k * 0x85EBCA77 + 0xC2B2AE3D) | 1) & m
    return w_t, w_v


def _bits_for(n_values: int) -> int:
    """Bits to store values 0..n_values-1 (>= 1). Restates ops/tile.bits_for;
    pinned against it in tests/test_constants.py."""
    return max(1, (n_values - 1).bit_length())


def pack_widths(cfg) -> dict:
    """Independent restatement of the compacted layout's pack-width table
    (ops/tile.pack_width_table): field -> (bits, bias, lo, hi), where lo..hi
    is the dense value range and bias shifts it non-negative before packing.
    Deliberately import-free of raft_sim_tpu -- this is the oracle's own
    derivation from the protocol bounds (next_index 1..cap+1 and match_index
    0..cap, non-compaction only; ack_age saturating at the restated ceiling;
    req_off -1..E with a +1 bias; resp_kind RESP_* 0..3) -- and pinned
    against the tile.py table in tests/test_constants.py."""
    cap, e, sat = cfg.log_capacity, cfg.max_entries_per_rpc, ack_age_sat(cfg)
    table = {}
    if cfg.compact_margin == 0:  # compaction carries dense absolute indices
        table["next_index"] = (_bits_for(cap + 2), 0, 1, cap + 1)
        table["match_index"] = (_bits_for(cap + 2), 0, 0, cap)
    table["ack_age"] = (_bits_for(sat + 1), 0, 0, sat)
    table["mb.req_off"] = (_bits_for(e + 2), 1, -1, e)
    table["mb.resp_kind"] = (2, 0, 0, 3)
    return table


def unpack_values(words: np.ndarray, bits: int, count: int) -> np.ndarray:
    """Independent numpy restatement of the compacted sub-byte layout
    (ops/tile.py pack_words): k = 32 // bits values per uint32 word, value i
    at word i // k, bit lane (i % k) * bits. Returns int64 values."""
    words = np.asarray(words, np.uint32)
    k = 32 // bits
    i = np.arange(count)
    return (
        (words[i // k] >> np.uint32((i % k) * bits)) & np.uint32((1 << bits) - 1)
    ).astype(np.int64)


def _uncompact(cfg, d: dict) -> None:
    """Undo the compacted carry layout (cfg.compact_planes) in place: the
    per-edge value planes unpack from their bit-packed flat uint32 legs at
    the independently restated widths (next/match: log indices bounded by
    cap + 1, non-compaction only; ack_age: the saturation ceiling; req_off:
    -1..E with a +1 bias; resp_kind: RESP_* 0..3), the word/window planes
    reshape back from their flattened forms. The oracle's view -- and the
    parity comparison domain -- stays the dense one either way."""
    n, e, cap = cfg.n_nodes, cfg.max_entries_per_rpc, cfg.log_capacity
    w = (n + 31) // 32
    mb = d["mailbox"]
    idt = np.int8 if cap <= 41 else np.int16  # types.index_dtype, restated
    adt = np.int8 if ack_age_sat(cfg) < 127 else np.int16  # types.ack_dtype
    widths = pack_widths(cfg)

    def _un(leg, field, dtype):
        bits, bias, _lo, _hi = widths[field]
        vals = unpack_values(leg, bits, n * n)
        if bias:
            vals = vals - bias
        return vals.astype(dtype).reshape(n, n)

    if cfg.compact_margin == 0:  # compaction carries dense absolute indices
        d["next_index"] = _un(d["next_index"], "next_index", idt)
        d["match_index"] = _un(d["match_index"], "match_index", idt)
    d["ack_age"] = _un(d["ack_age"], "ack_age", adt)
    mb["req_off"] = _un(mb["req_off"], "mb.req_off", np.int8)
    mb["resp_kind"] = _un(mb["resp_kind"], "mb.resp_kind", np.int8)
    d["votes"] = d["votes"].reshape(n, w)
    for f in ("ent_term", "ent_val", "ent_tick", "ent_cfg"):
        mb[f] = mb[f].reshape(n, e)


def state_to_dict(state, cfg=None) -> dict:
    """Host-side copy of a single-cluster ClusterState (device pytree -> numpy).
    Bit-packed planes (votes, mailbox pv_grant) are unpacked to [N, N] bool:
    the oracle's view -- and the parity tests' comparison domain -- stays the
    dense boolean one. States carried in the compacted layout
    (cfg.compact_planes) need `cfg` so the restated bit widths can undo the
    packing first."""
    d = {
        f: np.asarray(v)
        for f, v in zip(state._fields, state)
        if f != "mailbox"
    }
    mb = state.mailbox
    d["mailbox"] = {f: np.asarray(v) for f, v in zip(mb._fields, mb)}
    if cfg is not None and cfg.compact_planes:
        _uncompact(cfg, d)
    n = d["role"].shape[0]
    d["votes"] = unpack_plane(d["votes"], n)
    d["mailbox"]["pv_grant"] = unpack_plane(d["mailbox"]["pv_grant"], n)
    # Reconfiguration / ReadIndex packed planes: the oracle's view (and the
    # parity tests' comparison domain) is the dense boolean one. member_old/
    # member_new/base_mold are PER-NODE rows ([N, W] -> [N, N]): row i is
    # node i's configuration as derived from its own log prefix.
    d["member_old"] = unpack_plane(d["member_old"], n)
    d["member_new"] = unpack_plane(d["member_new"], n)
    d["base_mold"] = unpack_plane(d["base_mold"], n)
    d["mailbox"]["req_base_mold"] = unpack_plane(d["mailbox"]["req_base_mold"], n)
    d["read_acks"] = unpack_plane(d["read_acks"], n)
    return d


def term_at(log_term: np.ndarray, index1: int) -> int:
    """Term of the 1-based entry `index1`; 0 for index1 == 0 (no entry)."""
    if index1 <= 0:
        return 0
    cap = log_term.shape[0]
    return int(log_term[min(index1 - 1, cap - 1)])


def term_at_ring(log_term: np.ndarray, base: int, base_term: int, index1: int) -> int:
    """Ring-aware term_at: 1-based entry index1 from slot (index1 - 1) mod CAP when
    live (index1 > base); base_term for the compacted prefix; 0 for no entry.
    Degenerates to term_at for base == 0 within the live range."""
    if index1 == 0:
        return 0
    if index1 <= base:
        return int(base_term)
    cap = log_term.shape[0]
    return int(log_term[(index1 - 1) % cap])


def oracle_step(cfg, s: dict, inp: dict) -> dict:
    """One tick for one cluster; returns a fresh state dict."""
    n, e, cap = cfg.n_nodes, cfg.max_entries_per_rpc, cfg.log_capacity
    # Offer-tick plane active: latency stamps ride log_tick / mailbox ent_tick
    # beside the (now arbitrary) payload values; inactive configs leave every
    # tick-plane leaf untouched (mirroring the kernel's passthrough legs).
    track = cfg.track_offer_ticks
    mb = s["mailbox"]

    rcf = cfg.reconfig
    xfr = cfg.leader_transfer
    rdx = cfg.read_index
    rdl = cfg.read_lease
    role = s["role"].copy()
    term = s["term"].copy()
    voted_for = s["voted_for"].copy()
    leader_id = s["leader_id"].copy()
    votes = s["votes"].copy()
    next_index = s["next_index"].copy()
    match_index = s["match_index"].copy()
    ack_age = s["ack_age"].copy()
    commit = s["commit_index"].copy()
    commit_chk = s["commit_chk"].copy()
    log_base = s["log_base"].copy()
    base_term = s["base_term"].copy()
    base_chk = s["base_chk"].copy()
    log_term = s["log_term"].copy()
    log_val = s["log_val"].copy()
    log_tick = s["log_tick"].copy()
    log_len = s["log_len"].copy()
    deadline = s["deadline"].copy()
    heard_clock = s["heard_clock"].copy()
    # Log-carried configuration (models/cfglog.py): per-node derived state.
    # Row d of member_old/member_new is node d's own view; the end-of-tick
    # derivation below recomputes all of it from the post-append log.
    member_old = s["member_old"].copy()  # [N, N] bool (oracle view: unpacked)
    member_new = s["member_new"].copy()
    cfg_epoch = np.asarray(s["cfg_epoch"], np.int32).copy()  # [N]
    cfg_pend = np.asarray(s["cfg_pend"], np.int32).copy()  # [N]
    log_cfg = s["log_cfg"].copy()  # [N, CAP] config-entry commands
    base_mold = s["base_mold"].copy()  # [N, N] bool: C_old at each node's base
    base_pend = np.asarray(s["base_pend"], np.int32).copy()  # [N]
    base_epoch = np.asarray(s["base_epoch"], np.int32).copy()  # [N]
    xfer_to = np.asarray(s["xfer_to"], np.int32).copy()
    # Durable storage plane (models/raft.py phase -1 / 7.5): the fsynced
    # prefix length and the term/vote snapshot at the last completed flush.
    dur = cfg.durable_storage
    dur_len = np.asarray(s["dur_len"], np.int32).copy()
    dur_term = np.asarray(s["dur_term"], np.int32).copy()
    dur_vote = np.asarray(s["dur_vote"], np.int32).copy()
    read_idx = s["read_idx"].copy()
    read_tick = s["read_tick"].copy()
    read_acks = np.asarray(s["read_acks"], bool).copy()
    read_fr = s["read_fr"].copy()

    alive = np.asarray(inp["alive"], bool)
    restarted = np.asarray(inp["restarted"], bool)

    # ---- phase -1: restart wipe (persistent term/vote/log -- including the
    # snapshot triple -- survive; volatile wiped; commit resumes at the base)
    for d in range(n):
        if restarted[d]:
            role[d] = FOLLOWER
            leader_id[d] = NIL
            votes[d, :] = False
            next_index[d, :] = 1
            match_index[d, :] = 0
            ack_age[d, :] = ack_age_sat(cfg)
            commit[d] = log_base[d]
            commit_chk[d] = base_chk[d]
            deadline[d] = int(s["clock"][d]) + int(inp["timeout_draw"][d])
            if dur:
                # Crash recovery: reload the durable term/vote snapshot and
                # truncate the un-fsynced (possibly torn) log suffix. The
                # fsync watermark FLOORS the recovered length -- a completed
                # flush never tears -- so torn_drop eats only the volatile
                # tail (models/raft.py phase -1).
                term[d] = dur_term[d]
                voted_for[d] = int(dur_vote[d]) if cfg.persist_vote else NIL
                log_len[d] = max(
                    int(dur_len[d]),
                    int(s["log_len"][d]) - int(inp["torn_drop"][d]),
                )
            if cfg.pre_vote or rdl or rcf:
                # a restarted node remembers no leader contact (pre-votes
                # grantable; under the lease or log-carried-config denial
                # gates, real votes too)
                heard_clock[d] = int(s["clock"][d]) - cfg.election_min_ticks
            if xfr:
                xfer_to[d] = NIL  # pending transfers die with the process
            if rdx:
                read_idx[d] = 0  # pending reads die with the process
                read_tick[d] = 0
                read_acks[d, :] = False
                if rdl:
                    read_fr[d] = 0  # the staleness anchor dies with the slot

    # Log-carried configuration: the TICK-START derivation governs every
    # quorum test this tick (models/raft.py); the end-of-tick block
    # recomputes it from the post-append log. Each node masks by ITS OWN
    # rows -- dual (both configs) while that node's own prefix holds an
    # uncompleted joint entry. The helper closes over SNAPSHOTS so later
    # phases' rebinds cannot leak in.
    if rcf:
        q_member_old = member_old.copy()  # [N, N] tick-start, never rebound
        q_member_new = member_new.copy()
        joint0 = cfg_pend > 0  # [N]
        maj_old = q_member_old.sum(axis=1) // 2 + 1  # [N]
        maj_new = q_member_new.sum(axis=1) // 2 + 1
        # member_b[d]: is d a voter of ITS OWN config union? A node whose
        # log carries its removal quiesces; one whose log MISSES it still
        # campaigns -- the removed-server disruption the 4.2.3 denial below
        # defends against.
        member_b = np.array(
            [(q_member_old[d, d] or q_member_new[d, d]) for d in range(n)], bool
        )

    def packed_quorum_row(d: int, grants_row: np.ndarray) -> bool:
        """grants_row: [N] bool of node d's banked grants -> quorum under
        node d's OWN configuration(s)."""
        if not rcf:
            return int(grants_row.sum()) >= cfg.quorum
        ok = int((grants_row & q_member_old[d]).sum()) >= int(maj_old[d])
        if joint0[d]:
            ok = ok and int((grants_row & q_member_new[d]).sum()) >= int(maj_new[d])
        return ok

    # ---- phase 0: delivery
    # Input mask is per physical edge [to, from]; request headers are per sender
    # (broadcasts; the [sender, receiver] masks read the edge mask transposed),
    # responses are [receiver, responder] packed words (direct).
    # A receiver must be alive now AND at send time (last tick): alive & ~restarted.
    # The delivery mask arrives bit-packed over the source axis; unpack to the
    # dense [to, from] bool form the handler loops read. Under the compacted
    # layout (cfg.compact_planes) the word plane additionally ships FLAT
    # ([N*W]): restore the [N, W] row view first.
    dm = np.asarray(inp["deliver_mask"])
    if dm.ndim == 1:
        dm = dm.reshape(n, -1)
    edge_ok = unpack_plane(dm, n).copy()
    np.fill_diagonal(edge_ok, False)
    recv_up = alive & ~restarted
    req_in = edge_ok.T & alive[:, None] & recv_up[None, :] & (mb["req_type"] != 0)[:, None]
    resp_in = edge_ok & recv_up[:, None] & alive[None, :] & (mb["resp_kind"] != 0)

    # Heard-a-leader denial window (thesis 4.2.3; models/raft.py): shared by
    # the log-carried membership defense (rcf) and the lease vote denial
    # (rdl), bypassed per sender by the transfer override flag.
    if rcf or rdl:
        def rv_denied(src: int, d: int) -> bool:
            clock_d = int(s["clock"][d]) + int(inp["skew"][d])
            recent = clock_d - int(heard_clock[d]) < cfg.election_min_ticks
            if xfr and int(mb["req_disrupt"][src]) != 0:
                return False  # transfer-sanctioned election: always processed
            return recent

    # ---- phase 1: term adoption
    saw_higher = np.zeros(n, bool)
    for d in range(n):
        in_term = 0
        for src in range(n):
            # a PreVote probe's term is prospective: never adopted
            if req_in[src, d] and mb["req_type"][src] != REQ_PREVOTE:
                if (
                    rcf
                    and mb["req_type"][src] == REQ_VOTE
                    and rv_denied(src, d)
                ):
                    # 4.2.3 in full: a denied RequestVote is not PROCESSED --
                    # no term adoption (the removed-server disruption
                    # defense; under rdl alone adoption stays legal).
                    continue
                in_term = max(in_term, int(mb["req_term"][src]))
            if resp_in[d, src]:
                in_term = max(in_term, int(mb["resp_term"][src]))
        if in_term > term[d]:
            saw_higher[d] = True
            term[d] = in_term
            role[d] = FOLLOWER
            voted_for[d] = NIL
            leader_id[d] = NIL
            votes[d, :] = False

    # ---- phase 2: RequestVote requests
    granted_any = np.zeros(n, bool)
    vr_out = np.zeros((n, n), bool)  # [dst, src]: respond to src
    v_to = np.full(n, NIL, np.int32)  # the one candidate granted this tick
    for d in range(n):
        my_last_idx = int(s["log_len"][d])
        my_last_term = term_at_ring(
            s["log_term"][d], int(log_base[d]), int(base_term[d]), my_last_idx
        )
        can = []
        for src in range(n):
            if not (req_in[src, d] and mb["req_type"][src] == REQ_VOTE):
                continue
            vr_out[d, src] = True
            if mb["req_term"][src] != term[d]:
                continue
            c_idx = int(mb["req_last_index"][src])
            c_term = int(mb["req_last_term"][src])
            up_to_date = c_term > my_last_term or (
                c_term == my_last_term and c_idx >= my_last_idx
            )
            if up_to_date:
                can.append(src)
        if rcf or rdl:
            # Heard-a-leader vote denial (thesis 4.2.3; models/raft.py
            # phase 2): a voter that heard from a current leader within the
            # minimum election timeout on its LOCAL clock denies
            # RequestVote -- unless the sender carries the transfer
            # override (rv_denied folds it in).
            can = [src for src in can if not rv_denied(src, d)]
        if not can:
            continue
        if voted_for[d] != NIL:
            if voted_for[d] in can:  # idempotent re-grant
                v_to[d] = voted_for[d]
                granted_any[d] = True
        else:
            winner = min(can)
            v_to[d] = winner
            granted_any[d] = True
            voted_for[d] = winner

    # ---- phase 3: AppendEntries requests (incl. the InstallSnapshot analogue).
    # Response payloads are per RESPONDER (sparse by construction: at most one
    # success target per tick; the nack hint is the responder's log length toward
    # every sender -- types.Mailbox docstring).
    has_ae = np.zeros(n, bool)
    snap_applied = np.zeros(n, bool)
    ar_out = np.zeros((n, n), bool)
    a_ok_to = np.full(n, NIL, np.int32)
    a_match = np.zeros(n, np.int32)
    for d in range(n):
        cur = [
            src
            for src in range(n)
            if req_in[src, d]
            and mb["req_type"][src] == REQ_APPEND
        ]
        for src in cur:
            ar_out[d, src] = True
        cur_term = [src for src in cur if mb["req_term"][src] == term[d]]
        if not cur_term:
            continue
        src = min(cur_term)
        has_ae[d] = True
        if role[d] == CANDIDATE or (cfg.pre_vote and role[d] == PRECANDIDATE):
            role[d] = FOLLOWER
        leader_id[d] = src

        j = int(mb["req_off"][src, d])
        if j < 0:
            # InstallSnapshot analogue (req_off sentinel -1): install the sender's
            # compaction base. If our log extends through L with the snapshot's
            # term, retain the suffix; else discard the log. L <= our base needs
            # nothing. Always ack with match = L.
            L = int(mb["req_base"][src])
            if L > int(log_base[d]):
                keep = L <= int(log_len[d]) and term_at_ring(
                    log_term[d], int(log_base[d]), int(base_term[d]), L
                ) == int(mb["req_base_term"][src])
                base_term[d] = int(mb["req_base_term"][src])
                base_chk[d] = mb["req_base_chk"][src]
                log_base[d] = L
                if not keep:
                    log_len[d] = L
                commit[d] = max(int(commit[d]), L)
                snap_applied[d] = True
                if rcf:
                    # The snapshot carries its configuration context: the
                    # sender's C_old/pending-toggle/entry-count at L, so the
                    # receiver's derivation stays exact over config entries
                    # it never saw (models/raft.py phase 3).
                    base_mold[d] = np.asarray(mb["req_base_mold"][src], bool)
                    base_pend[d] = int(mb["req_base_pend"][src])
                    base_epoch[d] = int(mb["req_base_epoch"][src])
            a_ok_to[d] = src
            a_match[d] = L
            continue

        # Reconstruct the per-edge AE header from the sender's broadcast record plus
        # this edge's window offset j (Mailbox docstring): prev = ent_start + j,
        # prev term = ent_prev_term for j == 0 else window slot j-1, and n_entries =
        # whatever of the window lies past j.
        ws = int(mb["ent_start"][src])
        prev_i = ws + j
        prev_t = (
            int(mb["ent_prev_term"][src]) if j == 0 else int(mb["ent_term"][src, j - 1])
        )
        lcommit = int(mb["req_commit"][src])
        n_ent = min(max(int(mb["ent_count"][src]) - j, 0), e)
        # This receiver's entries start at window slot j (clipped reads past the
        # window occur only at masked k >= n_ent positions).
        ent_t = [int(mb["ent_term"][src, min(j + k, e - 1)]) for k in range(e)]
        ent_v = [int(mb["ent_val"][src, min(j + k, e - 1)]) for k in range(e)]
        ent_tk = [int(mb["ent_tick"][src, min(j + k, e - 1)]) for k in range(e)]
        ent_cf = [int(mb["ent_cfg"][src, min(j + k, e - 1)]) for k in range(e)]

        b = int(log_base[d])
        # prev below our base is committed-and-compacted: consistent by leader
        # completeness; at prev == base the check compares against base_term.
        consistent = prev_i == 0 or prev_i < b or (
            prev_i <= int(s["log_len"][d])
            and term_at_ring(log_term[d], b, int(base_term[d]), prev_i) == prev_t
        )
        if not consistent:
            continue

        # Skip entries already compacted (<= base), accept only what the ring can
        # hold (<= base + CAP).
        lo = min(max(b - prev_i, 0), e)
        n_acc = min(n_ent, max(b + cap - prev_i, 0))
        any_mismatch = any(
            lo <= k < n_acc
            and prev_i + k < int(s["log_len"][d])
            and int(log_term[d, (prev_i + k) % cap]) != int(ent_t[k])
            for k in range(e)
        )
        appended_len = prev_i + n_acc
        new_len = appended_len if any_mismatch else max(int(s["log_len"][d]), appended_len)
        for k in range(lo, n_acc):
            log_term[d, (prev_i + k) % cap] = ent_t[k]
            log_val[d, (prev_i + k) % cap] = ent_v[k]
            if track:
                # The offer stamp replicates with the entry it tags.
                log_tick[d, (prev_i + k) % cap] = ent_tk[k]
            if rcf:
                # The config command replicates beside the entry; non-config
                # entries ship 0, scrubbing stale commands off reused slots.
                log_cfg[d, (prev_i + k) % cap] = ent_cf[k]
        log_len[d] = new_len

        last_new = min(prev_i + n_acc, new_len)
        commit[d] = max(int(commit[d]), min(lcommit, last_new))
        a_ok_to[d] = src
        a_match[d] = last_new

    # Durable watermark after the AE conflict truncation (models/raft.py
    # phase 3): a truncation below the watermark drags it down with the log.
    if dur:
        dur_mid = np.minimum(dur_len, log_len.astype(np.int32))

    # NACK catch-up hint: every unsuccessful AE response carries the responder's
    # (post-append) log length -- the conflict-index optimization (raft.py
    # phase 3). Per responder: the same hint toward every nacked sender.
    a_hint = log_len.astype(np.int32).copy()

    # ---- phase 3.5: PreVote requests (thesis 9.6; raft.py): grant iff the
    # probe's prospective term is not behind us, its log is up to date, and we
    # are quiet (not leader, no valid AE within the minimum election timeout).
    pv_out = np.zeros((n, n), bool)
    pv_grant = np.zeros((n, n), bool)
    if (rdl or rcf) and not cfg.pre_vote:
        # heard_clock maintenance for the lease / removed-server vote
        # denials (the pre-vote branch below maintains it when that gate is
        # on too).
        for d in range(n):
            if has_ae[d]:
                heard_clock[d] = int(s["clock"][d]) + int(inp["skew"][d])
    if cfg.pre_vote:
        for d in range(n):
            clock_pv = int(s["clock"][d]) + int(inp["skew"][d])
            if has_ae[d]:
                heard_clock[d] = clock_pv
            quiet = (
                clock_pv - int(heard_clock[d]) >= cfg.election_min_ticks
                and role[d] != LEADER
            )
            my_last_idx = int(s["log_len"][d])
            my_last_term = term_at_ring(
                s["log_term"][d], int(s["log_base"][d]), int(s["base_term"][d]),
                my_last_idx,
            )
            for src in range(n):
                if not (req_in[src, d] and mb["req_type"][src] == REQ_PREVOTE):
                    continue
                pv_out[d, src] = True
                c_idx = int(mb["req_last_index"][src])
                c_term = int(mb["req_last_term"][src])
                up = c_term > my_last_term or (
                    c_term == my_last_term and c_idx >= my_last_idx
                )
                if quiet and up and int(mb["req_term"][src]) >= int(term[d]):
                    pv_grant[d, src] = True

    # ---- phase 3.7: TimeoutNow receipt (thesis 3.10; models/raft.py)
    xfer_elect = np.zeros(n, bool)
    coup = np.zeros(n, bool)
    if xfr:
        for d in range(n):
            if role[d] == LEADER or not alive[d]:
                continue
            if rcf and not member_b[d]:
                continue  # non-voters never campaign
            got = any(
                req_in[src, d]
                and mb["req_type"][src] == REQ_TIMEOUT_NOW
                and int(mb["xfer_tgt"][src]) == d
                and int(mb["req_term"][src]) == int(term[d])
                for src in range(n)
            )
            if not got:
                continue
            if cfg.xfer_election:
                xfer_elect[d] = True
            else:
                # TEST-ONLY mutant: transfer as a coup (no vote round).
                coup[d] = True
                term[d] += 1
                role[d] = LEADER
                leader_id[d] = d

    # ---- phase 4: responses
    # Everyone's ack age grows one tick (saturating); stamps below zero it.
    ack_age = np.minimum(ack_age + 1, ack_age_sat(cfg)).astype(ack_age.dtype)
    for d in range(n):
        for src in range(n):
            if (
                resp_in[d, src]
                and mb["resp_kind"][d, src] == RESP_VOTE
                and mb["v_to"][src] == d
                and mb["resp_term"][src] == term[d]
                and role[d] == CANDIDATE
            ):
                votes[d, src] = True
    win = np.zeros(n, bool)
    for d in range(n):
        campaign_ok = role[d] == CANDIDATE and packed_quorum_row(d, votes[d]) and alive[d]
        if rcf and not member_b[d]:
            campaign_ok = False  # removed nodes cannot win on banked votes
        if campaign_ok or coup[d]:
            win[d] = True
            role[d] = LEADER
            leader_id[d] = d
            next_index[d, :] = log_len[d] + 1
            match_index[d, :] = 0
            ack_age[d, :] = 0  # grace-zero every peer (see raft.py phase 4)

    # ---- phase 4.5: PreVote responses + promotion (thesis 9.6; raft.py)
    pre_win = np.zeros(n, bool)
    if cfg.pre_vote:
        for d in range(n):
            if role[d] != PRECANDIDATE:
                continue
            for src in range(n):
                # The grant bit rides the packed pv_grant plane (unpacked to
                # [receiver, responder] bool by state_to_dict).
                if (
                    resp_in[d, src]
                    and int(mb["resp_kind"][d, src]) == RESP_PREVOTE
                    and bool(mb["pv_grant"][d, src])
                ):
                    votes[d, src] = True
            if (
                packed_quorum_row(d, votes[d])
                and alive[d]
                and not (rcf and not member_b[d])
            ):
                pre_win[d] = True
                term[d] += 1
                role[d] = CANDIDATE
                voted_for[d] = d
                votes[d, :] = False
                votes[d, d] = True
    aresp_pairs = np.zeros((n, n), bool)  # [leader, responder]: AE response seen
    for d in range(n):
        if role[d] != LEADER:
            continue
        for src in range(n):
            if not (
                resp_in[d, src]
                and mb["resp_kind"][d, src] == RESP_APPEND
                and mb["resp_term"][src] == term[d]
            ):
                continue
            aresp_pairs[d, src] = True
            if mb["a_ok_to"][src] == d:
                m = int(mb["a_match"][src])
                match_index[d, src] = max(int(match_index[d, src]), m)
                next_index[d, src] = max(int(next_index[d, src]), m + 1)
            else:
                # Back off to min(next-1, hint+1): the nack hint is the
                # responder's log length (conflict-index hint, raft.py phase 4).
                next_index[d, src] = max(
                    min(int(next_index[d, src]) - 1, int(mb["a_hint"][src]) + 1), 1
                )
            # Any AE response (success or failure) proves the peer is up.
            ack_age[d, src] = 0

    # ---- phase 5: leader commit advancement
    def masked_qmatch(match: np.ndarray, mask: np.ndarray, maj: int) -> int:
        """Largest index replicated to >= maj members of `mask` (0 if none);
        candidates range over the members' own match values (raft.py)."""
        best = 0
        for j in range(n):
            if not mask[j]:
                continue
            v = int(match[j])
            if sum(1 for k in range(n) if mask[k] and int(match[k]) >= v) >= maj:
                best = max(best, v)
        return best

    for d in range(n):
        if role[d] != LEADER or not alive[d]:
            continue
        match = match_index[d].copy()
        # A leader's own quorum vote is its DURABLE length under the storage
        # plane's ack gate (models/raft.py phase 5).
        match[d] = dur_mid[d] if (dur and cfg.durable_acks) else log_len[d]
        if rcf:
            # Each leader's OWN derived configuration masks its commit
            # quorum (tick-start rows; models/raft.py phase 5).
            quorum_match = masked_qmatch(
                match, q_member_old[d], int(maj_old[d])
            )
            if joint0[d]:
                quorum_match = min(
                    quorum_match,
                    masked_qmatch(match, q_member_new[d], int(maj_new[d])),
                )
        else:
            quorum_match = int(np.sort(match)[::-1][cfg.quorum - 1])
        if quorum_match > commit[d] and term_at_ring(
            log_term[d], int(log_base[d]), int(base_term[d]), quorum_match
        ) == term[d]:
            commit[d] = quorum_match

    # ---- phase 5.2: reconfiguration transitions moved INTO the log
    # (log-carried membership: no admin transition block -- config changes
    # are appends in phase 6, each node's configuration re-derives from its
    # own prefix at end of tick; models/raft.py phase 5.2 comment)
    xfer_pend = np.zeros(n, bool)
    if xfr:
        for d in range(n):
            if xfer_to[d] != NIL:
                t = int(xfer_to[d])
                if (
                    role[d] != LEADER
                    or int(ack_age[d, t]) > cfg.ack_timeout_ticks
                ):
                    xfer_to[d] = NIL  # abort: deposed or unresponsive target
        t_x = int(inp["transfer_cmd"])
        ld_ok = [
            d
            for d in range(n)
            if role[d] == LEADER and alive[d] and not (rcf and not member_b[d])
        ]
        if t_x != NIL and ld_ok:
            ldx = min(ld_ok)
            # Target must be a voter of the LEADER's own target config
            # (per-node derived rows; tick-start like every config read).
            t_voter = bool(q_member_new[ldx, t_x]) if rcf else True
            if t_x != ldx and t_voter and xfer_to[ldx] == NIL:
                xfer_to[ldx] = t_x
        xfer_pend = xfer_to != NIL
    if rdx:
        # Bank this tick's AE responses, serve confirmed reads, capture new.
        pend0_arr = read_idx > 0  # pending at tick start (pre-serve/capture)
        for d in range(n):
            pend0 = bool(pend0_arr[d])
            if pend0 and role[d] == LEADER:
                read_acks[d] |= aresp_pairs[d]
                acks_eff = read_acks[d].copy()
                acks_eff[d] = True
                confirmed = packed_quorum_row(d, acks_eff)
                served = (confirmed if cfg.read_confirm else True) and alive[d]
                if rdl and not served and alive[d]:
                    # Lease fast path (thesis 6.4.1; models/raft.py): a
                    # fresh config quorum of AE acks serves with NO
                    # confirmation round. The lease-skew mutant widens the
                    # window to the no-skew bound.
                    lease_w = (
                        cfg.read_lease_ticks
                        if cfg.lease_skew_safe
                        else cfg.election_min_ticks + 2
                    )
                    fresh_row = np.asarray(ack_age[d] <= lease_w, bool).copy()
                    fresh_row[d] = True
                    served = packed_quorum_row(d, fresh_row)
                    if xfr and xfer_pend[d]:
                        # Transfer handoff covers the read path: the lease
                        # fast path stops while a transfer pends
                        # (models/raft.py phase 5).
                        served = False
                if served:
                    # serve (the latency metric rides StepInfo, which the
                    # oracle does not produce; parity pins the slot clears)
                    read_idx[d] = 0
                    read_tick[d] = 0
                    read_acks[d, :] = False
                    if rdl:
                        read_fr[d] = 0
            elif pend0:
                read_idx[d] = 0  # role loss / adoption cancels the read
                read_tick[d] = 0
                read_acks[d, :] = False
                if rdl:
                    read_fr[d] = 0
        if int(inp["read_cmd"]) != NIL:
            caps = []
            for d in range(n):
                if not (role[d] == LEADER and alive[d] and not pend0_arr[d]):
                    continue
                if xfr and xfer_pend[d]:
                    continue
                if cfg.read_confirm and term_at_ring(
                    log_term[d], int(log_base[d]), int(base_term[d]),
                    int(commit[d]),
                ) != int(term[d]):
                    continue  # no current-term entry committed yet
                caps.append(d)
            if caps:
                d = min(caps)
                read_idx[d] = int(commit[d]) + 1
                read_tick[d] = int(s["now"]) + 1
                read_acks[d, :] = False
                if rdl:
                    # Staleness anchor: the committed frontier at capture
                    # (lat_frontier semantics -- models/raft.py phase 5).
                    read_fr[d] = max(int(s["lat_frontier"]),
                                     int(commit.max()))

    # ---- phase 5.5: log compaction (advance base toward commit when fewer than
    # compact_margin free ring slots remain; base_chk extends in the checksum pass)
    base_mid = log_base.copy()
    base_chk_mid = base_chk.copy()
    if cfg.compact_margin > 0:
        for d in range(n):
            target = min(int(commit[d]), int(log_len[d]) - (cap - cfg.compact_margin))
            if target > int(log_base[d]):
                base_term[d] = term_at_ring(
                    log_term[d], int(log_base[d]), int(base_term[d]), target
                )
                if rcf:
                    # Fold the compacted span's config entries into the
                    # snapshot context (models/cfglog.py fold_span): final
                    # toggles into base_mold, the latest entry's jointness
                    # into base_pend, the count into base_epoch. Runs before
                    # phase 6 can reuse freed slots.
                    span = [
                        (a, int(log_cfg[d, (a - 1) % cap]))
                        for a in range(int(log_base[d]) + 1, target + 1)
                        if int(log_cfg[d, (a - 1) % cap]) != 0
                    ]
                    for _, code in span:
                        if code < 0 or not cfg.joint_consensus:
                            v = abs(code) - 1
                            base_mold[d, v] = not base_mold[d, v]
                    if span and cfg.joint_consensus:
                        code_hi = span[-1][1]
                        base_pend[d] = code_hi if code_hi > 0 else 0
                    base_epoch[d] += len(span)
                log_base[d] = target

    # ---- committed-prefix checksum (log_ops.chk_weights analogue): weights by
    # ABSOLUTE entry index, anchored at the pre-compaction base (base_mid); the
    # same pass extends base_chk over the newly compacted span. Runs BEFORE
    # injection -- a write into a slot freed by this tick's rebase would alias
    # under the anchored slot->index map (raft.py). Under compaction the sums are
    # maintained even with invariant checking off (base_chk is wire state).
    if cfg.check_invariants or cfg.compact_margin > 0:
        M = (1 << 32) - 1
        for d in range(n):
            acc = int(base_chk_mid[d])
            accb = int(base_chk_mid[d])
            for a in range(int(base_mid[d]), int(commit[d])):  # 0-based abs index
                w_t, w_v = chk_weights(a)
                contrib = int(log_term[d, a % cap]) * w_t + int(log_val[d, a % cap]) * w_v
                acc = (acc + contrib) & M
                if a < int(log_base[d]):
                    accb = (accb + contrib) & M
            commit_chk[d] = np.uint32(acc)
            base_chk[d] = np.uint32(accb)

    # ---- phase 6: client injection (ring slot; space = retained window < CAP),
    # redirect routing, and the election-win leader no-op (raft.py phase 6)
    cmd_in = int(inp["client_cmd"])
    now0 = int(s["now"])  # pre-increment tick: a fresh offer's stamp is now0 + 1
    comp = cfg.compact_margin > 0
    reserve = max(1, cfg.compact_margin // 2)
    K = cfg.client_pipeline
    client_pend = [int(x) for x in np.atleast_1d(s["client_pend"])]
    client_dst = [int(x) for x in np.atleast_1d(s["client_dst"])]
    client_tick = [int(x) for x in np.atleast_1d(s["client_tick"])]

    def noop_at(d):
        return comp and win[d] and int(log_len[d]) - int(log_base[d]) < cap

    def room_at(d):
        retained = int(log_len[d]) - int(log_base[d])
        return retained < (cap - reserve if comp else cap)

    def append(d, value, stamp, code=0):
        log_term[d, log_len[d] % cap] = term[d]
        log_val[d, log_len[d] % cap] = value
        if track:
            # Offer stamp beside the payload (no-ops/protocol filler: 0).
            log_tick[d, log_len[d] % cap] = stamp
        if rcf:
            # EVERY append writes the config plane (0 for non-config
            # entries): a reused slot never leaks its old command.
            log_cfg[d, log_len[d] % cap] = code
        log_len[d] += 1

    # Config-entry origination (log-carried membership; models/raft.py
    # phase 6): config changes are LOG WRITES sharing the one-append-per-
    # node slot at priority no-op > config entry > client command, judged
    # on each leader's OWN tick-start derived configuration.
    cfg_write = np.zeros(n, bool)
    cfg_code = np.zeros(n, np.int32)
    if rcf:
        t_r = int(inp["reconfig_cmd"])
        ld_ok_c = [
            d
            for d in range(n)
            if role[d] == LEADER and alive[d] and member_b[d]
            and room_at(d) and not noop_at(d)
        ]
        # JOINT entry (+v+1): the admin's toggle, accepted by the lowest-id
        # eligible leader whose own prefix is NOT already joint; refused
        # when the toggle would leave C_new below 2 voters.
        non_joint = [d for d in ld_ok_c if not joint0[d]]
        if t_r != NIL and 0 <= t_r < n and non_joint:
            d = min(non_joint)
            toggled = q_member_new[d].copy()
            toggled[t_r] = not toggled[t_r]
            if int(toggled.sum()) >= 2:
                cfg_write[d] = True
                cfg_code[d] = t_r + 1
        if cfg.joint_consensus:
            # FINAL entry (-v-1): appended once the governing joint entry
            # commits on the leader -- "C_old,new committed -> append C_new".
            for d in ld_ok_c:
                if joint0[d] and int(commit[d]) >= int(cfg_pend[d]):
                    diff = q_member_old[d] ^ q_member_new[d]
                    pend_v = int(np.argmax(diff))  # lowest differing bit
                    cfg_write[d] = True
                    cfg_code[d] = -(pend_v + 1)
        # (cfg.joint_consensus False, TEST-ONLY single-server-change mutant:
        # one final-acting entry per change, no completing entry.)

    if cfg.client_redirect:
        # K commands in flight chasing 302 redirects (raft.py phase 6): a fresh
        # offer takes the first free slot; at most one slot is accepted per
        # node per tick, lowest slot index first.
        pend = list(client_pend)
        tgt = list(client_dst)
        ptk = list(client_tick)
        if cmd_in != NIL:
            for k in range(K):
                if pend[k] == NIL:
                    pend[k] = cmd_in
                    tgt[k] = int(inp["client_target"])
                    ptk[k] = now0 + 1
                    break
        accepted = [False] * K
        for d in range(n):
            if noop_at(d):
                append(d, NOOP, 0)
                continue
            if rcf and cfg_write[d]:
                # Config entries carry value 0 and stamp 0 (the command
                # rides the log_cfg plane); the slot is taken this tick.
                append(d, 0, 0, int(cfg_code[d]))
                continue
            here = [k for k in range(K) if pend[k] != NIL and tgt[k] == d]
            if (
                here and role[d] == LEADER and alive[d] and room_at(d)
                and not (xfr and xfer_pend[d])  # transfer lease handoff
            ):
                k = min(here)
                append(d, pend[k], ptk[k])
                accepted[k] = True
        for k in range(K):
            if pend[k] != NIL and not accepted[k]:
                t = tgt[k]
                tl = int(leader_id[t])
                client_pend[k] = pend[k]
                client_dst[k] = (
                    tl if (alive[t] and tl != NIL) else int(inp["client_bounce"][k])
                )
                if track:
                    client_tick[k] = ptk[k]
            else:
                client_pend[k], client_dst[k] = NIL, 0
                if track:
                    client_tick[k] = 0
    else:
        for d in range(n):
            if noop_at(d):
                append(d, NOOP, 0)
            elif rcf and cfg_write[d]:
                append(d, 0, 0, int(cfg_code[d]))  # the slot holds a config entry
            elif (
                cmd_in != NIL and role[d] == LEADER and alive[d] and room_at(d)
                and not (xfr and xfer_pend[d])  # transfer lease handoff
            ):
                append(d, cmd_in, now0 + 1)

    # ---- phase 7: timers
    clock = s["clock"] + np.asarray(inp["skew"], np.int32)
    heartbeat = np.zeros(n, bool)
    start_election = np.zeros(n, bool)
    start_prevote = np.zeros(n, bool)
    for d in range(n):
        if granted_any[d] or has_ae[d] or saw_higher[d]:
            deadline[d] = clock[d] + int(inp["timeout_draw"][d])
        if win[d]:
            deadline[d] = clock[d] + cfg.heartbeat_ticks
        if cfg.pre_vote and pre_win[d]:
            deadline[d] = clock[d] + int(inp["timeout_draw"][d])
        expired = clock[d] >= deadline[d] and alive[d]
        if expired and role[d] == LEADER:
            heartbeat[d] = True
            deadline[d] = clock[d] + cfg.heartbeat_ticks
        elif expired and cfg.pre_vote and (
            not (rcf and not member_b[d])  # non-voters never campaign
            and not (xfr and xfer_elect[d])  # thesis-3.10 pre-vote bypass
        ):
            # expiry starts a PRE-vote probe: no term bump, votedFor untouched
            start_prevote[d] = True
            role[d] = PRECANDIDATE
            leader_id[d] = NIL
            votes[d, :] = False
            votes[d, d] = True
            deadline[d] = clock[d] + int(inp["timeout_draw"][d])
        elif expired and not cfg.pre_vote and not (rcf and not member_b[d]):
            start_election[d] = True
            term[d] += 1
            role[d] = CANDIDATE
            voted_for[d] = d
            leader_id[d] = NIL
            votes[d, :] = False
            votes[d, d] = True
            deadline[d] = clock[d] + int(inp["timeout_draw"][d])
    if cfg.pre_vote:
        # real RequestVote broadcasts come from this tick's promotions
        start_election = pre_win.copy()
    xe = np.zeros(n, bool)  # transfer-triggered elections (req_disrupt flag)
    if xfr:
        # TimeoutNow elections: the real-election start, bypassing timer and
        # pre-vote (~LEADER re-checked: a phase-4 win may have promoted).
        for d in range(n):
            if xfer_elect[d] and role[d] != LEADER:
                if cfg.pre_vote and start_election[d]:
                    continue  # kernel: xe = xfer_elect & ~pre_win & ~is_leader
                xe[d] = True
                if not start_election[d]:
                    start_election[d] = True
                    term[d] += 1
                    role[d] = CANDIDATE
                    voted_for[d] = d
                    leader_id[d] = NIL
                    votes[d, :] = False
                    votes[d, d] = True
                    deadline[d] = clock[d] + int(inp["timeout_draw"][d])

    # ---- phase 7.5: fsync flush + durability gates (models/raft.py) --------
    # After elections finalize term/votedFor and injection finalizes log_len:
    # a completing flush snaps the durable snapshot to the live triple. Gates
    # (cfg.durable_acks; False = TEST-ONLY ack-before-fsync mutant): AE acks
    # reflect only the fsynced prefix, and a vote grant is exposed only once
    # the durable snapshot covers it -- the covering flush emits the withheld
    # response (late_grant overlay in the outbox below).
    late_grant = np.zeros(n, bool)
    if dur:
        dur2_len = dur_mid.astype(np.int32).copy()
        dur2_term = dur_term.copy()
        dur2_vote = dur_vote.copy()
        for d in range(n):
            if bool(inp["fsync_fire"][d]) and alive[d]:  # dead disks never flush
                dur2_len[d] = log_len[d]
                dur2_term[d] = term[d]
                dur2_vote[d] = voted_for[d]
        if cfg.durable_acks:
            a_match = np.minimum(a_match, dur2_len)
            for d in range(n):
                covered0 = (
                    int(dur_term[d]) == int(term[d])
                    and int(dur_vote[d]) == int(voted_for[d])
                    and int(voted_for[d]) != NIL
                )
                covered2 = (
                    int(dur2_term[d]) == int(term[d])
                    and int(dur2_vote[d]) == int(voted_for[d])
                    and int(voted_for[d]) != NIL
                )
                v_to[d] = int(voted_for[d]) if covered2 else NIL
                late_grant[d] = covered2 and not covered0 and not granted_any[d]
        dur_len, dur_term, dur_vote = dur2_len, dur2_term, dur2_vote

    # ---- phase 8: outbox (wire format v8: per-sender headers + per-edge offsets)
    z = lambda *shape: np.zeros(shape, np.int32)
    out = {
        "req_type": z(n),
        "req_term": z(n),
        "req_commit": z(n),
        "req_last_index": z(n),
        "req_last_term": z(n),
        "ent_start": z(n),
        "ent_prev_term": z(n),
        "ent_count": z(n),
        "ent_term": z(n, e),
        "ent_val": z(n, e),
        "ent_tick": z(n, e),
        "req_base": z(n),
        "req_base_term": z(n),
        "req_base_chk": np.zeros(n, np.uint32),
        "xfer_tgt": np.full(n, NIL, np.int32),
        "req_disrupt": z(n),
        "ent_cfg": z(n, e),
        "req_base_mold": np.zeros((n, n), bool),
        "req_base_pend": z(n),
        "req_base_epoch": z(n),
        "req_off": z(n, n),
        "resp_kind": z(n, n),
        "pv_grant": np.zeros((n, n), bool),
        "v_to": v_to,
        "a_ok_to": a_ok_to,
        "a_match": a_match,
        "a_hint": a_hint,
        "resp_term": z(n),
    }
    for src in range(n):
        out["resp_term"][src] = term[src]
        b = int(log_base[src])
        bt = int(base_term[src])
        if start_election[src]:
            last_idx = int(log_len[src])
            out["req_type"][src] = REQ_VOTE
            out["req_term"][src] = term[src]
            out["req_last_index"][src] = last_idx
            out["req_last_term"][src] = term_at_ring(log_term[src], b, bt, last_idx)
        elif cfg.pre_vote and start_prevote[src]:
            last_idx = int(log_len[src])
            out["req_type"][src] = REQ_PREVOTE
            out["req_term"][src] = term[src] + 1  # prospective (thesis 9.6)
            out["req_last_index"][src] = last_idx
            out["req_last_term"][src] = term_at_ring(log_term[src], b, bt, last_idx)
        elif win[src] or heartbeat[src]:
            # Shared entry window: starts at the minimum prev over RESPONSIVE peers
            # (acked an AE within ack_timeout_ticks), falling back to all peers when
            # none are -- a dead peer must not pin the window (raft.py phase 8) --
            # and never below the compaction base (those entries are gone; such
            # peers get the InstallSnapshot sentinel instead).
            prev_of = lambda dst: min(
                max(int(next_index[src, dst]) - 1, 0), int(log_len[src])
            )
            resp_prevs = [
                prev_of(dst)
                for dst in range(n)
                if dst != src and int(ack_age[src, dst]) <= cfg.ack_timeout_ticks
            ]
            all_prevs = [prev_of(dst) for dst in range(n) if dst != src]
            ws = min(min(resp_prevs or all_prevs), int(log_len[src]))
            ws = max(ws, b)
            n_ship = min(int(log_len[src]) - ws, e)
            out["req_type"][src] = REQ_APPEND
            out["req_term"][src] = term[src]
            out["req_commit"][src] = commit[src]
            out["ent_start"][src] = ws
            out["ent_prev_term"][src] = term_at_ring(log_term[src], b, bt, ws)
            out["ent_count"][src] = n_ship
            out["req_base"][src] = b
            out["req_base_term"][src] = bt
            out["req_base_chk"][src] = base_chk[src]
            if comp and rcf:
                # Snapshot config header: the sender's configuration context
                # at its base rides every AE broadcast (models/raft.py).
                out["req_base_mold"][src] = base_mold[src]
                out["req_base_pend"][src] = base_pend[src]
                out["req_base_epoch"][src] = base_epoch[src]
            for k in range(n_ship):
                out["ent_term"][src, k] = log_term[src, (ws + k) % cap]
                out["ent_val"][src, k] = log_val[src, (ws + k) % cap]
                if track:
                    out["ent_tick"][src, k] = log_tick[src, (ws + k) % cap]
                if rcf:
                    out["ent_cfg"][src, k] = log_cfg[src, (ws + k) % cap]
            for dst in range(n):
                if dst == src:
                    continue
                # Per-edge offset j = prev - ws, with prev clamped into [ws, ws+E]
                # (a peer ahead of the window gets a heartbeat over an older prefix;
                # an unresponsive laggard's prev is lifted to the window start); a
                # peer whose prev fell below the base gets the snapshot sentinel.
                p = prev_of(dst)
                if p < b:
                    out["req_off"][src, dst] = -1
                else:
                    out["req_off"][src, dst] = min(max(p, ws), ws + e) - ws
            if xfr and xfer_to[src] != NIL:
                # TimeoutNow fire (raft.py phase 8): replaces the heartbeat
                # slot once the target matched the leader's log; the AE
                # window fields above stay populated (receivers gate on
                # req_type == REQ_APPEND).
                t = int(xfer_to[src])
                caught = (not cfg.xfer_election) or int(
                    match_index[src, t]
                ) >= int(log_len[src])
                if caught:
                    out["req_type"][src] = REQ_TIMEOUT_NOW
                    out["xfer_tgt"][src] = t
    if xfr and (rcf or rdl):
        # Disruptive-RequestVote override flag (thesis 3.10/4.2.3): set on
        # transfer-triggered election broadcasts so heard-recent voters
        # still process them. Written only when a denial gate can read it.
        for src in range(n):
            if xe[src]:
                out["req_disrupt"][src] = 1
    # Responses travel back src<->dst: responder r answers requester q; the edge
    # plane carries only the type, payloads ride the per-responder fields above.
    for r in range(n):
        for q in range(n):
            rtype = 0
            if vr_out[r, q]:
                rtype += RESP_VOTE
            if ar_out[r, q]:
                rtype += RESP_APPEND
            if pv_out[r, q]:
                rtype += RESP_PREVOTE
                # The grant bit rides the (packed) pv_grant plane, not the kind.
                out["pv_grant"][q, r] = bool(pv_grant[r, q])
            out["resp_kind"][q, r] = rtype
    if dur and cfg.durable_acks:
        # Late vote-completion response (phase 7.5): the flush that made this
        # voter's grant durable emits the RESP_VOTE the grant tick withheld --
        # toward the recorded candidate, only where the edge carries no
        # response already (models/raft.py for the AE-collision argument).
        for r in range(n):
            if late_grant[r]:
                q = int(voted_for[r])
                if out["resp_kind"][q, r] == 0:
                    out["resp_kind"][q, r] = RESP_VOTE

    # Monotone commit-latency frontier (types.ClusterState.lat_frontier):
    # measurement state maintained only under client workloads, deduping the
    # latency metric against the highest commit any node ever reached.
    lat_frontier = int(s["lat_frontier"])
    if track:
        lat_frontier = max(lat_frontier, int(commit.max()))

    # ---- end-of-tick config derivation (log-carried membership): each
    # node's effective configuration recomputed from its post-append,
    # post-compaction log prefix (models/cfglog.py `derive`, scalar form).
    # Apply-on-append and roll-back-on-truncation are the SAME recompute.
    if rcf:
        for d in range(n):
            b = int(log_base[d])
            horizon = (
                int(log_len[d]) if cfg.act_on_append
                # TEST-ONLY act-on-commit mutant: the COMMITTED prefix only.
                else min(int(commit[d]), int(log_len[d]))
            )
            entries = [
                (a, int(log_cfg[d, (a - 1) % cap]))
                for a in range(b + 1, horizon + 1)
                if int(log_cfg[d, (a - 1) % cap]) != 0
            ]
            m_old = base_mold[d].copy()
            for _, code in entries:
                if code < 0 or not cfg.joint_consensus:
                    v = abs(code) - 1  # final toggles fold into C_old
                    m_old[v] = not m_old[v]
            d_epoch = int(base_epoch[d]) + len(entries)
            if cfg.joint_consensus:
                if entries:
                    hi, pend_code = entries[-1]
                else:
                    # No live entry: the snapshot context rules (a pending
                    # joint entry may sit at or below base).
                    hi, pend_code = max(b, 1), int(base_pend[d])
                if pend_code > 0:
                    m_new = m_old.copy()
                    m_new[pend_code - 1] = not m_new[pend_code - 1]
                    d_pend = hi
                else:
                    m_new = m_old.copy()
                    d_pend = 0
            else:
                m_new = m_old.copy()
                d_pend = 0
            d_hi = max(entries[-1][0] if entries else 0, b)
            if not cfg.truncation_rollback and d_epoch < int(cfg_epoch[d]):
                # TEST-ONLY ignore-truncation-rollback mutant: where the
                # prefix LOST config entries, keep acting on the stale
                # derived configuration (the demote check below still runs
                # on the stale masks, mirroring the kernel).
                m_old = member_old[d].copy()  # tick-start: untouched so far
                m_new = member_new[d].copy()
                d_pend = int(cfg_pend[d])
                d_epoch = int(cfg_epoch[d])
            member_old[d] = m_old
            member_new[d] = m_new
            cfg_pend[d] = d_pend
            cfg_epoch[d] = d_epoch
            # Removed-server stepdown (thesis 4.3): a leader whose own
            # config union excludes it leads on until the removing entry
            # commits on it; candidacies of removed nodes die immediately.
            self_in = bool(m_old[d] or m_new[d])
            is_cand = role[d] in (CANDIDATE, PRECANDIDATE)
            if not self_in and (
                (role[d] == LEADER and int(commit[d]) >= d_hi) or is_cand
            ):
                role[d] = FOLLOWER
                leader_id[d] = NIL

    return {
        "role": role,
        "term": term,
        "voted_for": voted_for,
        "leader_id": leader_id,
        "votes": votes,
        "next_index": next_index,
        "match_index": match_index,
        "ack_age": ack_age,
        "commit_index": commit,
        "commit_chk": commit_chk,
        "log_base": log_base,
        "base_term": base_term,
        "base_chk": base_chk,
        "log_term": log_term,
        "log_val": log_val,
        "log_tick": log_tick,
        "log_len": log_len,
        "dur_len": dur_len,
        "dur_term": dur_term,
        "dur_vote": dur_vote,
        "clock": clock,
        "deadline": deadline,
        "heard_clock": heard_clock,
        "member_old": member_old,
        "member_new": member_new,
        "cfg_epoch": cfg_epoch,
        "cfg_pend": cfg_pend,
        "log_cfg": log_cfg,
        "base_mold": base_mold,
        "base_pend": base_pend,
        "base_epoch": base_epoch,
        "xfer_to": xfer_to,
        "read_idx": read_idx,
        "read_tick": read_tick,
        "read_acks": read_acks,
        "read_fr": read_fr,
        "client_pend": np.asarray(client_pend, np.int32),
        "client_dst": np.asarray(client_dst, np.int32),
        "client_tick": np.asarray(client_tick, np.int32),
        "lat_frontier": np.int32(lat_frontier),
        "now": np.int32(int(s["now"]) + 1),
        "mailbox": out,
    }
