"""raft_sim_tpu: a TPU-native batched Raft cluster simulator in JAX.

Re-expresses the per-node behavior of the reference implementation (one networked
Clojure Raft process per node, /root/reference/src/raft/) as a pure, vmap'able
state-transition kernel over struct-of-arrays state, with the network as an N x N
adjacency-masked message scatter and the event loop as a jit-compiled `lax.scan`.
See SURVEY.md for the structural map between the two designs.
"""

import os as _os

import jax as _jax

# The simulator's reproducibility contract -- cluster i's trajectory is
# independent of batch size and device count (tests/test_fuzz.py
# test_batch_size_invariance, tests/test_parallel.py) -- requires
# jax.random.split(key, n) to be a prefix-stable function of the key. That is
# the partitionable-threefry semantics, the default from jax 0.6 on; on older
# jax (this image ships 0.4.x) the legacy stateful-counter derivation makes
# split(k, 4) disagree with split(k, 64)[:4], silently breaking the invariance
# the whole fleet design leans on. Pin the partitionable semantics explicitly
# so every jax version runs the same (documented) key-derivation scheme -- but
# respect a host program that explicitly pinned the flag itself via the
# standard env var (importing this package for one utility must not silently
# re-derive an embedding application's own random streams).
if "JAX_THREEFRY_PARTITIONABLE" not in _os.environ:
    _jax.config.update("jax_threefry_partitionable", True)

from raft_sim_tpu.types import (
    CANDIDATE,
    FOLLOWER,
    LEADER,
    NIL,
    ClusterState,
    Mailbox,
    StepInfo,
    StepInputs,
    init_batch,
    init_state,
)
from raft_sim_tpu.utils.checkpoint import FORMAT_VERSION as CHECKPOINT_FORMAT_VERSION
from raft_sim_tpu.utils.config import PRESETS, RaftConfig

__all__ = [
    "CANDIDATE",
    "CHECKPOINT_FORMAT_VERSION",
    "FOLLOWER",
    "LEADER",
    "NIL",
    "ClusterState",
    "Mailbox",
    "PRESETS",
    "RaftConfig",
    "StepInfo",
    "StepInputs",
    "init_batch",
    "init_state",
]

__version__ = "0.1.0"
