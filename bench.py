"""Headline benchmark: cluster-ticks/sec/chip on the BASELINE north-star workload.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}. The baseline is the
north-star target from BASELINE.json (>=1M cluster-ticks/sec/chip at 100k x 5-node
clusters with randomized election timeouts -- config 3); `vs_baseline` is
value / 1_000_000. The reference publishes no numbers of its own (SURVEY.md section 6).

Usage: python bench.py [--preset config3] [--batch N] [--ticks N] [--repeats N]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import jax

from raft_sim_tpu import PRESETS, RaftConfig
from raft_sim_tpu.sim import scan

NORTH_STAR = 1_000_000.0  # cluster-ticks/sec/chip, BASELINE.json north_star


def bench(cfg: RaftConfig, batch: int, ticks: int, repeats: int = 3) -> dict:
    # Warmup compiles init + scan; timed runs hit the executable cache.
    final, metrics = scan.simulate(cfg, 0, batch, ticks)
    jax.block_until_ready((final, metrics))

    best = float("inf")
    for r in range(1, repeats + 1):
        t0 = time.perf_counter()
        final, metrics = scan.simulate(cfg, r, batch, ticks)
        jax.block_until_ready((final, metrics))
        best = min(best, time.perf_counter() - t0)

    value = batch * ticks / best
    return {
        "metric": "cluster-ticks/sec/chip",
        "value": round(value, 1),
        "unit": "cluster-ticks/s",
        "vs_baseline": round(value / NORTH_STAR, 3),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="config3", choices=sorted(PRESETS))
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--ticks", type=int, default=1000)
    ap.add_argument("--repeats", type=int, default=3)
    args = ap.parse_args()

    cfg, preset_batch = PRESETS[args.preset]
    batch = args.batch if args.batch is not None else preset_batch
    result = bench(cfg, batch, args.ticks, args.repeats)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
