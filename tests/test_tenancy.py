"""The tenancy plane (ISSUE 11): partitioned multi-tenant fleets, the
overlapped serve loop, and the read-ingest path.

Acceptance surface pinned here:
  - per-tenant command acks round-trip (every offered value lands in its
    tenant's ack ledger) and per-tenant read demands are MET (served-read
    credits from the telemetry windows reach each demand);
  - per-tenant export streams validate (tenant-local deltas.jsonl density,
    fleet-schema windows.jsonl lines, the tenants.json manifest);
  - the overlap structure is a perf.jsonl FACT: every steady chunk's export
    + packing ran inside the dispatch->sync host window (annotated
    pack_s/export_s bounded by host_s), i.e. under device compute -- not in
    the serial gap;
  - one compiled program at every tenant count: a second session over the
    same config with a different partition adds ZERO jit-cache entries;
  - Session.offer_read (docs/SERVE.md's named follow-up) acks via the
    served-read counters, symmetric to offer()'s delta-stream acks.

Program budget: ONE serve chunk program (module fixture; the second-session
test reuses it by construction -- that IS the assertion) plus offer_read's
single-tick program and one small chunked run program.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from raft_sim_tpu import RaftConfig
from raft_sim_tpu.serve import ServeSession, Tenant, ingest, loop
from raft_sim_tpu.serve import deltas as deltas_mod
from raft_sim_tpu.serve.loop import serve_config
from raft_sim_tpu.types import NIL
from raft_sim_tpu.utils import telemetry_sink

# A small lease-read serve tier: writes + leased reads, overlap-friendly
# chunking. serve_config collapses both cadences into the external gates.
# compact_margin matters beyond ring semantics: election wins then append
# no-ops, so a READ-ONLY tenant's leaders satisfy the 6.4 current-term-commit
# capture gate without any client traffic (config9 makes the same choice;
# docs/SERVE.md "read-only tenants").
TCFG = RaftConfig(
    n_nodes=3,
    log_capacity=32,
    compact_margin=8,
    election_min_ticks=12,
    election_range_ticks=6,
    client_interval=4,
    read_interval=3,
    read_lease_ticks=4,
)
TB, TCHUNK, TW = 6, 64, 32


def test_pack_plane_tick_major_fill_and_validation():
    p = ingest.pack_plane([1, 2, 3, 4, 5], 3, 2)
    assert p.shape == (3, 2) and p.dtype == np.int32
    assert p.tolist() == [[1, 2], [3, 4], [5, NIL]]
    with pytest.raises(ValueError, match="fit"):
        ingest.pack_plane(list(range(7)), 3, 2)
    with pytest.raises(ValueError, match="sentinel"):
        ingest.pack_plane([NIL], 2, 2)


def test_tenant_router_partition_validation():
    from raft_sim_tpu.serve.tenancy import TenantRouter

    with pytest.raises(ValueError, match="sum to"):
        TenantRouter([Tenant("a", 2), Tenant("b", 2)], 6, True)
    with pytest.raises(ValueError, match="duplicate"):
        TenantRouter([Tenant("a", 3), Tenant("a", 3)], 6, True)
    with pytest.raises(ValueError, match="ReadIndex"):
        TenantRouter([Tenant("a", 6, reads=5)], 6, False)
    r = TenantRouter([Tenant("a", 2), Tenant("b", 4)], 6, True)
    assert (r.tenants[0].lo, r.tenants[0].hi) == (0, 2)
    assert (r.tenants[1].lo, r.tenants[1].hi) == (2, 6)


@pytest.fixture(scope="module")
def tenanted(tmp_path_factory):
    """ONE multi-tenant serving session shared by the module: three tenants
    (write-only, mixed, read-only) over a 6-cluster fleet, sink + perf
    attached -- one compiled chunk program."""
    from raft_sim_tpu.obs import ChunkTimer
    from raft_sim_tpu.utils.telemetry_sink import TelemetrySink

    sink_dir = str(tmp_path_factory.mktemp("tenant_sink"))
    scfg = serve_config(TCFG)
    sink = TelemetrySink(
        sink_dir, scfg, seed=3, batch=TB, window=TW, ring=0, source="serve"
    )
    perf = ChunkTimer(label="serve", batch=TB, sink=sink)
    tenants = [
        Tenant("writer", 2, source=[101, 102, 103, 104, 2**31 - 1]),
        Tenant("mixed", 2, source=[-201, -202], reads=24),
        Tenant("reader", 2, reads=30),
    ]
    sess = ServeSession(
        TCFG, batch=TB, seed=3, chunk=TCHUNK, window=TW, delta_depth=8,
        sink=sink, warmup_ticks=TCHUNK, perf=perf, tenants=tenants,
    )
    cache_sizes = []

    def progress(_st):
        cache_sizes.append(loop._serve_chunk._cache_size())

    stats = sess.serve(drain_chunks=3, progress=progress)
    return {
        "sess": sess, "stats": stats, "tenants": tenants,
        "sink_dir": sink_dir, "cache_sizes": cache_sizes, "scfg": scfg,
    }


def test_every_command_and_read_acks_round_trip(tenanted):
    """The CI smoke's core claim at test scale: every offered command comes
    back through its OWN tenant's ack ledger (payloads bit-exact, no
    cross-tenant leakage) and every read demand is served."""
    writer, mixed, reader = tenanted["tenants"]
    assert sorted(set(writer.acked_values)) == [101, 102, 103, 104, 2**31 - 1]
    assert sorted(set(mixed.acked_values)) == [-202, -201]
    assert reader.acked_values == []  # read-only: no write ever leaked in
    for t in (mixed, reader):
        assert t.reads_served >= t.reads, (t.name, t.reads_served, t.reads)
    assert writer.reads_offered == 0  # no demand, no offers
    assert tenanted["stats"]["violations"] == 0
    # ops_done = client commands acked + reads served (leader no-ops ride
    # the raw delta stream but never the throughput numerator): the serve
    # metric is commands+reads, never ticks.
    st = tenanted["stats"]
    assert st["ops_done"] == st["commands_acked"] + st["reads_served"]
    assert st["commands_acked"] == len(writer.acked_values) + len(
        mixed.acked_values
    )
    assert st["commands_acked"] <= st["deltas_exported"]  # no-ops excluded
    assert st["reads_served"] >= mixed.reads + reader.reads


def test_per_tenant_streams_validate(tenanted):
    sink_dir = tenanted["sink_dir"]
    assert telemetry_sink.validate(sink_dir) == []
    man = json.load(open(os.path.join(sink_dir, "tenants.json")))
    assert set(man) == {"writer", "mixed", "reader"}
    fleet_windows = sum(
        1 for _ in open(os.path.join(sink_dir, "windows.jsonl"))
    )
    for t in tenanted["tenants"]:
        d = os.path.join(sink_dir, "tenants", t.name)
        assert deltas_mod.validate_deltas(os.path.join(d, "deltas.jsonl")) == []
        rows = [json.loads(x) for x in open(os.path.join(d, "windows.jsonl"))]
        assert len(rows) == fleet_windows  # same window axis as the fleet
        assert [r["window"] for r in rows] == list(range(len(rows)))
        assert man[t.name] == {
            "lo": t.lo, "hi": t.hi, "offered": t.offered,
            "acked": len(t.acked_values), "reads_offered": t.reads_offered,
            "reads_served": t.reads_served,
        }
        # The credited serves are exactly the tenant's windows' read column.
        assert sum(r["reads"] for r in rows) == t.reads_served
        # Tenant-local delta rows stay inside the tenant's cluster range.
        for row in t.delta_rows:
            assert 0 <= row["cluster"] < t.clusters


def test_overlap_structure_asserted_from_perf_jsonl(tenanted):
    """ISSUE 11 acceptance: the perf stream shows host packing/drain-export
    overlapped under device compute. Every steady row's annotated pack_s +
    export_s fits inside host_s -- the dispatch->sync window, i.e. while the
    chunk ran on device -- and real export work happened there (not in the
    serial gap, where the pre-overlap loop did it)."""
    rows = [
        json.loads(x)
        for x in open(os.path.join(tenanted["sink_dir"], "perf.jsonl"))
    ]
    steady = [r for r in rows if not r["warmup"]]
    assert steady, rows
    for r in steady:
        assert "pack_s" in r and "export_s" in r, r
        assert r["pack_s"] + r["export_s"] <= r["host_s"] + 1e-3, r
    assert sum(r["export_s"] for r in steady) > 0  # real overlapped export
    assert sum(r["pack_s"] for r in steady) >= 0
    assert not rows[-1]["recompiled"]
    # The live rollup and the file agree (the obs contract).
    s = tenanted["sess"].perf.summary()
    assert s["recompiled_after_warmup"] is False


def test_jit_cache_flat_across_tenant_counts(tenanted):
    """The batch axis IS the tenancy axis: re-partitioning the same fleet
    (3 tenants -> 1) compiles NOTHING new -- the chunk program is blind to
    the partition. (The fixture session already pinned flatness across its
    own chunks.)"""
    sizes = tenanted["cache_sizes"]
    assert len(set(sizes)) == 1, f"serve chunk recompiled mid-session: {sizes}"
    before = loop._serve_chunk._cache_size()
    sess2 = ServeSession(
        TCFG, batch=TB, seed=9, chunk=TCHUNK, window=TW, delta_depth=8,
        warmup_ticks=TCHUNK,
        tenants=[Tenant("solo", TB, source=[7, 8, 9], reads=6)],
    )
    sess2.serve(drain_chunks=2)
    assert loop._serve_chunk._cache_size() == before, (
        "a tenant-count change forked the serve chunk program"
    )
    assert sorted(set(sess2.router.tenants[0].acked_values)) == [7, 8, 9]


def test_legacy_single_source_serve_still_broadcasts(tenanted):
    """serve(source) without tenants keeps the pre-tenancy semantics: one
    logical client, every command offered to (and acked by) EVERY cluster --
    and rides the same compiled chunk program."""
    from raft_sim_tpu.serve import CommandSource

    before = loop._serve_chunk._cache_size()
    sess = ServeSession(
        TCFG, batch=TB, seed=11, chunk=TCHUNK, window=TW, delta_depth=8,
        warmup_ticks=TCHUNK,
    )
    sess.serve(CommandSource([55, 66]), drain_chunks=2)
    for c in range(TB):
        acked = sess.acked_values(c)
        assert 55 in acked and 66 in acked, (c, acked)
    assert loop._serve_chunk._cache_size() == before


def test_weighted_offer_scheduler_proportional_ops(tenanted):
    """Per-tenant QoS (ROADMAP item 2's named follow-up, ISSUE 13): host-side
    weights on the router's offer schedule. An idle max-weight tenant pins
    the schedule's denominator (and keeps the weighted tenants below the
    ring-compaction export horizon); the two write tenants at weights 2:1
    then get offer ticks in EXACT Bresenham proportion, and their acked
    ops/s land proportional within commit/export-lag tolerance. The chunk
    program is reused untouched: weights only move NILs inside the packed
    planes (data, never shapes)."""
    import itertools

    before = loop._serve_chunk._cache_size()
    sess = ServeSession(
        TCFG, batch=TB, seed=5, chunk=TCHUNK, window=TW, delta_depth=8,
        warmup_ticks=TCHUNK,
        tenants=[
            Tenant("idle", 2, weight=8),
            Tenant("heavy", 2, source=itertools.count(1), weight=2),
            Tenant("light", 2, source=itertools.count(10_000_000), weight=1),
        ],
    )
    sess.serve(chunks=3)
    _idle, heavy, light = sess.router.tenants
    assert loop._serve_chunk._cache_size() == before, (
        "a weighting change forked the serve chunk program"
    )
    # Offer side: exact schedule proportionality (192 ticks packed; the
    # Bresenham credit line gives weight w exactly T*w/8 of them).
    ticks = 3 * TCHUNK
    assert heavy.offered == (ticks * 2 // 8) * heavy.clusters
    assert light.offered == (ticks * 1 // 8) * light.clusters
    assert heavy.offered == 2 * light.offered
    # Ack side: ops/s share follows the weight share (same wall clock, so
    # the acked-count ratio IS the ops/s ratio). Tolerance covers the
    # commits still in flight / undrained at the chunk-budget stop.
    assert light.acked_values, "light tenant starved outright"
    ratio = len(heavy.acked_values) / len(light.acked_values)
    assert 1.5 <= ratio <= 2.5, (
        f"acked ops not weight-proportional: {len(heavy.acked_values)} vs "
        f"{len(light.acked_values)} (ratio {ratio:.2f}, weights 2:1)"
    )
    # No cross-tenant payload leakage under the weighted schedule.
    assert all(0 < v < 10_000_000 for v in heavy.acked_values)
    assert all(v >= 10_000_000 for v in light.acked_values)
    with pytest.raises(ValueError, match="weight"):
        Tenant("bad", 1, weight=0)


def test_weighted_read_reoffer_never_starved():
    """Regression (review finding): the read cadence counts the tenant's
    ACTIVE ticks, not raw global phase. With weight 1 of w_max 2 the
    Bresenham schedule activates odd global ticks only, and a global-phase
    read_every=2 gate would select even ones -- empty intersection, reads
    starved to zero forever. Host-only: the router's pack loop, no device."""
    from raft_sim_tpu.serve.tenancy import TenantRouter

    heavy = Tenant("heavy", 2, weight=2)
    light = Tenant("light", 2, reads=10, read_every=2, weight=1)
    r = TenantRouter([heavy, light], 4, True)
    for _ in range(4):
        _cmds, reads = r.pack(64)
        assert reads is not None
    assert light.reads_offered > 0, (
        "weight-1 tenant's read re-offers starved by the weighted schedule"
    )
    # And the cadence still thins offers: at most every 2nd active tick.
    assert light.reads_offered <= 4 * 64 // 2 * light.clusters


def test_session_offer_read_acks_via_served_counter(tmp_path):
    """Session.offer_read -- the read-side Session.offer closing docs/
    SERVE.md's named follow-up. The ack is the served-read counter
    advancing (reads produce no log entry, so the delta stream has nothing
    to carry; the counter is the same per-cluster column the tenancy router
    credits demands from). Under the lease config the serve lands within a
    tick or two of capture -- no confirmation round."""
    from raft_sim_tpu.driver import Session

    sess = Session(TCFG, batch=4, seed=0)
    sess.run(TCHUNK, chunk=TCHUNK)  # elect leaders
    res = sess.offer_read(wait=12)
    assert res["served"] == 4, res  # every cluster's read acked
    assert res["captured"] >= 0
    # Without the ReadIndex plane the verb refuses loudly.
    plain = Session(RaftConfig(n_nodes=3, client_interval=4), batch=2, seed=0)
    with pytest.raises(ValueError, match="ReadIndex"):
        plain.offer_read()
    # And (like offer) it refuses to punch holes into an armed trace stream.
    import dataclasses

    tcfg = dataclasses.replace(TCFG, track_trace=True)
    traced = Session(tcfg, batch=2, seed=0)
    traced.attach_telemetry(str(tmp_path / "t"), window=16, ring=0)
    traced.attach_trace(depth=32)
    with pytest.raises(RuntimeError, match="trace"):
        traced.offer_read()
