"""Fleet telemetry: on-device windowed aggregation + a violation flight recorder.

The reference's entire observability story is an unconditional per-iteration
println of node + message (core.clj:182-186); the rebuild's full-fidelity
equivalent -- `scan.run(trace=True)` stacking per-tick StepInfo, or whole
ClusterStates -- is exactly the memory pattern a 100k-cluster soak cannot
survive ([T] rows of everything). This module is the production-scale middle
ground, two device-side mechanisms over the SAME tick body as the hot path
(`scan.tick_batch_minor`, so telemetry can never observe a different
trajectory than it perturbs):

1. **Windowed aggregation** (`run_batch_minor_telemetry`): a nested scan folds
   each tick's StepInfo into a window-local RunMetrics inside the inner carry
   and emits ONE `WindowRecord` per `window` ticks -- [T/W] records instead of
   [T] rows. Reduction is exact, not lossy: every RunMetrics fold is
   associative over window boundaries (sums, min/max, later-wins for
   min_commit), so merging the window records with `chunked.merge_metrics`
   reproduces the monolithic run's RunMetrics BIT-FOR-BIT
   (tests/test_telemetry.py pins this against the full per-tick stack).
   `first_viol_tick` adds the one thing the run-level metrics cannot recover:
   WHEN inside the window the first invariant trip happened.

2. **Violation flight recorder** (`FlightRecorder`): a K-deep device-side ring
   of the last K ticks' StepInfo per cluster that FREEZES on the first tick
   any `viol_*` flag fires -- when 1 cluster in 100k misbehaves, its final K
   ticks come home for `sim/trace.py` rendering without ever storing full
   trajectories. The freeze includes the violating tick itself (write first,
   then latch).

Both mechanisms live in EXTRA scan-carry legs beside (state, metrics); the
ClusterState carry and the checkpoint format are untouched, and with telemetry
disabled the plain `scan.run_batch_minor` path compiles exactly as before.
The extra HBM traffic telemetry does cost is accounted statically by
`tools/traffic_audit.py --telemetry-ring` (docs/OBSERVABILITY.md has the
window/ring sizing tradeoffs).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_sim_tpu.models import raft_batched
from raft_sim_tpu.sim import scan
from raft_sim_tpu.sim.chunked import _own_copy, merge_metrics
from raft_sim_tpu.types import StepInfo
from raft_sim_tpu.utils.config import RaftConfig

NEVER = scan.NEVER


class WindowRecord(NamedTuple):
    """One W-tick window's telemetry for every cluster (public layout: every
    leaf leads with [batch, n_windows, ...] after a run; batch-minor inside)."""

    start: jax.Array  # int32: absolute tick of the window's first tick
    # Absolute tick of the first invariant violation inside this window
    # (NEVER if the window is clean) -- the intra-window locator the
    # window-level RunMetrics cannot provide.
    first_viol_tick: jax.Array  # int32
    # RunMetrics accumulated over THIS window only. Folding these with
    # chunked.merge_metrics across windows reproduces the monolithic run's
    # metrics bit-for-bit (every fold op is associative across the cut).
    metrics: scan.RunMetrics


class FlightRecorder(NamedTuple):
    """Device-side ring of the last K ticks' StepInfo per cluster, frozen at
    the first violation. Internal layout is batch-minor (`ring` leaves
    [K, ..., B]); `from_batch_minor` restores the public [B, K, ...] form."""

    ring: StepInfo  # each StepInfo leaf stacked K deep along axis 0
    tick: jax.Array  # [K] int32: absolute tick held in each slot (-1 = empty)
    pos: jax.Array  # int32: ticks recorded so far (next slot = pos % K)
    frozen: jax.Array  # bool: latched by the first viol_* tick (inclusive)


def init_recorder(cfg: RaftConfig, k: int, batch: int) -> FlightRecorder:
    """Zeroed K-deep recorder, batch-minor ([..., B] trailing on every leaf)."""
    from raft_sim_tpu.types import LAT_HIST_BINS

    def leaf(dtype, *mid):
        return jnp.zeros((k, *mid, batch), dtype)

    ring = StepInfo(
        viol_election_safety=leaf(bool),
        viol_commit=leaf(bool),
        viol_log_matching=leaf(bool),
        leader=leaf(jnp.int32),
        n_leaders=leaf(jnp.int32),
        max_term=leaf(jnp.int32),
        max_commit=leaf(jnp.int32),
        min_commit=leaf(jnp.int32),
        msgs_delivered=leaf(jnp.int32),
        cmds_injected=leaf(jnp.int32),
        lat_sum=leaf(jnp.int32),
        lat_cnt=leaf(jnp.int32),
        lat_hist=leaf(jnp.int32, LAT_HIST_BINS),
        lat_excluded=leaf(jnp.int32),
        noop_blocked=leaf(jnp.int32),
        lm_skipped_pairs=leaf(jnp.int32),
        reads_served=leaf(jnp.int32),
        read_lat_sum=leaf(jnp.int32),
        read_hist=leaf(jnp.int32, LAT_HIST_BINS),
        viol_read_stale=leaf(bool),
        fsync_lag_sum=leaf(jnp.int32),
        fsync_lag_max=leaf(jnp.int32),
    )
    return FlightRecorder(
        ring=ring,
        tick=jnp.full((k, batch), -1, jnp.int32),
        pos=jnp.zeros((batch,), jnp.int32),
        frozen=jnp.zeros((batch,), bool),
    )


def _record(
    rec: FlightRecorder, info: StepInfo, now: jax.Array, k: int, trig: jax.Array
) -> FlightRecorder:
    """Write one tick's StepInfo into the ring (per-cluster slot pos % K),
    gated on ~frozen; latch frozen AFTER the write so the TRIGGERING tick is
    the ring's newest entry. `trig` is the [B] freeze predicate -- any viol_*
    flag by default, or "an event of the armed kind fired" when a trigger
    kind is set (run_batch_minor_telemetry `trigger_kind`): the lead-up to a
    non-violating anomaly is capturable, not only violations."""
    slot = rec.pos % k  # [B]
    write = ~rec.frozen  # [B]
    oh1 = (jnp.arange(k, dtype=jnp.int32)[:, None] == slot[None, :]) & write[None, :]

    def upd(leaf, val):
        # leaf [K, ..., B]; val [..., B]: broadcast the slot one-hot over the
        # middle dims (only lat_hist has one).
        oh = oh1.reshape((k,) + (1,) * (leaf.ndim - 2) + oh1.shape[-1:])
        return jnp.where(oh, val[None], leaf)

    ring = StepInfo(*(upd(l, v) for l, v in zip(rec.ring, info)))
    return FlightRecorder(
        ring=ring,
        tick=upd(rec.tick, now),
        pos=rec.pos + write,
        frozen=rec.frozen | (write & trig),
    )


def run_batch_minor_telemetry(
    cfg: RaftConfig,
    state,
    keys: jax.Array,
    n_ticks: int,
    window: int,
    recorder: FlightRecorder | None = None,
    step_fn=None,
    genome=None,
    seg_len: int = 1,
    trace_spec=None,
    trace_persist=None,
    trigger_kind: int | None = None,
):
    """`scan.run_batch_minor` with telemetry carry legs: same trajectories
    (bit-for-bit -- the tick body is shared), plus [n_ticks/window]
    WindowRecords and an optional flight recorder threaded through.
    `genome`/`seg_len` select the scenario input path (scan.tick_batch_minor):
    window records over a heterogeneous fleet are the search loop's fitness
    signal (scenario/search.py).

    `n_ticks` must divide by `window` (the chunked driver handles remainders
    by a final shorter call). `recorder` enters and leaves BATCH-MINOR (the
    chunked path threads it across calls without relayouting); pass
    `init_recorder(...)` to start one, None to disable. State/keys/metrics/
    records use the public [B, ...]-leading convention at entry/exit.

    The PROTOCOL TRACE PLANE (raft_sim_tpu/trace; requires cfg.track_trace):

      trace_spec     a trace.TraceSpec arms per-cluster event extraction +
                     the window event buffer + the transition-coverage
                     bitmap; the per-window exports ride a fifth/sixth
                     return value. `trace_persist` threads the cross-window
                     trace state between chunked calls (None starts fresh).
      trigger_kind   an EV_* kind re-arms the flight recorder's freeze on
                     the first occurrence of that event kind instead of the
                     default viol_* trigger -- "capture the lead-up to the
                     first leadership change/crash/...", the gap
                     docs/OBSERVABILITY.md used to note.

    With neither set, this function lowers EXACTLY as before -- no trace leg
    exists in the program (the zero-cost-when-off contract config.track_trace
    documents; tests/test_trace.py pins bit-exactness both ways).

    Returns (final_state, metrics, records, recorder), plus
    (trace_windows, trace_persist) appended when trace_spec is given --
    trace_windows is a batch-minor stacked trace.TraceWindowOut (leaves
    [n_windows, ..., B]), trace_persist the carried trace.TracePersist.
    """
    if n_ticks % window:
        raise ValueError(f"n_ticks {n_ticks} must divide by window {window}")
    if step_fn is None:
        step_fn = raft_batched.step_b
    batch = state.role.shape[0]
    ring_k = 0 if recorder is None else recorder.tick.shape[0]
    need_events = trace_spec is not None or trigger_kind is not None
    if need_events and not cfg.track_trace:
        raise ValueError(
            "protocol tracing / event triggers need cfg.track_trace=True "
            "(the zero-cost-when-off contract: untraced configs must compile "
            "untraced programs -- utils/config.py)"
        )
    s_t = raft_batched.to_batch_minor(state)
    m0 = raft_batched.to_batch_minor(scan.init_metrics_batch(batch))

    if not need_events:

        def inner(carry, _):
            s, wm, fv, rec = carry
            now = s.now  # [B] absolute tick BEFORE the step (lockstep across B)
            s2, wm2, info = scan.tick_batch_minor(
                cfg, s, keys, wm, step_fn=step_fn, genome=genome, seg_len=seg_len
            )
            bad = scan.step_bad(info)
            fv2 = jnp.minimum(fv, jnp.where(bad, now, NEVER))
            rec2 = _record(rec, info, now, ring_k, bad) if ring_k else rec
            return (s2, wm2, fv2, rec2), None

        def outer(carry, _):
            s, m, rec = carry
            start = s.now
            fv0 = jnp.full((batch,), NEVER, jnp.int32)
            (s2, wm, fv, rec2), _ = lax.scan(
                inner, (s, m0, fv0, rec), None, length=window
            )
            out = WindowRecord(start=start, first_viol_tick=fv, metrics=wm)
            return (s2, merge_metrics(m, wm), rec2), out

        (final_t, metrics, rec_t), recs = lax.scan(
            outer, (s_t, m0, recorder), None, length=n_ticks // window
        )
        # Records stack [n_windows, ..., B]: one batch-axis move yields the
        # public [B, n_windows, ...] layout (per-cluster leading).
        return (
            raft_batched.from_batch_minor(final_t),
            raft_batched.from_batch_minor(metrics),
            raft_batched.from_batch_minor(recs),
            rec_t,
        )

    from raft_sim_tpu.trace import events as tev
    from raft_sim_tpu.trace import ring as tring

    if trace_spec is not None and trace_persist is None:
        trace_persist = tring.init_persist(trace_spec, batch)
    tp0 = trace_persist if trace_spec is not None else ()

    def inner_t(carry, _):
        s, wm, fv, rec, tw, tp = carry
        now = s.now
        s2, wm2, info, ev = scan.tick_batch_minor(
            cfg, s, keys, wm, step_fn=step_fn, genome=genome, seg_len=seg_len,
            events=True,
        )
        bad = scan.step_bad(info)
        fv2 = jnp.minimum(fv, jnp.where(bad, now, NEVER))
        trig = bad if trigger_kind is None else tev.any_of_kind(cfg, ev, trigger_kind)
        rec2 = _record(rec, info, now, ring_k, trig) if ring_k else rec
        if trace_spec is not None:
            tw, tp = tring.record(cfg, trace_spec, tw, tp, ev, now)
        return (s2, wm2, fv2, rec2, tw, tp), None

    def outer_t(carry, _):
        s, m, rec, tp = carry
        start = s.now
        fv0 = jnp.full((batch,), NEVER, jnp.int32)
        tw0 = tring.init_window(trace_spec, batch) if trace_spec is not None else ()
        (s2, wm, fv, rec2, tw, tp2), _ = lax.scan(
            inner_t, (s, m0, fv0, rec, tw0, tp), None, length=window
        )
        rec_out = WindowRecord(start=start, first_viol_tick=fv, metrics=wm)
        out = (
            (rec_out, tring.TraceWindowOut(win=tw, cov=tp2.cov))
            if trace_spec is not None
            else rec_out
        )
        return (s2, merge_metrics(m, wm), rec2, tp2), out

    (final_t, metrics, rec_t, tp_final), outs = lax.scan(
        outer_t, (s_t, m0, recorder, tp0), None, length=n_ticks // window
    )
    recs, traws = outs if trace_spec is not None else (outs, None)
    base = (
        raft_batched.from_batch_minor(final_t),
        raft_batched.from_batch_minor(metrics),
        raft_batched.from_batch_minor(recs),
        rec_t,
    )
    if trace_spec is None:
        return base
    # Trace exports stay batch-minor (leaves [n_windows, ..., B]): the sink /
    # history builder consume them host-side per window, like the recorder.
    return base + (traws, tp_final)


@functools.partial(jax.jit, static_argnums=(0, 2, 3, 4, 5, 7, 8, 9))
def simulate_windowed(
    cfg: RaftConfig, seed, batch: int, n_ticks: int, window: int, ring: int = 0,
    genome=None, seg_len: int = 1, trace=None, trigger_kind: int | None = None,
):
    """`scan.simulate` with telemetry: one-call batched init + windowed scan.
    Returns (final_state, metrics, records, recorder) -- metrics/trajectories
    bit-identical to `scan.simulate` for the same (cfg, seed, batch, n_ticks).
    `ring` > 0 enables the flight recorder at that depth. `genome` ([B, S]
    rows, traced) selects the scenario path: the search loop evaluates a whole
    genome population in THIS one device call, and new genome values reuse the
    compiled program (only a new S/seg_len recompiles). `trace` (a static
    trace.TraceSpec; requires cfg.track_trace) arms the protocol trace plane
    and appends (trace_windows, trace_persist) to the return -- the coverage
    search's per-generation call; `trigger_kind` re-arms the flight
    recorder's freeze on an event kind (run_batch_minor_telemetry)."""
    root = jax.random.key(seed)
    k_init, k_run = jax.random.split(root)
    from raft_sim_tpu.types import init_batch

    state = init_batch(cfg, k_init, batch)
    keys = jax.random.split(k_run, batch)
    rec = init_recorder(cfg, ring, batch) if ring else None
    return run_batch_minor_telemetry(
        cfg, state, keys, n_ticks, window, rec, genome=genome, seg_len=seg_len,
        trace_spec=trace, trigger_kind=trigger_kind,
    )


@functools.partial(jax.jit, static_argnums=(0, 4, 5, 6, 8, 9, 11), donate_argnums=(1,))
def _chunk_t_donate_trace(cfg, state, keys, rec, n, window, ring_k, genome=None,
                          seg_len=1, trace_spec=None, trace_persist=None,
                          trigger_kind=None):
    """The traced-soak chunk: `_chunk_t_donate` plus the trace-plane legs
    (separate entry point so the UNTRACED soak program and its donation pin
    stay byte-identical to pre-trace builds). Same donation contract: the
    fleet carry is donated chunk-to-chunk; the trace persist legs are small
    ([B]-scalars + COV_WORDS words) and threaded un-donated like the
    recorder."""
    recorder = rec if ring_k else None
    return run_batch_minor_telemetry(
        cfg, state, keys, n, window, recorder, genome=genome, seg_len=seg_len,
        trace_spec=trace_spec, trace_persist=trace_persist,
        trigger_kind=trigger_kind,
    )


@functools.partial(jax.jit, static_argnums=(0, 4, 5, 6, 8), donate_argnums=(1,))
def _chunk_t_donate(cfg, state, keys, rec, n, window, ring_k, genome=None,
                    seg_len=1):
    """The soak-path steady-state chunk: like `chunked._chunk_donate`, the
    previous chunk's state is donated so a 10M-tick telemetry run holds ONE
    fleet in HBM, not two. The recorder is small (K ring slots) and threaded
    un-donated. Pinned by the cost model's donation audit (`cost-donation`),
    same as the plain chunk loop."""
    recorder = rec if ring_k else None
    return run_batch_minor_telemetry(
        cfg, state, keys, n, window, recorder, genome=genome, seg_len=seg_len
    )


def run_chunked_telemetry(
    cfg: RaftConfig,
    state,
    keys: jax.Array,
    n_ticks: int,
    window: int,
    recorder: FlightRecorder | None = None,
    chunk: int = 4096,
    callback=None,
    genome=None,
    seg_len: int = 1,
    perf=None,
    trace_spec=None,
    trace_persist=None,
    trigger_kind: int | None = None,
    trace_callback=None,
    chunk_hook=None,
):
    """Long-horizon telemetry runs: the `chunked.run_chunked` analogue with
    window records offloaded to the host between chunks (so a 10M-tick soak
    holds at most chunk/window records on device at once).

    `chunk_hook(ticks_done, recorder)` is a host-side observer handed the
    CARRIED flight recorder (batch-minor) after each chunk -- the health
    plane's evidence hook: a firing alert snapshots the named clusters' rings
    via `export_cluster` without freezing or perturbing the device carry.
    Read-only by contract; it cannot return a replacement.

    `perf` (an obs.ChunkTimer) attributes each chunk's wall time and samples
    the soak program's jit cache at chunk boundaries (recompile watchdog),
    exactly like `chunked.run_chunked` -- see docs/OBSERVABILITY.md.

    Chunks are rounded to whole windows; a final REMAINDER window shorter than
    `window` is emitted if n_ticks does not divide (records are
    self-describing: `metrics.ticks` carries each window's true width).
    `callback(ticks_done, state, merged_metrics, records)` receives each
    chunk's records in the public [B, n_windows, ...] layout; returning True
    stops early. Returns (final_state, merged_metrics, recorder) -- with
    `trace_persist` appended when `trace_spec` is given.

    The trace plane (trace_spec / trace_persist / trigger_kind: see
    run_batch_minor_telemetry) streams like the window records: each chunk's
    stacked TraceWindowOut (batch-minor) is handed to
    `trace_callback(ticks_done, trace_windows)` -- the sink's
    `append_trace` -- and the cross-window trace state threads chunk to
    chunk. Untraced calls run the IDENTICAL `_chunk_t_donate` program as
    before (the traced soak is its own pinned entry point).

    Buffer ownership matches `chunked.run_chunked`: the caller's `state` stays
    valid (one up-front copy, owned by the loop), each chunk's state is
    donated to the next, and a `state` captured inside `callback` is only
    valid until the callback returns -- `jax.device_get` anything it keeps.
    Same for `chunk_hook`'s recorder and the callback's `records`: the
    telemetry soak is walked by analysis Pass D's use-after-donate lint and
    run under the donation-poison sanitizer (`tools/check.py --race
    --dynamic`), so a hook that retains the live carry past its return is a
    gated finding, not a latent chip-session bug.
    """
    batch = state.role.shape[0]
    ring_k = 0 if recorder is None else recorder.tick.shape[0]
    need_events = trace_spec is not None or trigger_kind is not None
    win_per_chunk = max(1, chunk // window)
    metrics = scan.init_metrics_batch(batch)
    done = 0
    state = _own_copy(state)
    if trace_spec is not None and trace_persist is None:
        from raft_sim_tpu.trace import ring as tring

        trace_persist = tring.init_persist(trace_spec, batch)
    if perf is not None:
        probe = _chunk_t_donate_trace if need_events else _chunk_t_donate
        perf.add_probe("telemetry._chunk_t_donate", probe)
    while done < n_ticks:
        left = n_ticks - done
        if left >= window:
            n = min(win_per_chunk, left // window) * window
            w = window
        else:
            n = w = left  # remainder: one final short window
        if perf is not None:
            perf.begin(n)
        if need_events:
            out = _chunk_t_donate_trace(
                cfg, state, keys, recorder, n, w, ring_k, genome, seg_len,
                trace_spec, trace_persist, trigger_kind,
            )
            if trace_spec is not None:
                state, m, recs, recorder, traws, trace_persist = out
            else:
                state, m, recs, recorder = out
                traws = None
        else:
            state, m, recs, recorder = _chunk_t_donate(
                cfg, state, keys, recorder, n, w, ring_k, genome, seg_len
            )
            traws = None
        if perf is not None:
            perf.dispatched()
        metrics = merge_metrics(metrics, m)
        done += n
        # The callback's window export (sink append, apply-log update) is
        # this chunk's host gap; close after it, synced on the chunk metrics.
        if traws is not None and trace_callback is not None:
            trace_callback(done, traws)
        if chunk_hook is not None:
            chunk_hook(done, recorder)
        stop = callback is not None and callback(done, state, metrics, recs)
        if perf is not None:
            perf.end(sync=lambda: np.asarray(m.ticks))
        if stop:
            break
    if trace_spec is not None:
        return state, metrics, recorder, trace_persist
    return state, metrics, recorder


def reduce_records(records: WindowRecord) -> scan.RunMetrics:
    """Fold a stacked WindowRecord (leaves [B, n_windows, ...]) back into the
    run-level RunMetrics ([B, ...]) -- the host-side half of the bit-exactness
    contract: this equals the monolithic scan's metrics exactly."""
    n_windows = records.start.shape[1]
    take = lambda w: jax.tree.map(lambda x: x[:, w], records.metrics)
    m = take(0)
    for w in range(1, n_windows):
        m = merge_metrics(m, take(w))
    return m


def window_cluster_counters(records: WindowRecord) -> list[dict]:
    """Split a stacked WindowRecord (public layout: leaves [B, n_windows, ...])
    into one host-side dict of per-cluster numpy counters PER WINDOW -- the
    health plane's window units (health/sli.py consumes them; health/evidence
    freezes them per culprit cluster). `leaderless` marks clusters whose
    window-local first_leader_tick never latched: no tick in that window
    observed a leader, the availability = 1 - leaderless-fraction signal.
    Read-only host math over an already-fetched record -- the sink path calls
    this on the same host copy it aggregates into windows.jsonl lines."""
    start = np.asarray(records.start)
    n_windows = start.shape[1]
    m = {
        f: np.asarray(getattr(records.metrics, f))
        for f in ("ticks", "violations", "first_leader_tick", "total_cmds",
                  "reads_served", "lat_sum", "lat_cnt", "lat_hist",
                  "read_hist", "fsync_lag_sum", "fsync_lag_max")
    }
    units = []
    for w in range(n_windows):
        units.append({
            "start": int(start[0, w]),
            "ticks": int(m["ticks"][0, w]),
            "violations": m["violations"][:, w].astype(np.int64),
            "leaderless": m["first_leader_tick"][:, w] == NEVER,
            "cmds": m["total_cmds"][:, w].astype(np.int64),
            "reads": m["reads_served"][:, w].astype(np.int64),
            "lat_sum": m["lat_sum"][:, w].astype(np.int64),
            "lat_cnt": m["lat_cnt"][:, w].astype(np.int64),
            "lat_hist": m["lat_hist"][:, w].astype(np.int64),
            "read_hist": m["read_hist"][:, w].astype(np.int64),
            # Durable storage plane (raft_sim_tpu/storage): node-tick-summed
            # and window-max fsync lag (log_len - dur_len). All-zero when
            # the plane is off (the gated StepInfo legs are host zeros).
            "fsync_lag_sum": m["fsync_lag_sum"][:, w].astype(np.int64),
            "fsync_lag_max": m["fsync_lag_max"][:, w].astype(np.int64),
        })
    return units


def export_cluster(recorder: FlightRecorder, cluster: int):
    """Decode one cluster's ring into chronological (ticks, stacked StepInfo)
    ready for `trace.info_lines` -- the flight-recorder readout. Takes the
    recorder in its carried batch-minor layout (what every run/chunk call
    returns); empty slots (tick < 0) are dropped.

    Returns (ticks [k_valid] np.ndarray, StepInfo with leading [k_valid] axis),
    oldest tick first -- for a frozen cluster the last row IS the violation."""

    def leaf(x):  # [K, ..., B] -> this cluster's [K, ...]
        return np.moveaxis(np.asarray(x), -1, 0)[cluster]

    ticks = leaf(recorder.tick)  # [K]
    order = np.argsort(ticks, kind="stable")
    order = order[ticks[order] >= 0]
    infos = StepInfo(*(leaf(l)[order] for l in recorder.ring))
    return ticks[order], infos
