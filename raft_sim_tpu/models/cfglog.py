"""Log-carried configuration: per-node membership derived from the log prefix.

The dissertation (ch. 4) requires configuration entries to be *acted on when
appended, not when committed*: each server uses the latest configuration in
its own log, and a truncation that removes a config entry must roll the
server back to the previous one. This module is the SINGLE statement of that
derivation for both kernels: given a node's config-entry plane
(ClusterState.log_cfg), its log bounds, and its snapshot config context
(base_mold/base_pend/base_epoch), recompute the node's effective
(member_old, member_new, cfg_pend, cfg_epoch) -- executed at the end of
every tick over the post-append, post-compaction log, so "apply on append"
and "roll back on truncation" are the same code path: the configuration IS a
function of the log prefix, never separately-mutated state.

Why a full re-derivation is cheap enough to run every tick: toggles commute
(membership is a bit set; a final entry XORs one bit), so C_old at the
prefix end is base_mold XOR the parity-fold of the final-entry toggles in
the live range -- one masked [N, CAP, N] parity pass packed back into [W]
words (ops/bitplane), plus two masked max/select reductions for the
latest-entry joint test. O(N^2 * CAP) bools per cluster, the same order as
the phase-9 log-matching check, and compiled only when cfg.reconfig.

Entry encoding (ClusterState.log_cfg docstring): 0 none, +(v+1) a JOINT
entry toggling node v (member_new diverges; quorums go dual), -(v+1) the
FINAL entry completing that toggle (member_old absorbs it). Within any
single log the two alternate -- every append chain passes through a leader
that refuses a joint entry while its own prefix is already joint -- but the
derivation never assumes it: the latest live entry's sign alone decides
jointness, and the parity fold is order-free.

TEST-ONLY mutant hooks (scenario/mutation.py) weaken exactly one rule each:
  cfg.act_on_append  False -> derive from the COMMITTED prefix ("act on
                     commit": disjoint-quorum bug);
  cfg.joint_consensus False -> every entry is final at append (single-server
                     change: the known-unsafe interleaving);
  cfg.truncation_rollback False -> applied where the epoch count DROPPED,
                     i.e. the caller keeps the stale carried config after a
                     truncation (models/raft.py end-of-tick block).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from raft_sim_tpu.ops import bitplane, log_ops
from raft_sim_tpu.utils.config import RaftConfig


def _abs1(cfg: RaftConfig, base, n: int, cap: int, batch_shape=()):
    """[N, CAP(, B)] 1-based absolute entry index of each log slot (ring-aware
    under compaction; the plain prefix layout otherwise)."""
    if batch_shape:
        sl = log_ops.iota((1, cap, 1), 1)
        if cfg.compaction:
            b = base[:, None, :]
            return b + (sl - b) % cap + 1
        return jnp.broadcast_to(sl + 1, (n, cap) + batch_shape)
    sl = jnp.arange(cap, dtype=jnp.int32)[None, :]
    if cfg.compaction:
        return base[:, None] + (sl - base[:, None]) % cap + 1
    return jnp.broadcast_to(sl + 1, (n, cap))


def _one_bit_rows(v, n: int):
    """Packed one-hot rows for per-node toggle ids: v [N(, B)] ->
    [N, W(, B)] (bitplane.one_bit yields the word axis LEADING; move it
    behind the node axis)."""
    return jnp.moveaxis(bitplane.one_bit(v, n), 0, 1)


def _fold_core(cfg: RaftConfig, log_cfg, anchor, lo, hi, batched: bool):
    """THE masked parity-fold over config entries in (lo, hi]: the single
    statement of the trickiest index math in this module, shared by the
    live derivation (`derive`: lo = base, hi = the acting horizon) and the
    compaction-rebase advance (`fold_span`: (b0, b1], anchored at the
    PRE-advance base). Returns (fold [N, W(, B)] -- the XOR of final-entry
    toggles, mutant fold_mask rule included; hi_idx [N(, B)] -- the latest
    live entry's absolute index, 0 when none; code_hi -- that entry's
    command; count -- config entries in the span)."""
    n, cap = cfg.n_nodes, cfg.log_capacity
    bshape = log_cfg.shape[2:] if batched else ()
    abs1 = _abs1(cfg, anchor, n, cap, bshape)
    ax = 1  # the CAP axis, both layouts

    def up(x):  # [N(, B)] -> broadcastable against [N, CAP(, B)]
        return x[:, None, :] if batched else x[:, None]

    span = (abs1 > up(lo)) & (abs1 <= up(hi))
    code = jnp.where(span, log_cfg, 0)
    is_cfg = code != 0
    if cfg.joint_consensus:
        fold_mask = code < 0  # final entries fold into C_old
    else:
        fold_mask = is_cfg  # single-server change: every entry is final
    vfold = jnp.abs(code) - 1  # toggle target (garbage where ~fold_mask)
    # Parity fold of the toggle bits: count hits per (node, target) and keep
    # the low bit -- XOR of one-hot rows without an XOR reduction primitive
    # (sum/compare/pack only: the op vocabulary both kernels already use).
    if batched:
        tgt = log_ops.iota((1, 1, n, 1), 2)
        hits = fold_mask[:, :, None, :] & (vfold[:, :, None, :] == tgt)
    else:
        tgt = jnp.arange(n, dtype=jnp.int32)[None, None, :]
        hits = fold_mask[:, :, None] & (vfold[:, :, None] == tgt)
    par = (jnp.sum(hits, axis=ax, dtype=jnp.int32) % 2) != 0  # [N, n(, B)]
    fold = bitplane.pack(par, axis=1)  # [N, W(, B)]
    # Latest live config entry: its absolute index and command.
    hi_idx = jnp.max(jnp.where(is_cfg, abs1, 0), axis=ax)  # [N(, B)]
    code_hi = jnp.sum(
        jnp.where(is_cfg & (abs1 == up(hi_idx)), code, 0), axis=ax
    )
    count = jnp.sum(is_cfg, axis=ax, dtype=jnp.int32)
    return fold, hi_idx, code_hi, count


def derive(
    cfg: RaftConfig,
    log_cfg: jax.Array,
    log_len: jax.Array,
    commit: jax.Array,
    base: jax.Array,
    base_mold: jax.Array,
    base_pend: jax.Array,
    base_epoch: jax.Array,
    batched: bool = False,
):
    """Effective per-node configuration from the log prefix.

    Shapes: single-cluster (log_cfg [N, CAP], vectors [N], base_mold [N, W])
    or batch-minor (`batched=True`: trailing B on every leaf). Returns
    (member_old [N, W(, B)], member_new, cfg_pend [N(, B)], cfg_epoch,
    cfg_hi) where cfg_hi is the absolute index of the latest live config
    entry (base when none survive) -- the removed-leader stepdown gate
    compares commit against it.
    """
    n = cfg.n_nodes
    horizon = log_len if cfg.act_on_append else jnp.minimum(commit, log_len)
    fold, hi, code_hi, count = _fold_core(
        cfg, log_cfg, base, base, horizon, batched
    )
    m_old = base_mold ^ fold
    if cfg.joint_consensus:
        has = hi > 0
        # No live entry: the snapshot context rules (a pending joint entry
        # may sit at or below base -- committed, compacted, still governing).
        pend_code = jnp.where(has, code_hi, base_pend)
        joint = pend_code > 0
        pend_v = pend_code - 1  # valid only where joint
        pend_idx = jnp.where(has, hi, jnp.maximum(base, 1))
        mb_ = (joint[:, None, :] if batched else joint[:, None])
        m_new = jnp.where(mb_, m_old ^ _one_bit_rows(pend_v, n), m_old)
        cfg_pend = jnp.where(joint, pend_idx, 0)
    else:
        m_new = m_old
        cfg_pend = jnp.zeros_like(hi)
    cfg_epoch = base_epoch + count
    cfg_hi = jnp.maximum(hi, base)
    return m_old, m_new, cfg_pend, cfg_epoch, cfg_hi


def fold_span(
    cfg: RaftConfig,
    log_cfg: jax.Array,
    b0: jax.Array,
    b1: jax.Array,
    base_mold: jax.Array,
    base_pend: jax.Array,
    base_epoch: jax.Array,
    batched: bool = False,
):
    """Advance the snapshot config context across a compaction rebase: fold
    the config entries in (b0, b1] -- final toggles into base_mold, the
    latest entry's jointness into base_pend, the count into base_epoch.
    Slot->index anchoring uses b0 (the PRE-advance base), the same anchor
    rule the checksum pass documents: this must run before phase-6 injection
    can reuse freed slots."""
    fold, hi, code_hi, count = _fold_core(cfg, log_cfg, b0, b0, b1, batched)
    new_mold = base_mold ^ fold
    if cfg.joint_consensus:
        new_pend = jnp.where(
            hi > 0, jnp.where(code_hi > 0, code_hi, 0), base_pend
        )
    else:
        new_pend = base_pend  # never joint: stays zero
    new_epoch = base_epoch + count
    return new_mold, new_pend, new_epoch
