"""Multi-window multi-burn-rate alerting (Google SRE Workbook, ch. 5).

Burn rate = (bad-event fraction over a window) / (error budget). A burn of
1.0 spends exactly the budget over the objective's horizon; 6.0 exhausts it
six times faster. Each spec rule pairs a LONG window (is the budget really
burning?) with a SHORT one (is it burning NOW, i.e. the alert resets quickly
once the cause stops) -- an alert condition is met only when BOTH windows
burn at or above the rule's threshold. Windows are measured in evaluation
periods (the fleet's clock is the telemetry window, not wall time), with
partial history allowed at the front of a run so a standing loop is covered
from its first eval.

Lifecycle per (objective, rule):

    ok --met--> pending --met x pending_evals--> firing --clean x
    resolve_evals--> resolved (-> ok)

`pending_evals` consecutive ADDITIONAL met evals promote pending to firing
(default 1: fire on the 2nd consecutive met eval; 0 = page immediately --
the safety/recompile default). A pending alert whose condition clears drops
straight back to ok. Budget-0 objectives report BURN_INF when burning: any
rule fires.
"""

from __future__ import annotations

import dataclasses

# Stand-in for an infinite burn (budget 0, error > 0): finite so it survives
# JSON, larger than any sane rule threshold.
BURN_INF = 1e9

ALERT_STATES = ("ok", "pending", "firing", "resolved")


def burn_rate(err_mean: float, budget: float) -> float:
    if budget <= 0:
        return BURN_INF if err_mean > 0 else 0.0
    return min(err_mean / budget, BURN_INF)


@dataclasses.dataclass
class _RuleState:
    state: str = "ok"
    met_evals: int = 0
    clean_evals: int = 0


class BurnEngine:
    """Streaming burn-rate evaluator over one scope's eval stream. Feed it
    each period's {objective: err fraction} + budgets; it returns the alert
    TRANSITIONS (state changes only -- steady states emit nothing), each
    carrying the short/long burns that justified it."""

    def __init__(self, spec: dict):
        self.spec = spec
        self.rules = spec["rules"]
        max_long = max(r["long"] for r in self.rules)
        self._hist: dict[str, list[float]] = {
            name: [] for name in spec["objectives"]
        }
        self._max_long = max_long
        self._state: dict[tuple[str, str], _RuleState] = {
            (name, r["name"]): _RuleState()
            for name in spec["objectives"]
            for r in self.rules
        }

    def _burns(self, name: str, budget: float, rule: dict) -> tuple[float, float]:
        h = self._hist[name]
        short = h[-rule["short"]:]
        long = h[-rule["long"]:]
        return (
            burn_rate(sum(short) / len(short), budget),
            burn_rate(sum(long) / len(long), budget),
        )

    def update(self, errs: dict, budgets: dict) -> list[dict]:
        """Advance one evaluation period; returns transition dicts
        {objective, rule, state, burn_short, burn_long}."""
        transitions = []
        for name in self.spec["objectives"]:
            h = self._hist[name]
            h.append(float(errs[name]))
            del h[:-self._max_long]
            obj = self.spec["objectives"][name]
            pending_evals = obj.get("pending_evals", 1)
            resolve_evals = obj.get(
                "resolve_evals", self.spec["resolve_evals"]
            )
            for rule in self.rules:
                bs, bl = self._burns(name, budgets[name], rule)
                met = bs >= rule["burn"] and bl >= rule["burn"]
                st = self._state[(name, rule["name"])]
                new = None
                if st.state in ("ok", "resolved"):
                    if met:
                        st.met_evals = 1
                        new = "firing" if st.met_evals > pending_evals else "pending"
                elif st.state == "pending":
                    if met:
                        st.met_evals += 1
                        if st.met_evals > pending_evals:
                            new = "firing"
                    else:
                        st.met_evals = 0
                        new = "ok"
                elif st.state == "firing":
                    if met:
                        st.clean_evals = 0
                    else:
                        st.clean_evals += 1
                        if st.clean_evals >= resolve_evals:
                            st.met_evals = 0
                            st.clean_evals = 0
                            new = "resolved"
                if new is not None:
                    st.state = new
                    transitions.append({
                        "objective": name,
                        "rule": rule["name"],
                        "state": new,
                        "burn_short": round(bs, 4),
                        "burn_long": round(bl, 4),
                    })
        return transitions

    def burns(self, name: str, budget: float) -> dict:
        """Current per-rule [short, long] burns for the health line."""
        if not self._hist[name]:
            return {}
        return {
            r["name"]: [round(b, 4) for b in self._burns(name, budget, r)]
            for r in self.rules
        }

    def status(self) -> str:
        """Worst live state across every (objective, rule): the one-word
        answer a dashboard wants. `resolved` reads as ok -- it is a
        transition label, not a standing state."""
        states = {st.state for st in self._state.values()}
        if "firing" in states:
            return "firing"
        if "pending" in states:
            return "pending"
        return "ok"

    def firing(self) -> list[tuple[str, str]]:
        return sorted(
            key for key, st in self._state.items() if st.state == "firing"
        )
