"""Reconfiguration plane: LOG-CARRIED membership change (models/cfglog.py),
TimeoutNow leadership transfer, and ReadIndex reads.

Kernel-vs-oracle bit-exactness for these extensions rides tests/
test_oracle_parity.py (the n5-reconfig-plane / n5-reconfig-truncation rows);
this file covers the protocol semantics the parity rows cannot state
directly: configuration-masked quorums at bitplane word boundaries, the
log-carried joint lifecycle (joint entry -> replicate -> commit -> final
entry -> removed-leader stepdown), per-node config DIVERGENCE and the
truncation ROLLBACK at word-boundary N, the disruptive-RequestVote transfer
override under the lease denial, the TEST-ONLY mutants' violations (and the
real kernel's cleanliness under the same programs), the checker's
unconditional election safety (EPOCH_EXEMPT_DISTANCE deleted), and the v24
checkpoint round trip + v23 migration error.

Program budget: the word-boundary and lifecycle tests drive single `step`
calls (tiny jit programs); the run-level tests share two small scan programs
and the mutant/checker tests two small windowed trace programs.
"""

from __future__ import annotations

import dataclasses
import functools
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_sim_tpu import RaftConfig, init_state
from raft_sim_tpu.models import raft
from raft_sim_tpu.ops import bitplane
from raft_sim_tpu.scenario.mutation import mutant_config
from raft_sim_tpu.sim import scan, telemetry
from raft_sim_tpu.trace import checker as tchecker
from raft_sim_tpu.trace import events as tev
from raft_sim_tpu.trace import history as thistory
from raft_sim_tpu.trace.ring import TraceSpec
from raft_sim_tpu.types import CANDIDATE, FOLLOWER, LEADER, NIL, StepInputs
from raft_sim_tpu.utils import checkpoint
from raft_sim_tpu.utils.config import PRESETS


def _quiet_inputs(cfg: RaftConfig, **over) -> StepInputs:
    """No faults, no messages dropped, timers far in the future."""
    n = cfg.n_nodes
    far = 10_000
    base = dict(
        deliver_mask=bitplane.pack(jnp.ones((n, n), bool), axis=1),
        skew=jnp.ones((n,), jnp.int32),
        timeout_draw=jnp.full((n,), far, jnp.int32),
        client_cmd=jnp.int32(NIL),
        client_target=jnp.int32(0),
        client_bounce=jnp.zeros((cfg.client_pipeline,), jnp.int32),
        alive=jnp.ones((n,), bool),
        restarted=jnp.zeros((n,), bool),
    )
    base.update(over)
    return StepInputs(**base)


def _mask(n: int, members) -> jnp.ndarray:
    return bitplane.pack(
        jnp.asarray([i in members for i in range(n)], bool)
    )


def _mask_rows(n: int, members) -> jnp.ndarray:
    """Per-node derived-config rows ([N, W]): every node holding the same
    view -- the cache-injection helper for quorum-lattice tests (the
    end-of-tick derivation rebinds the cache from the log; quorum tests read
    the TICK-START values, which is what these tests pin)."""
    return jnp.broadcast_to(_mask(n, members), (n, bitplane.n_words(n)))


def _unpack_rows(words, n: int) -> np.ndarray:
    """[N, W] packed rows -> [N, N] bool."""
    return np.asarray(bitplane.unpack(words, n, axis=1))


# ----------------------------------- packed dual quorum at word boundaries


@pytest.mark.parametrize(
    "n",
    [
        5, 32,
        # Slow tier (870s budget): each N is a fresh ~8s step compile. Tier
        # 1 keeps the default width and the exact word crossing (32); the
        # boundary NEIGHBORS ride the slow tier since ISSUE 13 -- the
        # log-carried divergence test below re-pins the full 31/32/33
        # triplet on the SAME packed member rows (its derivation exercises
        # the identical word arithmetic), and test_bitplane pins the N=51
        # popcount itself.
        pytest.param(31, marks=pytest.mark.slow),
        pytest.param(33, marks=pytest.mark.slow),
        pytest.param(51, marks=pytest.mark.slow),
    ],
)
def test_joint_dual_quorum_at_word_boundaries(n):
    """While a candidate's OWN prefix is joint it needs majorities of BOTH
    its packed configurations. Exercised at the bitplane word boundaries
    (31/32/33 and the config5 width 51): one vote short of either majority
    loses, and a vote set that satisfies C_old via the to-be-removed node
    does NOT satisfy C_new."""
    cfg = RaftConfig(n_nodes=n, log_capacity=8, reconfig_interval=1000)
    removed = n - 1
    maj_old = n // 2 + 1
    maj_new = (n - 1) // 2 + 1

    def outcome(voters) -> bool:
        s = init_state(cfg, jax.random.key(0))
        s = s._replace(
            role=s.role.at[0].set(CANDIDATE),
            term=jnp.full((n,), 5, jnp.int32),
            voted_for=s.voted_for.at[0].set(0),
            votes=s.votes.at[0].set(_mask(n, set(voters))),
            member_new=_mask_rows(n, set(range(n)) - {removed}),
            cfg_pend=jnp.full((n,), 1000, jnp.int32),  # joint: exit far away
        )
        s2, _ = jax.jit(lambda st, i: raft.step(cfg, st, i))(
            s, _quiet_inputs(cfg)
        )
        return int(s2.role[0]) == LEADER

    need = max(maj_old, maj_new)
    assert outcome(range(need))  # both majorities met
    assert not outcome(range(need - 1))  # one short of the larger majority
    # C_old-majority via the removed node, but one short in C_new: the dual
    # test must refuse (a single-config kernel would elect -- the mutant).
    if maj_old == maj_new:
        tricky = list(range(maj_old - 1)) + [removed]
        assert not outcome(tricky)


def test_single_config_quorum_when_not_joint():
    """Outside a joint phase the masked quorum degenerates to the plain
    majority of the (single) current configuration."""
    n = 7
    cfg = RaftConfig(n_nodes=n, log_capacity=8, reconfig_interval=1000)
    s = init_state(cfg, jax.random.key(0))
    s = s._replace(
        role=s.role.at[2].set(CANDIDATE),
        term=jnp.full((n,), 3, jnp.int32),
        voted_for=s.voted_for.at[2].set(2),
        votes=s.votes.at[2].set(_mask(n, {1, 2, 3, 4})),
    )
    s2, _ = jax.jit(lambda st, i: raft.step(cfg, st, i))(s, _quiet_inputs(cfg))
    assert int(s2.role[2]) == LEADER


# ------------------------------- log-carried joint lifecycle + stepdown


def test_log_carried_joint_lifecycle_and_removed_leader_stepdown():
    """The full thesis-4.3 cycle as LOG WRITES: the admin's toggle becomes a
    JOINT entry on the leader (applied to ITS config the same tick --
    divergence from the followers until replication), the FINAL entry
    auto-appends once the joint entry commits, and the removed leader leads
    THROUGH its own removal until the final entry commits on it, then steps
    down and never campaigns again."""
    n = 5
    cfg = RaftConfig(n_nodes=n, log_capacity=8, reconfig_interval=1000)
    s = init_state(cfg, jax.random.key(0))
    s = s._replace(
        role=s.role.at[0].set(LEADER),
        term=jnp.full((n,), 2, jnp.int32),
        leader_id=jnp.zeros((n,), jnp.int32),
        ack_age=jnp.zeros((n, n), s.ack_age.dtype),  # everyone responsive
        deadline=s.deadline.at[0].set(1),  # heartbeats start immediately
    )
    step = jax.jit(lambda st, i: raft.step(cfg, st, i))
    # Tick 1: the admin offers "toggle node 0" -> the leader appends the
    # joint entry and applies it ON APPEND: its own derived config goes
    # joint while every follower still derives the boot config (divergence).
    s, _ = step(s, _quiet_inputs(cfg, reconfig_cmd=jnp.int32(0)))
    assert int(s.log_len[0]) == 1 and int(s.log_cfg[0, 0]) == 1  # +(0+1)
    assert int(s.cfg_epoch[0]) == 1 and int(s.cfg_pend[0]) == 1
    assert not _unpack_rows(s.member_new, n)[0, 0]  # leader: 0 leaving C_new
    assert _unpack_rows(s.member_old, n)[0, 0]  # ...but still in C_old
    assert np.all(np.asarray(s.cfg_epoch)[1:] == 0)  # followers: not yet
    assert int(s.role[0]) == LEADER  # leads through its own removal
    # Drive heartbeat/replication ticks: the joint entry replicates (every
    # node applies on append), commits under the DUAL quorum, the FINAL
    # entry auto-appends and commits, and the removed leader steps down.
    saw_joint_everywhere = False
    for _ in range(12):
        s, _ = step(s, _quiet_inputs(cfg))
        ep = np.asarray(s.cfg_epoch)
        if np.all(ep >= 1) and not saw_joint_everywhere:
            saw_joint_everywhere = True
        if int(s.role[0]) == FOLLOWER:
            break
    assert saw_joint_everywhere
    assert int(s.role[0]) == FOLLOWER  # removed leader stepped down...
    assert int(s.log_cfg[0, 1]) == -1  # ...after appending the final entry
    assert int(s.commit_index[0]) >= 2  # which committed on it first
    mo = _unpack_rows(s.member_old, n)
    assert not mo[0, 0]  # node 0's own view: removed
    assert np.all(np.asarray(s.cfg_pend) == 0)  # joint phase closed
    # Quiescence: the removed node never campaigns again (phase-7 gate).
    for _ in range(4):
        s, info = step(s, _quiet_inputs(cfg))
        assert int(s.role[0]) == FOLLOWER
        assert not bool(info.viol_election_safety)


def test_reconfig_command_refused_while_joint_and_below_two_voters():
    """Origination refusals, judged on the leader's OWN tick-start derived
    config (cache-injected): a toggle is refused while the leader's prefix
    is already joint, and refused when it would leave C_new below 2
    voters."""
    n = 3
    cfg = RaftConfig(n_nodes=n, log_capacity=8, reconfig_interval=1000)
    s = init_state(cfg, jax.random.key(0))
    s = s._replace(
        role=s.role.at[0].set(LEADER),
        term=jnp.full((n,), 2, jnp.int32),
        member_new=_mask_rows(n, {0, 1}),
        cfg_pend=jnp.full((n,), 1000, jnp.int32),  # joint pending, exit far
    )
    step = jax.jit(lambda st, i: raft.step(cfg, st, i))
    s2, _ = step(s, _quiet_inputs(cfg, reconfig_cmd=jnp.int32(1)))
    assert int(s2.log_len[0]) == 0  # refused: joint phase pending, no entry
    # Not joint, but the toggle would strand a single voter: refused.
    s3 = s._replace(
        cfg_pend=jnp.zeros((n,), jnp.int32),
        member_old=_mask_rows(n, {0, 1}),
    )
    s4, _ = step(s3, _quiet_inputs(cfg, reconfig_cmd=jnp.int32(1)))
    assert int(s4.log_len[0]) == 0
    assert np.all(np.asarray(s4.log_cfg) == 0)


# ----------------- per-node divergence + truncation rollback at boundaries


@pytest.mark.parametrize(
    "n",
    [
        5, 33,
        # Slow tier (budget re-tier, ISSUE 14): 31/32 straddle the same
        # 1->2-word boundary the tier-1 n=33 row crosses with the same
        # packed arithmetic (the dual-quorum test above re-pins the word
        # math per width), and 51 is the same arithmetic at W=2 -- each
        # param is a step-compile pair the 870s tier-1 budget cannot
        # absorb beside the ISSUE-14 layout tests.
        pytest.param(31, marks=pytest.mark.slow),
        pytest.param(32, marks=pytest.mark.slow),
        pytest.param(51, marks=pytest.mark.slow),
    ],
)
def test_config_divergence_and_truncation_rollback_at_word_boundaries(n):
    """The dissertation's rollback rule, deterministic, at bitplane word
    boundaries: an isolated node carries an uncommitted joint entry (its
    derived config goes joint -- DIVERGED from the majority), then the
    majority's leader overwrites that suffix and the node's config must ROLL
    BACK to the boot mask. Apply-on-append and roll-back-on-truncation are
    the same derivation (models/cfglog.py)."""
    cfg = RaftConfig(n_nodes=n, log_capacity=8, reconfig_interval=1000)
    x, v = n - 1, n - 1  # the isolated node; it toggles its own removal
    s = init_state(cfg, jax.random.key(0))
    s = s._replace(
        role=s.role.at[0].set(LEADER),
        term=jnp.full((n,), 3, jnp.int32),
        leader_id=jnp.zeros((n,), jnp.int32),
        ack_age=jnp.zeros((n, n), s.ack_age.dtype),
        # Leader 0: a term-3 client entry at index 1 (the overwriting log).
        log_term=s.log_term.at[0, 0].set(3),
        log_val=s.log_val.at[0, 0].set(77),
        log_len=s.log_len.at[0].set(1),
        # Node x: an uncommitted term-2 JOINT entry at index 1.
        deadline=s.deadline.at[0].set(2),  # heartbeat on tick 2
    )
    s = s._replace(
        log_term=s.log_term.at[x, 0].set(2),
        log_cfg=s.log_cfg.at[x, 0].set(v + 1),
        log_len=s.log_len.at[x].set(1),
    )
    step = jax.jit(lambda st, i: raft.step(cfg, st, i))
    # Tick 1 (no delivery): the end-of-tick derivation APPLIES x's entry --
    # per-node divergence: x joint and missing v from C_new, majority boot.
    s, _ = step(s, _quiet_inputs(cfg))
    assert int(s.cfg_epoch[x]) == 1 and int(s.cfg_pend[x]) == 1
    mn = _unpack_rows(s.member_new, n)
    assert not mn[x, v] and mn[0, v]  # x's own view diverged from node 0's
    assert np.all(np.asarray(s.cfg_epoch)[:x] == 0)
    # Ticks 2-3: the leader's heartbeat ships its term-3 entry; x's prefix
    # mismatches at index 1 and is overwritten -- the config entry is GONE
    # and the derivation must roll x back to the boot config.
    s, _ = step(s, _quiet_inputs(cfg))
    s, _ = step(s, _quiet_inputs(cfg))
    assert int(s.log_cfg[x, 0]) == 0  # scrubbed by the non-config overwrite
    assert int(s.cfg_epoch[x]) == 0 and int(s.cfg_pend[x]) == 0  # rollback
    mn2 = _unpack_rows(s.member_new, n)
    assert mn2[x, v]  # the prior mask is restored
    assert int(s.log_term[x, 0]) == 3 and int(s.log_val[x, 0]) == 77


# --------------------------------------------------- transfer lease + flow


def test_transfer_lease_blocks_writes_and_fires_timeout_now():
    """An accepted transfer parks on xfer_to, refuses client commands (the
    lease handoff), and fires REQ_TIMEOUT_NOW at the caught-up target on the
    leader's heartbeat tick."""
    from raft_sim_tpu.types import REQ_TIMEOUT_NOW

    n = 5
    cfg = RaftConfig(n_nodes=n, log_capacity=8, transfer_interval=1000,
                     client_interval=4)
    s = init_state(cfg, jax.random.key(0))
    s = s._replace(
        role=s.role.at[0].set(LEADER),
        term=jnp.full((n,), 2, jnp.int32),
        leader_id=jnp.zeros((n,), jnp.int32),
        ack_age=jnp.zeros((n, n), s.ack_age.dtype),  # everyone responsive
        deadline=s.deadline.at[0].set(1),  # heartbeat fires next tick
    )
    step = jax.jit(lambda st, i: raft.step(cfg, st, i))
    s, _ = step(s, _quiet_inputs(
        cfg, transfer_cmd=jnp.int32(3), client_cmd=jnp.int32(77)
    ))
    assert int(s.xfer_to[0]) == 3
    assert int(s.log_len[0]) == 0  # lease: the offered command was refused
    # Heartbeat tick: target matches (log empty), so the broadcast slot is
    # the TimeoutNow, not the heartbeat.
    assert int(s.mailbox.req_type[0]) == REQ_TIMEOUT_NOW
    assert int(s.mailbox.xfer_tgt[0]) == 3


def test_transfer_fires_and_elects_during_joint_phase():
    """A TimeoutNow transfer accepted, fired, received, and COMPLETED while
    a membership change is parked in its joint phase -- now LOG-BACKED: the
    joint entry sits uncommitted in every log (a current-term leader cannot
    commit the prior-term entry without new appends, thesis 3.6.2), so the
    joint phase stays open across the handoff. The target's bypass election
    runs under
    the DUAL quorum and the deposed old leader's pending transfer aborts on
    term adoption."""
    from raft_sim_tpu.types import REQ_TIMEOUT_NOW, REQ_VOTE

    n = 5
    cfg = RaftConfig(
        n_nodes=n, log_capacity=8, reconfig_interval=1000,
        transfer_interval=1000, client_interval=4,
    )
    s = init_state(cfg, jax.random.key(0))
    joint_code = 4 + 1  # joint entry toggling node 4 (removal)
    s = s._replace(
        role=s.role.at[0].set(LEADER),
        term=jnp.full((n,), 2, jnp.int32),
        leader_id=jnp.zeros((n,), jnp.int32),
        ack_age=jnp.zeros((n, n), s.ack_age.dtype),  # everyone responsive
        deadline=s.deadline.at[0].set(1),  # heartbeat fires on tick 1
        # Joint entry in EVERY log, uncommitted AND uncommittable for now:
        # it carries the PRIOR term 1, so no term-2 (or term-3) leader can
        # commit it without a fresh entry on top (thesis 3.6.2's gate) --
        # the joint phase stays open across the whole handoff. The leader's
        # match bookkeeping covers it (the transfer's caught-up gate reads
        # match_index).
        log_term=s.log_term.at[:, 0].set(1),
        log_cfg=s.log_cfg.at[:, 0].set(joint_code),
        log_len=jnp.ones((n,), s.log_len.dtype),
        match_index=s.match_index.at[0, :].set(1),
        next_index=s.next_index.at[0, :].set(2),
        # The derived cache matching those logs (tick-start reads).
        member_new=_mask_rows(n, {0, 1, 2, 3}),
        cfg_pend=jnp.ones((n,), jnp.int32),
        cfg_epoch=jnp.ones((n,), jnp.int32),
    )
    step = jax.jit(lambda st, i: raft.step(cfg, st, i))
    # Tick 1: transfer to node 1 accepted WHILE joint; the heartbeat slot
    # carries the TimeoutNow (target trivially caught up: equal logs).
    s, _ = step(s, _quiet_inputs(cfg, transfer_cmd=jnp.int32(1)))
    assert int(s.xfer_to[0]) == 1 and np.all(np.asarray(s.cfg_pend) == 1)
    assert int(s.mailbox.req_type[0]) == REQ_TIMEOUT_NOW
    assert int(s.mailbox.xfer_tgt[0]) == 1
    # Tick 2: the target receives it at the current term and starts a REAL
    # election immediately -- term bump, self-vote, RequestVote broadcast
    # carrying the disruptive-override flag (thesis 3.10/4.2.3).
    s, _ = step(s, _quiet_inputs(cfg))
    assert int(s.role[1]) == CANDIDATE and int(s.term[1]) == 3
    assert int(s.mailbox.req_type[1]) == REQ_VOTE
    assert int(s.mailbox.req_disrupt[1]) == 1
    # Tick 3: voters adopt term 3 and grant; the deposed old leader's
    # pending transfer aborts on adoption (volatile leader state).
    s, _ = step(s, _quiet_inputs(cfg))
    assert int(s.role[0]) == FOLLOWER and int(s.term[0]) == 3
    assert int(s.xfer_to[0]) == NIL
    # Tick 4: the target banks a DUAL quorum (majorities of C_old AND C_new
    # -- all five granted here, covering both) and wins, with the joint
    # phase still open: leadership moved INSIDE the membership change.
    s, _ = step(s, _quiet_inputs(cfg))
    assert int(s.role[1]) == LEADER
    assert np.all(np.asarray(s.cfg_pend) == 1)
    assert np.all(np.asarray(s.cfg_epoch) == 1)
    # One more quiet tick: no spurious final entry (the term-1 joint entry
    # cannot commit under the term-3 leader) and exactly one leader.
    s, info = step(s, _quiet_inputs(cfg))
    assert np.all(np.asarray(s.cfg_pend) == 1)
    assert int(info.n_leaders) == 1 and not bool(info.viol_election_safety)


def test_transfer_overrides_lease_denial_deterministically():
    """ISSUE-13 satellite: read_lease_ticks and TimeoutNow transfers now
    COEXIST (the PR-11 mutual-exclusion validator is gone). A transfer
    target's election broadcast carries req_disrupt, so voters inside their
    heard-a-leader denial window still process it and leadership moves; a
    plain timer election under the same conditions is denied."""
    from raft_sim_tpu.types import REQ_VOTE

    n = 5
    cfg = RaftConfig(
        n_nodes=n, log_capacity=8, client_interval=2, read_interval=3,
        election_min_ticks=12, election_range_ticks=6, read_lease_ticks=4,
        transfer_interval=1000,  # legal together now: no validator trip
    )

    def fresh_leader_state():
        s = init_state(cfg, jax.random.key(0))
        return s._replace(
            role=s.role.at[0].set(LEADER),
            term=jnp.full((n,), 2, jnp.int32),
            leader_id=jnp.zeros((n,), jnp.int32),
            ack_age=jnp.zeros((n, n), s.ack_age.dtype),
            # Every voter heard the leader JUST NOW: denial window armed.
            heard_clock=jnp.zeros((n,), jnp.int32),
            deadline=s.deadline.at[0].set(1),  # leader heartbeat tick 1
        )

    step = jax.jit(lambda st, i: raft.step(cfg, st, i))
    # Transfer path: accepted tick 1 (TimeoutNow fires), received tick 2
    # (override election, flag set), granted tick 3 DESPITE the armed
    # denial, won tick 4.
    s = fresh_leader_state()
    s, _ = step(s, _quiet_inputs(cfg, transfer_cmd=jnp.int32(2)))
    assert int(s.xfer_to[0]) == 2
    s, _ = step(s, _quiet_inputs(cfg))
    assert int(s.role[2]) == CANDIDATE and int(s.term[2]) == 3
    assert int(s.mailbox.req_type[2]) == REQ_VOTE
    assert int(s.mailbox.req_disrupt[2]) == 1
    s, _ = step(s, _quiet_inputs(cfg))
    s, _ = step(s, _quiet_inputs(cfg))
    assert int(s.role[2]) == LEADER and int(s.term[2]) == 3
    # Plain election under the same armed denial: a candidate without the
    # flag gathers NO grants (the 4.2.3 denial the lease leans on).
    s = fresh_leader_state()
    s = s._replace(
        role=s.role.at[3].set(CANDIDATE),
        term=s.term.at[3].set(3),
        voted_for=s.voted_for.at[3].set(3),
        votes=s.votes.at[3].set(_mask(n, {3})),
    )
    # Its broadcast goes out this tick...
    s = s._replace(deadline=s.deadline.at[3].set(1))
    s, _ = step(s, _quiet_inputs(cfg))
    # ...and is denied by every heard-recent voter: no grants banked, no
    # leadership, terms un-adopted nowhere needed (rdl keeps adoption).
    s, _ = step(s, _quiet_inputs(cfg))
    s, _ = step(s, _quiet_inputs(cfg))
    assert int(s.role[3]) != LEADER


# --------------------------------------------------------- ReadIndex reads


def test_reads_serve_with_metrics():
    cfg = RaftConfig(n_nodes=5, log_capacity=32, client_interval=2,
                     read_interval=2)
    _, m = scan.simulate(cfg, 7, 8, 300)
    served = int(np.sum(np.asarray(m.reads_served)))
    assert served > 0
    assert int(np.sum(np.asarray(m.read_hist))) == served
    # Every served read waited at least the one-tick confirmation round.
    assert int(np.sum(np.asarray(m.read_lat_sum))) >= served


def test_read_confirmation_uses_tick_start_config_at_joint_exit():
    """Kernel-vs-oracle pin for the tick-start config rule on the read path:
    a pending read's confirmation is judged under the TICK-START (joint)
    per-node derivation even when the end-of-tick re-derivation dissolves
    that joint state, so a read whose acks satisfy only the incoming
    configuration stays pending through the switch (a late-bound oracle
    closure once served it -- review regression)."""
    from tests import oracle

    n = 5
    cfg = RaftConfig(n_nodes=n, log_capacity=8, reconfig_interval=1000,
                     read_interval=1000)
    s = init_state(cfg, jax.random.key(0))
    # Cache-injected joint {0,1,2,3} -> {0..4}: leader 0 holds a pending
    # read acked by {1, 4}; with self that is 3 -- a majority of the NEW
    # config (maj 3) but only 2 of the OLD members {0,1,2,3} (maj 3).
    # Tick-start rule: NOT confirmed this tick, even though the end-of-tick
    # derivation (empty log) dissolves the joint state.
    s = s._replace(
        role=s.role.at[0].set(LEADER),
        term=jnp.full((n,), 2, jnp.int32),
        leader_id=jnp.zeros((n,), jnp.int32),
        member_old=_mask_rows(n, {0, 1, 2, 3}),
        member_new=_mask_rows(n, {0, 1, 2, 3, 4}),
        cfg_pend=jnp.ones((n,), jnp.int32),
        read_idx=s.read_idx.at[0].set(1),
        read_tick=s.read_tick.at[0].set(1),
        read_acks=s.read_acks.at[0].set(_mask(n, {1, 4})),
    )
    inp = _quiet_inputs(cfg)
    s2, _ = jax.jit(lambda st, i: raft.step(cfg, st, i))(s, inp)
    assert np.all(np.asarray(s2.cfg_pend) == 0)  # joint state dissolved...
    assert int(s2.read_idx[0]) == 1  # ...but the read stayed pending
    inp_np = {f: np.asarray(v) for f, v in zip(inp._fields, inp)}
    got = oracle.oracle_step(cfg, oracle.state_to_dict(s), inp_np)
    assert int(got["read_idx"][0]) == 1  # oracle agrees (tick-start masks)
    assert np.array_equal(np.asarray(got["read_idx"]), np.asarray(s2.read_idx))


@pytest.mark.slow  # budget re-tier (PR 12): the read_cmd override is
# exercised every tier-1 run through its production consumers -- Session.
# offer_read (test_lease) and the tenancy serve fixture's read planes
# (test_tenancy) -- so this direct-unit form, which pays its own windowed
# compile, rides the slow tier.
def test_tick_batch_minor_read_cmd_override():
    """External read ingest on the serve tick body (docs/SERVE.md): the
    per-tick read_cmd override drives captures exactly like the scheduled
    cadence -- a fleet fed reads via the override serves them; NIL feeds
    none. Uses a huge scheduled cadence so every served read is
    override-attributable."""
    from raft_sim_tpu.models import raft_batched
    from raft_sim_tpu.types import init_batch

    cfg = RaftConfig(n_nodes=5, log_capacity=32, client_interval=4,
                     read_interval=100_000)
    root = jax.random.key(4)
    k_init, k_run = jax.random.split(root)
    B = 4
    keys = jax.random.split(k_run, B)

    def drive(ticks, read_every):
        s = raft_batched.to_batch_minor(init_batch(cfg, k_init, B))
        m = raft_batched.to_batch_minor(scan.init_metrics_batch(B))
        for t in range(ticks):
            rc = 1 if (read_every and t % read_every == 0) else NIL
            s, m, _ = scan.tick_batch_minor(cfg, s, keys, m, read_cmd=rc)
        return int(np.sum(np.asarray(m.reads_served)))

    assert drive(60, read_every=3) > 0
    assert drive(30, read_every=0) == 0


# ------------------------------------------------- mutants vs real kernel


@pytest.mark.slow  # budget re-tier (ISSUE 13): the property-level rejection
# (and the real kernel's clean pass) is pinned in tier 1 by the corpus
# replay of tests/corpus/blind-transfer-n5.json, and CI's reconfig smoke
# re-hunts the mutant every push -- the in-suite device sim joins its
# single-server-change sibling in the slow tier.
def test_blind_transfer_mutant_violates_real_kernel_clean():
    """The transfer-as-a-coup mutant truncates committed entries off
    followers (device commit-checksum violations); the REAL kernel under the
    identical program stays clean -- the CE hunt's target signal."""
    base = RaftConfig(n_nodes=5, log_capacity=16, client_interval=2,
                      drop_prob=0.25, transfer_interval=9)
    _, m_real = scan.simulate(base, 0, 16, 400)
    _, m_mut = scan.simulate(mutant_config("blind-transfer", base), 0, 16, 400)
    assert int(np.sum(np.asarray(m_real.violations))) == 0
    assert int(np.sum(np.asarray(m_mut.violations))) > 0


@pytest.mark.slow
def test_single_server_change_mutant_violates_real_kernel_clean():
    """The single-server-change mutant (one final-acting entry per change,
    no joint phase): consecutive toggles under partitions + drop produce
    non-intersecting quorums -> device violations. Needs a longer horizon
    and a wider fleet than the coup mutant (the race window is narrow), so
    it rides the slow tier; the corpus replay (tests/test_corpus.py) pins
    the property-level rejection in tier 1."""
    base = RaftConfig(n_nodes=5, log_capacity=16, client_interval=2,
                      drop_prob=0.3, partition_period=16, partition_prob=0.6,
                      reconfig_interval=7)
    _, m_real = scan.simulate(base, 0, 64, 800)
    _, m_mut = scan.simulate(
        mutant_config("single-server-change", base), 0, 64, 800
    )
    assert int(np.sum(np.asarray(m_real.violations))) == 0
    assert int(np.sum(np.asarray(m_mut.violations))) > 0


# ------------------------------------------- trace checker, new properties


CFG_TRACE = RaftConfig(
    n_nodes=5, client_interval=4, reconfig_interval=17, transfer_interval=23,
    read_interval=5, drop_prob=0.25, partition_period=16, partition_prob=0.5,
    crash_prob=0.2, crash_period=32, crash_down_ticks=8, track_trace=True,
)
SPEC = TraceSpec(depth=512)


@functools.lru_cache(maxsize=1)
def _real_report():
    out = telemetry.simulate_windowed(CFG_TRACE, 5, 12, 448, 64, 0, None, 1, SPEC)
    return tchecker.check_history(thistory.from_device(out[4]))


@pytest.mark.slow  # budget re-tier (PR 12): real-kernel-passes-the-checker
# is now pinned three times per tier-1 run by the corpus checker tests
# (test_corpus.py real-kernel replays, incl. a transfer-carrying config),
# and CI's reconfig smoke runs this exact add/remove-under-fire leg through
# the driver -- the in-suite variant joins the slow tier.
def test_real_kernel_passes_all_properties_under_add_remove_under_fire():
    """The acceptance run: membership toggles + transfers + reads under
    drop/partition/crash churn; the whole-history checker passes every
    property on a COMPLETE history -- with election safety now
    UNCONDITIONAL per term (no epoch carve-out)."""
    rep = _real_report()
    assert rep.complete, rep.problems
    assert rep.ok, {k: r.note for k, r in rep.results.items() if not r.ok}
    assert set(rep.results) == set(tchecker.PROPERTIES)
    assert "read_linearizability" in rep.results


def test_stale_read_mutant_rejected_with_witness():
    """The stale-read mutant serves unconfirmed reads; a deposed leader in a
    minority partition then serves below the committed frontier, and the
    checker names read_linearizability with the (issue, serve) witness."""
    cfg = dataclasses.replace(
        CFG_TRACE, reconfig_interval=0, transfer_interval=0,
        read_interval=2, crash_prob=0.0,
    )
    out = telemetry.simulate_windowed(
        mutant_config("stale-read", cfg), 3, 8, 256, 32, 0, None, 1, SPEC
    )
    rep = tchecker.check_history(thistory.from_device(out[4]))
    assert "read_linearizability" in rep.violated
    w = rep.results["read_linearizability"].witness
    assert [e["kind"] for e in w] == ["read_issue", "read_serve"]
    assert "below the committed frontier" in rep.results["read_linearizability"].note


def _hist(events_by_cluster):
    ev = {c: [thistory.Event(*e) for e in evs]
          for c, evs in events_by_cluster.items()}
    return thistory.History(
        events=ev,
        emitted={c: len(v) for c, v in ev.items()},
        dropped={c: 0 for c in ev},
        n_windows=1,
        problems=[],
    )


def test_checker_unconditional_election_safety():
    """EPOCH_EXEMPT_DISTANCE is DELETED: under log-carried configuration
    every electorate chains from the boot config, so two same-term leaders
    are a violation at ANY config distance. Synthetic negatives both
    directions (ISSUE-13 acceptance)."""
    assert not hasattr(tchecker, "EPOCH_EXEMPT_DISTANCE")
    L, CA = tev.EV_LEADER, tev.EV_CFG_APPLY
    # Two leaders for one term, no config motion: violation (unchanged).
    rep = tchecker.check_history(_hist({0: [(5, 0, L, 3), (9, 2, L, 3)]}))
    assert rep.violated == ["election_safety"]
    assert "log-carried" in rep.results["election_safety"].note
    # The admin-era EXCUSED case -- same-term leaders with the config moved
    # 4+ epochs between them -- is now REJECTED: per-node log-carried
    # configs cannot produce legally-disjoint same-term electorates.
    far = [(5, 0, L, 3)] + [
        (10 + i, nd, CA, i + 1) for i in range(6) for nd in range(5)
    ] + [(30, 2, L, 3)]
    rep = tchecker.check_history(_hist({0: far}))
    assert rep.violated == ["election_safety"]
    # Distinct terms across the same config motion: legal.
    ok = [(5, 0, L, 3)] + [
        (10 + i, nd, CA, i + 1) for i in range(6) for nd in range(5)
    ] + [(30, 2, L, 4)]
    rep = tchecker.check_history(_hist({0: ok}))
    assert rep.ok


def test_checker_double_vote_keyed_on_voter_term():
    """Election safety is additionally keyed on each node's state at VOTE
    time: two different-candidate grants in one term are named directly,
    while the legal single-config double-vote (an idempotent re-grant of the
    SAME candidate, e.g. after a restart) still passes."""
    T, V, R = tev.EV_TERM, tev.EV_VOTE, tev.EV_RESTART
    # Node 1 votes for 0 then for 2 in the same term: violation.
    rep = tchecker.check_history(_hist({0: [
        (4, 1, T, 7), (5, 1, V, 0), (9, 1, V, 2),
    ]}))
    assert rep.violated == ["election_safety"]
    assert "voted for both" in rep.results["election_safety"].note
    # Legal re-grant: restart, same candidate again -- passes.
    rep = tchecker.check_history(_hist({0: [
        (4, 1, T, 7), (5, 1, V, 0), (8, 1, R, 0), (9, 1, V, 0),
    ]}))
    assert rep.ok
    # New term between the votes: both grants legal.
    rep = tchecker.check_history(_hist({0: [
        (4, 1, T, 7), (5, 1, V, 0), (8, 1, T, 8), (9, 1, V, 2),
    ]}))
    assert rep.ok


def test_checker_read_linearizability_negatives():
    C, RI, RS = tev.EV_COMMIT, tev.EV_READ_ISSUE, tev.EV_READ_SERVE
    # A read issued at index 3 while the frontier sits at 5: serving it is
    # the violation (it misses committed writes).
    rep = tchecker.check_history(_hist({0: [
        (4, 0, C, 5), (8, 1, RI, 3), (10, 1, RS, 3),
    ]}))
    assert rep.violated == ["read_linearizability"]
    # A read at the frontier is linearizable.
    rep = tchecker.check_history(_hist({0: [
        (4, 0, C, 5), (8, 0, RI, 5), (10, 0, RS, 5),
    ]}))
    assert rep.ok
    # An issued-but-never-served stale read is NOT a violation (the real
    # kernel's confirmation round kills exactly these).
    rep = tchecker.check_history(_hist({0: [
        (4, 0, C, 5), (8, 1, RI, 3),
    ]}))
    assert rep.ok


# ------------------------------------------------------- checkpoint v24


def test_checkpoint_v24_round_trips_log_carried_config_state(tmp_path):
    """The per-node config planes ride the checkpoint: a mid-run
    config8-family fleet saves and loads bit-identically (per-node member
    rows, the log_cfg entry plane, the snapshot config context, transfer
    and read slots included)."""
    from raft_sim_tpu.types import init_batch

    cfg, _ = PRESETS["config8"]
    root = jax.random.key(9)
    k_init, k_run = jax.random.split(root)
    state = init_batch(cfg, k_init, 2)
    keys = jax.random.split(k_run, 2)
    state, metrics = scan.run_batch_minor(cfg, state, keys, 120)
    assert int(np.max(np.asarray(state.cfg_epoch))) > 0  # churn happened
    assert int(np.sum(np.abs(np.asarray(state.log_cfg)) > 0)) > 0
    path = checkpoint.save(str(tmp_path / "ck"), cfg, state, keys, metrics, seed=9)
    cfg2, state2, keys2, metrics2, seed2, scenario = checkpoint.load(path)
    assert cfg2 == cfg and seed2 == 9 and scenario is None
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(state2)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(metrics), jax.tree.leaves(metrics2)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_checkpoint_v23_file_refused_with_migration_error(tmp_path, monkeypatch):
    """A pre-v24 checkpoint (admin-era scalar config state) must be REFUSED
    with the migration-pointing error, not half-loaded into the per-node
    schema: the version log names the field changes and the error says how
    to regenerate."""
    cfg = RaftConfig(n_nodes=3, log_capacity=8)
    s = init_state(cfg, jax.random.key(0))
    state = jax.tree.map(lambda x: jnp.stack([x]), s)  # batch of 1
    keys = jax.random.split(jax.random.key(1), 1)
    metrics = scan.init_metrics_batch(1)
    monkeypatch.setattr(checkpoint, "_FORMAT_VERSION", 23)
    path = checkpoint.save(str(tmp_path / "old"), cfg, state, keys, metrics)
    monkeypatch.undo()
    with pytest.raises(ValueError, match="v23.*v24|format v23"):
        checkpoint.load(path)
