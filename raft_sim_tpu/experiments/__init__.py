"""Parked experimental engines -- real, parity-tested code whose production use is
blocked by toolchain limits, kept out of the supported `models/` surface.

Currently: `pallas_engine` (the whole tick as one fused pallas_call; interpret-only
until this image's Mosaic gains int16 reductions -- see docs/DESIGN.md)."""
