"""Pass D, runtime leg: the donation-poison sanitizer.

The static lint (analysis/race_audit.py) proves the SOURCE respects donation
discipline; this harness proves the RUNNING loops do. Arming it makes every
run behave like the strictest possible donating backend: each registered
donating entry point is wrapped, and as soon as a chunk's outputs are
materialized the wrapper POISONS the donated argument's buffers
(`jax.Array.delete()` -- the same deletion real donation performs). Any late
host access then raises "Array has been deleted" at the exact access site,
instead of silently reading stale memory on hardware. Current JAX already
invalidates donated inputs at dispatch even on CPU (where aliasing is
ignored), so the poison is the BACKSTOP for any path where donation was
dropped or unusable; what arming adds on every backend is the coverage
counters, the forced dispatch->sync serialization, and the bit-exactness
pin below -- the timing half of the race class that deletion alone cannot
exercise.

Wrapping is by module-global patch of the entry points registered in
`policy.donating_entry_points` -- the same single-sourced registry the static
lint and Pass C's aliasing pin read -- so a new donating entry point is
covered by all three the moment it is registered (and flagged by
`race-unregistered-donation` the moment it is not). The wrapper syncs on the
chunk's outputs before poisoning (`jax.block_until_ready`), which serializes
the dispatch->sync overlap but changes no value: sanitizer-armed runs are
bit-exact against plain runs, and `run_dynamic` pins exactly that for each
standing loop (rule `race-donation-poison` on any raise or divergence).

`farm/core.run_farm` has no donating entry point of its own -- members
evaluate through the non-donating `telemetry.simulate_windowed` /
`mesh.simulate_windowed_sharded` paths and hold genomes, not fleet carries --
so its coverage is the registry's `not-donated` rows plus the static lint
over `farm/core.py`; `run_dynamic` records that rationale in its info dict
rather than inventing a donation to poison.

Entry points: `tools/check.py --race --dynamic` (findings engine) and
`driver.py run/serve --sanitize` (arm a real session).
"""

from __future__ import annotations

import contextlib
import functools
import importlib

import jax
import numpy as np

from raft_sim_tpu.analysis import race_audit
from raft_sim_tpu.analysis import policy
from raft_sim_tpu.analysis.findings import Finding


def _poison(tree) -> tuple[int, int]:
    """Delete every live jax.Array buffer in `tree` (what real donation does
    the moment the donated program runs); return (poisoned, already_deleted).
    Current JAX invalidates donated inputs at dispatch even on CPU, so in
    the common case every leaf lands in the second bucket and the delete()
    is the backstop for any path where donation was dropped or unusable --
    the counters prove which regime the run was in."""
    poisoned = already = 0
    for leaf in jax.tree.leaves(tree):
        if not isinstance(leaf, jax.Array):
            continue
        if leaf.is_deleted():
            already += 1
        else:
            leaf.delete()
            poisoned += 1
    return poisoned, already


def _wrap(real, idx: int, pname: str, label: str, stats: dict):
    @functools.wraps(real)
    def wrapper(*args, **kwargs):
        donated = kwargs.get(pname)
        if donated is None and idx < len(args):
            donated = args[idx]
        out = real(*args, **kwargs)
        # Materialize the chunk's outputs first: poisoning must emulate
        # donation (input buffers recycled), never corrupt the computation.
        jax.block_until_ready(out)
        stats["calls"][label] = stats["calls"].get(label, 0) + 1
        if donated is not None:
            poisoned, already = _poison(donated)
            stats["poisoned"] += poisoned
            stats["pre_deleted"] += already
        return out

    # The loops' recompile watchdog probes resolve these module globals at
    # call time and read the jit cache size through them.
    if hasattr(real, "_cache_size"):
        wrapper._cache_size = real._cache_size
    wrapper._race_sanitizer_real = real
    return wrapper


@contextlib.contextmanager
def armed():
    """Patch every registered donating entry point with the poisoning
    wrapper for the duration of the block. Yields the stats dict
    ({'calls': {label: n}, 'poisoned': total buffers deleted}) so callers
    can prove the harness actually covered their loop. Reentrant arming is
    a no-op for already-armed entries."""
    stats = {"calls": {}, "poisoned": 0, "pre_deleted": 0}
    sigs = race_audit.donating_signatures()
    patched = []
    for e in policy.donating_entry_points():
        if e.expected != "donated" or e.func not in sigs:
            continue
        mod = importlib.import_module(e.path[:-3].replace("/", "."))
        real = getattr(mod, e.func)
        if hasattr(real, "_race_sanitizer_real"):
            continue
        idx, pname, _ = sigs[e.func]
        setattr(mod, e.func, _wrap(real, idx, pname, e.label, stats))
        patched.append((mod, e.func, real))
    try:
        yield stats
    finally:
        for mod, name, real in patched:
            setattr(mod, name, real)


# --------------------------------------------------------- bit-exactness pin


def mismatched_leaves(a, b) -> list[str]:
    """Paths of leaves where two pytrees are not bit-identical (after a host
    fetch). Empty list == bit-exact."""
    fa = jax.tree_util.tree_flatten_with_path(jax.device_get(a))[0]
    fb = jax.tree_util.tree_flatten_with_path(jax.device_get(b))[0]
    if len(fa) != len(fb):
        return ["<tree structure differs>"]
    bad = []
    for (pa, la), (_, lb) in zip(fa, fb):
        xa, xb = np.asarray(la), np.asarray(lb)
        if xa.dtype != xb.dtype or xa.shape != xb.shape or not np.array_equal(
            xa, xb
        ):
            bad.append(jax.tree_util.keystr(pa))
    return bad


# ----------------------------------------------------------- the dynamic leg


_TINY_TICKS = 8
_TINY_CHUNK = 4
_TINY_BATCH = 2


def _tiny_cfg():
    from raft_sim_tpu.utils.config import RaftConfig

    return RaftConfig(n_nodes=3, log_capacity=4, max_entries_per_rpc=1)


def _leg_chunked():
    from raft_sim_tpu.sim import chunked
    from raft_sim_tpu.types import init_batch

    cfg = _tiny_cfg()
    state0 = init_batch(cfg, jax.random.key(0), _TINY_BATCH)
    keys = jax.random.split(jax.random.key(1), _TINY_BATCH)

    def once():
        return chunked.run_chunked(
            cfg, state0, keys, _TINY_TICKS, chunk=_TINY_CHUNK)

    return "sim.chunked.run_chunked", "raft_sim_tpu/sim/chunked.py", once


def _leg_telemetry():
    from raft_sim_tpu.sim import telemetry
    from raft_sim_tpu.types import init_batch

    cfg = _tiny_cfg()
    state0 = init_batch(cfg, jax.random.key(0), _TINY_BATCH)
    keys = jax.random.split(jax.random.key(1), _TINY_BATCH)

    def once():
        return telemetry.run_chunked_telemetry(
            cfg, state0, keys, _TINY_TICKS, _TINY_CHUNK, chunk=_TINY_CHUNK)

    return (
        "sim.telemetry.run_chunked_telemetry",
        "raft_sim_tpu/sim/telemetry.py",
        once,
    )


def _leg_serve():
    from raft_sim_tpu.serve import loop
    from raft_sim_tpu.serve.ingest import CommandSource

    cfg = _tiny_cfg()

    def once():
        sess = loop.ServeSession(
            cfg, batch=_TINY_BATCH, seed=3, chunk=8, window=4, delta_depth=4)
        stats = sess.serve(
            CommandSource(iter([7, 1, 2, 9])), drain_chunks=2)
        # Wall-clock fields are the one thing arming legitimately changes
        # (the overlap is serialized); every counter must stay bit-exact.
        stats = {k: v for k, v in stats.items() if not k.endswith("_s")}
        return sess.state, stats

    return "serve.loop.ServeSession.serve", "raft_sim_tpu/serve/loop.py", once


def run_dynamic() -> tuple[list[Finding], dict]:
    """Run each donating standing loop one short session plain, then the same
    session sanitizer-armed, and pin (a) the armed run neither raises a
    poisoned-buffer access nor diverges, (b) the wrapper actually fired (the
    harness covered the loop). Any violation is a `race-donation-poison`
    finding naming the loop. Returns (findings, info) where info carries the
    per-loop call/poison counters and the farm-coverage rationale."""
    findings: list[Finding] = []
    info: dict = {
        "farm": (
            "no donating entry point (members evaluate via non-donating "
            "simulate_windowed); covered by the registry's not-donated rows "
            "and the static lint"
        ),
        "loops": {},
    }
    for label, path, once in (_leg_chunked(), _leg_telemetry(), _leg_serve()):
        plain = once()
        try:
            with armed() as stats:
                poisoned = once()
        except Exception as ex:  # noqa: BLE001 -- the raise IS the finding
            findings.append(Finding(
                rule="race-donation-poison",
                path=path,
                message=(
                    f"{label}: sanitizer-armed session raised "
                    f"{type(ex).__name__}: {ex} -- a host access touched a "
                    "donated buffer after its dispatch (use-after-donate "
                    "that real donation would corrupt silently)"
                ),
            ))
            continue
        info["loops"][label] = {
            "calls": dict(stats["calls"]),
            "poisoned_buffers": stats["poisoned"],
            "pre_deleted_buffers": stats["pre_deleted"],
        }
        if not stats["calls"]:
            findings.append(Finding(
                rule="race-donation-poison",
                path=path,
                message=(
                    f"{label}: sanitizer-armed session never hit a wrapped "
                    "donating entry point -- the harness is not covering "
                    "this loop (registry or loop wiring drifted)"
                ),
            ))
        bad = mismatched_leaves(plain, poisoned)
        if bad:
            findings.append(Finding(
                rule="race-donation-poison",
                path=path,
                message=(
                    f"{label}: sanitizer-armed run diverged from the plain "
                    f"run at {len(bad)} leaves (first: {bad[0]}) -- arming "
                    "must only serialize the overlap, never change a value"
                ),
            ))
    return findings, info
