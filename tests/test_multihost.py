"""Multi-host execution proof in CI (SURVEY.md section 5, distributed backend;
the reference's deployment shape is N cooperating OS processes, core.clj:197-203).

Runs tools/multihost_check.py: two local processes (CPU backend, 4 virtual
devices each) form a JAX distributed cluster over a localhost coordinator, run
`simulate_sharded` on the global 8-device mesh, and the process-0-gathered
metrics must match a single-process 8-device run bit for bit."""

import json
import os
import subprocess
import sys

import jax
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Environment gate, not a flake: the two-process proof needs cross-process
# collectives on the CPU backend, which jaxlib only implements from the 0.5
# line on -- on this image's jax 0.4.x the child processes die with
# "INVALID_ARGUMENT: Multiprocess computations aren't implemented on the CPU
# backend". Single-process multi-device sharding (tests/test_parallel.py)
# covers the mesh path everywhere; this proof re-arms automatically once the
# environment can run it.
_JAX_TOO_OLD = tuple(int(x) for x in jax.__version__.split(".")[:2]) < (0, 5)


def test_multichip_artifact_schema():
    """The standing MULTICHIP row must validate (multichip-v2: throughput,
    per-device bytes, parity hash) and the historical rc-only stubs must be
    reported as legacy, not silently passed."""
    from raft_sim_tpu.utils.telemetry_sink import validate_multichip

    assert validate_multichip(os.path.join(REPO, "MULTICHIP_r06.json")) == []
    errs = validate_multichip(os.path.join(REPO, "MULTICHIP_r01.json"))
    assert errs and "legacy" in errs[0], errs


@pytest.mark.skipif(
    _JAX_TOO_OLD,
    reason="jax<0.5 CPU backend: 'Multiprocess computations aren't implemented'",
)
def test_two_process_cluster_matches_single_process():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "multihost_check.py")],
        capture_output=True,
        text=True,
        timeout=540,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    verdict = json.loads(proc.stdout.strip().splitlines()[-1])
    assert verdict["match"] is True
    assert verdict["n_processes"] == 2
    assert verdict["global_devices"] == 8
    assert verdict["violations"] == 0
    # the workload did real work on the global mesh
    assert verdict["summary"]["total_cmds"] > 0
    assert verdict["summary"]["p50_commit_latency"] is not None
