"""Orchestration: run the passes, apply waivers, build the report.

`tools/check.py` is the CLI face; this module is the library face (tests call
it directly). The default waiver file is `analysis/waivers.json` next to this
package -- intentional exceptions live there with one-line justifications
(findings.py documents the format).
"""

from __future__ import annotations

import os
import time

from raft_sim_tpu.analysis import ast_lint, findings as F, jaxpr_audit

DEFAULT_WAIVERS = os.path.join(os.path.dirname(os.path.abspath(__file__)), "waivers.json")


def package_root() -> str:
    """The raft_sim_tpu package directory (the AST pass's default root)."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_all(
    *,
    do_ast: bool = True,
    do_jaxpr: bool = True,
    do_cost: bool = True,
    do_race: bool = True,
    do_range: bool = True,
    do_dynamic: bool = False,
    config_names=jaxpr_audit.AUDIT_CONFIGS,
    waivers_path: str | None = DEFAULT_WAIVERS,
):
    """Run the selected passes. Returns (findings, unused_waivers, problems,
    timings): `problems` are waiver-file format errors (always fatal for the
    CLI -- a typo'd waiver must not silently stop waiving); `timings` is
    {pass name: wall seconds} for the passes that ran (the CI artifact
    records it, and tests/test_cost_model.py pins the analyzer's budget).
    `do_dynamic` adds Pass D's runtime donation-poison leg (short
    sanitizer-armed standing-loop sessions -- the only part of the gate that
    executes device code beyond tiny donation probes)."""
    from raft_sim_tpu.analysis import cost_model, race_audit, range_audit

    found: list[F.Finding] = []
    active_rules: set[str] = set()
    timings: dict[str, float] = {}
    all_rules = (
        ast_lint.RULES | jaxpr_audit.RULES | cost_model.RULES
        | race_audit.RULES | range_audit.RULES
    )
    if do_ast:
        t0 = time.monotonic()
        found.extend(ast_lint.run_pass(package_root()))
        timings["ast"] = round(time.monotonic() - t0, 2)
        active_rules |= ast_lint.RULES
    if do_jaxpr:
        t0 = time.monotonic()
        found.extend(jaxpr_audit.run_pass(config_names))
        timings["jaxpr"] = round(time.monotonic() - t0, 2)
        active_rules |= jaxpr_audit.RULES
    if do_cost:
        t0 = time.monotonic()
        found.extend(cost_model.run_pass(config_names))
        timings["cost"] = round(time.monotonic() - t0, 2)
        active_rules |= cost_model.RULES
    if do_race:
        t0 = time.monotonic()
        found.extend(race_audit.run_pass(package_root()))
        if do_dynamic:
            from raft_sim_tpu.analysis import sanitizer

            dyn_findings, _info = sanitizer.run_dynamic()
            found.extend(dyn_findings)
        timings["race"] = round(time.monotonic() - t0, 2)
        active_rules |= race_audit.RULES
    if do_range:
        t0 = time.monotonic()
        found.extend(range_audit.run_pass(config_names))
        timings["range"] = round(time.monotonic() - t0, 2)
        active_rules |= range_audit.RULES
    unused: list[dict] = []
    problems: list[str] = []
    if waivers_path:
        entries, problems = F.load_waivers(waivers_path)
        unused = F.apply_waivers(found, entries)
        # A waiver is only STALE if the pass owning its rule actually ran (a
        # --jaxpr-only run must not condemn the AST pass's waivers). A rule
        # no pass knows -- a typo -- is stale whenever the full gate ran.
        full = do_ast and do_jaxpr and do_cost and do_race and do_range
        unused = [
            w for w in unused
            if w.get("rule") in active_rules
            or (full and w.get("rule") not in all_rules)
        ]
    return found, unused, problems, timings
