"""Scenario-engine tier: genomes, phased programs, the violation hunt, and
the shrink/replay loop.

The load-bearing property mirrors the telemetry tier's: the scenario path
must be a pure RE-PARAMETERIZATION of the simulator, not a second simulator.
A homogeneous genome built from a config's scalars must reproduce the scalar
path BIT-FOR-BIT -- fleet state, run metrics, and telemetry windows -- which
both paths guarantee by drawing through the same uint32 threshold helpers
from the same key streams (sim/faults.py). Above that sit the hunt's two
acceptance halves: the search must drive a deliberately weakened kernel
(scenario/mutation.py) to a violation within a bounded generation budget,
and must leave the real kernel clean under the same budget; a hit must
shrink to an artifact that replays to the IDENTICAL violation tick.

Compile budget: every windowed evaluation in this module shares ONE
(config, batch, ticks, window) shape -- the scalar parity run, the genome
parity run, the heterogeneous-fleet check, and the real-kernel search all
reuse two compiled programs; the mutant search adds one (different quorum
literal), the phased S=2 program one, and the shrink/replay pair two small
single-cluster programs. Everything else is host-side.
"""

import importlib.util
import json
import os
from types import SimpleNamespace

import jax
import numpy as np
import pytest

from raft_sim_tpu import RaftConfig
from raft_sim_tpu.scenario import genome as genome_mod
from raft_sim_tpu.scenario import program as program_mod
from raft_sim_tpu.scenario import search as search_mod
from raft_sim_tpu.scenario import shrink as shrink_mod
from raft_sim_tpu.scenario.mutation import WeakQuorumConfig, mutant_config
from raft_sim_tpu.sim import scan, telemetry
from raft_sim_tpu.utils import checkpoint

# One kitchen-sink config + shapes shared by every device evaluation here
# (see module docstring): all four fault mechanisms on, client traffic on,
# so parity covers every genome field against a nonzero scalar.
CFG = RaftConfig(
    n_nodes=5,
    log_capacity=8,
    client_interval=4,
    drop_prob=0.2,
    partition_period=16,
    partition_prob=0.3,
    crash_prob=0.3,
    crash_period=32,
    crash_down_ticks=8,
    clock_skew_prob=0.1,
)
BATCH, TICKS, WINDOW = 16, 128, 32
SPEC = search_mod.SearchSpec(
    generations=4, population=BATCH, ticks=TICKS, window=WINDOW, seed=0
)


def tree_eq(a, b, msg=""):
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb), err_msg=msg)


@pytest.fixture(scope="module")
def scalar_run():
    return telemetry.simulate_windowed(CFG, 0, BATCH, TICKS, WINDOW)


@pytest.fixture(scope="module")
def genome_run():
    g = genome_mod.broadcast(genome_mod.from_config(CFG), BATCH)
    genome_mod.validate(CFG, g)
    return telemetry.simulate_windowed(CFG, 0, BATCH, TICKS, WINDOW, genome=g)


# ------------------------------------------------------ homogeneous parity


def test_homogeneous_genome_is_bit_exact_with_scalar_path(scalar_run, genome_run):
    """The tentpole contract: a genome replicating the config scalars IS the
    scalar run -- same fleet state, same RunMetrics, same telemetry windows,
    bit for bit. Anything weaker and every search verdict would be about a
    different simulator than the one the presets run."""
    f1, m1, r1, _ = scalar_run
    f2, m2, r2, _ = genome_run
    tree_eq(f1, f2, "genome path perturbed the fleet state")
    tree_eq(m1, m2, "genome path perturbed the run metrics")
    tree_eq(r1, r2, "genome path perturbed the telemetry windows")


def test_heterogeneous_fleet_one_program(genome_run):
    """Per-cluster genomes really are per-cluster: a drop=1.0 row delivers
    nothing while its neighbors keep running -- in the SAME compiled program
    the homogeneous run used (same shapes; genome values are traced data)."""
    rows = [genome_mod.from_config(CFG) for _ in range(BATCH)]
    het = genome_mod.stack_rows(rows)
    het = het._replace(drop=het.drop.at[0].set(np.uint32((1 << 32) - 1)))
    _, m, _, _ = telemetry.simulate_windowed(CFG, 0, BATCH, TICKS, WINDOW, genome=het)
    msgs = np.asarray(m.total_msgs)
    assert msgs[0] == 0, "drop=1.0 cluster still delivered messages"
    assert (msgs[1:] > 0).all(), "healthy clusters stopped delivering"
    # And the untouched rows are bit-identical to the homogeneous run.
    _, m_hom, _, _ = genome_run
    np.testing.assert_array_equal(msgs[1:], np.asarray(m_hom.total_msgs)[1:])


# ------------------------------------------------------ phased programs


def test_segment_resolution_on_device():
    """faults.genome_at resolves the `[S]` table by now // seg_len with the
    final segment holding forever -- checked at the input level (no scan
    compile; the full phased pipeline rides the slow tier below and the CI
    scenario smoke)."""
    from raft_sim_tpu.sim import faults

    prog = program_mod.from_dict(
        {"seg_len": 8, "segments": [{"drop_prob": 1.0}, {}, {"clock_skew_prob": 1.0}]},
        CFG,
    )
    key = jax.random.key(0)
    for now, seg in [(0, 0), (7, 0), (8, 1), (23, 2), (999, 2)]:
        inp = faults.make_inputs(
            CFG, key, jax.numpy.int32(now), genome=prog.genome, seg_len=8
        )
        n_deliv = int(np.asarray(inp.deliver_mask).sum())
        skewed = bool((np.asarray(inp.skew) != 1).all())
        if seg == 0:
            assert n_deliv == 0, f"tick {now}: blackout segment delivered"
        else:
            assert n_deliv > 0, f"tick {now}: healed segment delivered nothing"
        assert skewed == (seg == 2), f"tick {now}: wrong skew segment"


@pytest.mark.slow
def test_phased_program_switches_segments_on_device():
    """A 2-segment nemesis (total blackout -> heal) switches at
    seg_len on-device: zero delivered records in the blackout windows, then
    traffic resumes -- one compiled program for the whole timeline."""
    prog = program_mod.from_dict(
        {
            "name": "blackout-heal",
            "seg_len": 2 * WINDOW,
            "segments": [{"drop_prob": 1.0}, {}],
        },
        CFG,
    )
    g = genome_mod.broadcast(prog.genome, BATCH)
    _, m, recs, _ = telemetry.simulate_windowed(
        CFG, 0, BATCH, TICKS, WINDOW, genome=g, seg_len=prog.seg_len
    )
    per_window = np.asarray(recs.metrics.total_msgs)  # [B, 4]
    assert (per_window[:, :2] == 0).all(), "blackout segment delivered records"
    assert (per_window[:, 2:].sum(axis=1) > 0).all(), "fleet never healed"


def test_program_json_round_trip(tmp_path):
    doc = {
        "name": "partition-heal-crash",
        "seg_len": 64,
        "segments": [
            {"partition_period": 16, "partition_prob": 1.0},
            {},
            {"crash_prob": 0.4, "crash_down_ticks": 8},
        ],
    }
    prog = program_mod.from_dict(doc, CFG)
    assert prog.n_segments == 3 and prog.span == 128
    path = program_mod.save(str(tmp_path / "p.json"), prog)
    prog2 = program_mod.load(path, CFG)
    tree_eq(prog.genome, prog2.genome, "JSON round trip changed the genome")
    assert prog2.seg_len == prog.seg_len and prog2.name == prog.name


def test_program_checkpoint_dict_is_bit_exact():
    """to_dict(exact=True) -> from_dict must return the IDENTICAL genome:
    decode() rounds probabilities to 9 decimals, so a segments-only round
    trip can shift a uint32 threshold by an ulp -- a resumed scenario run
    (checkpoint v20) must not silently continue a different trajectory."""
    prog = program_mod.from_dict(
        {"seg_len": 4, "segments": [{"drop_prob": 7e-10}, {"crash_prob": 0.3,
                                                           "crash_down_ticks": 5}]},
        CFG,
    )
    assert int(np.asarray(prog.genome.drop)[0]) == 3  # p_to_u32(7e-10)
    rt = program_mod.from_dict(
        json.loads(json.dumps(program_mod.to_dict(prog, exact=True))), CFG
    )
    tree_eq(prog.genome, rt.genome, "exact checkpoint round trip drifted")
    # The human-unit-only round trip is what exact=True exists to beat:
    lossy = program_mod.from_dict(program_mod.to_dict(prog), CFG)
    assert int(np.asarray(lossy.genome.drop)[0]) != 3  # 9-decimal rounding


def test_program_schema_errors():
    with pytest.raises(ValueError, match="unknown keys"):
        program_mod.from_dict({"segments": [{"drop": 0.1}]}, CFG)
    with pytest.raises(ValueError, match="non-empty"):
        program_mod.from_dict({"segments": []}, CFG)
    with pytest.raises(ValueError, match="seg_len"):
        program_mod.from_dict({"seg_len": 0, "segments": [{}]}, CFG)


# ------------------------------------------------------ genome validation


def test_validate_rejects_bad_genomes():
    g = genome_mod.from_config(CFG)
    with pytest.raises(ValueError, match="crash_down"):
        genome_mod.validate(CFG, g._replace(crash_down=g.crash_down * 0))
    with pytest.raises(ValueError, match="crash_down"):
        genome_mod.validate(
            CFG, g._replace(crash_down=g.crash_down * 0 + CFG.crash_period + 1)
        )
    no_client = RaftConfig(n_nodes=5)
    with pytest.raises(ValueError, match="client_interval"):
        genome_mod.validate(no_client, g)


def test_from_config_rejects_uniform_drop():
    with pytest.raises(ValueError, match="drop_prob_uniform"):
        genome_mod.from_config(RaftConfig(drop_prob=0.3, drop_prob_uniform=True))


def test_raw_round_trip_is_exact():
    g = genome_mod.from_config(CFG)
    g2 = genome_mod.from_raw(json.loads(json.dumps(genome_mod.to_raw(g))))
    tree_eq(g, g2, "raw artifact round trip changed the genome")


# ------------------------------------------------------ the hunt


@pytest.fixture(scope="module")
def mutant_hit():
    """The search demo against the weakened kernel -- shared by the budget
    test and the shrink pipeline (one search, one extra compile)."""
    mcfg = mutant_config("weak-quorum", CFG)
    assert isinstance(mcfg, WeakQuorumConfig) and mcfg.quorum == 2
    res = search_mod.search(mcfg, SPEC)
    return mcfg, res


def test_search_drives_mutant_to_violation_within_budget(mutant_hit):
    """The hunt hunts: the quorum-off-by-one kernel falls within the fixed
    generation budget, and the hit is fully replayable data."""
    _, res = mutant_hit
    assert res.hit is not None, (
        f"mutant survived {SPEC.generations} generations: {res.generations}"
    )
    assert len(res.generations) <= SPEC.generations
    hit = res.hit
    assert set(hit) >= {"seed", "batch", "cluster", "ticks", "seg_len",
                        "genome_raw", "first_viol_tick"}
    assert 0 <= hit["cluster"] < SPEC.population
    assert 0 <= hit["first_viol_tick"] < SPEC.ticks


def test_search_leaves_real_kernel_clean_under_same_budget():
    """Same spec, same seeds, real quorum: zero violations (and the windowed
    evaluation reuses the genome parity program -- same shapes)."""
    res = search_mod.search(CFG, SPEC)
    assert res.hit is None
    assert all(g["violating_clusters"] == 0 for g in res.generations)


def test_fitness_prefers_distress():
    """Violations dominate lexicographically; below them leaderless windows
    raise the score (hand-built records, no device work)."""
    B, W = 3, 4
    zeros = np.zeros((B, W), np.int32)
    mk = lambda **kw: SimpleNamespace(
        metrics=SimpleNamespace(
            last_leaderless_tick=kw.get("llt", zeros - 1),
            max_commit=kw.get("mc", zeros),
        ),
        first_viol_tick=zeros + telemetry.NEVER,
    )
    metrics = SimpleNamespace(
        violations=np.array([0, 0, 1]),
        max_term=np.array([3, 3, 3]),
        total_cmds=np.array([0, 0, 0]),
        lat_excluded=np.array([0, 0, 0]),
        multi_leader=np.array([0, 7, 0]),
    )
    llt = zeros - 1
    llt = llt.copy()
    llt[1] = 5  # cluster 1 saw leaderless windows AND multi-leader ticks
    fit = search_mod.fitness_from_records(mk(llt=llt), metrics)
    assert fit[1] > fit[0], "distress (leaderless + multi-leader) must raise fitness"
    assert fit[2] > fit[1] * 10, "a violation must dominate any distress"
    # multi_leader alone moves the score (the election-safety precursor).
    m2 = SimpleNamespace(**{**metrics.__dict__, "multi_leader": np.array([0, 0, 0])})
    fit2 = search_mod.fitness_from_records(mk(llt=llt), m2)
    assert fit[1] > fit2[1], "multi-leader ticks must raise fitness"


# ------------------------------------------------- shrink + bit-exact replay


@pytest.mark.slow  # budget re-tier (PR 12): the shrink -> bit-exact-replay
# contract is pinned every tier-1 run by the farm's fresh-freeze test
# (shrinks a hit, freezes it, replays via tools/repro.py --corpus) and the
# corpus one-command replay over tests/corpus; this direct pipeline form
# (plus the --scenario CLI leg, which CI's scenario smoke runs) rides the
# slow tier with the rest of the hunt soaks.
def test_shrink_minimizes_and_replays_to_identical_tick(mutant_hit, tmp_path):
    mcfg, res = mutant_hit
    art = shrink_mod.shrink(mcfg, res.hit, mutant="weak-quorum")
    # Minimization really removed or reduced something relative to the hit.
    assert art["schema"] == "scenario-repro-v1"
    assert art["kinds"], "artifact must name the violated invariant(s)"
    assert art["ticks"] == art["tick"] + 1, "horizon must be trimmed"
    assert art["mutant"] == "weak-quorum"
    # The artifact file round-trips and replays to the IDENTICAL tick.
    path = shrink_mod.save_artifact(str(tmp_path / "repro.json"), art)
    art2 = shrink_mod.load_artifact(path)
    rep = shrink_mod.replay_artifact(art2)
    assert rep["reproduced"], rep
    assert rep["tick"] == art["tick"] and rep["kinds"] == art["kinds"]
    # tools/repro.py --scenario is the same replay: exit 0.
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "repro_scenario", os.path.join(repo, "tools", "repro.py")
    )
    repro = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(repro)
    assert repro.main(["--scenario", path]) == 0


@pytest.mark.slow
def test_shrink_rejects_non_reproducing_hit(mutant_hit):
    """Broken (genome, seed) bookkeeping must fail loudly, not shrink noise:
    the same hit replayed under the REAL kernel runs clean."""
    _, res = mutant_hit
    with pytest.raises(ValueError, match="does not reproduce"):
        shrink_mod.shrink(CFG, res.hit)


# --------------------------------------------- checkpoint v20 (scenario rides)


def test_checkpoint_v20_carries_scenario_and_gates_plain_resume(tmp_path):
    from raft_sim_tpu.driver import Session
    from raft_sim_tpu.sim.scan import init_metrics_batch
    from raft_sim_tpu.types import init_batch

    cfg = RaftConfig(n_nodes=2, log_capacity=4, max_entries_per_rpc=1)
    key = jax.random.key(0)
    scen = {"name": "t", "seg_len": 4, "segments": [{"drop_prob": 0.5}, {}]}
    path = checkpoint.save(
        str(tmp_path / "ck"), cfg, init_batch(cfg, key, 1),
        jax.random.split(key, 1), init_metrics_batch(1), scenario=scen,
    )
    *_, scen2 = checkpoint.load(path)
    assert scen2 == scen
    # Plain resume must refuse: continuing without the genome path would
    # silently run a different experiment.
    with pytest.raises(ValueError, match="scenario"):
        Session.restore(path)
    # A plain checkpoint round-trips scenario=None.
    p2 = checkpoint.save(
        str(tmp_path / "ck2"), cfg, init_batch(cfg, key, 1),
        jax.random.split(key, 1), init_metrics_batch(1),
    )
    *_, none_scen = checkpoint.load(p2)
    assert none_scen is None


def test_checkpoint_v22_migration_error_names_versions(tmp_path):
    """A v22 file (the pre-lease format: no read_fr staleness leg) errors
    with the migration hint -- the PR 3 hygiene rule, applied across the
    v23/v24/v25 bumps (the v23-file case rides tests/test_reconfig.py)."""
    from raft_sim_tpu.sim.scan import init_metrics_batch
    from raft_sim_tpu.types import init_batch

    assert checkpoint._FORMAT_VERSION == 25  # v25: durable watermarks
    assert checkpoint._SCHEMA_FINGERPRINT[0] == 25
    cfg = RaftConfig(n_nodes=2, log_capacity=4, max_entries_per_rpc=1)
    key = jax.random.key(0)
    path = checkpoint.save(
        str(tmp_path / "ck"), cfg, init_batch(cfg, key, 1),
        jax.random.split(key, 1), init_metrics_batch(1),
    )
    with np.load(path) as z:
        arrays = {k: z[k] for k in z.files}
    arrays["__version__"] = np.int32(22)
    np.savez_compressed(path, **arrays)
    with pytest.raises(ValueError) as ex:
        checkpoint.load(path)
    msg = str(ex.value)
    assert "v22" in msg and "v25" in msg and "version log" in msg
