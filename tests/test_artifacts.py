"""Bitrot insurance for the repo-root driver artifacts: bench.py's measurement
harness and __graft_entry__.py's compile-contract entry points must keep working
as the kernels evolve (both are executed by external automation, so nothing else
in the suite touches them)."""

import sys

import jax
import pytest


sys.path.insert(0, ".")  # repo root: bench.py / __graft_entry__.py live there


def test_bench_harness_runs_cpu_sized():
    import bench

    from raft_sim_tpu import RaftConfig

    row = bench.bench(RaftConfig(n_nodes=5), batch=64, ticks=50, repeats=1)
    assert row["violations"] == 0
    assert row["cluster_ticks_per_s"] > 0
    assert 0 <= row["pct_stable"] <= 100
    # Quality fields come from the fixed-seed run: a second invocation agrees.
    row2 = bench.bench(RaftConfig(n_nodes=5), batch=64, ticks=50, repeats=1)
    assert row["p50_stable_tick"] == row2["p50_stable_tick"]
    assert row["pct_stable"] == row2["pct_stable"]


def test_graft_entry_compiles():
    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn).lower(*args).compile()(*args)
    new_state, info = out
    assert new_state.role.shape == args[0].role.shape
