"""Static bytes-moved-per-tick audit: the quantitative side of the bit-packing
work (and, where packing cannot win, the roofline argument).

Every `lax.scan` tick reads the whole carry (ClusterState + Mailbox +
RunMetrics) from HBM and writes it back, and materializes the per-tick
StepInputs; at large N those planes ARE the tick's HBM traffic (docs/PERF.md
"what the profile says"). This tool enumerates the carry exactly as the
kernels declare it -- `jax.eval_shape` over `init_state`/`make_inputs`, so the
accounting can never drift from the real structures -- and prices each leaf
two ways:

  - logical bytes (shape x itemsize), and
  - TPU-padded bytes in the batch-minor layout ([..., B]: the minor dim rides
    the 128-wide lane tile, the second-minor dim pads to the dtype's sublane
    multiple -- 8 for 4-byte, 16 for 2-byte, 32 for 1-byte elements), the
    physical footprint models/raft_batched.py exists to control.

It then rebuilds the same table for the DENSE pre-packing layout (votes and
deliver_mask as [N, N] bool, pre-vote grants riding resp_kind, no pv_grant
plane) and reports the per-config delta plus a roofline projection: given the
recorded round-5 throughput of each config (docs/PERF.md history table,
measured on the real chip), the implied HBM rate is ticks/s x bytes/tick; a
layout change can speed up an HBM-bound config by at most the traffic ratio.
That makes the config5 verdict honest either way -- either the packed layout's
reduction projects past the 3M ticks/s bar, or this audit documents that the
bool planes were never a large enough fraction of the tick for packing to get
there (docs/PERF.md "bit-packing audit" section holds the conclusions).

Runs on CPU (nothing is executed on device -- eval_shape only):

    python tools/traffic_audit.py                     # configs 3/4/5 table
    python tools/traffic_audit.py --configs config5 --top 12
    python tools/traffic_audit.py --json              # machine-readable
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from raft_sim_tpu.analysis.policy import invariant_leaves
from raft_sim_tpu.ops import bitplane
from raft_sim_tpu.sim import faults, scan
from raft_sim_tpu.types import init_state
from raft_sim_tpu.utils.config import PRESETS, RaftConfig

# Recorded round-5 throughput per preset (docs/PERF.md history table, real
# chip, best-of-2): the anchor for the implied-HBM-rate roofline. A config
# absent here gets bytes accounting but no projection.
RECORDED_TICKS_PER_S = {
    "config3": 38.1e6,
    "config4": 22.7e6,
    "config5": 2.14e6,
}

# TPU minor-tile sublane multiple by element width (lane dim is always 128).
_SUBLANE = {4: 8, 2: 16, 1: 32}


# Loop-invariant carry legs (excluded from the traffic totals: XLA elides
# them from the per-tick HBM round trip -- the round-4 lesson recorded in
# docs/PERF.md). Single-sourced from analysis/policy.py, where the jaxpr pass
# (rule carry-passthrough) STATICALLY enforces that the legs named there are
# in fact passed through the scan body untouched -- so this audit and the
# analyzer can never disagree about which legs are free.
_invariant_leaves = invariant_leaves


def _leaf_rows(cfg: RaftConfig):
    """(group, name, shape, dtype) for every scan-carry leaf + per-tick input,
    taken from the real structures via eval_shape (shapes are per cluster);
    loop-invariant carry legs (see _invariant_leaves) are dropped."""
    key = jax.eval_shape(lambda: jax.random.key(0))
    state = jax.eval_shape(lambda k: init_state(cfg, k), key)
    inputs = jax.eval_shape(
        lambda k: faults.make_inputs(cfg, k, jnp.int32(0)), key
    )
    metrics = jax.eval_shape(scan.init_metrics)
    rows = []
    for f, v in zip(state._fields, state):
        if f == "mailbox":
            continue
        rows.append(("state", f, tuple(v.shape), v.dtype.itemsize))
    for f, v in zip(state.mailbox._fields, state.mailbox):
        rows.append(("mailbox", f"mb.{f}", tuple(v.shape), v.dtype.itemsize))
    for f, v in zip(inputs._fields, inputs):
        rows.append(("inputs", f"in.{f}", tuple(v.shape), v.dtype.itemsize))
    for f, v in zip(metrics._fields, metrics):
        rows.append(("metrics", f"metric.{f}", tuple(v.shape), v.dtype.itemsize))
    skip = _invariant_leaves(cfg)
    return [r for r in rows if r[1] not in skip]


def _densify(rows, cfg: RaftConfig):
    """The pre-packing layout of the same carry: [N, N] bool votes and
    delivery mask, pre-vote grants riding resp_kind (no pv_grant plane)."""
    n = cfg.n_nodes
    out = []
    for g, name, shape, isize in rows:
        if name == "votes" or name == "in.deliver_mask":
            out.append((g, name + " (dense)", (n, n), 1))
        elif name == "mb.pv_grant":
            continue  # its bit rode the resp_kind byte plane
        else:
            out.append((g, name, shape, isize))
    return out


def _logical(shape, isize):
    return math.prod(shape) * isize if shape else isize


def _padded(shape, isize, batch):
    """Physical bytes per cluster in the batch-minor layout: shape + (B,) with
    the trailing two dims tiled (sublane x 128 lanes). Divided back by B, so
    lane padding amortizes across the batch and the reported overhead is the
    sublane padding the layout actually pays per cluster."""
    bm = tuple(shape) + (batch,)
    dims = list(bm)
    dims[-1] = -(-dims[-1] // 128) * 128
    if len(dims) >= 2:
        sub = _SUBLANE[isize]
        dims[-2] = -(-dims[-2] // sub) * sub
    return math.prod(dims) * isize / batch


def _telemetry_rows(cfg: RaftConfig, ring_k: int):
    """(group, name, shape, dtype-size) rows for the telemetry carry legs
    (sim/telemetry.py), taken from the real structures via eval_shape like
    everything else: the windowed-aggregation leg is a second RunMetrics
    (window-local accumulator) + the first-violation tick; the flight-recorder
    leg is K stacked StepInfos + slot ticks + pos/frozen. All are scan-carry
    components (read + write per tick), which is exactly why the ring must
    stay small -- the audit prices the decision (docs/OBSERVABILITY.md)."""
    metrics = jax.eval_shape(scan.init_metrics)
    rows = [
        ("telemetry", f"tel.wm.{f}", tuple(v.shape), v.dtype.itemsize)
        for f, v in zip(metrics._fields, metrics)
    ]
    rows.append(("telemetry", "tel.first_viol", (), 4))
    if ring_k > 0:
        from raft_sim_tpu.sim import telemetry

        rec = jax.eval_shape(lambda: telemetry.init_recorder(cfg, ring_k, 1))
        for f, v in zip(rec.ring._fields, rec.ring):
            rows.append(
                ("telemetry", f"tel.ring.{f}", tuple(v.shape[:-1]), v.dtype.itemsize)
            )
        rows.append(("telemetry", "tel.ring.tick", (ring_k,), 4))
        rows.append(("telemetry", "tel.pos", (), 4))
        rows.append(("telemetry", "tel.frozen", (), 1))
    return rows


def _scenario_rows(s_count: int):
    """(group, name, shape, dtype-size) rows for the scenario-engine genome:
    7 `[S]` per-cluster leaves (uint32 thresholds / int32 cadences -- the set
    single-sourced from analysis/policy.py:scenario_genome_leaves, which the
    genome path actually reads). The genome rides the scan body as loop
    CONSTANTS -- priced once per tick like the other inputs (the per-tick
    segment gather touches one element per leaf; pricing the whole `[S]`
    table is the conservative bound)."""
    from raft_sim_tpu.analysis.policy import scenario_genome_leaves

    return [
        ("scenario", f"gen.{name}", (s_count,), 4)
        for name, _dtype in scenario_genome_leaves()
    ]


def audit(cfg: RaftConfig, batch: int):
    """Both layouts' per-cluster-tick byte totals. Carry leaves move twice per
    tick (read + write); inputs once (materialized from the key stream)."""

    def total(rows):
        log = pad = 0.0
        for g, _, shape, isize in rows:
            mult = 1 if g == "inputs" else 2
            log += mult * _logical(shape, isize)
            pad += mult * _padded(shape, isize, batch)
        return log, pad

    packed_rows = _leaf_rows(cfg)
    dense_rows = _densify(packed_rows, cfg)
    packed_log, packed_pad = total(packed_rows)
    dense_log, dense_pad = total(dense_rows)
    # The limiting case of ANY bool-plane compression: the boolean planes cost
    # zero bytes. If even this cannot reach a throughput bar, no packing can.
    boolfree = [
        r
        for r in packed_rows
        if r[1] not in ("votes", "in.deliver_mask", "mb.pv_grant")
    ]
    boolfree_log, boolfree_pad = total(boolfree)
    return {
        "packed_rows": packed_rows,
        "dense_rows": dense_rows,
        "packed_logical": packed_log,
        "packed_padded": packed_pad,
        "dense_logical": dense_log,
        "dense_padded": dense_pad,
        "boolfree_logical": boolfree_log,
        "boolfree_padded": boolfree_pad,
    }


def _fmt_bytes(b):
    return f"{b / 1024:.2f} KiB" if b >= 1024 else f"{b:.0f} B"


def report(name: str, cfg: RaftConfig, batch: int, top: int, out=sys.stdout,
           telemetry_ring: int | None = None, scenario_segments: int | None = None):
    a = audit(cfg, batch)
    w = bitplane.n_words(cfg.n_nodes)
    print(f"\n== {name}: N={cfg.n_nodes} (W={w}), CAP={cfg.log_capacity}, "
          f"E={cfg.max_entries_per_rpc}, batch={batch} ==", file=out)
    print(f"{'plane':28} {'shape':>14} {'logical':>10} {'padded':>10}", file=out)
    biggest = sorted(
        a["packed_rows"],
        key=lambda r: -_padded(r[2], r[3], batch),
    )[:top]
    for g, nm, shape, isize in biggest:
        print(
            f"{nm:28} {str(shape):>14} {_logical(shape, isize):>10,} "
            f"{_padded(shape, isize, batch):>10,.0f}",
            file=out,
        )
    dl, dp = a["dense_logical"], a["dense_padded"]
    pl, pp = a["packed_logical"], a["packed_padded"]
    print(f"{'per-cluster-tick DENSE':28} {'':>14} {dl:>10,.0f} {dp:>10,.0f}", file=out)
    print(f"{'per-cluster-tick PACKED':28} {'':>14} {pl:>10,.0f} {pp:>10,.0f}", file=out)
    print(
        f"reduction: logical {100 * (1 - pl / dl):.1f}%  "
        f"padded {100 * (1 - pp / dp):.1f}%",
        file=out,
    )
    rec = RECORDED_TICKS_PER_S.get(name)
    res = {
        "config": name,
        "n": cfg.n_nodes,
        "dense_logical": dl,
        "dense_padded": dp,
        "packed_logical": pl,
        "packed_padded": pp,
        "boolfree_padded": a["boolfree_padded"],
    }
    if rec:
        bw = rec * dp
        ceiling = bw / pp
        bound = bw / a["boolfree_padded"]
        res |= {
            "recorded_ticks_per_s": rec,
            "implied_hbm_bytes_per_s": bw,
            "packed_roofline_ticks_per_s": ceiling,
            "boolfree_roofline_ticks_per_s": bound,
        }
        print(
            f"recorded (r05, chip): {rec / 1e6:.2f}M ticks/s -> implied HBM rate "
            f"{bw / 1e9:.1f} GB/s on the dense carry",
            file=out,
        )
        print(
            f"packed roofline at that rate: {ceiling / 1e6:.2f}M ticks/s "
            f"({ceiling / rec:.3f}x)",
            file=out,
        )
        print(
            f"bool-free bound (boolean planes at ZERO bytes): "
            f"{bound / 1e6:.2f}M ticks/s ({bound / rec:.3f}x) -- no bool-plane "
            "compression can beat this",
            file=out,
        )
    if telemetry_ring is not None:
        # Observability overhead: the telemetry carry legs (window accumulator
        # always; ring buffer at depth K) priced against the packed tick.
        tel_rows = _telemetry_rows(cfg, telemetry_ring)
        tel_log = sum(2 * _logical(s, i) for _, _, s, i in tel_rows)
        tel_pad = sum(2 * _padded(s, i, batch) for _, _, s, i in tel_rows)
        wm_rows = [r for r in tel_rows if not r[1].startswith("tel.ring")
                   and r[1] not in ("tel.pos", "tel.frozen")]
        wm_pad = sum(2 * _padded(s, i, batch) for _, _, s, i in wm_rows)
        print(
            f"telemetry carry legs (window accumulator"
            + (f" + ring K={telemetry_ring}" if telemetry_ring else "")
            + f"): {_fmt_bytes(tel_log)} logical / {_fmt_bytes(tel_pad)} padded "
            f"per cluster-tick = +{100 * tel_pad / pp:.1f}% over the packed tick "
            f"(windows alone: +{100 * wm_pad / pp:.1f}%)",
            file=out,
        )
        res |= {
            "telemetry_ring": telemetry_ring,
            "telemetry_logical": tel_log,
            "telemetry_padded": tel_pad,
            "telemetry_window_only_padded": wm_pad,
            "telemetry_overhead_frac": tel_pad / pp,
        }
    if scenario_segments is not None:
        # Scenario-engine overhead: the genome broadcast (S-segment program
        # table, 7 leaves x 4 B per cluster) read each tick by the genome
        # input path. Inputs move ONCE per tick (like in.*); the carry is
        # untouched (the genome is a scan const, never a carry leg), so this
        # is the WHOLE per-cluster traffic cost of heterogeneous fault
        # space -- docs/PERF.md "scenario path" records the standing verdict.
        sc_rows = _scenario_rows(scenario_segments)
        sc_log = sum(_logical(s, i) for _, _, s, i in sc_rows)
        sc_pad = sum(_padded(s, i, batch) for _, _, s, i in sc_rows)
        print(
            f"scenario genome table (S={scenario_segments} segments, "
            f"{len(sc_rows)} leaves): {_fmt_bytes(sc_log)} logical / "
            f"{_fmt_bytes(sc_pad)} padded per cluster-tick = "
            f"+{100 * sc_pad / pp:.2f}% over the packed tick",
            file=out,
        )
        res |= {
            "scenario_segments": scenario_segments,
            "scenario_logical": sc_log,
            "scenario_padded": sc_pad,
            "scenario_overhead_frac": sc_pad / pp,
        }
    return res


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--configs",
        default="config3,config4,config5",
        help="comma-separated preset names (see raft_sim_tpu.utils.config.PRESETS)",
    )
    ap.add_argument("--top", type=int, default=8, help="largest planes listed")
    ap.add_argument("--json", action="store_true", help="emit one JSON line")
    ap.add_argument("--telemetry-ring", type=int, default=None, metavar="K",
                    help="also price the telemetry carry legs: the window "
                         "accumulator plus a K-deep flight-recorder ring "
                         "(K=0 prices windowed aggregation alone)")
    ap.add_argument("--scenario", type=int, default=None, metavar="S",
                    help="also price the scenario-engine genome broadcast: "
                         "an S-segment program table per cluster "
                         "(raft_sim_tpu/scenario; S=1 prices a plain "
                         "heterogeneous-fleet genome)")
    args = ap.parse_args(argv)

    # With --json the human tables go to stderr so stdout is exactly one
    # parseable JSON line (the bench-artifact lesson: machine output must not
    # interleave with narration).
    table_out = sys.stderr if args.json else sys.stdout
    results = []
    for name in args.configs.split(","):
        name = name.strip()
        if name not in PRESETS:
            print(f"unknown preset {name!r}", file=sys.stderr)
            return 2
        cfg, batch = PRESETS[name]
        results.append(report(name, cfg, batch, args.top, out=table_out,
                              telemetry_ring=args.telemetry_ring,
                              scenario_segments=args.scenario))
    if args.json:
        print(json.dumps(results))
    return 0


if __name__ == "__main__":
    sys.exit(main())
