"""The standing-fleet service loop: streaming ingest in, telemetry + deltas out.

`driver serve`'s engine -- the sixth subsystem's core. One compiled scan
program (`run_windowed_served`) advances the whole fleet chunk by chunk with
the per-tick client command coming from an EXPLICIT [T] offer plane (scan xs)
instead of the scheduled cadence, folding telemetry windows on device exactly
like sim/telemetry.py. Around it, `ServeSession` runs the double-buffered
host<->device exchange ISSUE 6 specifies:

    dispatch chunk k (async)  ->  pack chunk k+1's offer plane from the
    ingest queue while the device runs  ->  collect chunk k's telemetry
    windows + commit deltas  ->  repeat.

Buffer discipline matches the other long-horizon loops: the previous chunk's
fleet state is DONATED (`_serve_chunk`, pinned by the cost model's donation
audit), so a standing service holds ONE fleet in HBM; the ingest plane and the
delta watermark are the only per-chunk host traffic. After warmup the loop
compiles NOTHING: chunk shape, window, and config are fixed, commands are
traced data (the distinct-lowering pin in tests/golden_jaxpr_hist.json gates
this, and tests/test_serve.py asserts the jit cache stays at one entry).
"""

from __future__ import annotations

import dataclasses
import functools
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_sim_tpu.models import raft_batched
from raft_sim_tpu.serve import deltas as deltas_mod
from raft_sim_tpu.serve.ingest import CommandSource
from raft_sim_tpu.sim import scan
from raft_sim_tpu.sim.chunked import _own_copy, merge_metrics
from raft_sim_tpu.sim.telemetry import NEVER, WindowRecord
from raft_sim_tpu.types import NIL
from raft_sim_tpu.utils.config import RaftConfig


def serve_config(cfg: RaftConfig) -> RaftConfig:
    """The serve-mode variant of a config: external ingest replaces the
    scheduled cadence (client_interval forced 0 -- ALL traffic is offered),
    with the offer-tick plane kept live via serve_ingest."""
    if cfg.serve_ingest and cfg.client_interval == 0:
        return cfg
    return dataclasses.replace(cfg, serve_ingest=True, client_interval=0)


def run_windowed_served(cfg: RaftConfig, state, keys, cmds, window: int):
    """Scan the fleet through one chunk of `cmds` ([T] int32 offer plane,
    NIL = no offer that tick), emitting one WindowRecord per `window` ticks.

    Same shared tick body as every other loop (scan.tick_batch_minor with the
    per-tick client_cmd override Session.offer already uses), so the served
    path can never drift from run(); same window algebra as
    telemetry.run_batch_minor_telemetry, so the streamed records merge
    bit-exactly into run-level metrics. T must divide by `window`.
    Returns (final_state, chunk_metrics, records) in public [B, ...] layouts.
    """
    n_ticks = cmds.shape[0]
    if n_ticks % window:
        raise ValueError(f"chunk of {n_ticks} ticks must divide by window {window}")
    batch = state.role.shape[0]
    s_t = raft_batched.to_batch_minor(state)
    m0 = raft_batched.to_batch_minor(scan.init_metrics_batch(batch))

    def inner(carry, cmd):
        s, wm, fv = carry
        now = s.now  # [B] absolute tick BEFORE the step (lockstep across B)
        s2, wm2, info = scan.tick_batch_minor(cfg, s, keys, wm, client_cmd=cmd)
        bad = info.viol_election_safety | info.viol_commit | info.viol_log_matching
        fv2 = jnp.minimum(fv, jnp.where(bad, now, NEVER))
        return (s2, wm2, fv2), None

    def outer(carry, cmd_win):
        s, m = carry
        start = s.now
        fv0 = jnp.full((batch,), NEVER, jnp.int32)
        (s2, wm, fv), _ = lax.scan(inner, (s, m0, fv0), cmd_win)
        out = WindowRecord(start=start, first_viol_tick=fv, metrics=wm)
        return (s2, merge_metrics(m, wm)), out

    cmd_wins = cmds.reshape(n_ticks // window, window)
    (final_t, metrics), recs = lax.scan(outer, (s_t, m0), cmd_wins)
    return (
        raft_batched.from_batch_minor(final_t),
        raft_batched.from_batch_minor(metrics),
        raft_batched.from_batch_minor(recs),
    )


@functools.partial(jax.jit, static_argnums=(0, 4), donate_argnums=(1,))
def _serve_chunk(cfg: RaftConfig, state, keys, cmds, window: int):
    """The steady-state serve chunk: the previous chunk's fleet is DONATED
    back to XLA (one fleet in HBM, like chunked._chunk_donate -- donation
    status pinned by the cost model's `cost-donation` rule). `keys` and the
    offer plane are never donated."""
    return run_windowed_served(cfg, state, keys, cmds, window)


@functools.partial(jax.jit, static_argnums=(0, 2, 4))
def simulate_serve(cfg: RaftConfig, seed, batch: int, cmds, window: int):
    """One-call served simulation from a seed: init + served windowed scan.
    The audit entry the static gates lower (`jaxpr_audit.serve_scan_jaxpr` ->
    Pass A rules + Pass C pricing) and the parity-test entry (two runs
    differing only in offer VALUES share this one compiled program)."""
    root = jax.random.key(seed)
    k_init, k_run = jax.random.split(root)
    from raft_sim_tpu.types import init_batch

    state = init_batch(cfg, k_init, batch)
    keys = jax.random.split(k_run, batch)
    return run_windowed_served(cfg, state, keys, cmds, window)


class ServeSession:
    """A standing fleet accepting streamed commands between chunks.

    >>> s = ServeSession(RaftConfig(n_nodes=5), batch=8, seed=0, chunk=128)
    >>> stats = s.serve(CommandSource([7, 7, 2**31 - 1]), chunks=4)
    >>> s.delta_rows  # every cluster's committed (index, value, tick) stream

    `sink` (a utils/telemetry_sink.TelemetrySink) streams telemetry windows to
    windows.jsonl and commit deltas to deltas.jsonl continuously -- the
    schema'd export surface, validated by the CI serve smoke job.
    """

    def __init__(
        self,
        cfg: RaftConfig,
        batch: int = 1,
        seed: int = 0,
        chunk: int = 256,
        window: int = 64,
        delta_depth: int = 64,
        sink=None,
        warmup_ticks: int = 0,
        perf=None,
    ):
        if chunk % window:
            raise ValueError(f"chunk {chunk} must divide by window {window}")
        self.cfg = serve_config(cfg)
        self.batch = batch
        self.seed = seed
        self.chunk = chunk
        self.window = window
        self.sink = sink
        # Per-chunk runtime attribution (obs.ChunkTimer): dispatch in
        # _dispatch, ingest packing as the host gap, the _collect device_get
        # as the device wait -- the double buffer's natural phase boundaries,
        # so serving pays NO extra sync for attribution. The serve chunk's
        # jit cache is sampled every boundary (the flat-cache discipline
        # tests/test_serve.py pins, now a streamed watchdog counter too).
        self.perf = perf
        if perf is not None:
            perf.add_probe("serve._serve_chunk", _serve_chunk)
            if warmup_ticks:
                # Warmup chunks (leader election before the first offer) are
                # compile + convergence time, never steady serving -- and the
                # FIRST serving chunk after them pays the one-time
                # donated-carry respecialization (timer docstring), so it is
                # excluded too.
                perf.warmup_chunks = max(
                    perf.warmup_chunks,
                    self._round_up(warmup_ticks) // chunk + 1,
                )
        if sink is not None:
            # The session owns the sink directory's delta stream (the sink
            # itself owns manifest/windows/summary): truncate any stale file
            # up front so per-cluster streams always start dense at index 1
            # (appending after an old run would trip validate_deltas).
            self._deltas_path = os.path.join(sink.directory, "deltas.jsonl")
            open(self._deltas_path, "w").close()
        root = jax.random.key(seed)
        k_init, k_run = jax.random.split(root)
        from raft_sim_tpu.types import init_batch

        # The loop owns its fleet copy (donation discipline: see _serve_chunk).
        self.state = _own_copy(init_batch(self.cfg, k_init, batch))
        self.keys = jax.random.split(k_run, batch)
        self.metrics = scan.init_metrics_batch(batch)
        self.deltas = deltas_mod.DeltaStream(batch, depth=delta_depth)
        self.delta_rows: list[dict] = []
        self.chunks_done = 0
        self.ticks_done = 0
        self.warmup_chunks = 0
        if warmup_ticks:
            # Elect leaders before the first real offer plane (an offer into a
            # leaderless tick is dropped, exactly like the reference's curl
            # against a booting cluster). Warmup is accounted separately:
            # serve()'s chunk budget and throughput stats cover SERVING only.
            self._advance(np.full((self._round_up(warmup_ticks),), NIL, np.int32))
            self.warmup_chunks, self.chunks_done = self.chunks_done, 0
            self.ticks_done = 0

    def _round_up(self, ticks: int) -> int:
        return -(-ticks // self.chunk) * self.chunk

    def _advance(self, cmds_np: np.ndarray) -> None:
        for i in range(0, len(cmds_np), self.chunk):
            self._dispatch(cmds_np[i:i + self.chunk])
            self._collect()

    def _dispatch(self, cmds_np: np.ndarray):
        """Issue one chunk (async under jax dispatch); the caller packs the
        NEXT chunk while this one runs."""
        if self.perf is not None:
            self.perf.begin(int(cmds_np.shape[0]))
        cmds = jnp.asarray(cmds_np, jnp.int32)
        self.state, self._m_pending, self._recs_pending = _serve_chunk(
            self.cfg, self.state, self.keys, cmds, self.window
        )
        if self.perf is not None:
            self.perf.dispatched()
        self.chunks_done += 1
        self.ticks_done += int(cmds_np.shape[0])

    def _collect(self) -> list[dict]:
        """Merge the dispatched chunk's outputs and stream them out (the
        device_get here is the synchronization point of the double buffer)."""
        self.metrics = merge_metrics(self.metrics, self._m_pending)
        if self.perf is not None:
            # The ingest packing between _dispatch and here was the host gap;
            # the sync on this chunk's metric leaf is the device wait. The
            # export below (sink writes, delta drain) lands in the NEXT row's
            # gap_s -- still host-attributed, never device.
            self.perf.end(sync=lambda: np.asarray(self._m_pending.ticks))
        recs = jax.device_get(self._recs_pending)
        if self.sink is not None:
            self.sink.append_windows(recs)
        rows = self.deltas.drain(self.state)
        self.delta_rows.extend(rows)
        if self.sink is not None:
            deltas_mod.append_delta_rows(self._deltas_path, rows)
        return rows

    def serve(
        self,
        source: CommandSource,
        chunks: int | None = None,
        drain_chunks: int = 4,
        progress=None,
    ) -> dict:
        """Run the double-buffered service loop against `source`.

        Stops after `chunks` serving chunks when given (warmup chunks are
        accounted separately and never consume the budget); otherwise when the
        source is exhausted AND `drain_chunks` further empty chunks have
        flushed trailing commits through the delta stream.
        `progress(stats_dict)` is called after each chunk. Returns the serve
        stats dict.
        """
        t0 = time.perf_counter()
        next_cmds = source.next_chunk(self.chunk)
        while True:
            offered = int(np.sum(next_cmds != NIL))
            self._dispatch(next_cmds)
            # Decide BEFORE prefetching whether this was the last chunk: a
            # prefetch past the stop would pull commands from the source only
            # to drop them (and over-count stats["offered"]).
            if chunks is not None:
                stop = self.chunks_done >= chunks
            else:
                if source.exhausted and offered == 0:
                    drain_chunks -= 1
                stop = source.exhausted and drain_chunks <= 0
            if not stop:
                # Double buffer: pack the NEXT chunk's offer plane from the
                # ingest queue while the device executes the current one.
                next_cmds = source.next_chunk(self.chunk)
            self._collect()
            if progress is not None:
                progress(self.stats())
            if stop:
                break
        stats = self.stats()
        stats["wall_s"] = round(time.perf_counter() - t0, 3)
        stats["offered"] = source.offered
        if self.perf is not None:
            # Steady-state rollup + the recompile-watchdog finding (stderr).
            stats["perf"] = self.perf.finish()
        if self.sink is not None:
            from raft_sim_tpu.parallel import summarize

            self.sink.write_summary({**summarize(self.metrics)._asdict(), **stats})
        return stats

    def stats(self) -> dict:
        return {
            "chunks": self.chunks_done,
            "ticks": self.ticks_done,
            "warmup_chunks": self.warmup_chunks,
            "batch": self.batch,
            "chunk": self.chunk,
            "window": self.window,
            "deltas_exported": self.deltas.exported,
            "delta_gap_entries": self.deltas.gap_entries,
            "violations": int(np.sum(np.asarray(self.metrics.violations))),
        }

    def acked_values(self, cluster: int = 0) -> list[int]:
        """The commit-ack stream of one cluster: committed client values in
        commit order (no-ops filtered) -- what the reference's commit watch
        should have delivered per entry (log.clj:83-87, bug 2.3.9)."""
        return deltas_mod.applied_values(self.delta_rows, cluster)
