"""End-to-end smoke: a single 5-node cluster on a reliable network elects a stable
leader and replicates client commands (BASELINE config 1 semantics, shortened)."""

import jax
import jax.numpy as jnp

from raft_sim_tpu import LEADER, NIL, RaftConfig, init_state
from raft_sim_tpu.sim import scan


def test_single_cluster_elects_and_replicates():
    cfg = RaftConfig(n_nodes=5, client_interval=8, check_log_matching=True)
    key = jax.random.key(0)
    k_init, k_run = jax.random.split(key)
    state = init_state(cfg, k_init)
    final, metrics, _ = jax.jit(
        lambda s, k: scan.run(cfg, s, k, 300)
    )(state, k_run)

    assert int(metrics.violations) == 0
    # Exactly one leader at the end, and every node agrees who it is.
    roles = jax.device_get(final.role)
    assert (roles == LEADER).sum() == 1
    leader = int(jnp.argmax(final.role == LEADER))
    assert all(int(l) == leader for l in jax.device_get(final.leader_id))
    # A leader emerged reasonably fast and stayed.
    assert int(metrics.first_leader_tick) < 40
    assert int(scan.stable_leader_ticks(metrics)) < 2**30
    # Client commands were injected, replicated, and committed on every node.
    commits = jax.device_get(final.commit_index)
    assert commits.min() > 5
    # Committed prefixes match across nodes (log matching, checked host-side too).
    terms = jax.device_get(final.log_term)
    vals = jax.device_get(final.log_val)
    c = commits.min()
    for i in range(1, 5):
        assert (terms[0, :c] == terms[i, :c]).all()
        assert (vals[0, :c] == vals[i, :c]).all()


def test_deterministic_replay():
    """Same seed => bit-identical trajectory (the determinism check that replaces the
    reference's structural race avoidance, SURVEY.md section 5)."""
    cfg = RaftConfig(n_nodes=5, client_interval=8)
    key = jax.random.key(7)
    k_init, k_run = jax.random.split(key)

    def go():
        state = init_state(cfg, k_init)
        final, metrics, _ = jax.jit(lambda s, k: scan.run(cfg, s, k, 200))(state, k_run)
        return jax.device_get(final), jax.device_get(metrics)

    f1, m1 = go()
    f2, m2 = go()
    for a, b in zip(jax.tree.leaves(f1), jax.tree.leaves(f2)):
        assert (a == b).all()
    for a, b in zip(jax.tree.leaves(m1), jax.tree.leaves(m2)):
        assert (a == b).all()
