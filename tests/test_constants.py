"""Drift tests: the oracle re-states several implementation constants in its own
words (tests/oracle.py must stay import-independent of raft_sim_tpu so it is a real
second implementation). These tests pin each restated constant/formula to the
original, so an update to one side without the other fails loudly instead of
surfacing as a mystery parity diff."""

import numpy as np

from raft_sim_tpu import types
from raft_sim_tpu.ops import log_ops
from raft_sim_tpu.utils import config
from tests import oracle


def test_ack_age_sat_matches():
    assert oracle.ACK_AGE_SAT == config.ACK_AGE_SAT == types.ACK_AGE_SAT
    assert oracle.ACK_AGE_SAT_NARROW == config.ACK_AGE_SAT_NARROW == types.ACK_AGE_SAT_NARROW
    # The saturation-ceiling selection formula, restated by the oracle, must
    # agree with the config property at both tiers.
    from raft_sim_tpu.utils.config import RaftConfig

    for timeout in (7, 12, 100, 119, 120, 500):
        cfg = RaftConfig(ack_timeout_ticks=timeout)
        assert oracle.ack_age_sat(cfg) == cfg.ack_age_sat


def test_noop_sentinel_matches():
    assert oracle.NOOP == types.NOOP
    assert types.NOOP != types.NIL  # distinct sentinels


def test_chk_weights_at_extends_chk_weights():
    """The absolute-index weight form (ring compaction) agrees with the per-slot
    form on the first CAP indices and with the oracle far beyond them."""
    import jax.numpy as jnp

    cap = 32
    w_t, w_v = log_ops.chk_weights(cap)
    w_t2, w_v2 = log_ops.chk_weights_at(jnp.arange(cap, dtype=jnp.uint32))
    np.testing.assert_array_equal(np.asarray(w_t), np.asarray(w_t2))
    np.testing.assert_array_equal(np.asarray(w_v), np.asarray(w_v2))
    far = np.array([100, 5000, 2**20, 2**31 - 1], dtype=np.uint32)
    g_t, g_v = log_ops.chk_weights_at(jnp.asarray(far))
    want = np.array([oracle.chk_weights(int(a)) for a in far], dtype=np.uint32)
    np.testing.assert_array_equal(np.asarray(g_t), want[:, 0])
    np.testing.assert_array_equal(np.asarray(g_v), want[:, 1])


def test_chk_weights_match():
    cap = 64
    w_t, w_v = log_ops.chk_weights(cap)
    want = np.array([oracle.chk_weights(k) for k in range(cap)], dtype=np.uint32)
    np.testing.assert_array_equal(np.asarray(w_t), want[:, 0])
    np.testing.assert_array_equal(np.asarray(w_v), want[:, 1])


def test_wire_constants_match():
    """Roles, request/response kinds, and the nil sentinel -- the enums both the
    mailbox type plane (v9) and the oracle's dispatch compare against."""
    assert (oracle.FOLLOWER, oracle.CANDIDATE, oracle.LEADER) == (
        types.FOLLOWER,
        types.CANDIDATE,
        types.LEADER,
    )
    assert (oracle.REQ_NONE, oracle.REQ_VOTE, oracle.REQ_APPEND) == (
        types.REQ_NONE,
        types.REQ_VOTE,
        types.REQ_APPEND,
    )
    assert (oracle.RESP_NONE, oracle.RESP_VOTE, oracle.RESP_APPEND) == (
        types.RESP_NONE,
        types.RESP_VOTE,
        types.RESP_APPEND,
    )
    assert oracle.NIL == types.NIL
