"""Driver tier: Session verbs, chunked-scan parity, checkpoint/resume, trace decoding,
CLI entry (the dev/user.clj + -main analogues, SURVEY.md sections 3.1/3.6)."""

import json

import jax
import numpy as np
import pytest

from raft_sim_tpu import RaftConfig, init_batch
from raft_sim_tpu.driver import Session, build_config, main
from raft_sim_tpu.sim import chunked, scan, trace
from raft_sim_tpu.utils import checkpoint

CFG = RaftConfig(n_nodes=5, client_interval=8)


def test_chunked_matches_monolithic():
    """Chunk boundaries must not perturb trajectories: inputs are pure functions of
    (key, state.now), so 3x100 ticks == 300 ticks."""
    key = jax.random.key(0)
    k_init, k_run = jax.random.split(key)
    state = init_batch(CFG, k_init, 8)
    keys = jax.random.split(k_run, 8)

    f_mono, m_mono, _ = scan.run_batch(CFG, state, keys, 300)
    f_chunk, m_chunk = chunked.run_chunked(CFG, state, keys, 300, chunk=100)

    for a, b in zip(jax.tree.leaves(jax.device_get(f_mono)), jax.tree.leaves(jax.device_get(f_chunk))):
        np.testing.assert_array_equal(a, b)
    for f, a, b in zip(m_mono._fields, jax.device_get(m_mono), jax.device_get(m_chunk)):
        np.testing.assert_array_equal(a, b, err_msg=f)


def test_chunked_callback_early_stop():
    key = jax.random.key(0)
    k_init, k_run = jax.random.split(key)
    state = init_batch(CFG, k_init, 4)
    keys = jax.random.split(k_run, 4)
    seen = []

    def cb(done, _s, _m):
        seen.append(done)
        return done >= 100

    _, m = chunked.run_chunked(CFG, state, keys, 1000, chunk=50, callback=cb)
    assert seen == [50, 100]
    assert int(np.asarray(m.ticks)[0]) == 100


def test_session_run_reset_deterministic():
    s = Session(CFG, batch=4, seed=3)
    s.run(150, chunk=64)
    first = jax.device_get(s.state)
    summary1 = s.summary()
    s.reset()
    s.run(150, chunk=64)
    for a, b in zip(jax.tree.leaves(first), jax.tree.leaves(jax.device_get(s.state))):
        np.testing.assert_array_equal(a, b)
    assert s.summary() == summary1
    assert summary1["total_violations"] == 0
    assert summary1["n_stable"] == 4


def test_checkpoint_roundtrip(tmp_path):
    """Resume from a checkpoint must continue the exact trajectory: run 100+100 with a
    save/load at the boundary == run 200 straight."""
    s = Session(CFG, batch=4, seed=5)
    s.run(100, chunk=50)
    # Bare path (no .npz): save normalizes and returns the real path; load accepts both.
    p = s.save(str(tmp_path / "ckpt"))
    assert p.endswith(".npz")

    s2 = Session.restore(str(tmp_path / "ckpt"))
    assert s2.cfg == CFG
    assert s2.seed == 5  # seed travels with the checkpoint
    s2.run(100, chunk=50)

    ref = Session(CFG, batch=4, seed=5)
    ref.run(200, chunk=50)
    for a, b in zip(jax.tree.leaves(jax.device_get(ref.state)), jax.tree.leaves(jax.device_get(s2.state))):
        np.testing.assert_array_equal(a, b)
    # Metrics resume too: the interrupted session's summary matches the straight run.
    assert s2.summary() == ref.summary()


def test_checkpoint_rejects_bad_version(tmp_path):
    import numpy as np_

    p = str(tmp_path / "bad.npz")
    np_.savez(p, __version__=np_.int32(1), config_json=np_.bytes_(b"{}"))
    # The mismatch error names both versions and points at the migration path
    # (the checkpoint.py version log).
    with pytest.raises(ValueError, match=r"format v1.*reads v\d+.*version log"):
        checkpoint.load(p)


def test_trace_events_readable():
    s = Session(CFG, batch=2, seed=0)
    infos, states = s.trace(120, cluster=0)
    evs = list(trace.events(states))
    kinds = " ".join(e for _, e in evs)
    assert "starts election" in kinds
    assert "becomes leader" in kinds
    assert "commits through" in kinds
    lines = list(trace.info_lines(infos, every=10))
    assert len(lines) == 12
    assert "VIOLATION" not in "".join(lines)
    # node_line renders every node at the final tick
    for i in range(CFG.n_nodes):
        assert f"node {i}:" in trace.node_line(states, 119, i)


def test_build_config_preset_with_overrides():
    class A:
        preset = "config4"
        batch = None

    a = A()
    import dataclasses as dc

    for f in dc.fields(RaftConfig):
        if not hasattr(a, f.name):
            setattr(a, f.name, None)
    a.n_nodes = 9
    cfg, batch = build_config(a)
    assert cfg.n_nodes == 9  # override applied
    assert cfg.drop_prob == 0.3  # preset preserved
    assert batch == 100_000  # preset batch filled in


def test_cli_run_and_presets(capsys):
    assert main(["presets"]) == 0
    out = capsys.readouterr().out
    assert "config1" in out and "config5" in out

    rc = main(["run", "--batch", "2", "--ticks", "60", "--client-interval", "8"])
    assert rc == 0
    payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert payload["n_clusters"] == 2
    assert payload["total_violations"] == 0
    assert payload["cluster_ticks_per_s"] > 0


def test_cli_trace_events(capsys):
    rc = main(["run", "--batch", "1", "--trace-events", "--ticks", "80"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "becomes leader" in out


def test_session_offer_interactive_client_write():
    """Session.offer = the reference's ad-hoc client-set POST: a command offered
    while leaders exist is accepted, appended with the offered value, and later
    committed; the offered tick participates in metric accounting like run()."""
    from raft_sim_tpu import RaftConfig
    from raft_sim_tpu.driver import Session
    import numpy as np

    s = Session(RaftConfig(n_nodes=5), batch=8, seed=0)
    s.run(60)  # elect leaders everywhere (reliable net)
    r = s.offer(424242)
    assert r["accepted"] == 8
    s.run(40)  # let it replicate + commit
    st = s.state
    logs = np.asarray(st.log_val)
    commits = np.asarray(st.commit_index)
    for c in range(8):
        lead = int(np.argmax(np.asarray(st.log_len[c])))
        vals = logs[c, lead, : int(commits[c, lead])]
        assert 424242 in vals, f"cluster {c}: offered value not committed"
    assert int(np.asarray(s.metrics.ticks).max()) == 101  # offer tick counted
    # No leader -> honestly rejected (unlike reference bug 2.3.9's silent hang).
    s2 = Session(RaftConfig(n_nodes=5), batch=4, seed=1)
    assert s2.offer(7)["accepted"] == 0  # tick 0: nobody is leader yet
