"""Shrink a violating (genome, seed, horizon) triple to a minimal repro.

A search hit names one cluster of a heterogeneous fleet whose on-device
invariants tripped. This module minimizes it the Molly/QuickCheck way --
greedy delta-debugging over the genome's fault mechanisms (drop each whole
mechanism, then halve surviving thresholds), every trial a bit-exact
single-cluster replay of the SAME trajectory prefix the fleet ran (keys are
split per cluster before the scan; tests/test_batched_parity.py pins the
equivalence) -- and emits a small JSON artifact:

  - the minimized genome (exact uint32 leaves AND decoded human units),
  - (config, seed, batch, cluster, seg_len, horizon = first violating
    tick + 1, violation kinds),
  - the decoded event log around the violation and per-node state lines
    (sim/trace.py -- the flight-recorder rendering), and
  - a standalone replay command.

`tools/repro.py --scenario artifact.json` replays the artifact and exits 0
iff the violation reproduces at the identical tick. Compile discipline:
every trial reuses ONE jitted traced replay (genome values are traced, so
ablations never recompile; only the final horizon-trimmed confirmation run
compiles a second program).
"""

from __future__ import annotations

import dataclasses
import functools
import json

import jax
import numpy as np

from raft_sim_tpu import init_batch
from raft_sim_tpu.scenario import genome as genome_mod
from raft_sim_tpu.sim import scan, trace
from raft_sim_tpu.utils.config import RaftConfig

VIOL_FIELDS = (
    "viol_election_safety", "viol_commit", "viol_log_matching",
    "viol_read_stale",
)

# Ablation groups tried whole-mechanism-first (any order is sound; cheap and
# usually-removable mechanisms go first so the artifact shrinks fastest), then
# threshold knobs halved while the violation survives.
ABLATIONS = (
    ("clock skew", {"skew": 0}),
    ("client traffic", {"client_interval": 0}),
    ("leadership transfers", {"transfer_interval": 0}),
    ("reads", {"read_interval": 0}),
    ("membership changes", {"reconfig_interval": 0}),
    ("message drop", {"drop": 0}),
    ("partitions", {"part": 0, "part_period": 0}),
    ("crashes", {"crash": 0}),
)
HALVABLE = ("drop", "part", "crash", "skew")


def _single_cluster(cfg: RaftConfig, seed: int, batch: int, cluster: int):
    """The (state, key) of one cluster of the seeded fleet -- identical to its
    slice of the batched run (init splits keys per cluster before the scan)."""
    root = jax.random.key(seed)
    k_init, k_run = jax.random.split(root)
    state = init_batch(cfg, k_init, batch)
    keys = jax.random.split(k_run, batch)
    take = lambda x: jax.tree.map(lambda v: v[cluster], x)
    return take(state), keys[cluster]


@functools.lru_cache(maxsize=8)
def _replay_fn(cfg: RaftConfig, n_ticks: int, seg_len: int):
    """One jitted traced single-cluster scenario replay per (cfg, horizon,
    seg_len): every ablation/halving trial reuses it (genomes are traced)."""
    return jax.jit(
        lambda s, k, g: scan.run(
            cfg, s, k, n_ticks, trace_states=True, genome=g, seg_len=seg_len
        )
    )


def _first_violation(infos) -> tuple[int | None, list[str]]:
    """(first violating tick index, kinds at that tick) from stacked StepInfo."""
    flags = {f: np.asarray(getattr(infos, f)) for f in VIOL_FIELDS}
    bad = np.zeros_like(next(iter(flags.values())))
    for v in flags.values():
        bad = bad | v
    if not bad.any():
        return None, []
    t = int(np.argmax(bad))
    return t, [f for f, v in flags.items() if bool(v[t])]


def _zero(genome, fields: dict):
    return genome._replace(
        **{f: jax.numpy.zeros_like(getattr(genome, f)) for f in fields}
    )


def shrink(
    cfg: RaftConfig,
    hit: dict,
    mutant: str | None = None,
    halving_rounds: int = 3,
    context: int = 30,
) -> dict:
    """Minimize a search hit (see search.py's hit schema) to a repro artifact.

    `cfg` must already be the kernel the hit was found against (pass the
    mutation.py config for mutant hunts; `mutant` only LABELS the artifact so
    the replayer rebuilds the same kernel). Raises ValueError if the hit does
    not reproduce at its recorded horizon -- a non-replayable hit means the
    caller's (genome, seed) bookkeeping is broken and must not be papered
    over.
    """
    seed, batch, cluster = hit["seed"], hit["batch"], hit["cluster"]
    seg_len, horizon = int(hit["seg_len"]), int(hit["ticks"])
    g0 = genome_mod.from_raw(hit["genome_raw"])
    state, key = _single_cluster(cfg, seed, batch, cluster)
    replay = _replay_fn(cfg, horizon, seg_len)

    def violates(g):
        _, _, (infos, _) = replay(state, key, g)
        return _first_violation(infos)[0] is not None

    if not violates(g0):
        raise ValueError(
            "hit does not reproduce: cluster "
            f"{cluster} of seed {seed} ran {horizon} ticks clean under its "
            "recorded genome -- (genome, seed, horizon) bookkeeping is broken"
        )

    # Phase 1: drop whole fault mechanisms while the violation survives.
    g, removed = g0, []
    for label, fields in ABLATIONS:
        cand = _zero(g, fields)
        if violates(cand):
            g, removed = cand, removed + [label]

    # Phase 2: halve surviving thresholds (a coarse "lowest rate that still
    # breaks" pass; `halving_rounds` bounds the budget).
    for _ in range(halving_rounds):
        any_halved = False
        for f in HALVABLE:
            leaf = getattr(g, f)
            if not np.asarray(leaf).any():
                continue
            cand = g._replace(**{f: leaf // 2})
            if violates(cand):
                g, any_halved = cand, True
        if not any_halved:
            break

    # Final confirmation at the minimized genome: exact tick, kinds, events,
    # state lines; the artifact's horizon is trimmed to tick + 1.
    _, _, (infos, states) = replay(state, key, g)
    tick, kinds = _first_violation(infos)
    events = [(t, e) for t, e in trace.events(states) if abs(t - tick) <= context]
    state_lines = [trace.node_line(states, tick, i) for i in range(cfg.n_nodes)]

    art = {
        "schema": "scenario-repro-v1",
        "config": {
            f.name: getattr(cfg, f.name)
            for f in dataclasses.fields(RaftConfig)
            if getattr(cfg, f.name) != f.default
        },
        "mutant": mutant,
        "seed": int(seed),
        "batch": int(batch),
        "cluster": int(cluster),
        "seg_len": seg_len,
        "ticks": int(tick) + 1,
        "tick": int(tick),
        "kinds": kinds,
        "removed": removed,
        "genome_raw": genome_mod.to_raw(g),
        "segments": genome_mod.decode(g),
        "events": events,
        "state_lines": state_lines,
        "repro_cmd": "python tools/repro.py --scenario <artifact.json>",
    }
    return art


def save_artifact(path: str, art: dict) -> str:
    with open(path, "w") as f:
        json.dump(art, f, indent=1)
        f.write("\n")
    return path


# Replayable artifact schemas. v1 is the raw shrink output; v2 adds the
# REQUIRED provenance block (farm/corpus.py stamps it: who found the hit,
# which fitness member, which generation/seed, what the shrink ablated, and
# the farm manifest hash) -- corpus-frozen artifacts must be v2
# (farm.corpus.validate_artifact), but the replayer accepts both: replay
# depends only on (config, mutant, genome, seed, horizon), which v1 carries.
ARTIFACT_SCHEMAS = ("scenario-repro-v1", "scenario-repro-v2")


def load_artifact(path: str) -> dict:
    with open(path) as f:
        art = json.load(f)
    if art.get("schema") not in ARTIFACT_SCHEMAS:
        raise ValueError(f"not a scenario repro artifact: {path}")
    return art


def artifact_config(art: dict) -> RaftConfig:
    """Rebuild the exact kernel the artifact was minimized against (the
    mutant label routes through mutation.py's registry)."""
    cfg = RaftConfig(**art.get("config", {}))
    if art.get("mutant"):
        from raft_sim_tpu.scenario.mutation import mutant_config

        cfg = mutant_config(art["mutant"], cfg)
    return cfg


def replay_artifact(art: dict, context: int = 30) -> dict:
    """Replay an artifact at its trimmed horizon. Returns
    {"reproduced": bool, "tick", "expected_tick", "kinds", "events"} --
    `reproduced` means the SAME first violating tick and kinds came back
    (trajectories are pure functions of (config, genome, seed), so anything
    else is an environment or code drift worth failing loudly on)."""
    cfg = artifact_config(art)
    g = genome_mod.from_raw(art["genome_raw"])
    state, key = _single_cluster(cfg, art["seed"], art["batch"], art["cluster"])
    replay = _replay_fn(cfg, int(art["ticks"]), int(art["seg_len"]))
    _, _, (infos, states) = replay(state, key, g)
    tick, kinds = _first_violation(infos)
    events = (
        [(t, e) for t, e in trace.events(states) if abs(t - tick) <= context]
        if tick is not None
        else []
    )
    return {
        "reproduced": tick == art["tick"] and kinds == art["kinds"],
        "tick": tick,
        "expected_tick": art["tick"],
        "kinds": kinds,
        "expected_kinds": art["kinds"],
        "events": events,
    }
