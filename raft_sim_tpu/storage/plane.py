"""The durability rules as elementwise lattices -- ONE statement for both
kernels.

Every rule here is a pure elementwise `jnp.where` lattice over per-node
leaves, so the same functions serve the single-cluster kernel's `[N]`
orientation and the batch-minor kernel's `[N, B]` (models/raft.py /
raft_batched.py): broadcasting does the layout work, and the two kernels
cannot drift on the semantics. The scalar oracle (tests/oracle.py)
deliberately does NOT import this module -- it restates the rules in
host-side numpy so kernel/oracle parity remains an independent check, not a
tautology. The package docstring (storage/__init__.py) is the prose
contract; sim/faults._storage_draws is the input side (fsync_fire /
torn_drop draws).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from raft_sim_tpu.types import NIL
from raft_sim_tpu.utils.config import RaftConfig


def recovered_log_len(dur_len: jax.Array, log_len: jax.Array,
                      torn_drop: jax.Array) -> jax.Array:
    """Entries a restart recovers: the fsynced prefix is a FLOOR (a
    completed flush can never tear), the un-fsynced tail survives as far as
    the in-flight writes reached minus the torn tail the recovery checksum
    rejects (`torn_drop` entries, drawn every tick, consumed only on
    restart ticks)."""
    return jnp.maximum(dur_len, log_len - torn_drop)


def recover(cfg: RaftConfig, rs: jax.Array, torn_drop: jax.Array,
            dur_len, dur_term, dur_vote, term, voted_for, log_len):
    """Crash recovery: rewind term/votedFor to the durable snapshot and
    truncate the log to the recovered length, on restarting nodes only
    (`rs`). Returns the post-recovery (term, voted_for, log_len). Sound
    because of the section-3.8 gate: everything a node ever EXPOSED (vote
    grants, AE acks) was durable first, so the rewind un-promises nothing.
    TEST-ONLY mutant (cfg.persist_vote False): recovery forgets votedFor --
    the reference's own restart bug (log.clj:16-18, SURVEY.md 2.3.12) -- so
    a restarted voter can grant a second vote in the same term (the
    election_safety break the volatile-vote hunt re-finds)."""
    rec_len = recovered_log_len(dur_len, log_len, torn_drop)
    return (
        jnp.where(rs, dur_term, term),
        jnp.where(
            rs,
            dur_vote if cfg.persist_vote else jnp.int32(NIL),
            voted_for,
        ),
        jnp.where(rs, rec_len, log_len),
    )


def covered(dur_term, dur_vote, term, voted_for) -> jax.Array:
    """True where the live (term, votedFor) pair is durably recorded -- the
    exposure predicate for vote grants (gate 2): a grant is visible to the
    candidate only while covered. NIL votedFor is never covered (there is
    no grant to expose)."""
    return (dur_term == term) & (dur_vote == voted_for) & (voted_for != NIL)


def flush(fs_fire, dur_mid, dur_term, dur_vote, log_len, term, voted_for):
    """The fsync completion lattice (phase 7.5): where a node's flush
    completes this tick (`fs_fire` -- cadence minus jitter stall, dead
    disks never flush), the durable snapshot snaps to the node's FINAL
    live state (post-injection log length, post-election term/vote);
    elsewhere it carries (`dur_mid` is the truncation-clamped watermark).
    Returns the post-flush (dur_len, dur_term, dur_vote)."""
    return (
        jnp.where(fs_fire, log_len, dur_mid),
        jnp.where(fs_fire, term, dur_term),
        jnp.where(fs_fire, voted_for, dur_vote),
    )
