"""raft_sim_tpu.farm: the fuzzing farm (tenth subsystem).

Portfolio hunts (many fitness functions, one compiled program per
generation), coverage-guided mutation against a farm-wide seen set, and the
self-growing checker-gated safety corpus. See farm/core.py for the loop,
farm/portfolio.py for the members, farm/corpus.py for the freeze policy,
and docs/SCENARIOS.md "Running the farm" for the workflow.
"""

from raft_sim_tpu.farm.core import (  # noqa: F401
    FARM_MANIFEST_SCHEMA,
    FARM_NEGATIVE_SCHEMA,
    FarmResult,
    FarmSpec,
    manifest_hash,
    run_farm,
    validate_farm_dir,
)
from raft_sim_tpu.farm.portfolio import FITNESS, parse_portfolio  # noqa: F401
