"""Device-side commit-delta extraction: the apply stream without a state diff.

The reference watches commits per entry with a per-log watch that was meant to
ack clients at apply time (log.clj:66-87; its commit watch never fires -- bug
2.3.9), and Ongaro's dissertation (section 6) treats commit acknowledgment as
part of the client protocol contract. The simulator's previous answers were a
host-side snapshot-diff poll (`Session._committed_mask`: a full [B, N, CAP]
device_get + ring scan per probe) and the single-cluster `ApplyLogWriter` --
neither scales to a standing fleet exporting every cluster's apply stream.

This module is the device-side replacement: a tiny jitted kernel (`extract`)
that, given the fleet state and a per-cluster WATERMARK of the last exported
apply index, gathers the newly committed node-0 entries of EVERY cluster into
a fixed-capacity [B, D] buffer -- values + offer stamps + absolute indices --
and advances the watermark. Per chunk the host round-trip is O(B * D) bytes
instead of O(B * N * CAP), and the watermark carry costs 4 B/cluster (priced
against the ~KBs/cluster fleet state by the gated cost model: well under the
5%% overhead ceiling ISSUE 6 sets).

Semantics:
  - The exported stream is node 0's committed prefix, in commit order -- the
    canonical apply stream (log matching makes every node's committed prefix
    identical, so node choice only affects WHEN an entry appears, not what).
  - Fixed capacity D is backpressure, not loss: a cluster committing more
    than D entries between drains simply exports the remainder on the next
    `drain` round (DeltaStream loops until dry), so the stream is exact.
  - Entries compacted past node 0's log_base before export are gone (they
    exist only as the snapshot triple); they surface as a per-cluster `gap`
    count, mirroring ApplyLogWriter's `# snapshot gap` marker. On healthy
    chunk cadences (commit advance < CAP - margin per chunk) no gaps occur.
  - Leader no-op entries (types.NOOP) ride the raw stream (indices stay
    dense); apply-stream consumers filter them, as ApplyLogWriter does.
"""

from __future__ import annotations

import functools
import json
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_sim_tpu.types import NIL, NOOP


class DeltaBatch(NamedTuple):
    """One extraction round (all leaves batch-leading [B, ...])."""

    start: jax.Array  # [B] int32: 1-based index BEFORE the first exported entry
    count: jax.Array  # [B] int32: entries exported this round (<= depth)
    gap: jax.Array  # [B] int32: entries lost to compaction since the watermark
    values: jax.Array  # [B, D] int32: committed payload values (NIL past count)
    ticks: jax.Array  # [B, D] int32: offer stamps (log_tick plane; 0 past count)
    watermark: jax.Array  # [B] int32: new watermark (= start + count)


@functools.partial(jax.jit, static_argnums=(2,))
def extract(state, watermark, depth: int) -> DeltaBatch:
    """One fixed-capacity extraction round over the whole fleet.

    `state` is the batched [B, ...] ClusterState, `watermark` the [B] int32
    last-exported apply index (0 = nothing exported yet). Gathers up to
    `depth` newly committed node-0 entries per cluster from the ring (works
    for the plain prefix layout too: log_base stays 0 there and slot = idx-1).
    Pure gather -- no scan, no donation; the fleet state is read-only.
    """
    cap = state.log_val.shape[-1]
    commit = state.commit_index[:, 0]  # [B] node 0's commit index
    base = state.log_base[:, 0]
    # Entries in (watermark, base] were compacted before export: gap, skip.
    start = jnp.maximum(watermark, base)
    gap = start - watermark
    count = jnp.clip(commit - start, 0, depth)
    k = jnp.arange(depth, dtype=jnp.int32)
    idx0 = start[:, None] + k[None, :]  # [B, D] 0-based absolute entry index
    slot = idx0 % cap  # ring slot (degenerates to idx0 for the prefix layout)
    valid = k[None, :] < count[:, None]
    vals = jnp.take_along_axis(state.log_val[:, 0, :], slot, axis=1)
    ticks = jnp.take_along_axis(state.log_tick[:, 0, :], slot, axis=1)
    return DeltaBatch(
        start=start,
        count=count,
        gap=gap,
        values=jnp.where(valid, vals, NIL),
        ticks=jnp.where(valid, ticks, 0),
        watermark=start + count,
    )


class DeltaStream:
    """Host-side consumer of `extract`: owns the watermark across chunks.

    `drain(state)` loops extraction rounds until every cluster is dry and
    returns the newly committed rows; `totals` accumulates export statistics.
    The watermark is the ONLY cross-chunk state (4 B/cluster on device).
    """

    def __init__(self, batch: int, depth: int = 64):
        if depth < 1:
            raise ValueError(f"delta depth must be >= 1, got {depth}")
        self.batch = batch
        self.depth = depth
        self.watermark = jnp.zeros((batch,), jnp.int32)
        self.exported = 0  # entries exported (incl. no-ops)
        # Client entries exported (no-ops excluded): the commands-ACKED
        # count -- what the serve throughput metric reports, so election
        # churn's protocol filler can never inflate commands+reads/s.
        self.applied = 0
        self.gap_entries = 0  # entries lost to compaction before export

    def skip_to_now(self, state) -> None:
        """Fast-forward the watermark past everything ALREADY committed
        anywhere in each cluster -- the max over nodes, not node 0's possibly
        lagging view: log matching puts those entries at the same indices in
        node 0's stream, so they are pre-offer history even if node 0 has not
        caught up yet. Subsequent drains then report only commits that happen
        after this call (Session.offer's pre-offer reset -- O(1) instead of
        draining a long backlog it would discard anyway)."""
        self.watermark = jnp.maximum(
            self.watermark, jnp.max(state.commit_index, axis=1)
        )

    def _rows_of(self, d: "DeltaBatch") -> list[dict]:
        """Host-side row building + export accounting for one fetched round
        (shared by the sync drain loop and the async fixed-round path)."""
        counts = np.asarray(d.count)
        gaps = np.asarray(d.gap)
        rows: list[dict] = []
        if not counts.any() and not gaps.any():
            return rows
        starts = np.asarray(d.start)
        values = np.asarray(d.values)
        ticks = np.asarray(d.ticks)
        for c in np.flatnonzero(counts | gaps):
            cnt = int(counts[c])
            vals = [int(v) for v in values[c, :cnt]]
            rows.append({
                "cluster": int(c),
                "start": int(starts[c]) + 1,
                "gap": int(gaps[c]),
                "values": vals,
                "ticks": [int(t) for t in ticks[c, :cnt]],
            })
            self.exported += cnt
            self.applied += sum(1 for v in vals if v != NOOP)
            self.gap_entries += int(gaps[c])
        return rows

    def drain(self, state, max_rounds: int = 1024) -> list[dict]:
        """Extract until no cluster has pending deltas. Returns one row per
        (cluster, round) with anything new:
        {"cluster", "start" (1-based index of the first value), "gap",
         "values" [..], "ticks" [..]} -- values are raw (no-ops included;
        apply-stream consumers filter types.NOOP)."""
        rows: list[dict] = []
        for _ in range(max_rounds):
            d: DeltaBatch = extract(state, self.watermark, self.depth)
            counts = np.asarray(d.count)
            new = self._rows_of(d)
            if not new:
                break
            rows.extend(new)
            self.watermark = d.watermark
            if int(counts.max(initial=0)) < self.depth:
                break  # nobody filled the buffer: everyone is dry
        return rows

    def begin_rounds(self, state, rounds: int) -> list["DeltaBatch"]:
        """The OVERLAPPED drain's dispatch half: enqueue a fixed number of
        extraction rounds against `state` (async under jax dispatch -- the
        serve loop queues them behind the chunk that produced the state and
        fetches after its sync, so the donation of `state` to the next chunk
        never races a pending read). `rounds * depth >= commit throughput
        per chunk` keeps the stream dry in steady state; any remainder is
        backpressure picked up next chunk, never loss. Advances the
        watermark to the final round's (a device future)."""
        futs = []
        wm = self.watermark
        for _ in range(rounds):
            d = extract(state, wm, self.depth)
            futs.append(d)
            wm = d.watermark
        self.watermark = wm
        return futs

    def finish_rounds(self, futs: list["DeltaBatch"]) -> list[dict]:
        """The overlapped drain's fetch half: rows from the enqueued rounds
        (call after the producing chunk's sync; the extractions have then
        already executed)."""
        rows: list[dict] = []
        for d in futs:
            rows.extend(self._rows_of(d))
        return rows


# ----------------------------------------------------------- stream file form

DELTA_FIELDS = ("cluster", "start", "gap")  # per line; values/ticks are lists


def append_delta_rows(path: str, rows: list[dict]) -> int:
    """Append drained rows to a deltas.jsonl stream (the serve sink's export
    half; schema checked by `validate_deltas`)."""
    if not rows:
        return 0
    with open(path, "a") as f:
        for row in rows:
            f.write(json.dumps(row) + "\n")
    return len(rows)


def validate_deltas(path: str) -> list[str]:
    """Schema-check a deltas.jsonl stream (dependency-free, like
    telemetry_sink.validate): per-cluster indices must be dense and
    monotone -- `start` picks up exactly where the previous row's
    start + gap + len(values) left off."""
    errors: list[str] = []
    next_start: dict[int, int] = {}
    try:
        f = open(path)
    except OSError as ex:
        return [f"{path}: unreadable: {ex}"]
    with f:
        for ln, raw in enumerate(f, 1):
            try:
                row = json.loads(raw)
            except json.JSONDecodeError as ex:
                errors.append(f"deltas.jsonl:{ln}: not JSON: {ex}")
                continue
            for k in DELTA_FIELDS:
                if not isinstance(row.get(k), int):
                    errors.append(f"deltas.jsonl:{ln}: field {k!r} missing or non-int")
            vals, ticks = row.get("values"), row.get("ticks")
            for name, lst in (("values", vals), ("ticks", ticks)):
                if not isinstance(lst, list) or not all(
                    isinstance(x, int) for x in lst
                ):
                    errors.append(f"deltas.jsonl:{ln}: {name} must be a list of ints")
            if isinstance(vals, list) and isinstance(ticks, list) and len(vals) != len(ticks):
                errors.append(f"deltas.jsonl:{ln}: values/ticks length mismatch")
            if not (isinstance(row.get("cluster"), int) and isinstance(row.get("start"), int)):
                continue
            c, start = row["cluster"], row["start"]
            want = next_start.get(c)
            got = start - row.get("gap", 0)
            if want is not None and got != want:
                errors.append(
                    f"deltas.jsonl:{ln}: cluster {c} stream not dense: "
                    f"start - gap = {got}, expected {want}"
                )
            next_start[c] = start + (len(vals) if isinstance(vals, list) else 0)
    return errors


def applied_values(rows: list[dict], cluster: int) -> list[int]:
    """The apply-stream view of drained/loaded rows for one cluster: committed
    client values in commit order, no-ops filtered (ApplyLogWriter.values
    equivalence -- tests pin the two streams equal)."""
    out: list[int] = []
    for row in rows:
        if row["cluster"] == cluster:
            out.extend(v for v in row["values"] if v != NOOP)
    return out
